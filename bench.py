"""Benchmark: NCF MovieLens-1M training throughput (samples/sec/chip).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no absolute NCF numbers (BASELINE.md), so the
baseline here is the *same training step on the host CPU* — the honest
stand-in for "BigDL-on-CPU on this machine" given BigDL targets CPU.  The
north-star is vs_baseline ≥ 10.
"""

import json
import time

import numpy as np


def build_step(model, tx, loss_fn):
    import jax
    import optax

    def step(params, state, opt_state, users, items, labels):
        def lossf(p):
            preds, ns = model.call(p, state, users, items, training=True)
            return loss_fn(labels, preds), ns

        (loss, new_state), grads = jax.value_and_grad(
            lossf, has_aux=True)(params)
        updates, new_opt = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), new_state, new_opt,
                loss)

    return step


def measure(device, batch=8192, warmup=3, iters=20):
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.models import NeuralCF
    from analytics_zoo_tpu.nn import reset_name_scope
    from analytics_zoo_tpu.nn.objectives import (
        sparse_categorical_crossentropy)
    from analytics_zoo_tpu.train.optimizers import Adam

    reset_name_scope()
    # MovieLens-1M shape, reference default hyper-params
    # (NeuralCF.scala:45: userEmbed/itemEmbed/mfEmbed=20, hidden 40/20/10)
    ncf = NeuralCF(user_count=6040, item_count=3706, class_num=5,
                   user_embed=20, item_embed=20, hidden_layers=(40, 20, 10),
                   mf_embed=20)
    model = ncf.model
    rs = np.random.RandomState(0)
    users = rs.randint(1, 6041, (batch, 1)).astype(np.int32)
    items = rs.randint(1, 3707, (batch, 1)).astype(np.int32)
    labels = rs.randint(0, 5, batch).astype(np.int32)

    with jax.default_device(device):
        params, state = model.init(jax.random.PRNGKey(0))
        tx = Adam(lr=1e-3)
        opt_state = tx.init(params)
        step = jax.jit(build_step(model, tx, sparse_categorical_crossentropy),
                       donate_argnums=(0, 1, 2))
        u = jax.device_put(jnp.asarray(users), device)
        i = jax.device_put(jnp.asarray(items), device)
        y = jax.device_put(jnp.asarray(labels), device)
        params = jax.device_put(params, device)
        state = jax.device_put(state, device)
        opt_state = jax.device_put(opt_state, device)

        for _ in range(warmup):
            params, state, opt_state, loss = step(params, state, opt_state,
                                                  u, i, y)
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            params, state, opt_state, loss = step(params, state, opt_state,
                                                  u, i, y)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
    return batch * iters / dt


def main():
    import jax

    accel = jax.devices()[0]
    value = measure(accel)

    vs_baseline = None
    try:
        cpu = jax.local_devices(backend="cpu")[0]
        cpu_tput = measure(cpu, batch=8192, warmup=1, iters=5)
        if cpu_tput > 0:
            vs_baseline = value / cpu_tput
    except Exception:
        pass

    print(json.dumps({
        "metric": "ncf_movielens1m_train_samples_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(vs_baseline, 3) if vs_baseline else None,
    }))


if __name__ == "__main__":
    main()
