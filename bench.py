"""Benchmark: the north star is NCF MovieLens-1M training throughput
(samples/sec/chip) *at matched accuracy* (BASELINE.json: >=10x CPU).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
The primary metric is NCF training throughput (bf16 compute); "extra"
carries the supporting evidence the north star asks for:

- ncf_hitrate_at_10: a real negative-sampled MovieLens-1M-shaped run
  through FeatureSet -> Estimator (prefetch + the full framework path),
  trained to convergence and evaluated with the NCF paper's protocol
  (held-out positive vs 99 negatives, HR@10).  The true MovieLens file
  is not fetchable here (zero egress); the generator reproduces its
  shape (6040x3706), sparsity (50 interactions/user - ml-1m's true mean
  is ~165), and a learnable latent-factor structure with a quoted
  oracle ceiling: HR@10 0.86 vs oracle 0.975, i.e. the framework
  recovers ~88%% of the recoverable signal.
- ncf_f32 / ncf_bf16: the mixed-precision delta (compute_dtype knob).
- featureset_data_paths: end-to-end samples/sec of BOTH Estimator data
  paths (host PrefetchIterator vs HBM-resident FeatureSet with
  on-device shuffle) on NCF- and WideAndDeep-shaped inputs, so the
  host-input gap closure is measured, not asserted.
- resnet50_ghostbn025_imgs_per_sec: BASELINE config #2 throughput
  (bf16 train step, ghost-BN stats_fraction=0.25; batch 256 by on-chip
  sweep - 1559 imgs/s vs 305 at batch 32, the MXU needs the batch to
  tile).  resnet50_imgs_per_sec_per_chip is the full-BN leg under the
  historical key, so cross-round comparisons stay variant-matched.
- resnet_accuracy: config #2's accuracy leg — cats-vs-dogs-shaped
  convergence with a quoted ceiling.
- wide_and_deep_samples_per_sec / nnframes: BASELINE configs #4 and #3,
  so all five configs carry measurements.
- attention_l{1024,2048,8192}: the hand-written Pallas kernel ON SILICON
  vs the pure-XLA blockwise fallback vs the STOCK pallas tpu kernel
  (adopt-or-beat).

Baseline: the same jitted training step on the host CPU — the honest
stand-in for "BigDL-on-CPU on this machine" given BigDL targets CPU and
publishes no absolute numbers (BASELINE.md).
"""

import contextlib as _contextlib
import json
import os
import time

import numpy as np

def _enable_compilation_cache():
    """Persistent XLA compilation cache: bench programs deserialize
    instead of recompiling on reruns — measured r5: 14.7s -> 8.8s for
    one flash fori-program; across the ~20 bench programs this buys the
    accuracy legs their window.  The dir is gitignored (binary
    executables, ~100MB/entry) but persists on the bench host between
    the interactive population run and the driver run.  NOTE: this JAX
    build ignores JAX_COMPILATION_CACHE_DIR — only the in-process
    config works."""
    import jax

    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          2.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:                   # older config names: cache is an
        pass                            # optimization, never a failure

# Wall-clock budget: optional extras are skipped once exceeded so the
# primary metric always prints within the driver's window.
_T0 = time.time()
# r2 evidence bounds the driver's window: its artifact captured a run
# that spent 0.8*460s in preflight retries plus a <=240s CPU fallback
# (~600s wall).  r5 adds a watchdog (below) that GUARANTEES the JSON
# line prints with whatever sections completed, so the budget can sit
# at the generous end without risking an empty artifact.
_BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "700"))


def _remaining() -> float:
    return _BUDGET_S - (time.time() - _T0)


# Typed skip reasons: every leg the runner elides goes through _skip()
# so the artifact's skip markers form a closed vocabulary that drift
# checks and dashboards can rely on (no free-form strings).
SKIP_TIME_BUDGET = "time budget"
SKIP_SHM = "POSIX shared memory unavailable"
_SKIP_REASONS = frozenset({SKIP_TIME_BUDGET, SKIP_SHM})


def _skip(into, name, reason=SKIP_TIME_BUDGET):
    """Record one skipped leg as ``{name}_skipped: reason`` (the key
    shape r4 pinned) and reject unknown reasons loudly."""
    if reason not in _SKIP_REASONS:
        raise ValueError(f"unknown skip reason: {reason!r}")
    into[f"{name}_skipped"] = reason
    return into


def _safe_ratio(num, den, nd=2):
    """Ratio of two measurements, or None when either side is missing,
    non-finite, or non-positive.  r5 shipped flash_vs_stock=Infinity
    because a sub-resolution denominator rounded to 0.0 — a ratio the
    artifact can't justify must be absent, not infinite."""
    try:
        num, den = float(num), float(den)
    except (TypeError, ValueError):
        return None
    if not (np.isfinite(num) and np.isfinite(den)) or num <= 0 or den <= 0:
        return None
    return round(num / den, nd)


def _roofline(bytes_ideal, bytes_moved, seconds=None):
    """Roofline-style HBM traffic row for one kernel leg.

    ``bytes_ideal`` is the compulsory traffic at this shape (inputs read
    once + outputs written once); ``bytes_moved`` what the measured
    implementation actually streams (analytic, from its blocking).
    ``traffic_ratio`` > 1 is the lowering's redundancy factor; with a
    measured ``seconds`` the achieved GB/s rides along.  Ratios go
    through ``_safe_ratio`` so a degenerate leg publishes an ABSENT
    number, never Infinity."""
    row = {"bytes_ideal": int(bytes_ideal),
           "bytes_moved": int(bytes_moved),
           "traffic_ratio": _safe_ratio(bytes_moved, bytes_ideal)}
    gbps = _safe_ratio(bytes_moved, (seconds or 0) * 1e9, nd=1)
    if gbps is not None:
        row["gbps_achieved"] = gbps
    return row


def _sanitize_json(obj):
    """Replace non-finite floats with None so the emitted report is
    strict JSON (json.dumps happily prints Infinity/NaN, which breaks
    every conforming parser downstream)."""
    if isinstance(obj, dict):
        return {k: _sanitize_json(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize_json(v) for v in obj]
    if isinstance(obj, float) and not np.isfinite(obj):
        return None
    return obj


class _Watchdog:
    """Prints the (partially filled) report and exits if the run outlives
    the budget by ``grace`` seconds — a wedged section or an impatient
    driver can no longer produce an EMPTY artifact (r4's worst failure
    mode was one section wedging the whole report)."""

    def __init__(self, report: dict, grace: float = 45.0):
        import threading

        self.report = report
        self._lock = threading.Lock()
        self._printed = False
        t = threading.Thread(target=self._arm, args=(grace,), daemon=True)
        t.start()

    def _arm(self, grace):
        delay = max(1.0, _BUDGET_S + grace - (time.time() - _T0))
        time.sleep(delay)
        if self.emit(tag="watchdog"):
            os._exit(0)

    def emit(self, tag: str = "") -> bool:
        with self._lock:
            if self._printed:
                return False
            self._printed = True
            if tag:
                self.report["extra"]["emitted_by"] = tag
            print(json.dumps(_sanitize_json(self.report)), flush=True)
            return True


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

def build_step(model, tx, loss_fn, compute_dtype=None):
    import jax
    import jax.numpy as jnp
    import optax

    # the exact cast policy the framework ships (no drift between what is
    # measured and what Estimator runs)
    from analytics_zoo_tpu.train.estimator import _cast_floats, _cast_like

    def step(params, state, opt_state, xs, labels):
        def lossf(p):
            if compute_dtype is not None:
                p = _cast_floats(p, compute_dtype)
                xs_c = _cast_floats(xs, compute_dtype)
            else:
                xs_c = xs
            preds, ns = model.call(p, state, *xs_c, training=True)
            if compute_dtype is not None:
                preds = _cast_floats(preds, jnp.float32)
                ns = _cast_like(ns, state)
            return loss_fn(labels, preds), ns

        (loss, new_state), grads = jax.value_and_grad(
            lossf, has_aux=True)(params)
        updates, new_opt = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), new_state, new_opt,
                loss)

    return step


def _sync(x) -> None:
    """True device sync costing ONE element of transfer.

    Two measured properties of the tunnelled-TPU transport shape every
    number in this file: (a) ``jax.block_until_ready`` is not a reliable
    barrier for non-scalar buffers (a 20-call Pallas loop "finished" in
    0.5ms under it), so a host read of the result is required; (b)
    device->host bandwidth is ~10MB/s, so that read must be one element —
    ``np.asarray(full_result)`` would bill megabytes of transfer to the
    compute being measured.  Indexing on device first makes the read 4
    bytes; in-order execution means syncing the last result drains the
    whole queue."""
    import jax
    import numpy as np_

    leaf = jax.tree_util.tree_leaves(x)[0]
    jax.block_until_ready(leaf)
    np_.asarray(leaf.ravel()[0] if getattr(leaf, "ndim", 0) else leaf)


def _time_steps(step, carry, args, warmup, iters):
    """Per-step device time via a two-point slope.

    The tunnel's end-sync is a full host round trip (measured p50
    ~110ms) — including it once in an N-step window inflates every step
    by sync/N.  Timing two windows (N and 2N) and taking the slope
    cancels the constant sync exactly while keeping the real pipelined
    per-dispatch cost in the number (steps serialize through the donated
    carry, so window time is genuinely N steps of device work)."""
    params, state, opt_state = carry
    for _ in range(warmup):
        params, state, opt_state, loss = step(params, state, opt_state,
                                              *args)
    _sync(loss)

    def window(n):
        nonlocal params, state, opt_state
        t0 = time.perf_counter()
        for _ in range(n):
            params, state, opt_state, loss = step(params, state, opt_state,
                                                  *args)
        _sync(loss)
        return time.perf_counter() - t0

    t1 = window(iters)
    t2 = window(2 * iters)
    if t2 > t1:
        return t2 - t1          # slope over `iters` steps
    return t1                   # noise guard: fall back to the window


# ---------------------------------------------------------------------------
# NCF throughput (the headline number)
# ---------------------------------------------------------------------------

def bench_ncf(device, batch=8192, warmup=1, iters=5, k_steps=64,
              compute_dtype=None):
    """Throughput of the framework's actual hot path: ``k_steps``
    optimizer steps fused into ONE dispatch via lax.scan over a stacked
    (K, B) superbatch — exactly what Estimator ships as
    ``steps_per_execution``.  Per-launch transport latency (measured
    ~2.5-8ms on the tunnelled chip; the reference measured the same
    effect as >10%% Spark task-launch overhead, wp-bigdl.md:171) is
    amortized to ~zero, so the number reflects device compute, not RPC
    round trips."""
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.models import NeuralCF
    from analytics_zoo_tpu.nn import reset_name_scope
    from analytics_zoo_tpu.nn.objectives import (
        sparse_categorical_crossentropy)
    from analytics_zoo_tpu.train.optimizers import Adam

    reset_name_scope()
    # MovieLens-1M shape, reference default hyper-params
    # (NeuralCF.scala:45: userEmbed/itemEmbed/mfEmbed=20, hidden 40/20/10)
    ncf = NeuralCF(user_count=6040, item_count=3706, class_num=5,
                   user_embed=20, item_embed=20, hidden_layers=(40, 20, 10),
                   mf_embed=20)
    model = ncf.model

    with jax.default_device(device):
        params, state = model.init(jax.random.PRNGKey(0))
        tx = Adam(lr=1e-3)
        opt_state = tx.init(params)
        step = build_step(model, tx, sparse_categorical_crossentropy,
                          compute_dtype=compute_dtype)

        def fused(params, state, opt_state, xs_stack, y_stack):
            def body(carry, bt):
                p, s, o = carry
                (bu, bi), by = bt
                p, s, o, loss = step(p, s, o, [bu, bi], by)
                return (p, s, o), loss

            (params, state, opt_state), losses = jax.lax.scan(
                body, (params, state, opt_state),
                ((xs_stack[0], xs_stack[1]), y_stack))
            return params, state, opt_state, losses[-1]

        fused = jax.jit(fused, donate_argnums=(0, 1, 2))
        # synthetic id stream generated ON DEVICE — the 100MB host
        # superbatch upload the old bench paid (~10s on the tunnel) told
        # us nothing about the training engine being measured
        @jax.jit
        def gen(key):
            ku, ki, ky = jax.random.split(key, 3)
            return (jax.random.randint(ku, (k_steps, batch, 1), 1, 6041,
                                       jnp.int32),
                    jax.random.randint(ki, (k_steps, batch, 1), 1, 3707,
                                       jnp.int32),
                    jax.random.randint(ky, (k_steps, batch), 0, 5,
                                       jnp.int32))

        users, items, labels = gen(jax.random.PRNGKey(0))
        xs = [users, items]
        y = labels
        carry = (jax.device_put(params, device),
                 jax.device_put(state, device),
                 jax.device_put(opt_state, device))
        dt = _time_steps(fused, carry, (xs, y), warmup, iters)
    return batch * k_steps * iters / dt


# ---------------------------------------------------------------------------
# NCF convergence: negative-sampled MovieLens-1M-shaped run + HR@10
# ---------------------------------------------------------------------------

def _movielens_like(n_users=6040, n_items=3706, latent=8, pos_per_user=20,
                    seed=0):
    """MovieLens-1M-shaped implicit-feedback data with latent structure:
    each user's positives are drawn from their top-scoring items under a
    low-rank preference model, so a factorization model can actually
    learn it (and HR@10 separates trained from untrained)."""
    rs = np.random.RandomState(seed)
    zu = rs.randn(n_users + 1, latent).astype(np.float32)
    zi = rs.randn(n_items + 1, latent).astype(np.float32)
    scores = zu @ zi.T                                  # (U+1, I+1)
    scores[:, 0] = -np.inf                              # pad row
    # preference set = top ~8% of items (300 for the MovieLens-1M shape)
    top_k = min(300, max(pos_per_user + 1, n_items // 12))
    top = np.argpartition(-scores, top_k, axis=1)[:, :top_k]
    users, items, heldout = [], [], np.zeros(n_users + 1, np.int64)
    for u in range(1, n_users + 1):
        cand = top[u]
        cand = cand[cand > 0]
        picks = cand[rs.choice(len(cand), pos_per_user + 1, replace=False)]
        heldout[u] = picks[0]                           # test positive
        users.extend([u] * pos_per_user)
        items.extend(picks[1:].tolist())
    return (np.asarray(users, np.int64), np.asarray(items, np.int64),
            heldout, scores)


def bench_ncf_convergence(epochs=12, batch=2048, n_users=6040, n_items=3706,
                          n_eval=2000, embed=16, mf_embed=16,
                          hidden=(64, 32, 16), lr=2e-3, pos_per_user=50,
                          dropout=0.6, neg_per_pos=8, swa_from=3,
                          ensemble=1, seed=42, k_steps=128,
                          cpu_baseline_epochs=3):
    """The north star in ONE run: matched-accuracy convergence whose own
    sustained samples/sec is compared against a CPU run of the SAME code
    path (BASELINE.json: >=10x CPU at matched accuracy).

    The data path is fully device-resident: ALL epochs' negatives are
    sampled on-chip in one jitted program
    (``presample_implicit_epochs``), and ``Estimator.fit`` consumes
    epoch slices of the resident arrays directly — the epoch loop moves
    zero bytes host→device (r4's 120x gap between the fused microbench
    and the convergence run was host numpy sampling + per-epoch
    FeatureSet rebuild; both are gone).

    Recipe (r3 CPU sweep; r4 on-silicon): fresh negatives EVERY epoch, 8
    per positive; MODEST factors (embed 16 — embed 64 memorizes); MLP
    dropout 0.6; tail-averaged weights (SWA from ``swa_from``).
    Measured r4: single model 0.9255, 2-seed ensemble 0.929, against a
    practical bound of 0.9625 (MAP with true item factors; the 0.975
    "oracle" needs exact latent knowledge no training set conveys).
    Rejected knobs (measured no better): wd 1e-4/1e-5, cosine decay,
    wider GMF, longer training, late SWA, neg_per_pos 16.

    The CPU baseline runs ``cpu_baseline_epochs`` of the identical
    recipe on the host CPU backend (same Estimator, same presampler,
    same shapes — bit-identical programs, r4-proven) and reports its
    sustained post-compile throughput; set 0 to skip."""
    import jax as _jax

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models import NeuralCF, presample_implicit_epochs
    from analytics_zoo_tpu.nn import reset_name_scope
    from analytics_zoo_tpu.train.optimizers import Adam

    users, items, heldout, true_scores = _movielens_like(
        n_users, n_items, pos_per_user=pos_per_user)

    def train_member(member_seed, n_epochs, platform=None,
                     stream_frac=1.0):
        """One full convergence run; returns (model, history).

        ``stream_frac < 1`` trains on a leading slice of each epoch's
        stream — the per-chunk program (shapes, K, batch) is identical,
        only the chunk count drops, so per-sample throughput is the same
        measurement at a fraction of the wall cost (used to keep the CPU
        leg affordable: its dropout threefry makes CPU ~40k samples/s)."""
        init_zoo_context(steps_per_execution=k_steps, seed=member_seed,
                         platform=platform)
        reset_name_scope()
        dev = _jax.local_devices(backend=platform)[0] if platform else None
        ctxmgr = (_jax.default_device(dev) if dev is not None
                  else _contextlib.nullcontext())
        with ctxmgr:
            if stream_frac < 1.0:       # slice the positives up front so
                n_keep = max(batch, int(len(users) * stream_frac))
                use_u, use_i = users[:n_keep], items[:n_keep]
            else:                       # the presample cost shrinks too
                use_u, use_i = users, items
            tr_u, tr_i, tr_y = presample_implicit_epochs(
                use_u, use_i, n_items, epochs=n_epochs,
                neg_per_pos=neg_per_pos, seed=member_seed + 1,
                trim_multiple=batch, user_count=n_users)
            ncf = NeuralCF(user_count=n_users, item_count=n_items,
                           class_num=2, user_embed=embed, item_embed=embed,
                           hidden_layers=hidden, mf_embed=mf_embed,
                           dropout=dropout)
            ncf.compile(optimizer=Adam(lr=lr),
                        loss="sparse_categorical_crossentropy",
                        metrics=["accuracy"])
            avg, n_avg = None, 0
            for done in range(n_epochs):
                # epoch slices stay on device; the stream is pre-shuffled
                # per epoch by the presampler, so shuffle=False
                ncf.estimator.fit(
                    [tr_u[done][:, None], tr_i[done][:, None]], tr_y[done],
                    batch_size=batch, epochs=done + 1, shuffle=False,
                    verbose=False)
                if done + 1 >= swa_from:
                    cur = _jax.device_get(ncf.estimator.params)
                    if avg is None:
                        avg, n_avg = cur, 1
                    else:
                        n_avg += 1
                        avg = _jax.tree_util.tree_map(
                            lambda a, c: a + (c - a) / n_avg, avg, cur)
            # evaluate the tail-averaged weights (dropout is identity at
            # inference; no BN here, so no stat recompute)
            if avg is not None:
                ncf.estimator.set_initial_weights(
                    avg, _jax.device_get(ncf.estimator.state))
            return ncf, ncf.estimator.history

    t0 = time.perf_counter()
    # seed-ensemble: independently-trained members' softmax scores are
    # averaged at ranking time (each member's errors are partly
    # idiosyncratic; the mean sharpens the common latent signal)
    trained = [train_member(seed + 1000 * m, epochs)
               for m in range(max(1, ensemble))]
    train_s = time.perf_counter() - t0
    members = [t[0] for t in trained]
    # sustained = post-compile per-epoch throughput (epoch 1 carries the
    # XLA compiles); epochs 2+ are steady state
    epoch_tputs = [r["throughput"] for _, h in trained for r in h[1:]]
    sustained = float(np.median(epoch_tputs)) if epoch_tputs else 0.0
    samples_per_member = (len(users) * (1 + neg_per_pos) // batch) \
        * batch * epochs

    # HR@10, the NCF paper's protocol: held-out positive vs 99 negatives
    # the user has NOT interacted with (train positives + heldout are the
    # only exclusions — hard negatives from the latent preference set
    # remain eligible).  An oracle HR on the same candidate lists (ranking
    # by the true latent scores) calibrates the ceiling.
    rs = np.random.RandomState(2)
    n_eval = min(n_eval, n_users)       # subset of users for time-bound eval
    eval_users = rs.choice(np.arange(1, n_users + 1), n_eval, replace=False)
    seen = {int(u): {0} for u in eval_users}
    for u, i in zip(users, items):
        if int(u) in seen:
            seen[int(u)].add(int(i))
    all_u, all_i = [], []
    for u in eval_users:
        s = seen[int(u)]
        s.add(int(heldout[u]))
        negs = []
        while len(negs) < 99:
            j = int(rs.randint(1, n_items + 1))
            if j not in s:
                negs.append(j)
        all_u.extend([u] * 100)
        all_i.extend([int(heldout[u])] + negs)
    pu = np.asarray(all_u, np.int32)[:, None]
    pi = np.asarray(all_i, np.int32)[:, None]
    probs = np.mean([np.asarray(m.predict([pu, pi], batch_size=8192))
                     for m in members], axis=0)         # (N, 2) softmax
    pos_scores = probs[:, 1].reshape(n_eval, 100)
    ranks = (pos_scores[:, 1:] >= pos_scores[:, :1]).sum(axis=1)
    hr10 = float((ranks < 10).mean())
    oracle = true_scores[pu[:, 0], pi[:, 0]].reshape(n_eval, 100)
    oracle_hr10 = float(
        ((oracle[:, 1:] >= oracle[:, :1]).sum(axis=1) < 10).mean())
    samples = samples_per_member * len(members)
    out = {"hitrate_at_10": round(hr10, 4),
           "ensemble": len(members),
           "oracle_hitrate_at_10": round(oracle_hr10, 4),
           # r4 measured ceiling for ANY learner on this data: MAP user
           # estimation GIVEN the true item factors + generative link
           # reaches 0.9625 from 50 positives/user — the 0.975 oracle
           # needs exact latent knowledge no training set conveys
           # (docs/PERFORMANCE.md "the 0.975 oracle is not reachable").
           "practical_bound_hr10": 0.9625,
           "tpu_convergence_samples_per_sec": round(sustained, 1),
           "tpu_end_to_end_samples_per_sec": round(samples / train_s, 1),
           "train_samples": samples,
           "train_wall_s": round(train_s, 1)}
    if cpu_baseline_epochs > 0:
        try:
            t0 = time.perf_counter()
            # quarter-stream slice: identical per-chunk program, so the
            # per-sample rate is the same measurement at 1/4 the wall
            cpu_frac = 0.25
            _, cpu_hist = train_member(seed, cpu_baseline_epochs,
                                       platform="cpu",
                                       stream_frac=cpu_frac)
            cpu_wall = time.perf_counter() - t0
            cpu_tputs = [r["throughput"] for r in cpu_hist[1:]]
            # fallback (single-epoch history): wall-clock rate of the
            # quarter-stream run — scale the per-epoch sample count by
            # the SAME fraction the run actually trained on
            cpu_sustained = (float(np.median(cpu_tputs)) if cpu_tputs
                             else samples_per_member * cpu_frac
                             / epochs * cpu_baseline_epochs / cpu_wall)
            out["cpu_convergence_samples_per_sec"] = round(cpu_sustained, 1)
            out["cpu_baseline_epochs"] = cpu_baseline_epochs
            out["cpu_stream_frac"] = 0.25
            if cpu_sustained > 0:
                out["convergence_speedup_vs_cpu"] = round(
                    sustained / cpu_sustained, 2)
        except Exception as e:          # noqa: BLE001 — record, don't zero
            out["cpu_convergence_error"] = f"{type(e).__name__}: {e}"
    return out


# ---------------------------------------------------------------------------
# ResNet-50 (BASELINE config #2)
# ---------------------------------------------------------------------------

def bench_resnet50(device, batch=256, n1=4, rounds=2,
                   bn_stats_fraction=1.0):
    """ResNet-50 bf16 train step: ONE compiled program, launch-amortized
    and transport-safe by construction.

    Supersedes the r4 plain/fused pair: r4's fused leg shipped a
    (K, B, 224, 224, 3) float32 superbatch = 2.47GB in ONE buffer, which
    wedged the tunnel and recorded 43.86 imgs/s as the round's official
    number (docs/PERFORMANCE.md:33-35 documents the >~2GB hazard).  Now
    ONE uint8 batch (38.5MB, the serving wire format — normalize fuses
    into conv1) is uploaded; a fori_loop with RUNTIME trip count runs
    n and 2n optimizer steps through the same executable, and the slope
    cancels dispatch+sync exactly (per-step launch latency amortizes
    like steps_per_execution in production).  Parameter updates chain
    every iteration, so the dispatch-memoizing tunnel runtime (r5
    finding) cannot fake the number."""
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.models.image.imageclassification import resnet50
    from analytics_zoo_tpu.nn import reset_name_scope
    from analytics_zoo_tpu.nn.objectives import (
        sparse_categorical_crossentropy_with_logits)
    from analytics_zoo_tpu.train.optimizers import Adam

    reset_name_scope()
    model = resnet50(class_num=1000,   # logits head (fc, no softmax)
                     bn_stats_fraction=bn_stats_fraction)
    rs = np.random.RandomState(0)
    x_u8 = rs.randint(0, 256, (batch, 224, 224, 3)).astype(np.uint8)
    y = rs.randint(0, 1000, batch).astype(np.int32)

    with jax.default_device(device):
        params, state = model.init(jax.random.PRNGKey(0))
        tx = Adam(lr=1e-3)
        opt_state = tx.init(params)
        step = build_step(model, tx,
                          sparse_categorical_crossentropy_with_logits,
                          compute_dtype=jnp.bfloat16)

        @jax.jit
        def many(carry, xu8, yb, n):
            xb = [xu8.astype(jnp.float32) / 255.0]

            def body(_, c):
                p, s, o = c
                p, s, o, _loss = step(p, s, o, xb, yb)
                return (p, s, o)

            return jax.lax.fori_loop(0, n, body, carry)

        xd = jax.device_put(jnp.asarray(x_u8), device)
        yd = jax.device_put(jnp.asarray(y), device)
        carry = (jax.device_put(params, device),
                 jax.device_put(state, device),
                 jax.device_put(opt_state, device))
        _sync(many(carry, xd, yd, 1))          # compile + warm

        def t(n):
            t0 = time.perf_counter()
            _sync(many(carry, xd, yd, n))
            return time.perf_counter() - t0

        # distinct trip counts per dispatch (memoization-proof) +
        # least-squares slope, as in _measure_scan
        pts = [((r + 2) * n1, t((r + 2) * n1))
               for r in range(max(2, rounds))]
        ns = np.asarray([p[0] for p in pts], np.float64)
        ts = np.asarray([p[1] for p in pts], np.float64)
        denom = ((ns - ns.mean()) ** 2).sum()
        slope = ((ns - ns.mean()) * (ts - ts.mean())).sum() / denom
        per_step = max(slope, 1e-12)
    return batch / per_step


def bench_resnet_accuracy(device, n=4096, size=32, epochs=3, batch=256,
                          lr=3e-4):
    """Accuracy evidence for BASELINE config #2: a cold ResNet-50 trains
    to real VALIDATION accuracy through the full Estimator path on a
    dogs-vs-cats-shaped scene task (warm circles vs cool bars on noise —
    structured cues, fully separable, quoted ceiling 1.0).

    r5 post-mortem (the leg had never actually landed in any artifact):
    the original recipe paired resnet50's LOGITS head with the
    probability-space "sparse_categorical_crossentropy" — the net
    memorized the train set through the clipped loss and validated at
    CHANCE in every configuration until the with_logits loss was used
    (then 0.993 in 4 epochs).  bn_momentum=0.3 so the eval path's
    moving statistics converge within the leg's ~50 updates."""
    import jax

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models.image.imageclassification import resnet50
    from analytics_zoo_tpu.nn import reset_name_scope
    from analytics_zoo_tpu.train.optimizers import Adam

    import cv2

    def scene(kind, rs):
        img = (rs.rand(size, size, 3) * 60).astype(np.uint8)
        cx, cy = rs.randint(6, size - 6, 2)
        if kind:        # warm circle
            color = (int(rs.randint(0, 80)), int(rs.randint(60, 140)),
                     int(rs.randint(170, 255)))
            cv2.circle(img, (cx, cy), int(rs.randint(4, size // 4)),
                       color, -1)
        else:           # cool bar
            color = (int(rs.randint(170, 255)), int(rs.randint(60, 140)),
                     int(rs.randint(0, 80)))
            cv2.rectangle(img, (cx, cy),
                          (min(size - 1, cx + 12), min(size - 1, cy + 5)),
                          color, -1)
        return img.astype(np.float32) / 255.0

    init_zoo_context(compute_dtype="bfloat16", steps_per_execution=4)
    reset_name_scope()
    rs = np.random.RandomState(0)
    y = rs.randint(0, 2, n).astype(np.int32)
    x = np.stack([scene(int(t), rs) for t in y])
    split = int(0.9 * n)
    model = resnet50(class_num=2, input_shape=(size, size, 3),
                     bn_momentum=0.3)
    model.compile(optimizer=Adam(lr=lr),
                  loss="sparse_categorical_crossentropy_with_logits",
                  metrics=["accuracy"])
    t0 = time.perf_counter()
    model.fit(x[:split], y[:split], batch_size=batch, nb_epoch=epochs,
              verbose=False)
    dt = time.perf_counter() - t0
    res = model.evaluate(x[split:], y[split:], batch_size=512)
    return {"val_accuracy": round(float(res["accuracy"]), 4),
            "ceiling": 1.0, "epochs": epochs,
            "train_imgs_per_sec": round(split * epochs / dt, 1)}


# ---------------------------------------------------------------------------
# WideAndDeep (BASELINE config #4) + NNFrames pipeline (config #3)
# ---------------------------------------------------------------------------

def bench_wide_and_deep(device, batch=8192, k_steps=32, iters=3,
                        compute_dtype="bfloat16"):
    """WideAndDeep training throughput, census-shaped features
    (reference WideAndDeepExample.scala; BASELINE config #4): 2 wide
    cross columns, 2 embedding columns, 11 continuous — fused K-step
    dispatch like the NCF headline."""
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.models import WideAndDeep
    from analytics_zoo_tpu.nn import reset_name_scope
    from analytics_zoo_tpu.nn.objectives import (
        sparse_categorical_crossentropy)
    from analytics_zoo_tpu.train.optimizers import Adam

    reset_name_scope()
    wnd = WideAndDeep(class_num=2, wide_base_dims=(1000, 1000),
                      embed_in_dims=(5000, 1000), embed_out_dims=(64, 64),
                      continuous_cols=11, hidden_layers=(100, 75, 50, 25))
    model = wnd.model
    rs = np.random.RandomState(0)
    wide = rs.randint(0, 1000, (k_steps, batch, 2)).astype(np.int32)
    wide[:, :, 1] += 1000
    emb = np.stack([rs.randint(0, 5000, (k_steps, batch)),
                    rs.randint(0, 1000, (k_steps, batch))],
                   axis=-1).astype(np.int32)
    cont = rs.randn(k_steps, batch, 11).astype(np.float32)
    yk = rs.randint(0, 2, (k_steps, batch)).astype(np.int32)

    with jax.default_device(device):
        params, state = model.init(jax.random.PRNGKey(0))
        tx = Adam(lr=1e-3)
        opt_state = tx.init(params)
        cd = jnp.bfloat16 if compute_dtype == "bfloat16" else None
        step = build_step(model, tx, sparse_categorical_crossentropy,
                          compute_dtype=cd)

        def fused(params, state, opt_state, xs_stack, y_stack):
            def body(carry, bt):
                p, s, o = carry
                (bw, be, bc), by = bt
                p, s, o, loss = step(p, s, o, [bw, be, bc], by)
                return (p, s, o), loss

            (params, state, opt_state), losses = jax.lax.scan(
                body, (params, state, opt_state),
                ((xs_stack[0], xs_stack[1], xs_stack[2]), y_stack))
            return params, state, opt_state, losses[-1]

        fused = jax.jit(fused, donate_argnums=(0, 1, 2))
        xs = [jax.device_put(jnp.asarray(a), device)
              for a in (wide, emb, cont)]
        yd = jax.device_put(jnp.asarray(yk), device)
        carry = (jax.device_put(params, device),
                 jax.device_put(state, device),
                 jax.device_put(opt_state, device))
        dt = _time_steps(fused, carry, (xs, yd), 1, iters)
    return batch * k_steps * iters / dt


def bench_data_paths(n_rows=1 << 20, batch=8192, epochs=3, k_steps=32):
    """Host-prefetch vs HBM-resident FeatureSet through the SAME
    ``Estimator.fit``: end-to-end samples/sec of both data paths on NCF-
    and WideAndDeep-shaped inputs, so the host-input gap closure (r5:
    NCF step compute 8.35M samples/s vs 891k end-to-end through the host
    path) is measured, not asserted.

    Per model two legs run: the default HOST path (background
    ``PrefetchIterator`` feeding the K-step fused program) and
    ``fs.cache("DEVICE")`` (one HBM materialization up front; per-epoch
    ``jax.random.permutation`` + gather inside ONE jitted fori_loop, so
    an epoch is one dispatch and zero host->device bytes).  Sustained =
    median post-compile epoch throughput (epoch 1 carries the XLA
    compile).  ``data_path`` records the route
    ``Estimator._resolve_data_path`` actually took, so a silently
    fallen-back device leg cannot masquerade as resident."""
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.data import FeatureSet
    from analytics_zoo_tpu.models import NeuralCF, WideAndDeep
    from analytics_zoo_tpu.nn import reset_name_scope
    from analytics_zoo_tpu.train.optimizers import Adam

    rs = np.random.RandomState(0)
    n = max(batch, (n_rows // batch) * batch)

    def make_ncf():
        m = NeuralCF(user_count=6040, item_count=3706, class_num=2,
                     user_embed=16, item_embed=16, mf_embed=16,
                     hidden_layers=(64, 32, 16))
        xs = [rs.randint(1, 6041, (n, 1)).astype(np.int32),
              rs.randint(1, 3707, (n, 1)).astype(np.int32)]
        return m, xs

    def make_wnd():
        m = WideAndDeep(class_num=2, wide_base_dims=(1000, 1000),
                        embed_in_dims=(5000, 1000),
                        embed_out_dims=(64, 64), continuous_cols=11,
                        hidden_layers=(100, 75, 50, 25))
        wide = rs.randint(0, 1000, (n, 2)).astype(np.int32)
        wide[:, 1] += 1000                  # shared-table column offset
        emb = np.stack([rs.randint(0, 5000, n),
                        rs.randint(0, 1000, n)], axis=-1).astype(np.int32)
        cont = rs.randn(n, 11).astype(np.float32)
        return m, [wide, emb, cont]

    out = {}
    for name, make in (("ncf", make_ncf), ("wide_deep", make_wnd)):
        legs = {}
        for leg, level in (("host", None), ("device", "DEVICE")):
            init_zoo_context(steps_per_execution=k_steps, seed=0)
            reset_name_scope()
            model, xs = make()
            model.compile(optimizer=Adam(lr=1e-3),
                          loss="sparse_categorical_crossentropy")
            y = rs.randint(0, 2, n).astype(np.int32)
            fs = FeatureSet.from_ndarrays(xs, y, cache_level=level)
            est = model.estimator
            est.fit(fs, batch_size=batch, epochs=epochs, verbose=False)
            tputs = [r["throughput"] for r in est.history[1:]]
            legs[leg] = {
                "tpu_end_to_end_samples_per_sec": round(
                    float(np.median(tputs)) if tputs else 0.0, 1),
                "data_path": est.last_data_path,
            }
        host = legs["host"]["tpu_end_to_end_samples_per_sec"]
        dev = legs["device"]["tpu_end_to_end_samples_per_sec"]
        legs["device_vs_host"] = round(dev / host, 2) if host else None
        out[name] = legs
    return out


def bench_featureset_streaming(n_rows=1 << 15, batch=4096, epochs=3,
                               budget_frac=4):
    """STREAM tier vs whole-dataset residency through the SAME
    ``Estimator.fit`` (ISSUE 10): an NCF-shaped dataset sized
    ``budget_frac``× the device budget rotates budget-sized shards
    through HBM with the double-buffered uploader, against a resident
    leg whose budget fits the whole dataset.

    Reported per leg: sustained end-to-end samples/sec (median
    post-compile epoch) and the route the budget router actually took;
    plus ``stream_vs_resident`` (the acceptance floor is ≥0.5×) and the
    stream leg's ``data_stream_overlap_frac`` gauge — the counter-proof
    that uploads overlapped compute rather than serialising with it."""
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.data import FeatureSet
    from analytics_zoo_tpu.models import NeuralCF
    from analytics_zoo_tpu.nn import reset_name_scope
    from analytics_zoo_tpu.observe import metrics as obs
    from analytics_zoo_tpu.train.optimizers import Adam

    rs = np.random.RandomState(0)
    n = max(batch, (n_rows // batch) * batch)
    xs_bytes = n * (4 + 4 + 4)          # user + item + label, int32

    def run(level, budget):
        init_zoo_context(steps_per_execution=1, seed=0)
        reset_name_scope()
        m = NeuralCF(user_count=6040, item_count=3706, class_num=2,
                     user_embed=16, item_embed=16, mf_embed=16,
                     hidden_layers=(64, 32, 16))
        m.compile(optimizer=Adam(lr=1e-3),
                  loss="sparse_categorical_crossentropy")
        xs = [rs.randint(1, 6041, (n, 1)).astype(np.int32),
              rs.randint(1, 3707, (n, 1)).astype(np.int32)]
        y = rs.randint(0, 2, n).astype(np.int32)
        fs = FeatureSet.from_ndarrays(xs, y, cache_level=level)
        est = m.estimator
        est.ctx.config.data_device_budget_bytes = budget
        est.fit(fs, batch_size=batch, epochs=epochs, verbose=False)
        tputs = [r["throughput"] for r in est.history[1:]]
        return est, {
            "tpu_end_to_end_samples_per_sec": round(
                float(np.median(tputs)) if tputs else 0.0, 1),
            "data_path": est.last_data_path,
        }

    out = {"dataset_bytes": xs_bytes,
           "device_budget_bytes": xs_bytes // budget_frac}
    _, resident = run("DEVICE", xs_bytes * 2)
    est_s, stream = run("STREAM", xs_bytes // budget_frac)
    snap = obs.METRICS.snapshot()
    stream["overlap_frac"] = round(float(
        snap.gauges.get(("data_stream_overlap_frac", ()), 0.0)), 3)
    if est_s._stream_plan is not None:
        stream["n_shards"] = est_s._stream_plan.n_shards
    out["resident"] = resident
    out["stream"] = stream
    res = resident["tpu_end_to_end_samples_per_sec"]
    out["stream_vs_resident"] = round(
        stream["tpu_end_to_end_samples_per_sec"] / res, 2) if res else None
    out["image"] = _bench_streaming_image_leg()
    return out


def _bench_streaming_image_leg(n=6144, batch=256, epochs=3,
                               budget_frac=4):
    """ResNet-shaped image leg of the streaming bench: float32
    32x32x3 rows trained through a small conv stem, with the device
    cache quantized to uint8 (``ZooConfig.data_cache_dtype``) so the
    rotation moves 4x fewer HBM bytes per shard than the host-side
    float payload.  Same contract as the NCF legs: STREAM at a
    ``budget_frac``x-over-budget dataset vs whole-dataset residency,
    both through the SAME ``Estimator.fit``."""
    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.data import FeatureSet
    from analytics_zoo_tpu.nn import Sequential, reset_name_scope
    from analytics_zoo_tpu.nn.layers.convolutional import Convolution2D
    from analytics_zoo_tpu.nn.layers.core import Dense
    from analytics_zoo_tpu.nn.layers.pooling import GlobalAveragePooling2D
    from analytics_zoo_tpu.train.optimizers import Adam

    rs = np.random.RandomState(0)
    x = rs.randint(0, 256, (n, 32, 32, 3)).astype(np.float32)
    y = rs.randint(0, 10, n).astype(np.int32)
    # the budget is held against the CACHED (uint8) footprint — that is
    # what actually occupies HBM slots during the rotation
    cached_bytes = x.size * 1 + y.nbytes

    def run(level, budget):
        init_zoo_context(steps_per_execution=1, seed=0)
        reset_name_scope()
        m = Sequential()
        m.add(Convolution2D(16, 3, 3, subsample=2, activation="relu",
                            border_mode="same", input_shape=(32, 32, 3)))
        m.add(Convolution2D(32, 3, 3, subsample=2, activation="relu",
                            border_mode="same"))
        m.add(GlobalAveragePooling2D())
        m.add(Dense(10, activation="softmax"))
        m.compile(optimizer=Adam(lr=1e-3),
                  loss="sparse_categorical_crossentropy")
        est = m.estimator
        est.ctx.config.data_device_budget_bytes = budget
        est.ctx.config.data_cache_dtype = "uint8"
        fs = FeatureSet.from_ndarrays([x], y, cache_level=level)
        est.fit(fs, batch_size=batch, epochs=epochs, verbose=False)
        tputs = [r["throughput"] for r in est.history[1:]]
        return est, {
            "tpu_end_to_end_samples_per_sec": round(
                float(np.median(tputs)) if tputs else 0.0, 1),
            "data_path": est.last_data_path,
        }

    out = {"dataset_bytes": int(x.nbytes + y.nbytes),
           "cached_bytes": int(cached_bytes),
           "device_budget_bytes": int(cached_bytes // budget_frac)}
    # the router holds the budget against the HOST payload, so the
    # resident leg needs headroom over the float32 bytes
    _, resident = run("DEVICE", (x.nbytes + y.nbytes) * 2)
    est_s, stream = run("STREAM", cached_bytes // budget_frac)
    if est_s._stream_plan is not None:
        stream["n_shards"] = est_s._stream_plan.n_shards
    out["resident"] = resident
    out["stream"] = stream
    res = resident["tpu_end_to_end_samples_per_sec"]
    out["stream_vs_resident"] = round(
        stream["tpu_end_to_end_samples_per_sec"] / res, 2) if res else None
    return out


def bench_checkpoint_overhead(n=1 << 15, batch=4096, epochs=4,
                              k_steps=8):
    """Cost of the durability layer (docs/ROBUSTNESS.md): the SAME
    NCF-shaped ``Estimator.fit`` run three ways — no checkpointing,
    async per-epoch snapshots (the default: CRC32-manifested atomic
    writes land on a background thread), and fully synchronous saves —
    plus the raw latency of one verified save and one verified
    restore.  The async column is the claim under test: durability at
    per-epoch granularity should cost ~nothing on the step path."""
    import shutil
    import tempfile

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.models import NeuralCF
    from analytics_zoo_tpu.nn import reset_name_scope
    from analytics_zoo_tpu.train import checkpoint as ckpt_lib
    from analytics_zoo_tpu.train.optimizers import Adam

    rs = np.random.RandomState(0)
    n = max(batch, (n // batch) * batch)
    out = {}
    est = None
    for leg, async_ckpt in (("no_ckpt", None), ("async", True),
                            ("sync", False)):
        init_zoo_context(steps_per_execution=k_steps, seed=0,
                         async_checkpoint=bool(async_ckpt))
        reset_name_scope()
        model = NeuralCF(user_count=6040, item_count=3706, class_num=2,
                         user_embed=16, item_embed=16, mf_embed=16,
                         hidden_layers=(64, 32, 16))
        xs = [rs.randint(1, 6041, (n, 1)).astype(np.int32),
              rs.randint(1, 3707, (n, 1)).astype(np.int32)]
        y = rs.randint(0, 2, n).astype(np.int32)
        model.compile(optimizer=Adam(lr=1e-3),
                      loss="sparse_categorical_crossentropy")
        est = model.estimator
        tmp = tempfile.mkdtemp(prefix="bench_ckpt_")
        try:
            if async_ckpt is not None:
                est.set_checkpoint(tmp)
            est.fit(xs, y, batch_size=batch, epochs=epochs,
                    verbose=False)
            tputs = [r["throughput"] for r in est.history[1:]]
            out[f"{leg}_samples_per_sec"] = round(
                float(np.median(tputs)) if tputs else 0.0, 1)
            if async_ckpt is not None and leg == "sync":
                # raw verified save/restore latency on the live snapshot
                mgr = ckpt_lib.CheckpointManager(tmp)
                t0 = time.perf_counter()
                mgr.save(est.global_step + 1, est._snapshot())
                out["save_verified_ms"] = round(
                    (time.perf_counter() - t0) * 1e3, 1)
                t0 = time.perf_counter()
                mgr.restore()
                out["restore_verified_ms"] = round(
                    (time.perf_counter() - t0) * 1e3, 1)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    base = out.get("no_ckpt_samples_per_sec") or 0
    for leg in ("async", "sync"):
        tput = out.get(f"{leg}_samples_per_sec")
        if base and tput:
            out[f"{leg}_overhead_pct"] = round(100 * (1 - tput / base), 1)
    return out


def bench_nnframes(n=120_000, epochs=2, batch=8192):
    """NNFrames end-to-end rows/sec (BASELINE config #3): DataFrame →
    NNEstimator.fit → NNModel.transform, including the pandas column
    extraction — the whole Spark-ML-shaped pipeline, not just the jitted
    step (reference NNEstimator.scala:414-491)."""
    import pandas as pd

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.nn import reset_name_scope
    from analytics_zoo_tpu.nn.layers.core import Dense
    from analytics_zoo_tpu.nn.topology import Sequential
    from analytics_zoo_tpu.nnframes import NNEstimator

    init_zoo_context(steps_per_execution=8)
    reset_name_scope()
    rs = np.random.RandomState(0)
    x = rs.randn(n, 16).astype(np.float32)
    yv = (x @ rs.randn(16)).astype(np.float32)
    df = pd.DataFrame({"features": list(x), "label": yv})

    m = Sequential()
    m.add(Dense(64, activation="relu", input_shape=(16,)))
    m.add(Dense(1))
    est = (NNEstimator(m, criterion="mse")
           .setBatchSize(batch).setMaxEpoch(epochs).setLearningRate(1e-3))
    t0 = time.perf_counter()
    nn_model = est.fit(df)
    fit_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = nn_model.transform(df)
    tr_s = time.perf_counter() - t0
    assert len(out) == n
    return {"fit_rows_per_sec": round(n * epochs / fit_s, 1),
            "transform_rows_per_sec": round(n / tr_s, 1)}


# ---------------------------------------------------------------------------
# Attention: Pallas flash kernel on silicon vs XLA blockwise fallback
# ---------------------------------------------------------------------------

def _scan_time_ms(fn, carry0, K=16, rounds=3, probe=True):
    """TRUE per-call device time: K data-DEPENDENT applications fused in
    ONE dispatch via lax.scan, slope over (K, 2K) dispatches.

    This replaced the repeated-thunk timer after r5 discovered the
    tunnel runtime MEMOIZES identical-input dispatches (10 calls of
    f(x) with the same buffer returned in ~0 device time, which is how
    r4's flash/int8 "wins" were minted).  Here every iteration's input
    is derived from the previous output (no memoization possible), the
    K iterations ride one dispatch (the ~20ms per-dispatch tunnel floor
    amortizes out), and the two-point slope cancels dispatch+sync
    exactly.  ``fn(carry) -> array_like_carry``."""
    many = _make_scan_program(fn)
    _sync(many(carry0, K))              # compile + warm (one program)
    return _measure_scan(many, carry0, K, rounds, probe)


def _make_scan_program(fn):
    """ONE compile per case: the trip count is a RUNTIME argument
    (fori_loop lowers to while_loop), so the K and 2K windows share the
    same executable — compiling two scan programs per case blew a 536s
    attention section in the first r5 validation run."""
    import jax

    @jax.jit
    def many(c0, n):
        def body(_, c):
            out = fn(c)
            return 0.5 * c + 0.5 * out.astype(c.dtype)
        return jax.lax.fori_loop(0, n, body, c0)

    return many


def _measure_scan(many, carry0, K, rounds, probe=True):
    """Slope measurement of an already-warmed scan program.

    EVERY timed dispatch uses a DISTINCT trip count (K, 2K, 3K, ...) so
    no two dispatches are byte-identical — the memoizing tunnel runtime
    (see module notes) can never serve a cached result into the fit.
    The least-squares slope over the (n, t) points cancels the constant
    dispatch+sync cost exactly like the two-point version did.

    Returns the per-iteration time in ms, or None when the slope stays
    below timer resolution (< 0.5us/iter) after escalating the trip
    count — callers must treat None as "unresolved", never as 0.  r5
    published attention_l2048.flash_ms=0.0 / flash_vs_stock=Infinity
    from exactly this failure."""
    def t(n):
        t0 = time.perf_counter()
        _sync(many(carry0, n))
        return time.perf_counter() - t0

    # auto-scale K until the window dwarfs transport jitter (~±10ms on
    # the tunnel); each probe n is distinct, so probes can't be cached.
    # The 64K probe ceiling matters for sub-microsecond iterations (the
    # attention_l2048 fwd legs): the old 4K cap left the whole window
    # inside timer resolution and the leg published null/unresolved
    while probe and K < 65536 and t(K + K // 4) < 0.08:
        K *= 4
    for attempt in range(5):
        pts = []
        for r in range(max(2, rounds + 1)):
            n = (r + 1) * K
            pts.append((n, t(n)))
        ns = np.asarray([p[0] for p in pts], np.float64)
        ts = np.asarray([p[1] for p in pts], np.float64)
        denom = ((ns - ns.mean()) ** 2).sum()
        slope_ms = float(((ns - ns.mean()) * (ts - ts.mean())).sum()
                         / denom) * 1e3
        if np.isfinite(slope_ms) and slope_ms >= 5e-4:
            return slope_ms
        # the whole window sat inside timer/transport noise, so the fit
        # is garbage; grow the windows and retry while the budget holds
        if attempt == 4 or K >= (1 << 20) or _remaining() < 30.0:
            return None
        K *= 8
    return None


def _warm_parallel(cases, threads=6):
    """Compile+warm scan programs CONCURRENTLY (XLA compilation releases
    the GIL; measured r5: 3 flash-kernel programs compile in 33.6s
    threaded vs 82.0s serial).  ``cases``: iterable of (many, carry0).
    Errors are captured per-case and returned, not raised."""
    from concurrent.futures import ThreadPoolExecutor

    errs = {}

    def one(idx_case):
        idx, (many, carry0) = idx_case
        try:
            _sync(many(carry0, 1))
        except Exception as e:          # noqa: BLE001 — per-case report
            errs[idx] = e
    with ThreadPoolExecutor(threads) as ex:
        list(ex.map(one, enumerate(cases)))
    return errs


def bench_attention(device, B=4, H=8, L=2048, D=64, K=None,
                    include_stock=True, include_bwd=True,
                    include_blockwise=True, blockwise_bwd=False,
                    rounds=3):
    """Hand-written Pallas flash kernel vs the XLA blockwise fallback vs
    the STOCK jax.experimental.pallas.ops.tpu flash kernel — the
    adopt-or-beat comparison (VERDICT r2 weak #5), measured with the
    memoization-proof scan-fused timer (r5 true-time methodology: data
    dependence between iterations, one dispatch per window).
    ``include_bwd=False`` halves the compile bill for the secondary
    context lengths so all three lengths always fit the bench window."""
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.ops.attention import blockwise_attention
    from analytics_zoo_tpu.ops.flash_attention import flash_attention

    if K is None:
        K = 4 if L >= 8192 else 16
    rs = np.random.RandomState(0)
    mk = lambda: jax.device_put(
        jnp.asarray(rs.randn(B, H, L, D).astype(np.float32)), device)
    q, k, v = mk(), mk(), mk()

    out = {}
    built = _build_attention_cases(out, q, k, v, D, K, rounds,
                                   include_stock, include_bwd,
                                   include_blockwise, blockwise_bwd)
    errs = _warm_parallel([(m, c) for _, m, c, _, _ in built])
    _finish_attention_cases(out, built, errs)
    _attention_roofline(out, B, H, L, D)
    return out


def _build_attention_cases(out, q, k, v, D, K, rounds, include_stock,
                           include_bwd, include_blockwise, blockwise_bwd):
    """Construct (key, many, carry, K, rounds) scan cases for one
    (q, k, v) shape — compilation deferred so a suite can warm every
    length's programs concurrently."""
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.ops.attention import blockwise_attention
    from analytics_zoo_tpu.ops.flash_attention import flash_attention

    pairs = [("flash", lambda q, k, v: flash_attention(
                  q, k, v, causal=True))]
    if include_blockwise:
        pairs.append(("blockwise", lambda q, k, v: blockwise_attention(
            q, k, v, causal=True)))
    if include_stock:
        try:
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention as stock_flash)
            sm = 1.0 / float(np.sqrt(D))
            pairs.append(("stock_pallas",
                          lambda q, k, v: stock_flash(q, k, v, causal=True,
                                                      sm_scale=sm)))
        except Exception as e:
            out["stock_pallas_error"] = type(e).__name__
    built = []
    for name, fn in pairs:
        built.append((f"{name}_ms", _make_scan_program(
            lambda c, fn=fn: fn(c, k, v)), q, K, rounds))
        if include_bwd and (name != "blockwise" or blockwise_bwd):
            grad_q = jax.grad(lambda a, b, c, fn=fn: jnp.sum(fn(a, b, c)))
            built.append((f"{name}_fwdbwd_ms", _make_scan_program(
                lambda c, g=grad_q: g(c, k, v)), q, max(2, K // 2),
                rounds))
    return built


def _finish_attention_cases(out, built, errs):
    for idx, (key, many, carry, K, rounds) in enumerate(built):
        if idx in errs:                 # pallas unavailable / OOM etc.
            out[key.replace("_ms", "_error")] = type(errs[idx]).__name__
            continue
        try:
            ms = _measure_scan(many, carry, K, rounds)
        except Exception as e:          # noqa: BLE001
            out[key.replace("_ms", "_error")] = type(e).__name__
            continue
        if ms is None:
            out[key] = None
            out[key.replace("_ms", "_unresolved")] = \
                "slope below timer resolution after escalation"
        else:
            out[key] = round(ms, 3)
    for rkey, num, den in (
            ("flash_speedup", "blockwise_ms", "flash_ms"),
            ("flash_bwd_speedup", "blockwise_fwdbwd_ms", "flash_fwdbwd_ms"),
            ("flash_vs_stock", "stock_pallas_ms", "flash_ms")):
        if num in out and den in out:
            out[rkey] = _safe_ratio(out[num], out[den])


def _attention_roofline(out, B, H, L, D, bq=256):
    """Analytic HBM traffic for the causal flash fwd leg at this shape.

    Ideal = Q, K, V read once + O written once.  The kernel re-streams
    K/V tiles once per q block (causal: only tiles at or below the
    diagonal), so bytes-moved grows as L^2/bq — the pinned bytes row in
    docs/PERFORMANCE.md makes the blocking visible, not just the
    wall-clock."""
    f32 = 4
    ideal = f32 * B * H * D * 4 * L
    bq = min(bq, L)
    kv_rows = sum(min(L, (i + 1) * bq) for i in range(max(1, L // bq)))
    moved = f32 * B * H * D * (2 * L + 2 * kv_rows)
    ms = out.get("flash_ms")
    out["roofline_flash_fwd"] = _roofline(ideal, moved,
                                          ms * 1e-3 if ms else None)


def bench_attention_suite(device, specs, into=None):
    """All context lengths in one pass: BUILD every case, warm ALL
    programs concurrently (threaded XLA compile, ~2.4x wall), then
    measure serially on the quiet device.  ``specs``: [(L, kw), ...]."""
    import jax
    import jax.numpy as jnp

    rs = np.random.RandomState(0)
    per_len = []
    all_cases = []
    for L, kw in specs:
        B, H, D = kw.pop("B", 4), kw.pop("H", 8), kw.pop("D", 64)
        K = kw.pop("K", 4 if L >= 8192 else 16)
        mk = lambda: jax.device_put(
            jnp.asarray(rs.randn(B, H, L, D).astype(np.float32)), device)
        q, k, v = mk(), mk(), mk()
        out = {}
        built = _build_attention_cases(
            out, q, k, v, D, K, kw.pop("rounds", 2),
            kw.pop("include_stock", True), kw.pop("include_bwd", True),
            kw.pop("include_blockwise", True),
            kw.pop("blockwise_bwd", False))
        per_len.append((L, (B, H, D), out, built, len(all_cases)))
        all_cases.extend((m, c) for _, m, c, _, _ in built)
    errs = _warm_parallel(all_cases)
    results = {}
    for L, (B, H, D), out, built, ofs in per_len:
        local_errs = {i - ofs: e for i, e in errs.items()
                      if ofs <= i < ofs + len(built)}
        # write INCREMENTALLY so a watchdog emit mid-suite still carries
        # every length measured so far
        if into is not None:
            into[f"attention_l{L}"] = out
        _finish_attention_cases(out, built, local_errs)
        _attention_roofline(out, B, H, L, D)
        results[f"attention_l{L}"] = out
    return results


# ---------------------------------------------------------------------------
# INT8 vs bf16/f32 matmul (the reference's int8-calibration ~2x claim,
# wp-bigdl.md:192, realised on the MXU's native int8 path)
# ---------------------------------------------------------------------------

def bench_int8(device, n=4096, K=128):
    """int8 MXU matmul vs bf16/f32 with the memoization-proof scan-fused
    timer (see _scan_time_ms).  n=4096 keeps the upload at 64MB on the
    ~10MB/s tunnel; true device times at this size are ~0.4-0.9ms so the
    K-fused windows dwarf transport jitter."""
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.ops.quantization import int8_dot, quantize_tensor

    rs = np.random.RandomState(0)
    x = jax.device_put(jnp.asarray(
        rs.randn(n, n).astype(np.float32)), device)
    w = rs.randn(n, n).astype(np.float32) * 0.1
    wq, wscale = quantize_tensor(w)
    wq = jax.device_put(wq, device)
    wscale = jax.device_put(jnp.asarray(wscale).reshape(-1), device)
    wd = jax.device_put(jnp.asarray(w), device)
    wbf = jax.device_put(jnp.asarray(w).astype(jnp.bfloat16), device)
    xscale = float(np.abs(rs.randn(10000)).max() / 127)

    out = {}
    # bf16 leg dropped from the artifact run: r5 measured bf16 within
    # 8% of f32 here (XLA computes f32 matmuls via bf16 passes on this
    # MXU), and each fori-program compile costs ~15s
    progs = {"f32_ms": _make_scan_program(lambda c: c @ wd),
             "int8_ms": _make_scan_program(
                 lambda c: int8_dot(c, wq, wscale, x_scale=xscale))}
    del wbf
    errs = _warm_parallel([(m, x) for m in progs.values()], threads=2)
    for idx, (key, many) in enumerate(progs.items()):
        if idx in errs:
            out[key.replace("_ms", "_error")] = type(errs[idx]).__name__
            continue
        ms = _measure_scan(many, x, K, rounds=2, probe=False)
        if ms is None:
            out[key] = None
            out[key.replace("_ms", "_unresolved")] = \
                "slope below timer resolution after escalation"
        else:
            out[key] = round(ms, 3)
    if "f32_ms" in out and "int8_ms" in out:
        out["int8_vs_f32_speedup"] = _safe_ratio(out["f32_ms"],
                                                 out["int8_ms"])
    if "bf16_ms" in out and "int8_ms" in out:
        out["int8_vs_bf16_speedup"] = _safe_ratio(out["bf16_ms"],
                                                  out["int8_ms"])
    return out


# ---------------------------------------------------------------------------
# ops/ fused kernels: embedding-bag gather-combine and dequantize-matmul
# vs their unfused XLA lowerings, with roofline bytes-moved rows alongside
# the wall-clock so the artifact records WHY the fusion wins, not just
# that it does
# ---------------------------------------------------------------------------


def _make_ids_scan(fn, vocab):
    """Scan program for an int32 ids carry: each iteration's bags derive
    from the previous output through a runtime-zero (but not provably
    zero) bump, so XLA can neither hoist the lookup out of the loop nor
    serve a memoized result — _make_scan_program's data-dependence
    discipline, specialised to integer carries."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def many(c0, n):
        def body(_, ids):
            out = fn(ids)
            bump = (jnp.abs(out[0, 0]) * 1e-20).astype(jnp.int32)
            return (ids + bump + 1) % vocab
        return jax.lax.fori_loop(0, n, body, c0)

    return many


def _kernel_leg_recorder(leg: str, profile_ms: float = 50.0):
    """FlightRecorder armed over one kernel bench leg: a floor breach
    trigger()s a capture AND a short device profiler trace into
    BENCH_PROFILE_DIR/<leg> — the trace that explains a regression lands
    next to the artifact instead of needing a manual re-run under the
    profiler."""
    from analytics_zoo_tpu.observe.recorder import FlightRecorder

    root = os.environ.get("BENCH_PROFILE_DIR",
                          os.path.join(os.getcwd(), "bench_profile"))
    pdir = os.path.join(root, leg)
    return FlightRecorder(out_dir=pdir, profile_dir=pdir,
                          profile_ms=profile_ms)


def _breach_check(out, leg, ratio_key, floor):
    """Capture a flight record + device profile when a speedup floor is
    breached; an unresolved ratio is NOT a breach (absent, not zero)."""
    spd = out.get(ratio_key)
    if spd is None or spd >= floor:
        return
    try:
        out["breach_flight_record"] = _kernel_leg_recorder(leg).trigger(
            f"{leg}_speedup_breach", {ratio_key: spd, "floor": floor})
    except Exception as e:      # noqa: BLE001 — never fail the leg
        out["breach_recorder_error"] = f"{type(e).__name__}: {e}"


def bench_embedding_bag(device, V=1 << 20, D=64, B=4096, N=32, K=16,
                        rounds=2):
    """Fused Pallas embedding-bag vs the unfused XLA gather+segment-sum
    at a DLRM-ish shape (1M-row table, 32-hot bags), scan-fused timing
    over an ids carry.  The roofline rows expose the mechanism: the
    unfused lowering writes the (B, N, D) gathered rows to HBM and
    reads them back for the reduce — ~3x the compulsory traffic the
    fused kernel moves."""
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.ops.embedding_bag import (
        embedding_bag, embedding_bag_reference)

    rs = np.random.RandomState(0)
    table = jax.device_put(jnp.asarray(
        rs.randn(V, D).astype(np.float32) * 0.05), device)
    ids = jax.device_put(jnp.asarray(
        rs.randint(0, V, size=(B, N)).astype(np.int32)), device)

    out = {"shape": {"vocab": V, "dim": D, "bags": B, "multi_hot": N}}
    progs = {
        "fused_ms": _make_ids_scan(
            lambda c: embedding_bag(table, c, "sum", None), V),
        "unfused_ms": _make_ids_scan(
            lambda c: embedding_bag_reference(table, c, "sum", None), V),
    }
    errs = _warm_parallel([(m, ids) for m in progs.values()], threads=2)
    for idx, (key, many) in enumerate(progs.items()):
        if idx in errs:
            out[key.replace("_ms", "_error")] = type(errs[idx]).__name__
            continue
        ms = _measure_scan(many, ids, K, rounds=rounds)
        if ms is None:
            out[key] = None
            out[key.replace("_ms", "_unresolved")] = \
                "slope below timer resolution after escalation"
        else:
            out[key] = round(ms, 3)
    out["fused_vs_unfused_speedup"] = _safe_ratio(
        out.get("unfused_ms"), out.get("fused_ms"))
    ideal = 4 * (B * N * D + B * D)     # rows read once + bags written
    fsec = out.get("fused_ms")
    usec = out.get("unfused_ms")
    out["roofline_fused"] = _roofline(
        ideal, ideal, fsec * 1e-3 if fsec else None)
    out["roofline_unfused"] = _roofline(
        ideal, 4 * (3 * B * N * D + B * D),
        usec * 1e-3 if usec else None)
    if jax.default_backend() == "tpu":
        # the acceptance floor only binds where the Pallas path runs
        _breach_check(out, "embedding_bag", "fused_vs_unfused_speedup",
                      1.3)
    return out


def bench_dlrm_sharded_child(giant=True, v_train=1 << 20, d_train=16,
                             b=4096, n=8, k_steps=8, rounds=3,
                             v_giant=100_000_000, d_giant=2,
                             b_giant=8192):
    """Measured legs of the DLRM sharded-embedding bench; runs in the
    subprocess ``bench_dlrm_sharded`` launches (dp×tp mesh over however
    many devices the child sees).  Three legs:

    - parity: sharded lookup vs the dense ``embedding_bag`` at rtol
      1e-6 on a small table (the correctness gate on everything below);
    - train: a table the bench budget cannot hold replicated (router
      must pick ``sharded``) trained for real steps — samples/sec, the
      per-chip table HBM actually resident, the Adam moments' placement,
      and the replicated twin's throughput for the speedup row;
    - giant (optional): a 10⁸-row table initialized shard-by-shard
      straight from the lazy ``SyntheticGiantTable`` generator — never
      materialized on the host — then timed on sharded lookups.
    """
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from analytics_zoo_tpu.core.context import init_zoo_context
    from analytics_zoo_tpu.data.giant_table import SyntheticGiantTable
    from analytics_zoo_tpu.ops.embedding_bag import embedding_bag
    from analytics_zoo_tpu.parallel.table_sharding import (
        choose_table_placement, init_table_sharded, sharded_bag,
        sharded_gather)

    ndev = len(jax.devices())
    ways = 4 if ndev % 4 == 0 and ndev >= 8 else \
        (2 if ndev % 2 == 0 else 1)
    ctx = init_zoo_context(mesh_shape=(ndev // ways, ways),
                           axis_names=("data", "model"))
    mesh = ctx.mesh
    out = {"mesh": {"data": ndev // ways, "model": ways},
           "platform": jax.devices()[0].platform}
    rs = np.random.RandomState(0)

    # --- parity gate: sharded vs dense bag on a small table ----------
    tb = jnp.asarray(rs.randn(256, 16).astype(np.float32) * 0.05)
    pid = jnp.asarray(rs.randint(0, 256, (64, 8)).astype(np.int32))
    ref = np.asarray(embedding_bag(tb, pid, "sum", None))
    got = np.asarray(sharded_bag(tb, pid, "sum", None, mesh=mesh,
                                 axis="model"))
    out["parity_max_abs_err"] = float(np.max(np.abs(ref - got)))
    out["parity_ok"] = bool(np.allclose(ref, got, rtol=1e-6, atol=1e-7))

    def timed(fn, *args):
        """min seconds per call over ``rounds`` of ``k_steps`` calls."""
        best = None
        res = fn(*args)                          # warm/compile
        jax.block_until_ready(res)
        for _ in range(rounds):
            t0 = time.perf_counter()
            for _ in range(k_steps):
                res = fn(*args) if not isinstance(res, tuple) else \
                    fn(*res)
            jax.block_until_ready(res)
            dt = (time.perf_counter() - t0) / k_steps
            best = dt if best is None else min(best, dt)
        return best, res

    # --- train leg: a table that does NOT fit replicated -------------
    nbytes = v_train * d_train * 4
    budget = nbytes // 2                 # replicated over, /ways under
    dec = choose_table_placement(nbytes=nbytes, rows=v_train,
                                 requested="auto", mesh=mesh,
                                 axis="model", budget_bytes=budget)
    train = {"rows": v_train, "dim": d_train, "nbytes": nbytes,
             "budget_bytes": budget, "router_placement": dec.placement,
             "router_reason": dec.reason_code}
    out["train"] = train
    host_table = rs.randn(v_train, d_train).astype(np.float32) * 0.05
    ids_h = rs.randint(0, v_train, (b, n)).astype(np.int32)
    y_h = rs.randn(b, d_train).astype(np.float32)
    tx = optax.adam(1e-3)
    d_sh = NamedSharding(mesh, P("data", None))
    ids = jax.device_put(jnp.asarray(ids_h), d_sh)
    y = jax.device_put(jnp.asarray(y_h), d_sh)

    def make_step(lookup):
        def loss_fn(tab):
            return jnp.mean((lookup(tab) - y) ** 2)

        @jax.jit
        def step(tab, opt):
            g = jax.grad(loss_fn)(tab)
            upd, opt = tx.update(g, opt, tab)
            return optax.apply_updates(tab, upd), opt
        return step

    table = jax.device_put(jnp.asarray(host_table),
                           NamedSharding(mesh, P("model", None)))
    opt0 = jax.jit(tx.init)(table)
    sec, (table_out, opt_out) = timed(
        make_step(lambda t: sharded_bag(t, ids, "sum", None, mesh=mesh,
                                        axis="model")), table, opt0)
    train["sharded_samples_per_sec"] = round(b / sec, 1) if sec else None
    train["hbm_table_bytes_per_chip"] = int(
        table_out.addressable_shards[0].data.nbytes)
    mu = jax.tree_util.tree_leaves(opt_out)
    moment = next((x for x in mu if getattr(x, "shape", ()) ==
                   table.shape), None)
    train["adam_moments_sharded"] = bool(
        moment is not None and
        moment.addressable_shards[0].data.shape[0] < table.shape[0])
    # replicated twin (same steps, dense bag) for the speedup row
    rep = jax.device_put(jnp.asarray(host_table),
                         NamedSharding(mesh, P()))
    sec_r, _ = timed(make_step(
        lambda t: embedding_bag(t, ids, "sum", None)), rep,
        jax.jit(tx.init)(rep))
    train["replicated_samples_per_sec"] = \
        round(b / sec_r, 1) if sec_r else None
    train["sharded_vs_replicated_speedup"] = _safe_ratio(
        train["sharded_samples_per_sec"],
        train["replicated_samples_per_sec"])

    # --- giant leg: 10⁸ rows, lazily generated, shard-resident -------
    if giant:
        src = SyntheticGiantTable(v_giant, d_giant, seed=11)
        t0 = time.time()
        gt = init_table_sharded(mesh, v_giant, d_giant, src,
                                axis="model")
        jax.block_until_ready(gt)
        g = {"rows": v_giant, "dim": d_giant, "nbytes": src.nbytes,
             "init_seconds": round(time.time() - t0, 1),
             "hbm_bytes_per_chip": int(
                 gt.addressable_shards[0].data.nbytes)}
        out["giant"] = g
        gids_h = rs.randint(0, v_giant, (b_giant,)).astype(np.int32)
        gids = jax.device_put(jnp.asarray(gids_h),
                              NamedSharding(mesh, P("data")))
        lookup = jax.jit(lambda t, i: sharded_gather(t, i, mesh=mesh,
                                                     axis="model"))
        sec_g, _ = timed(lookup, gt, gids)
        g["lookup_samples_per_sec"] = \
            round(b_giant / sec_g, 1) if sec_g else None
        # compulsory = touched rows read once + output written once;
        # the replicated lowering's moved bytes at this shape (every
        # lookup reads its row, no dedup) quantify what dedup could buy
        uniq = int(np.unique(gids_h).size)
        ideal = (uniq + b_giant) * d_giant * 4
        moved = 2 * b_giant * d_giant * 4
        g["roofline_replicated_lookup"] = _roofline(ideal, moved, sec_g)
    return out


def bench_dlrm_sharded(giant=True):
    """DLRM-scale sharded-embedding evidence (ISSUE 14).

    The ``geometry`` rows are pure arithmetic — per-chip table HBM under
    ``model``-axis sharding vs replicated, and the per-step exchange
    payload (the combined (B, D) psum) vs the (B, N, D) allgather a
    replicated-output lowering would move — deterministic, so the doc of
    record pins them.  The measured legs (parity, sharded-vs-replicated
    training, the 10⁸-row lazily-initialized lookup) run in a subprocess
    with a forced 8-device dryrun mesh: the geometry is identical on
    real silicon, and the child can never wedge this process's backend.
    """
    import subprocess
    import sys

    B, N, D_TRAIN = 4096, 8, 16
    WAYS = 4
    V_GIANT, D_GIANT = 100_000_000, 2
    giant_nbytes = V_GIANT * D_GIANT * 4
    out = {"geometry": {
        "giant_rows": V_GIANT,
        "giant_dim": D_GIANT,
        "giant_table_nbytes": giant_nbytes,
        "model_axis_ways": WAYS,
        "hbm_table_bytes_per_chip_sharded": giant_nbytes // WAYS,
        "hbm_table_bytes_per_chip_replicated": giant_nbytes,
        "hbm_chip_ratio": _safe_ratio(giant_nbytes,
                                      giant_nbytes // WAYS),
        "exchange_payload_bytes_per_step": B * D_TRAIN * 4,
        "allgather_bytes_per_step": B * N * D_TRAIN * 4,
        "exchange_vs_allgather_ratio": _safe_ratio(
            B * N * D_TRAIN * 4, B * D_TRAIN * 4),
    }}
    code = (
        "import os;"
        "os.environ['JAX_PLATFORMS']='cpu';"
        "os.environ['XLA_FLAGS']=os.environ.get('XLA_FLAGS','')"
        "+' --xla_force_host_platform_device_count=8';"
        "import sys, json; sys.path.insert(0, os.getcwd());"
        "from bench import bench_dlrm_sharded_child;"
        f"print('DLRMJSON', json.dumps(bench_dlrm_sharded_child("
        f"giant={bool(giant)})))")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=max(60, min(420, _remaining() - 20)),
            cwd=os.path.dirname(os.path.abspath(__file__)))
        for line in proc.stdout.splitlines():
            if line.startswith("DLRMJSON "):
                out.update(json.loads(line[len("DLRMJSON "):]))
                break
        else:
            out["child_error"] = (f"child rc={proc.returncode}: "
                                  f"{(proc.stderr or '')[-400:]}")
    except Exception as e:
        out["child_error"] = f"{type(e).__name__}: {e}"
    return out


def bench_table_hot_cache_child(tiny=False):
    """Measured + deterministic legs of the zipfian hot-cache/dedup
    bench (ISSUE 19); runs in the subprocess ``bench_table_hot_cache``
    launches (dp×tp mesh over the devices the child sees), or directly
    in the CI smoke with ``tiny=True``.

    - ``geometry``: pure arithmetic on the SHARED seeded zipf draw
      (``data.zipf.zipfian_ids`` — byte-identical to the loadgen
      payload class): steady-state hit rate, cold-unique counts, and
      the exchange/HBM bytes-moved reductions vs the uncached lookup —
      deterministic, so the doc of record pins them, and the ≥5×
      reduction gate at s=1.0 is asserted right here;
    - ``parity``: cached-vs-uncached gather AND bag on a real sharded
      mesh table at rtol 1e-6 (the correctness gate on the savings);
    - ``dedup``: dedup-vs-naive sharded lookup, forward and gradient;
    - ``timing_ms``: honest wall-clock of both paths (not gated — on a
      CPU dryrun mesh the host-routed cache mostly proves overheads).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from analytics_zoo_tpu.core.context import init_zoo_context
    from analytics_zoo_tpu.data.zipf import zipfian_ids
    from analytics_zoo_tpu.parallel.hot_cache import (
        HotRowCache, cached_sharded_bag, cached_sharded_gather,
        cold_bucket, table_row_reader)
    from analytics_zoo_tpu.parallel.table_sharding import (sharded_bag,
                                                           sharded_gather)

    if tiny:
        V, D, K, B, NBAG, S = 256, 8, 64, 1024, 4, 1.0
    else:
        V, D, K, B, NBAG, S = 4096, 64, 1024, 16384, 8, 1.0

    ndev = len(jax.devices())
    ways = 4 if ndev % 4 == 0 and ndev >= 8 else \
        (2 if ndev % 2 == 0 else 1)
    ctx = init_zoo_context(mesh_shape=(ndev // ways, ways),
                           axis_names=("data", "model"))
    mesh = ctx.mesh
    out = {"mesh": {"data": ndev // ways, "model": ways},
           "platform": jax.devices()[0].platform, "tiny": bool(tiny)}

    # --- geometry: deterministic, from the shared seeded draw --------
    warm = zipfian_ids(V, 4 * B, S, seed=0)   # the batcher's stream
    meas = zipfian_ids(V, B, S, seed=1)       # the measured batch
    counts = np.bincount(warm, minlength=V)
    order = np.lexsort((np.arange(V), -counts))   # count desc, id asc
    hot_ids = np.sort(order[:K])
    hot = np.isin(meas, hot_ids)
    cold_unique = int(np.unique(meas[~hot]).size)
    bucket = cold_bucket(cold_unique) if cold_unique else 0
    geometry = {
        "vocab": V, "dim": D, "capacity": K, "ids_per_batch": B,
        "skew_s": S,
        "hit_rate": round(float(hot.mean()), 4),
        "unique_ids_per_batch": int(np.unique(meas).size),
        "cold_unique_ids": cold_unique,
        "cold_bucket": bucket,
        # exchange: every uncached id rides the (B, D) psum; cached,
        # only the deduped cold bucket does (none at all when fully hot)
        "exchange_bytes_uncached": B * D * 4,
        "exchange_bytes_cached_ideal": cold_unique * D * 4,
        "exchange_bytes_cached_bucketed": bucket * D * 4,
        "exchange_reduction_ideal": _safe_ratio(B * D * 4,
                                                cold_unique * D * 4),
        "exchange_reduction_bucketed": _safe_ratio(B * D * 4,
                                                   bucket * D * 4),
        # HBM: naive reads one big-table row per slot; dedup+cache
        # reads each distinct cold row once (hot rows serve from the
        # K-row host-side replica, touching no HBM at all)
        "hbm_rows_touched_naive": B,
        "hbm_rows_touched_dedup_cached": cold_unique,
        "hbm_reduction": _safe_ratio(B, cold_unique),
        # the contrast row: the same cache under UNIFORM traffic —
        # skew is what pays for the replica, not the mechanism
        "uniform_hit_rate": round(float(np.isin(
            zipfian_ids(V, B, 0.0, seed=2), hot_ids).mean()), 4),
    }
    red = geometry["exchange_reduction_ideal"]
    geometry["reduction_gate_ok"] = bool(red is not None and red >= 5.0)
    out["geometry"] = geometry
    if not tiny and not geometry["reduction_gate_ok"]:
        raise AssertionError(
            f"exchange reduction {red} < 5x at s={S} "
            f"(V={V} K={K} B={B}) — the ISSUE 19 acceptance floor")

    # --- measured parity on a real sharded mesh table ----------------
    rs = np.random.RandomState(0)
    table = jax.device_put(
        jnp.asarray(rs.randn(V, D).astype(np.float32) * 0.05),
        NamedSharding(mesh, P("model", None)))
    cache = HotRowCache("bench/table", capacity=K, dim=D, mesh=mesh)
    cache.record(warm)
    cache.refresh(table_row_reader(table))
    with jax.transfer_guard("allow"):
        want = np.asarray(jax.device_get(sharded_gather(
            table, jnp.asarray(meas.astype(np.int32)), mesh=mesh,
            axis="model")))
    got = cached_sharded_gather(cache, table, meas, mesh=mesh,
                                axis="model", record=False)
    bag_ids = meas[:(B // NBAG) * NBAG].reshape(-1, NBAG)
    with jax.transfer_guard("allow"):
        want_bag = np.asarray(jax.device_get(sharded_bag(
            table, jnp.asarray(bag_ids.astype(np.int32)), "mean",
            pad_id=None, mesh=mesh, axis="model")))
    got_bag = cached_sharded_bag(cache, table, bag_ids, "mean",
                                 pad_id=None, mesh=mesh, axis="model",
                                 record=False)
    out["parity"] = {
        "gather_max_abs_err": float(np.max(np.abs(want - got))),
        "gather_ok": bool(np.allclose(want, got, rtol=1e-6, atol=1e-7)),
        "bag_max_abs_err": float(np.max(np.abs(want_bag - got_bag))),
        "bag_ok": bool(np.allclose(want_bag, got_bag, rtol=1e-6,
                                   atol=1e-7)),
        "measured_hit_rate": round(cache.stats()["hit_rate"], 4),
    }
    if not (out["parity"]["gather_ok"] and out["parity"]["bag_ok"]):
        raise AssertionError(f"cache parity breach: {out['parity']}")

    # --- dedup-vs-naive sharded lookup, forward and gradient ---------
    ids_j = jnp.asarray(bag_ids.astype(np.int32))

    def loss(tab, dedup):
        return jnp.sum(sharded_bag(tab, ids_j, "sum", pad_id=None,
                                   mesh=mesh, axis="model",
                                   dedup=dedup) ** 2)

    f_d = np.asarray(sharded_bag(table, ids_j, "sum", pad_id=None,
                                 mesh=mesh, axis="model", dedup=True))
    f_n = np.asarray(sharded_bag(table, ids_j, "sum", pad_id=None,
                                 mesh=mesh, axis="model", dedup=False))
    g_d = np.asarray(jax.grad(lambda t: loss(t, True))(table))
    g_n = np.asarray(jax.grad(lambda t: loss(t, False))(table))
    out["dedup"] = {
        "fwd_max_abs_err": float(np.max(np.abs(f_d - f_n))),
        "fwd_ok": bool(np.allclose(f_d, f_n, rtol=1e-6, atol=1e-7)),
        "grad_max_abs_err": float(np.max(np.abs(g_d - g_n))),
        "grad_ok": bool(np.allclose(g_d, g_n, rtol=1e-6, atol=1e-6)),
    }
    if not (out["dedup"]["fwd_ok"] and out["dedup"]["grad_ok"]):
        raise AssertionError(f"dedup parity breach: {out['dedup']}")

    # --- honest wall-clock of both lookup paths ----------------------
    def wall(fn, reps=3):
        fn()                                     # warm/compile
        best = None
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return round(best * 1e3, 3)

    ids_dev = jnp.asarray(meas.astype(np.int32))
    uncached = jax.jit(lambda t, i: sharded_gather(t, i, mesh=mesh,
                                                   axis="model"))
    out["timing_ms"] = {
        "uncached_gather": wall(lambda: jax.block_until_ready(
            uncached(table, ids_dev))),
        "cached_gather": wall(lambda: cached_sharded_gather(
            cache, table, meas, mesh=mesh, axis="model", record=False)),
    }
    return out


def bench_table_hot_cache():
    """Zipfian hot-row cache + dedup evidence (ISSUE 19) — geometry,
    parity, and timing from :func:`bench_table_hot_cache_child` in a
    subprocess with a forced 8-device dryrun mesh (the geometry rows
    are identical on real silicon; the child can never wedge this
    process's backend)."""
    import subprocess
    import sys

    out = {}
    code = (
        "import os;"
        "os.environ['JAX_PLATFORMS']='cpu';"
        "os.environ['XLA_FLAGS']=os.environ.get('XLA_FLAGS','')"
        "+' --xla_force_host_platform_device_count=8';"
        "import sys, json; sys.path.insert(0, os.getcwd());"
        "from bench import bench_table_hot_cache_child;"
        "print('HOTCACHEJSON', json.dumps("
        "bench_table_hot_cache_child()))")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=max(60, min(300, _remaining() - 20)),
            cwd=os.path.dirname(os.path.abspath(__file__)))
        for line in proc.stdout.splitlines():
            if line.startswith("HOTCACHEJSON "):
                out.update(json.loads(line[len("HOTCACHEJSON "):]))
                break
        else:
            out["child_error"] = (f"child rc={proc.returncode}: "
                                  f"{(proc.stderr or '')[-400:]}")
    except Exception as e:
        out["child_error"] = f"{type(e).__name__}: {e}"
    return out


def ring_attention_geometry(L, ways, B=1, H=8, D=64, dtype_bytes=4):
    """Pure-arithmetic ICI-traffic and residency rows for one ring
    configuration (ISSUE 17) — deterministic, so docs/PERFORMANCE.md
    pins them and ``tests/test_ring_attention.py`` machine-checks the
    pinned table against this function.

    Per hop every chip forwards its resident K AND V chunk one
    neighbour over: ``2·(L/ways)·D·dtype`` bytes per link per step,
    ``ways-1`` steps, each overlapped with that hop's attention compute
    (double-buffered ppermute).  An allgather lowering moves the same
    total ``(ways-1)·2·(L/ways)·D·dtype`` but as one up-front burst
    with nothing to overlap — and then holds the FULL gathered K/V per
    chip, which is exactly the O(L) residency the ring avoids: the ring
    keeps resident + in-flight chunk pairs only, O(L/ways) per chip.
    """
    per_chip = L // ways
    kv_chunk = B * H * per_chip * D * dtype_bytes    # one of K or V
    inbound = (ways - 1) * 2 * kv_chunk   # compulsory remote K/V bytes
    return {
        "l": L, "ways": ways, "tokens_per_chip": per_chip,
        "ring_bytes_per_step_per_link": 2 * kv_chunk,
        "ring_total_ici_bytes_per_chip": inbound,
        "allgather_burst_bytes_per_chip": inbound,
        "peak_kv_bytes_per_chip_ring": 4 * kv_chunk,
        "peak_kv_bytes_per_chip_gathered": 2 * ways * per_chip * B * H
        * D * dtype_bytes,
        "peak_kv_ratio": _safe_ratio(2 * ways * kv_chunk, 4 * kv_chunk),
        # traffic_ratio 1.0: the ring moves exactly the compulsory
        # remote-K/V bytes — no lowering can move less and still attend
        "roofline_ring_ici": _roofline(inbound, inbound),
    }


def bench_ring_attention_child(L=4096, ways=4, B=1, H=4, D=64,
                               k_steps=4, rounds=2):
    """Measured legs of the ring-attention bench (runs in the forced
    8-device subprocess ``bench_ring_attention`` launches): samples/sec
    of the sequence-sharded ring vs single-chip blockwise flash at the
    same shape, plus fwd parity."""
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.ops.attention import blockwise_attention
    from analytics_zoo_tpu.ops.ring_attention import ring_attention
    from analytics_zoo_tpu.parallel.sharding import seq_mesh

    rs = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rs.randn(B, H, L, D).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    mesh = seq_mesh(ways)
    out = {"l": L, "ways": ways, "batch": B, "heads": H, "head_dim": D}
    if mesh is None:
        out["error"] = f"no {ways}-device mesh available"
        return out

    ring = jax.jit(lambda a, b_, c: ring_attention(
        a, b_, c, mesh=mesh, causal=True, knob="on"))
    single = jax.jit(lambda a, b_, c: blockwise_attention(
        a, b_, c, causal=True))
    o_r = jax.block_until_ready(ring(q, k, v))
    o_s = jax.block_until_ready(single(q, k, v))
    out["parity_max_err"] = float(jnp.abs(o_r - o_s).max())

    def timed(fn):
        best = None
        for _ in range(rounds):
            t0 = time.perf_counter()
            r = None
            for _ in range(k_steps):
                r = fn(q, k, v)
            jax.block_until_ready(r)
            dt = (time.perf_counter() - t0) / k_steps
            best = dt if best is None else min(best, dt)
        return best

    sec_r, sec_s = timed(ring), timed(single)
    out["ring_samples_per_sec"] = round(B / sec_r, 2) if sec_r else None
    out["single_chip_samples_per_sec"] = \
        round(B / sec_s, 2) if sec_s else None
    out["ring_vs_single_speedup"] = _safe_ratio(sec_s, sec_r)
    g = ring_attention_geometry(L, ways, B=B, H=H, D=D)
    out["roofline_ring_ici"] = _roofline(
        g["ring_total_ici_bytes_per_chip"],
        g["ring_total_ici_bytes_per_chip"], sec_r)
    return out


def bench_ring_attention():
    """Sequence-parallel ring attention evidence (ISSUE 17).

    The ``geometry`` rows are pure arithmetic — bytes-over-ICI per ring
    step vs the allgather burst, and per-chip peak K/V residency
    O(L/ways) vs O(L) — at the 8k/32k/128k contexts the workload
    opens; deterministic, so the doc of record pins them.  The measured
    leg (ring vs single-chip blockwise at a CPU-sized shape) runs in a
    subprocess with a forced 8-device mesh: the geometry is identical
    on real silicon, and the child can never wedge this process's
    backend.  On TPU a breached speedup floor captures a flight record
    + device profiler trace under BENCH_PROFILE_DIR/ring_attention.
    """
    import subprocess
    import sys

    import jax

    WAYS = 4
    out = {"geometry": {
        f"l{L}": ring_attention_geometry(L, WAYS)
        for L in (8192, 32768, 131072)}}
    out["geometry"]["ways"] = WAYS
    code = (
        "import os;"
        "os.environ['JAX_PLATFORMS']='cpu';"
        "os.environ['XLA_FLAGS']=os.environ.get('XLA_FLAGS','')"
        "+' --xla_force_host_platform_device_count=8';"
        "import sys, json; sys.path.insert(0, os.getcwd());"
        "from bench import bench_ring_attention_child;"
        "print('RINGJSON', json.dumps(bench_ring_attention_child()))")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=max(60, min(300, _remaining() - 20)),
            cwd=os.path.dirname(os.path.abspath(__file__)))
        for line in proc.stdout.splitlines():
            if line.startswith("RINGJSON "):
                out["measured"] = json.loads(line[len("RINGJSON "):])
                break
        else:
            out["child_error"] = (f"child rc={proc.returncode}: "
                                  f"{(proc.stderr or '')[-400:]}")
    except Exception as e:
        out["child_error"] = f"{type(e).__name__}: {e}"
    spd = (out.get("measured") or {}).get("ring_vs_single_speedup")
    if spd is not None:
        out["ring_vs_single_speedup"] = spd
    if jax.default_backend() == "tpu":
        # the speedup floor only binds where real ICI links exist — a
        # breach ships its own device trace next to the artifact
        _breach_check(out, "ring_attention", "ring_vs_single_speedup",
                      1.0)
    return out


def bench_dequant_matmul(device, m=1024, n=4096, K=32, rounds=2):
    """Fused dequantize-matmul (int8 / packed-int4 weight storage) vs
    the f32 matmul: the serving-replica HBM-footprint claim.  The
    weight-bytes rows are exact (storage is deterministic); the parity
    rows quote relative error plus top-1 stability over the m output
    rows, the ranking-model acceptance criterion."""
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.ops.dequant_matmul import (
        dequant_matmul, quantize_weights)

    k = n       # square weight so the scan carry re-feeds the output
    rs = np.random.RandomState(0)
    x = jax.device_put(jnp.asarray(
        rs.randn(m, k).astype(np.float32)), device)
    w = rs.randn(k, n).astype(np.float32) * 0.1
    q8, s8 = quantize_weights(w, bits=8)
    q4, s4 = quantize_weights(w, bits=4)
    wd = jax.device_put(jnp.asarray(w), device)
    q8, s8, q4, s4 = (jax.device_put(a, device)
                      for a in (q8, s8, q4, s4))

    out = {"shape": {"m": m, "k": k, "n": n},
           "weight_bytes_f32": k * n * 4,
           "weight_bytes_int8": int(q8.size),
           "weight_bytes_int4": int(q4.size)}
    out["weight_hbm_ratio_int8"] = _safe_ratio(q8.size, k * n * 4)
    out["weight_hbm_ratio_int4"] = _safe_ratio(q4.size, k * n * 4, nd=3)

    yf = np.asarray(jax.jit(lambda a: a @ wd)(x))
    for bits, q, s in ((8, q8, s8), (4, q4, s4)):
        yq = np.asarray(jax.jit(
            lambda a, q=q, s=s, b=bits: dequant_matmul(
                a, q, s, bits=b, rows=k))(x))
        rel = float(np.linalg.norm(yq - yf) / np.linalg.norm(yf))
        out[f"rel_err_int{bits}"] = round(rel, 5)
        out[f"top1_match_int{bits}"] = round(float(
            (yq.argmax(-1) == yf.argmax(-1)).mean()), 4)

    progs = {
        "f32_ms": _make_scan_program(lambda c: c @ wd),
        "int8_ms": _make_scan_program(
            lambda c: dequant_matmul(c, q8, s8)),
        "int4_ms": _make_scan_program(
            lambda c: dequant_matmul(c, q4, s4, bits=4, rows=k)),
    }
    errs = _warm_parallel([(p, x) for p in progs.values()], threads=3)
    for idx, (key, many) in enumerate(progs.items()):
        if idx in errs:
            out[key.replace("_ms", "_error")] = type(errs[idx]).__name__
            continue
        ms = _measure_scan(many, x, K, rounds=rounds, probe=False)
        if ms is None:
            out[key] = None
            out[key.replace("_ms", "_unresolved")] = \
                "slope below timer resolution after escalation"
        else:
            out[key] = round(ms, 3)
    for bits in (8, 4):
        out[f"int{bits}_vs_f32_speedup"] = _safe_ratio(
            out.get("f32_ms"), out.get(f"int{bits}_ms"))
    # per-leg compulsory traffic: activations in/out + that leg's own
    # weight storage, read once (the fused kernel achieves it — the
    # dequant never materialises a f32 weight in HBM)
    io = 4 * (m * k + m * n)
    for key, wb in (("f32", k * n * 4), ("int8", int(q8.size)),
                    ("int4", int(q4.size))):
        ms = out.get(f"{key}_ms")
        out[f"roofline_{key}"] = _roofline(io + wb, io + wb,
                                           ms * 1e-3 if ms else None)
    return out


# ---------------------------------------------------------------------------
# Serving: InferenceModel latency/throughput (BASELINE config #5 evidence;
# the reference's Cluster Serving publishes only a "Serving Throughput"
# scalar, wp-bigdl/ClusterServingGuide — here are real numbers)
# ---------------------------------------------------------------------------

def bench_serving(n_requests=32, concurrency=8, n_saturated=256):
    import threading

    from analytics_zoo_tpu.core.profiling import TIMERS
    from analytics_zoo_tpu.deploy import (
        ClusterServing, DynamicBatcher, InferenceModel, InputQueue,
        MemoryQueue, OutputQueue, ServingConfig)
    from analytics_zoo_tpu.loadgen.payloads import saturated_images
    from analytics_zoo_tpu.models.image.imageclassification import mobilenet
    from analytics_zoo_tpu.nn import reset_name_scope

    # mobilenet: a real conv net with serving-relevant shape but ~4x
    # cheaper XLA compiles than resnet50 (two buckets = two compiles
    # per forward flavor, and the driver's bench window is finite)
    reset_name_scope()
    net = mobilenet(class_num=1000)
    import jax

    from analytics_zoo_tpu.deploy import imagenet_preprocess

    params, state = net.init(jax.random.PRNGKey(0))
    # uint8 wire format: clients ship raw bytes, the chip normalizes
    # in-program — 4x fewer host→device bytes than float32 (these
    # numbers ride a ~10MB/s tunnel, so transfer dominates; on a real
    # TPU host PCIe makes the same path ~1000x cheaper per byte)
    m = InferenceModel.from_keras_net(net, params, state,
                                      preprocess=imagenet_preprocess(),
                                      batch_buckets=(1, 32))
    rs = np.random.RandomState(0)
    # DISTINCT image per request: the tunnel runtime memoizes
    # identical-input dispatches (r5 finding), so re-sending one buffer
    # measures the cache, not the model
    imgs = [rs.randint(0, 256, (1, 224, 224, 3)).astype(np.uint8)
            for _ in range(12)]
    img = imgs[0]

    # warm BOTH shape buckets concurrently (threaded XLA compile)
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(2) as ex:
        futs = [ex.submit(m.predict, [img]),
                ex.submit(m.predict, [np.repeat(img, 32, axis=0)])]
        for f in futs:
            f.result()

    out = {"wire_format": "uint8+on-device normalize"}

    # --- sync baseline (the pre-pipeline engine, kept so the speedup is
    # measured in-repo: blocking predict per batch, no stage overlap) ---
    sync = {}
    lats = []
    for i in range(10):
        t0 = time.perf_counter()
        m.predict([imgs[1 + (i % 11)]])
        lats.append((time.perf_counter() - t0) * 1e3)
    lats.sort()
    sync["latency_p50_ms"] = round(lats[len(lats) // 2], 2)
    sync["latency_p99_ms"] = round(lats[-1], 2)

    batcher = DynamicBatcher(m, max_batch=32, max_latency_ms=5.0)
    try:
        batcher.predict([img])                     # bucket 32 pre-warmed
        done = []
        lock = threading.Lock()

        def client(k):
            crs = np.random.RandomState(100 + k)
            for _ in range(n_requests // concurrency):
                fresh = crs.randint(0, 256, (1, 224, 224, 3)).astype(
                    np.uint8)
                r = batcher.predict([fresh])
                with lock:
                    done.append(r)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        sync["batched_throughput_imgs_per_sec"] = round(len(done) / dt, 1)
        sync["concurrency"] = concurrency
    finally:
        batcher.close()
    out["serving_sync_baseline"] = sync

    # --- pipelined engine: the full queue path (enqueue → poller →
    # decode pool → DynamicBatcher → DeviceExecutor → respond pool) ---
    q = MemoryQueue()
    srv = ClusterServing(m, q, ServingConfig(
        batch_size=32, poll_timeout_s=0.01, max_batch_delay_ms=5.0,
        decode_workers=4, max_inflight=2)).start()
    inp, outp = InputQueue(q), OutputQueue(q)
    try:
        # warm the replica forward's two bucket programs (a fresh jitted
        # fn: params are explicit args so replicas can live per device)
        inp.enqueue(uri="warm1", x=imgs[1][0])
        outp.query("warm1", timeout=600.0)
        for i in range(32):
            inp.enqueue(uri=f"warm32_{i}", x=imgs[2 + i % 10][0])
        for i in range(32):
            outp.query(f"warm32_{i}", timeout=600.0)

        # trickle latency: sequential single requests, full queue path
        # (deadline flush + device + codec — what one user experiences)
        lats = []
        crs = np.random.RandomState(7)
        for i in range(10):
            fresh = crs.randint(0, 256, (224, 224, 3)).astype(np.uint8)
            t0 = time.perf_counter()
            inp.enqueue(uri=f"lat{i}", x=fresh)
            outp.query(f"lat{i}", timeout=120.0)
            lats.append((time.perf_counter() - t0) * 1e3)
        lats.sort()
        out["latency_p50_ms"] = round(lats[len(lats) // 2], 2)
        out["latency_p99_ms"] = round(lats[-1], 2)

        # saturated offered load: every request pre-enqueued (queue depth
        # >> batch) with a DISTINCT image, so the executor sees back-to-
        # back full batches and the decode pool overlaps device compute.
        # Timers reset first: the breakdown must attribute the steady
        # state, not warmup compiles.
        TIMERS.reset()
        sat = saturated_images(n_saturated, rs=crs)
        t0 = time.perf_counter()
        for i, im in enumerate(sat):
            inp.enqueue(uri=f"sat{i}", x=im)
        served = 0
        deadline = time.monotonic() + 600
        while served < n_saturated and time.monotonic() < deadline:
            served += len(outp.dequeue(timeout=1.0))
        dt = time.perf_counter() - t0
        out["batched_throughput_imgs_per_sec"] = round(served / dt, 1)
        out["saturated_requests"] = served

        # per-stage latency attribution + overlap counters (the same
        # rollups health() serves)
        breakdown = {}
        for k, v in TIMERS.stats().items():
            if k.startswith("serving/") and v["count"]:
                breakdown[k.split("/", 1)[1]] = {
                    "p50_ms": round(v["p50_s"] * 1e3, 2),
                    "p99_ms": round(v["p99_s"] * 1e3, 2)}
        out["stage_breakdown"] = breakdown
        out["pipeline_counters"] = {
            k.split("/", 1)[1]: n for k, n in TIMERS.counts().items()
            if k.startswith("serving/")}

        # where each served image's time actually went: device compute
        # vs wire/codec (decode+respond pools) vs queueing (stream wait
        # + batcher wait).  Stage totals sum across worker threads and
        # in-flight batches, so per-image numbers can exceed wall/served
        # and busy fractions can exceed 1.0 — that overlap is the
        # pipelining being measured, not an accounting bug.
        # Chaos injection is OFF here (no FaultInjector armed): this is
        # the fault-free baseline the serving acceptance bound tracks.
        stats = TIMERS.stats()
        tot = lambda nm: stats.get(nm, {}).get("total_s", 0.0)
        if served:
            per_img = lambda s: round(s * 1e3 / served, 3)
            wire_s = tot("serving/decode") + tot("serving/respond")
            queue_s = tot("serving/queue_wait") + tot("serving/batch_wait")
            out["breakdown"] = {
                "device_compute_ms_per_img": per_img(tot("serving/device")),
                "wire_codec_ms_per_img": per_img(wire_s),
                "queue_wait_ms_per_img": per_img(queue_s),
                "device_busy_frac": round(tot("serving/device") / dt, 3),
                "decode_busy_frac": round(tot("serving/decode") / dt, 3),
                "respond_busy_frac": round(tot("serving/respond") / dt, 3),
                "chaos_enabled": False,
            }
        out["speedup_vs_sync"] = _safe_ratio(
            out["batched_throughput_imgs_per_sec"],
            sync.get("batched_throughput_imgs_per_sec"))
    finally:
        srv.stop()

    # --- zero-copy shm leg: same model, same saturated pattern, the
    # shared-memory ring + binary wire instead of MemoryQueue + base64.
    # Own TIMERS window so the breakdown attributes this leg alone. ---
    from analytics_zoo_tpu.deploy.shmqueue import ShmQueue, shm_available

    if shm_available():
        q2 = ShmQueue(name="bench_serving", slots=max(64, n_saturated),
                      slot_bytes=1 << 20, push_timeout_s=30.0)
        srv2 = ClusterServing(m, q2, ServingConfig(
            batch_size=32, poll_timeout_s=0.01, max_batch_delay_ms=5.0,
            decode_workers=4, max_inflight=2)).start()
        inp2, outp2 = InputQueue(q2), OutputQueue(q2)
        try:
            inp2.enqueue(uri="warm1", x=imgs[1][0])
            outp2.query("warm1", timeout=600.0)
            TIMERS.reset()
            crs = np.random.RandomState(11)
            sat = saturated_images(n_saturated, rs=crs)
            t0 = time.perf_counter()
            for i, im in enumerate(sat):
                inp2.enqueue(uri=f"shm{i}", x=im)
            served = 0
            deadline = time.monotonic() + 600
            while served < n_saturated and time.monotonic() < deadline:
                served += len(outp2.dequeue(timeout=1.0))
            dt = time.perf_counter() - t0
            stats = TIMERS.stats()
            tot = lambda nm: stats.get(nm, {}).get("total_s", 0.0)
            counts = TIMERS.counts()
            shm_out = {
                "batched_throughput_imgs_per_sec": round(served / dt, 1),
                "saturated_requests": served,
                "wire_format": "shm ring + binary frames (zero-copy)",
            }
            if served:
                per_img = lambda s: round(s * 1e3 / served, 3)
                shm_out["breakdown"] = {
                    "device_compute_ms_per_img": per_img(
                        tot("serving/device")),
                    "wire_codec_ms_per_img": per_img(
                        tot("serving/decode") + tot("serving/respond")),
                    "queue_wait_ms_per_img": per_img(
                        tot("serving/queue_wait")
                        + tot("serving/batch_wait")),
                    "chaos_enabled": False,
                }
                # the zero-copy claim, re-verified at bench time
                shm_out["codec_b64_calls"] = (
                    counts.get("serving/codec_b64_encode", 0)
                    + counts.get("serving/codec_b64_decode", 0))
            out["serving_shm"] = shm_out
            out["shm_speedup_vs_memory_queue"] = _safe_ratio(
                shm_out["batched_throughput_imgs_per_sec"],
                out.get("batched_throughput_imgs_per_sec"))
        finally:
            srv2.stop()
            q2.stop()
    else:
        out["serving_shm"] = {"skipped": SKIP_SHM}
    return out


def bench_serving_wire_codecs(n_codec=64, n_queue=256):
    """The wire tax, isolated (docs/PERFORMANCE.md "Serving wire
    codecs"): how fast tensor payloads cross each serving wire, with the
    device and pipeline machinery factored out.

    Two tiers:
    - codec micro: encode+decode of one uint8 image record per codec —
      the legacy json+base64 envelope, the binary frame, and the binary
      frame through an actual shm slot (pack into the segment, decode a
      zero-copy view back out).
    - queue path: producer -> queue -> worker-side decode ->
      jax.device_put, per record, same run: the legacy serialized json
      wire (what File/Redis ship), the in-process MemoryQueue shortcut
      (dict hand-off, base64 tensors), and the ShmQueue binary ring.
      ``queue_path_speedup`` = shm vs the serialized json wire — the
      end-to-end zero-copy win.
    """
    import gc
    import json as _json

    import jax

    from analytics_zoo_tpu.core.profiling import TIMERS
    from analytics_zoo_tpu.deploy import (MemoryQueue, encode_tensor,
                                          pack_record, unpack_record)
    from analytics_zoo_tpu.deploy.serving import _decode_record
    from analytics_zoo_tpu.deploy.shmqueue import ShmQueue, shm_available

    rs = np.random.RandomState(0)
    img = rs.randint(0, 256, (224, 224, 3)).astype(np.uint8)
    nbytes = img.nbytes
    out = {"payload": "uint8 224x224x3", "payload_bytes": nbytes}
    mbs = lambda n, dt: round(n * nbytes / dt / 1e6, 1)

    # --- tier 1: raw codec round-trips --------------------------------
    def rec_of(i):
        return {"uri": f"c{i}", "ts": 0.0, "fmt": "tensor", "x": img}

    t0 = time.perf_counter()
    for i in range(n_codec):
        blob = _json.dumps({**rec_of(i), "x": encode_tensor(img)})
        back = _json.loads(blob)
        _decode_record(back)
    dt_json = time.perf_counter() - t0
    t0 = time.perf_counter()
    for i in range(n_codec):
        _decode_record(unpack_record(pack_record(rec_of(i))))
    dt_bin = time.perf_counter() - t0
    micro = {
        "json_b64_imgs_per_sec": round(n_codec / dt_json, 1),
        "json_b64_mb_per_sec": mbs(n_codec, dt_json),
        "binary_imgs_per_sec": round(n_codec / dt_bin, 1),
        "binary_mb_per_sec": mbs(n_codec, dt_bin),
        "binary_speedup": _safe_ratio(n_codec / dt_bin,
                                      n_codec / dt_json),
    }
    if shm_available():
        q = ShmQueue(name="codec_micro", slots=8,
                     slot_bytes=nbytes + (1 << 12))
        try:
            t0 = time.perf_counter()
            for i in range(n_codec):
                q.push(rec_of(i))
                [(_, rec)] = q.pop_batch(1, timeout=1.0)
                _decode_record(rec)
                del rec         # release the slot lease
            dt_shm = time.perf_counter() - t0
            micro["shm_imgs_per_sec"] = round(n_codec / dt_shm, 1)
            micro["shm_mb_per_sec"] = mbs(n_codec, dt_shm)
            micro["shm_speedup"] = _safe_ratio(n_codec / dt_shm,
                                               n_codec / dt_json)
        finally:
            q.stop()
    out["codec_micro"] = micro

    # --- tier 2: through the queue to the device ----------------------
    # Two payload sizes: the uint8 image wire (150KB — shm fixed costs
    # show) and the float32 tensor wire (600KB — the regime embeddings /
    # feature tensors live in, where the per-byte codec tax dominates).
    jax.device_put(img).block_until_ready()     # backend warmup

    def queue_leg(push_one, pop_decode, n, chunk=32):
        """push `chunk` records, pop + decode + device_put them, repeat;
        returns imgs/s.  Per-record device_put on both sides keeps the
        comparison honest (the device share is identical)."""
        t0 = time.perf_counter()
        done = 0
        while done < n:
            k = min(chunk, n - done)
            for i in range(k):
                push_one(done + i)
            popped = pop_decode(k)
            assert len(popped) == k
            for x in popped:
                jax.device_put(x).block_until_ready()
            done += k
            del popped, x
        return n / (time.perf_counter() - t0)

    out["queue_path"] = {}
    for dtype_name, a in (("uint8", img),
                          ("float32", img.astype(np.float32))):
        pb = a.nbytes
        pmbs = lambda rate: round(rate * pb / 1e6, 1)

        def rec_a(i):
            return {"uri": f"q{i}", "ts": 0.0, "fmt": "tensor", "x": a}

        qp = {"payload_bytes": pb}
        # legacy serialized wire: json envelope + base64 tensors (the
        # File/Redis legacy shape, writable-copy decode semantics),
        # transported over MemoryQueue so only the codec differs
        qj = MemoryQueue()
        rate = queue_leg(
            lambda i: qj.push(_json.loads(_json.dumps(
                {**rec_a(i), "x": encode_tensor(a)}))),
            lambda k: [_decode_record(r)["x"]
                       for _, r in qj.pop_batch(k, timeout=1.0)],
            n_queue)
        qp["json_wire_imgs_per_sec"] = round(rate, 1)
        qp["json_wire_mb_per_sec"] = pmbs(rate)
        # in-process shortcut: same base64 tensor payloads, no envelope
        qm = MemoryQueue()
        rate = queue_leg(
            lambda i: qm.push({**rec_a(i), "x": encode_tensor(a)}),
            lambda k: [_decode_record(r)["x"]
                       for _, r in qm.pop_batch(k, timeout=1.0)],
            n_queue)
        qp["memory_b64_imgs_per_sec"] = round(rate, 1)
        if shm_available():
            qs = ShmQueue(name="codec_path", slots=64,
                          slot_bytes=pb + (1 << 12), push_timeout_s=10.0)
            try:
                c0 = TIMERS.counts()
                rate = queue_leg(
                    lambda i: qs.push(rec_a(i)),
                    lambda k: [_decode_record(r)["x"]
                               for _, r in qs.pop_batch(k, timeout=1.0)],
                    n_queue)
                gc.collect()
                counts = TIMERS.counts()
                qp["shm_imgs_per_sec"] = round(rate, 1)
                qp["shm_mb_per_sec"] = pmbs(rate)
                # counter-verified zero-copy at bench time
                qp["shm_tensor_copies"] = (
                    counts.get("serving/codec_tensor_copies", 0)
                    - c0.get("serving/codec_tensor_copies", 0))
                qp["queue_path_speedup"] = _safe_ratio(
                    qp["shm_imgs_per_sec"],
                    qp["json_wire_imgs_per_sec"])
            finally:
                qs.stop()
        else:
            _skip(qp, "shm", SKIP_SHM)
        out["queue_path"][dtype_name] = qp
    return out


def _device_preflight(timeout_s: int = 150) -> bool:
    """Probe the accelerator in a SUBPROCESS: a wedged device transport
    (e.g. a dead tunnel) would hang any in-process op forever, and the
    driver must still receive a JSON line.  Retries with backoff —
    observed tunnel outages are sometimes transient, and one blip at
    bench time should not zero the round's numbers."""
    import subprocess
    import sys

    code = ("import jax, jax.numpy as jnp;"
            "x = (jnp.ones((64, 64)) @ jnp.ones((64, 64)));"
            "x.block_until_ready(); print('ok')")
    try:
        # Popen + poll (NOT subprocess.run): a child wedged in
        # uninterruptible device I/O ignores SIGKILL, and run()'s
        # pipe-drain after the timeout would block forever — poll and
        # abandon the orphan instead so the deadline is always honored.
        proc = subprocess.Popen([sys.executable, "-c", code],
                                stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL)
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            rc = proc.poll()
            if rc is not None:
                out = proc.stdout.read() if proc.stdout else b""
                return rc == 0 and b"ok" in out
            time.sleep(0.5)
        proc.kill()
        return False
    except Exception:
        return False


def _preflight_with_retry(budget_frac: float = 0.8,
                          retry_sleep_s: int = 15) -> bool:
    """Keep retrying the transport for ~``budget_frac`` of the bench
    budget before giving up.  An outage at bench time zeroes the round's
    TPU evidence (it did in r02 — BENCH_r02.json is a cpu_fallback), so
    nearly the whole window goes to reconnection attempts: a late real
    number beats an early fallback."""
    deadline = _T0 + budget_frac * _BUDGET_S
    attempt = 0
    while True:
        remaining = deadline - time.time()
        if remaining <= 5:
            return False
        # first attempt long enough for a cold backend init (~90-180s on
        # tunnelled slices); later probes shorter so blips get many shots
        timeout_s = min(150 if attempt == 0 else 60, remaining)
        if _device_preflight(timeout_s):
            return True
        attempt += 1
        time.sleep(min(retry_sleep_s, max(0, deadline - time.time())))


def bench_restart_to_slo_child(cache_dir, buckets=(1, 8, 32),
                               slo_ms=200.0, n_probe=12):
    """One process leg of the restart-to-SLO bench — run in a fresh
    subprocess so the in-process jit caches can't leak between the cold
    and warm legs.  The on-disk state of ``cache_dir`` is the only
    thing distinguishing them: empty = cold (every bucket pays a live
    XLA compile), populated = warm restart (``warm()`` pre-installs the
    persisted executables; docs/SERVING.md "Warm start & multi-model").

    Two clocks, both from model-ready (pipeline/queue overhead
    excluded — this times the replica forward path itself):

    - ``coverage_s`` — until every ``batch_buckets`` program has served
      a batch (full bucket coverage);
    - ``slo_s`` — until a probe request's p99 (sliding window over the
      last 10 probes, round-robin across buckets) first drops under
      ``slo_ms``.  Compiles land inside early probes, so the cold leg
      crosses the SLO line only after paying them.
    """
    import numpy as np

    from analytics_zoo_tpu.deploy import CompileCache, InferenceModel
    from analytics_zoo_tpu.nn import Sequential, reset_name_scope
    from analytics_zoo_tpu.nn.layers.core import Activation, Dense
    from analytics_zoo_tpu.train.optimizers import Adam

    in_dim, out_dim = 12, 4
    rs = np.random.RandomState(0)
    reset_name_scope()
    net = Sequential([Dense(64, input_shape=(in_dim,)), Activation("relu"),
                      Dense(out_dim)])
    net.compile(optimizer=Adam(1e-2), loss="mse")
    x = rs.randn(max(buckets), in_dim).astype(np.float32)
    net.fit(x, rs.randn(max(buckets), out_dim).astype(np.float32),
            batch_size=16, nb_epoch=1, verbose=False)
    m = InferenceModel.from_keras_net(net, net.estimator.params,
                                      net.estimator.state,
                                      batch_buckets=tuple(buckets))
    cache = CompileCache(cache_dir)
    m.attach_compile_cache(cache)

    t_start = time.monotonic()
    warmed = m.warm()
    for b in buckets:
        m.predict(x[:b])
    coverage_s = time.monotonic() - t_start

    lats = []
    slo_s = None
    for i in range(n_probe):
        b = buckets[i % len(buckets)]
        t0 = time.monotonic()
        m.predict(x[:b])
        lats.append((time.monotonic() - t0) * 1e3)
        win = sorted(lats[-10:])
        if slo_s is None and win[-1] <= slo_ms:
            slo_s = time.monotonic() - t_start
    return {"warmed": int(warmed),
            "compile_count": int(m.compile_count),
            "coverage_s": round(coverage_s, 3),
            "slo_s": round(slo_s, 3) if slo_s is not None else None,
            "probe_p99_ms": round(sorted(lats)[-1], 3),
            "cache_events": dict(cache.stats()["events"])}


def bench_serving_restart_to_slo(slo_ms=200.0):
    """Warm-start restart bench (ISSUE 15 acceptance): a cold process
    vs a restarted process over the same persistent compile-cache dir,
    each leg a REAL fresh OS process (``bench_restart_to_slo_child``).
    The honest claims: the warm leg performs ZERO live XLA compiles
    (counter-proven by ``compile_count``) and reaches full bucket
    coverage ≥ 5x faster than the cold leg.  Forced-CPU children, like
    the dlrm leg: compile cost is what's being measured and the warm/
    cold *ratio* is the claim, so the host CPU backend stands in; the
    jax persistent compilation cache is NOT enabled in the children
    (that would hide exactly the cost this bench measures).
    """
    import shutil
    import subprocess
    import sys
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="zoo_bench_xc_")
    out = {"slo_ms": slo_ms, "buckets": [1, 8, 32]}
    code = (
        "import os;"
        "os.environ['JAX_PLATFORMS']='cpu';"
        "import sys, json; sys.path.insert(0, os.getcwd());"
        "from bench import bench_restart_to_slo_child;"
        f"print('XCJSON', json.dumps(bench_restart_to_slo_child("
        f"{cache_dir!r}, slo_ms={slo_ms})))")
    try:
        for leg in ("cold", "warm"):
            proc = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                text=True, timeout=max(60, min(300, _remaining() - 20)),
                cwd=os.path.dirname(os.path.abspath(__file__)))
            for line in proc.stdout.splitlines():
                if line.startswith("XCJSON "):
                    out[leg] = json.loads(line[len("XCJSON "):])
                    break
            else:
                out[f"{leg}_error"] = (f"child rc={proc.returncode}: "
                                       f"{(proc.stderr or '')[-400:]}")
                return out
        out["warm_live_compiles"] = out["warm"]["compile_count"]
        out["coverage_speedup_warm_vs_cold"] = _safe_ratio(
            out["cold"]["coverage_s"], out["warm"]["coverage_s"])
        out["slo_speedup_warm_vs_cold"] = _safe_ratio(
            out["cold"]["slo_s"], out["warm"]["slo_s"])
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    return out


def _run_metadata(device=None):
    """Provenance stamp for BENCH_*.json artifacts: which commit, which
    jax, which silicon produced the numbers.  ``device=None`` (the
    cpu_fallback path) must NOT touch jax — initialising the wedged
    backend is exactly what that path is avoiding."""
    meta = {"timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    try:
        import subprocess
        sha = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__))).stdout.strip()
        if sha:
            meta["git_sha"] = sha
    except Exception:
        pass
    try:
        import jax
        meta["jax_version"] = jax.__version__
    except Exception:
        pass
    if device is not None:
        meta["device_kind"] = getattr(device, "device_kind", "unknown")
        meta["platform"] = getattr(device, "platform", "unknown")
    return meta


def main():
    import jax

    _enable_compilation_cache()
    if not _preflight_with_retry():
        # the chip is unreachable (wedged tunnel) — run the headline on
        # the host CPU so the round still records an honest, clearly
        # flagged number instead of a bare zero
        extra = {"error": "device preflight failed: accelerator "
                          "unreachable (transport hang?)",
                 "platform": "cpu_fallback",
                 "run_metadata": _run_metadata()}
        value = 0.0
        try:
            # subprocess with a forced-CPU jax: ANY jax call in this
            # process would initialise the default (wedged) backend and
            # hang exactly the way the preflight just detected
            import subprocess
            import sys
            code = ("import os; os.environ['JAX_PLATFORMS']='cpu';"
                    "import jax; jax.config.update('jax_platforms','cpu');"
                    "import sys; sys.path.insert(0, os.getcwd());"
                    "from bench import bench_ncf;"
                    "print('CPUTPUT', bench_ncf(jax.devices('cpu')[0],"
                    " warmup=1, iters=2, k_steps=8))")
            # the preflight may have spent ~80% of the budget retrying;
            # the fallback must fit in what remains or the driver's
            # window closes with no JSON line at all
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, text=True,
                                  timeout=max(30, min(240,
                                                      _remaining() - 15)),
                                  cwd=os.path.dirname(
                                      os.path.abspath(__file__)))
            for line in proc.stdout.splitlines():
                if line.startswith("CPUTPUT"):
                    value = float(line.split()[1])
            if value:
                extra["cpu_samples_per_sec"] = round(value, 1)
            else:       # a crashed child must be distinguishable from a
                extra["cpu_fallback_error"] = (     # measured zero
                    f"child rc={proc.returncode}: "
                    f"{(proc.stderr or '')[-400:]}")
        except Exception as e:
            extra["cpu_fallback_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps({
            "metric": "ncf_movielens1m_train_samples_per_sec_per_chip",
            "value": round(value, 1), "unit": "samples/sec/chip",
            "vs_baseline": 1.0 if value else None, "extra": extra}))
        return

    accel = jax.devices()[0]
    on_tpu = accel.platform != "cpu"
    extra = {}
    extra["run_metadata"] = _run_metadata(accel)
    section_s = {}
    extra["section_seconds"] = section_s
    report = {"metric": "ncf_movielens1m_train_samples_per_sec_per_chip",
              "value": 0.0, "unit": "samples/sec/chip",
              "vs_baseline": None, "extra": extra}
    watchdog = _Watchdog(report)

    def _mark(name, t0):
        import sys
        section_s[name] = round(time.time() - t0, 1)
        print(f"[bench] {name}: {section_s[name]}s "
              f"(elapsed {time.time() - _T0:.0f}s of {_BUDGET_S:.0f})",
              file=sys.stderr, flush=True)

    # --- ORDERING (r4 verdict #1 + r5 measured compile bills): every
    # section the r4 artifact dropped runs in the first ~250s (int8,
    # serving, WND, nnframes, then the headline), the accuracy legs
    # (convergence, resnet, resnet_accuracy) take the middle, and
    # attention — whose 6 kernel compiles are the single largest bill
    # (~150s: this backend recompiles even with the persistent cache) —
    # closes with per-length guards.  The watchdog guarantees the JSON
    # line regardless.

    # int8 MXU matmul vs f32 (the int8 inference claim)
    t0 = time.time()
    try:
        extra["matmul_4096"] = bench_int8(accel)
    except Exception as e:
        extra["int8_error"] = f"{type(e).__name__}: {e}"
    _mark("int8", t0)

    # ops/ fused kernels (PR 12): embedding-bag and dequant-matmul vs
    # their unfused XLA lowerings, roofline bytes rows alongside
    t0 = time.time()
    if _remaining() > 60:
        try:
            extra["embedding_bag"] = bench_embedding_bag(accel)
        except Exception as e:
            extra["embedding_bag_error"] = f"{type(e).__name__}: {e}"
    else:
        _skip(extra, "embedding_bag")
    _mark("embedding_bag", t0)

    t0 = time.time()
    if _remaining() > 60:
        try:
            extra["dequant_matmul"] = bench_dequant_matmul(accel)
        except Exception as e:
            extra["dequant_matmul_error"] = f"{type(e).__name__}: {e}"
    else:
        _skip(extra, "dequant_matmul")
    _mark("dequant_matmul", t0)

    # BASELINE config #5: serving latency + batched throughput
    t0 = time.time()
    try:
        extra["serving_mobilenet"] = bench_serving()
    except Exception as e:
        extra["serving_error"] = f"{type(e).__name__}: {e}"
    _mark("serving", t0)

    # serving wire codecs: the isolated wire tax (json+b64 vs binary vs
    # shm ring), device/pipeline factored out — runs on host, no accel
    t0 = time.time()
    try:
        extra["serving_wire_codecs"] = bench_serving_wire_codecs()
    except Exception as e:
        extra["serving_wire_codecs_error"] = f"{type(e).__name__}: {e}"
    _mark("serving_wire_codecs", t0)

    # restart-to-SLO: persistent compile cache, cold vs warm restart
    # (fresh forced-CPU subprocess per leg — host-side, no accel)
    t0 = time.time()
    if _remaining() > 90:
        try:
            extra["serving_restart_to_slo"] = bench_serving_restart_to_slo()
        except Exception as e:
            extra["serving_restart_to_slo_error"] = f"{type(e).__name__}: {e}"
    else:
        _skip(extra, "serving_restart_to_slo")
    _mark("serving_restart_to_slo", t0)

    # BASELINE config #4: WideAndDeep throughput
    t0 = time.time()
    try:
        extra["wide_and_deep_samples_per_sec"] = round(
            bench_wide_and_deep(accel), 1)
    except Exception as e:
        extra["wide_and_deep_error"] = f"{type(e).__name__}: {e}"
    _mark("wide_and_deep", t0)

    # BASELINE config #3: NNFrames DataFrame pipeline rows/sec
    t0 = time.time()
    try:
        extra["nnframes"] = bench_nnframes()
    except Exception as e:
        extra["nnframes_error"] = f"{type(e).__name__}: {e}"
    _mark("nnframes", t0)

    # headline: NCF throughput, bf16 (MXU) with f32 quoted alongside.
    # batch/k chosen by on-chip sweep (65536x128 fused: 19M vs 8.2M at
    # 8192x64 — per-op dispatch overhead amortizes with scale)
    t0 = time.time()
    hb, hk = (65536, 128) if on_tpu else (8192, 8)
    extra["headline_config"] = {"batch": hb, "k_steps": hk}
    value_f32 = bench_ncf(accel, batch=hb, k_steps=hk, iters=2)
    extra["ncf_f32_samples_per_sec"] = round(value_f32, 1)
    if on_tpu:
        value_bf16 = bench_ncf(accel, batch=hb, k_steps=hk, iters=2,
                               compute_dtype="bfloat16")
        extra["ncf_bf16_samples_per_sec"] = round(value_bf16, 1)
        value = max(value_bf16, value_f32)
        extra["dtype"] = ("bfloat16" if value_bf16 >= value_f32
                          else "float32")
    else:
        value = value_f32
        extra["dtype"] = "float32"
    report["value"] = round(value, 1)    # watchdog snapshot carries it
    _mark("ncf_headline", t0)

    vs_baseline = None
    t0 = time.time()
    try:
        # k_steps=8 keeps the baseline cheap; throughput is per-sample
        # normalized so vs_baseline stays comparable
        cpu = jax.local_devices(backend="cpu")[0]
        cpu_tput = (bench_ncf(cpu, warmup=1, iters=2, k_steps=8)
                    if _remaining() > 60 else 0)
        if cpu_tput > 0:
            vs_baseline = value / cpu_tput
            extra["cpu_baseline_samples_per_sec"] = round(cpu_tput, 1)
            report["vs_baseline"] = round(vs_baseline, 3)
    except Exception:
        pass
    _mark("cpu_baseline", t0)

    # tentpole evidence: host-prefetch vs HBM-resident FeatureSet through
    # the SAME Estimator.fit — both end-to-end data paths, NCF- and
    # WND-shaped (the gap the resident path exists to close)
    t0 = time.time()
    if _remaining() > 150:
        try:
            extra["featureset_data_paths"] = bench_data_paths(
                n_rows=(1 << 20) if on_tpu else (1 << 15),
                epochs=3 if on_tpu else 2)
        except Exception as e:
            extra["data_paths_error"] = f"{type(e).__name__}: {e}"
    else:
        _skip(extra, "data_paths")
    _mark("data_paths", t0)

    # streaming tier evidence (ISSUE 10): a dataset 4x the device budget
    # rotating through HBM vs whole-dataset residency — the ≥0.5x floor
    # plus the overlap-fraction counter-proof
    t0 = time.time()
    if _remaining() > 120:
        try:
            extra["featureset_streaming"] = bench_featureset_streaming(
                n_rows=(1 << 20) if on_tpu else (1 << 15),
                epochs=3 if on_tpu else 3)
        except Exception as e:
            extra["featureset_streaming_error"] = f"{type(e).__name__}: {e}"
    else:
        _skip(extra, "featureset_streaming")
    _mark("featureset_streaming", t0)

    # sharded giant-embedding evidence (ISSUE 14): per-chip table HBM
    # = replicated/ways + psum-exchange geometry (analytic, pinned in
    # docs/PERFORMANCE.md), plus measured parity/train/10⁸-row-lookup
    # legs on a subprocess dryrun dp×tp mesh
    t0 = time.time()
    if _remaining() > 150:
        try:
            extra["dlrm_sharded_embedding"] = bench_dlrm_sharded(
                giant=_remaining() > 240)
        except Exception as e:
            extra["dlrm_sharded_embedding_error"] = \
                f"{type(e).__name__}: {e}"
    else:
        _skip(extra, "dlrm_sharded_embedding")
    _mark("dlrm_sharded_embedding", t0)

    # hot-row cache + dedup for sharded lookups (ISSUE 19): zipfian
    # exchange/HBM bytes-moved geometry (deterministic, ≥5× gate at
    # s=1.0 pinned in docs/PERFORMANCE.md) plus measured cached-vs-
    # uncached and dedup-vs-naive parity on a subprocess dryrun mesh
    t0 = time.time()
    if _remaining() > 120:
        try:
            res = bench_table_hot_cache()
            extra["table_hot_cache"] = res
            geo = res.get("geometry")
            if isinstance(geo, dict):
                _breach_check(geo, "table_hot_cache",
                              "exchange_reduction_ideal", 5.0)
        except Exception as e:
            extra["table_hot_cache_error"] = f"{type(e).__name__}: {e}"
    else:
        _skip(extra, "table_hot_cache")
    _mark("table_hot_cache", t0)

    # sequence-parallel ring attention (ISSUE 17): analytic
    # bytes-over-ICI + peak-residency geometry at 8k/32k/128k (pinned
    # in docs/PERFORMANCE.md) and a measured ring-vs-single-chip leg on
    # a subprocess 8-device mesh
    t0 = time.time()
    if _remaining() > 90:
        try:
            extra["ring_attention"] = bench_ring_attention()
        except Exception as e:
            extra["ring_attention_error"] = f"{type(e).__name__}: {e}"
    else:
        _skip(extra, "ring_attention")
    _mark("ring_attention", t0)

    # durability layer cost (ISSUE 3): verified-checkpoint overhead on
    # the training path — async should be ~free, sync bounds the worst
    # case (the preemption-flush latency)
    t0 = time.time()
    if _remaining() > 120:
        try:
            extra["checkpoint_overhead"] = bench_checkpoint_overhead()
        except Exception as e:
            extra["checkpoint_overhead_error"] = f"{type(e).__name__}: {e}"
    else:
        _skip(extra, "checkpoint_overhead")
    _mark("checkpoint_overhead", t0)

    # north-star evidence in ONE run: matched-accuracy convergence with
    # device-resident data + the CPU leg of the SAME code path — the
    # BASELINE.json headline evidence, so it runs before everything
    # whose compile bill could crowd it out.  Depth adapts: the 2-seed
    # score ensemble buys ~+0.4 HR@10 points (r4: 0.929 at 2x8 vs
    # 0.9255 single-12) and runs when earlier sections underran.
    t0 = time.time()
    if _remaining() > 100:
        try:
            if _remaining() > 500:
                ens, ep = 2, 8
            else:
                ens, ep = 1, (12 if _remaining() > 140 else 8)
            extra["ncf_convergence"] = bench_ncf_convergence(
                epochs=ep, ensemble=ens,
                cpu_baseline_epochs=2 if on_tpu else 0)
        except Exception as e:
            extra["ncf_convergence_error"] = f"{type(e).__name__}: {e}"
    else:
        _skip(extra, "ncf_convergence")
    _mark("ncf_convergence", t0)

    # BASELINE config #2: ResNet-50 imgs/sec — one sound launch-amortized
    # measurement (see bench_resnet50: supersedes the r4 plain/fused
    # pair whose fused leg wedged the tunnel with a 2.47GB upload).
    # Primary leg = ghost-BN stats_fraction=0.25 (the r4 verdict's BN
    # bandwidth-wall attack: quarter-batch statistics cut the stats-pass
    # HBM traffic; accuracy parity in tests/test_ghost_bn.py) — r5
    # on-silicon: 2539 imgs/s vs 2433 full-BN (and 2743 at frac=0.125).
    t0 = time.time()
    if _remaining() > 90:
        try:
            # variant-explicit key (ADVICE r5): the ghost-BN number can
            # no longer masquerade as the full-BN headline across rounds
            tput = round(bench_resnet50(accel, bn_stats_fraction=0.25), 2)
            extra["resnet50_ghostbn025_imgs_per_sec"] = tput
            extra["resnet50_bn_stats_fraction"] = 0.25
            extra["resnet50_method"] = ("4/8-step fori slope, uint8 feed "
                                        "(launch-amortized; no superbatch)")
            if _remaining() > 150:      # full-BN alongside, so cross-round
                extra["resnet50_imgs_per_sec_per_chip"] = round(  # compares
                    bench_resnet50(accel, bn_stats_fraction=1.0), 2)
        except Exception as e:
            extra["resnet50_error"] = f"{type(e).__name__}: {e}"
    else:
        _skip(extra, "resnet50")
    _mark("resnet50", t0)

    # config #2 accuracy leg: cats-vs-dogs-shaped convergence
    t0 = time.time()
    if _remaining() > 180:
        try:
            extra["resnet_accuracy"] = bench_resnet_accuracy(accel)
        except Exception as e:
            extra["resnet_accuracy_error"] = f"{type(e).__name__}: {e}"
    else:
        _skip(extra, "resnet_accuracy")
    _mark("resnet_accuracy", t0)

    # Pallas flash attention on silicon vs the STOCK pallas kernel
    # (VERDICT r2 #10: flash-vs-stock at L∈{1k,2k,8k}) — fwd pinning at
    # every length; this backend recompiles each kernel (~22s, cache or
    # not), so the section closes the run and degrades per-length.  Bwd
    # evidence lives in docs/PERFORMANCE.md (r5 interactive: flash
    # fwd+bwd 3.0ms vs stock 5.1ms at L=2048).
    t0 = time.time()
    # bwd pinning at L2048 rides along when the window allows (2 extra
    # kernel compiles ~40s); fwd at all three lengths is the must-have
    specs = [(2048, dict(include_bwd=_remaining() > 190,
                         include_blockwise=False))]
    if _remaining() > 100:
        specs.append((8192, dict(include_bwd=False,
                                 include_blockwise=False)))
    else:
        _skip(extra, "attention_l8192")
    if _remaining() > 140:
        specs.append((1024, dict(include_bwd=False,
                                 include_blockwise=False)))
    else:
        _skip(extra, "attention_l1024")
    try:
        bench_attention_suite(accel, specs, into=extra)
    except Exception as e:
        extra["attention_error"] = f"{type(e).__name__}: {e}"
    _mark("attention", t0)
    report["value"] = round(value, 1)
    report["vs_baseline"] = round(vs_baseline, 3) if vs_baseline else None
    watchdog.emit()


if __name__ == "__main__":
    main()
