"""TFDataset facades: uniform (features, labels, batch) handles.

Reference capability: pyzoo/zoo/tfpark/tf_dataset.py:115-643 — the
``TFDataset`` hierarchy (from_rdd:304, from_ndarrays:360,
from_image_set:387, from_text_set:423, from_feature_set:499,
from_dataframe:611, from_tf_data_dataset:575).  There the dataset carried
a serialized tf.data graph executed inside each JVM executor; here it is a
plain host-side container handing numpy arrays to the SPMD Estimator —
the TPU infeed does the distribution.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["TFDataset"]


def _as_list(x) -> List[np.ndarray]:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return [np.asarray(a) for a in x]
    return [np.asarray(x)]


class TFDataset:
    """(features, labels) + batch size, with optional validation split."""

    def __init__(self, features, labels=None, batch_size: int = 32,
                 val_features=None, val_labels=None):
        self.features = _as_list(features)
        self.labels = _as_list(labels)
        if not self.features:
            raise ValueError("TFDataset needs at least one feature tensor")
        n = self.features[0].shape[0]
        for a in self.features + self.labels:
            if a.shape[0] != n:
                raise ValueError("all tensors must share the leading dim")
        self.batch_size = batch_size
        self.val_features = _as_list(val_features)
        self.val_labels = _as_list(val_labels)

    def __len__(self) -> int:
        return self.features[0].shape[0]

    @property
    def x(self):
        return (self.features[0] if len(self.features) == 1
                else self.features)

    @property
    def y(self):
        if not self.labels:
            return None
        return self.labels[0] if len(self.labels) == 1 else self.labels

    @property
    def validation(self) -> Optional[Tuple]:
        if not self.val_features:
            return None
        vx = (self.val_features[0] if len(self.val_features) == 1
              else self.val_features)
        vy = (self.val_labels[0] if len(self.val_labels) == 1
              else self.val_labels) if self.val_labels else None
        return (vx, vy)

    # -- constructors (reference tf_dataset.py:304-643) --------------------
    @classmethod
    def from_ndarrays(cls, tensors, batch_size: int = 32,
                      val_tensors=None) -> "TFDataset":
        """(x, y) tuple of ndarrays / lists (reference from_ndarrays:360)."""
        x, y = (tensors if isinstance(tensors, tuple) and len(tensors) == 2
                else (tensors, None))
        vx, vy = (val_tensors if val_tensors else (None, None))
        return cls(x, y, batch_size=batch_size, val_features=vx,
                   val_labels=vy)

    @classmethod
    def from_image_set(cls, image_set, batch_size: int = 32,
                       labels=None) -> "TFDataset":
        """Materialize an ``data.image.ImageSet`` pipeline (reference
        from_image_set:387)."""
        arr, y = image_set.to_arrays()
        if labels is not None:
            y = labels
        return cls(arr, y, batch_size=batch_size)

    @classmethod
    def from_text_set(cls, text_set, batch_size: int = 32) -> "TFDataset":
        """Materialize a ``data.text.TextSet`` (reference from_text_set:423)."""
        x, y = text_set.to_arrays()
        return cls(x, y, batch_size=batch_size)

    @classmethod
    def from_feature_set(cls, feature_set, has_labels: bool = True,
                         batch_size: int = 32) -> "TFDataset":
        """Wrap a ``data.featureset.FeatureSet`` (reference
        from_feature_set:499).  FeatureSet convention: labels, when
        present, are the last array."""
        arrays = feature_set.arrays
        if has_labels and len(arrays) >= 2:
            return cls(list(arrays[:-1]), arrays[-1],
                       batch_size=batch_size)
        return cls(list(arrays), None, batch_size=batch_size)

    @classmethod
    def from_dataframe(cls, df, feature_cols: Sequence[str],
                       label_cols: Optional[Sequence[str]] = None,
                       batch_size: int = 32) -> "TFDataset":
        """pandas/pyarrow DataFrame → tensors (reference from_dataframe:611)."""
        if hasattr(df, "to_pandas"):  # pyarrow Table
            df = df.to_pandas()
        xs = [np.stack(df[c].to_numpy()) for c in feature_cols]
        ys = ([np.stack(df[c].to_numpy()) for c in label_cols]
              if label_cols else None)
        return cls(xs, ys, batch_size=batch_size)

    @classmethod
    def from_tfrecord_file(cls, path: str, feature_cols: Sequence[str],
                           label_col: Optional[str] = None,
                           batch_size: int = 32) -> "TFDataset":
        """Parse tf.Example TFRecords WITHOUT TensorFlow (reference
        from_tfrecord_file:458 ran a TF graph per partition; here the
        record framing + Example protos are decoded natively —
        data/tfrecord.py, crc32c in C++ when built)."""
        from analytics_zoo_tpu.data.tfrecord import read_example_file

        examples = read_example_file(path)
        if not examples:
            raise ValueError(f"no records in {path}")
        xs = [np.stack([np.asarray(ex[c]) for ex in examples])
              for c in feature_cols]
        y = (np.stack([np.asarray(ex[label_col]) for ex in examples])
             if label_col else None)
        if y is not None and y.ndim == 2 and y.shape[1] == 1:
            y = y[:, 0]
        return cls(xs, y, batch_size=batch_size)

    @classmethod
    def from_tf_data_dataset(cls, dataset, batch_size: int = 32,
                             max_examples: Optional[int] = None
                             ) -> "TFDataset":
        """Drain a tf.data.Dataset to host arrays (reference
        from_tf_data_dataset:575 serialized the graph instead — on TPU the
        host pipeline feeds the infeed directly)."""
        xs_rows: List[Any] = []
        ys_rows: List[Any] = []
        for i, item in enumerate(dataset.as_numpy_iterator()):
            if max_examples is not None and i >= max_examples:
                break
            if isinstance(item, tuple) and len(item) == 2:
                x, y = item
                xs_rows.append(x)
                ys_rows.append(y)
            else:
                xs_rows.append(item)
        x = np.stack(xs_rows, axis=0)
        y = np.stack(ys_rows, axis=0) if ys_rows else None
        return cls(x, y, batch_size=batch_size)
