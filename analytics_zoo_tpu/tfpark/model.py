"""KerasModel / TFNet / TFOptimizer: train foreign models natively.

Reference capability: pyzoo/zoo/tfpark/model.py:34 (``KerasModel`` — a
tf.keras model trained on the zoo engine), tf_optimizer.py:336,441,556
(``TFOptimizer.from_keras``), tfnet.py:51 (``TFNet`` inference wrapper).

TPU-first: instead of exporting the TF graph and running TF inside each
worker (the reference's JNI two-runtime trick, TFTrainingHelper.scala:32),
the keras model is *converted* (tfpark/converter.py) into a pure JAX
program + weight pytree and trained by the standard SPMD Estimator — the
hot loop is one XLA program with zero TF involvement.  ``to_keras()``
writes trained weights back into the original tf.keras model, closing the
round trip the reference did with moveWeightsOutOfTF
(TFTrainingHelperV2.scala:83-98).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.nn.topology import KerasNet
from analytics_zoo_tpu.tfpark.converter import (GraphProgram,
                                                UnsupportedLayerError,
                                                convert_keras_model)
from analytics_zoo_tpu.tfpark.tf_dataset import TFDataset

__all__ = ["FunctionModel", "KerasModel", "TFGraphOptimizer", "TFNet",
           "TFOptimizer",
           "TorchModel"]


class FunctionModel(KerasNet):
    """A KerasNet over a converted GraphProgram (imported weights)."""

    def __init__(self, program: GraphProgram, **kw):
        super().__init__(**kw)
        self.program = program

    @property
    def layers(self):
        return []

    def build(self, rng, *input_shapes):
        # weights come from the foreign model — rng is unused by design.
        # Fresh copies: the estimator DONATES its param buffers into the
        # jitted step, and donating the program's own arrays would leave
        # program.params deleted (breaking re-builds / introspection).
        import jax
        import jax.numpy as jnp

        def copy(t):
            return jax.tree_util.tree_map(
                lambda a: jnp.array(a, copy=True), t)

        return copy(self.program.params), copy(self.program.state)

    def call(self, params, state, *inputs, training=False, rng=None):
        return self.program.call(params, state, *inputs, training=training,
                                 rng=rng)


def _map_keras_loss(model) -> str:
    """Map the compiled keras loss to a native loss name.

    Unknown losses raise (silently training with a different objective
    would be worse than failing); an uncompiled model defaults to mse.
    """
    loss = getattr(model, "loss", None)
    if loss is None:
        return "mse"
    name = (loss if isinstance(loss, str)
            else getattr(loss, "name", None) or type(loss).__name__)
    table = {
        "sparse_categorical_crossentropy": "sparse_categorical_crossentropy",
        "SparseCategoricalCrossentropy": "sparse_categorical_crossentropy",
        "categorical_crossentropy": "categorical_crossentropy",
        "CategoricalCrossentropy": "categorical_crossentropy",
        "binary_crossentropy": "binary_crossentropy",
        "BinaryCrossentropy": "binary_crossentropy",
        "mse": "mse", "mean_squared_error": "mse", "MeanSquaredError": "mse",
        "mae": "mae", "mean_absolute_error": "mae",
        "MeanAbsoluteError": "mae",
        "hinge": "hinge", "Hinge": "hinge",
    }
    if name not in table:
        raise UnsupportedLayerError(
            f"keras loss {name!r} has no native mapping; pass an explicit "
            "loss= to KerasModel")
    return table[name]


class KerasModel:
    """Train/evaluate/predict a tf.keras model on the TPU engine
    (reference tfpark/model.py:34; fit local-vs-distributed switch :105-185
    collapses — the Estimator is already SPMD)."""

    def __init__(self, keras_model, optimizer=None, loss=None, metrics=None):
        self._keras = keras_model
        self.program = convert_keras_model(keras_model)
        self.model = FunctionModel(self.program)
        from analytics_zoo_tpu.train.optimizers import Adam

        self.model.compile(
            optimizer=optimizer or Adam(lr=1e-3),
            loss=loss or _map_keras_loss(keras_model),
            metrics=metrics or ["accuracy"])

    # -- training facade (reference model.py:105-185) ---------------------
    def fit(self, x, y=None, batch_size: Optional[int] = None,
            epochs: int = 1, validation_data=None, **kw):
        if isinstance(x, TFDataset):
            validation_data = validation_data or x.validation
            batch_size = batch_size or x.batch_size
            x, y = x.x, x.y
        return self.model.fit(x, y, batch_size=batch_size or 32,
                              nb_epoch=epochs,
                              validation_data=validation_data, **kw)

    def evaluate(self, x, y=None, batch_size: Optional[int] = None):
        if isinstance(x, TFDataset):
            batch_size = batch_size or x.batch_size
            x, y = x.x, x.y
        return self.model.evaluate(x, y, batch_size=batch_size or 32)

    def predict(self, x, batch_size: Optional[int] = None, **kw):
        if isinstance(x, TFDataset):
            batch_size = batch_size or x.batch_size
            x = x.x
        return self.model.predict(x, batch_size=batch_size or 32)

    # -- weights round trip ----------------------------------------------
    @property
    def params(self):
        return self.model.estimator.params

    def to_keras(self):
        """Write trained weights back into the wrapped tf.keras model
        (reference moveWeightsOutOfTF, TFTrainingHelperV2.scala:83-98)."""
        params = self.params
        state = self.model.estimator.state
        for lname, p in (params or {}).items():
            klayer = self._keras.get_layer(lname)
            cur = klayer.get_weights()
            new = []
            order = {
                "Dense": ["kernel", "bias"],
                "Conv2D": ["kernel", "bias"], "Conv1D": ["kernel", "bias"],
                "DepthwiseConv2D": ["kernel", "bias"],
                "Embedding": ["table"],
                "BatchNormalization": ["gamma", "beta"],
                "LayerNormalization": ["gamma", "beta"],
            }.get(type(klayer).__name__)
            if order is None:
                continue
            for key in order:
                if key in p:
                    new.append(np.asarray(p[key]))
            if type(klayer).__name__ == "BatchNormalization":
                st = (state or {}).get(lname, {})
                new.append(np.asarray(st.get("mean", cur[-2])))
                new.append(np.asarray(st.get("var", cur[-1])))
            if len(new) == len(cur):
                klayer.set_weights(new)
        return self._keras

    def save_weights(self, path: str):
        from analytics_zoo_tpu.train import checkpoint as ckpt

        ckpt.save_pytree(path, {"params": self.params,
                                "state": self.model.estimator.state})

    def load_weights(self, path: str):
        from analytics_zoo_tpu.train import checkpoint as ckpt

        tree = ckpt.load_pytree(path)
        self.model.estimator.set_initial_weights(tree["params"],
                                                 tree.get("state", {}))


class TFNet:
    """Inference-only wrapper over a TF SavedModel / frozen function
    (reference TFNet.scala:56 / tfnet.py:51 — a TF graph used as a layer).
    Prefer ``KerasModel`` for anything trainable."""

    def __init__(self, path_or_model, signature: str = "serving_default"):
        from analytics_zoo_tpu.deploy.inference import InferenceModel

        if isinstance(path_or_model, str):
            self._m = InferenceModel.load_tf_saved_model(
                path_or_model, signature=signature)
        else:
            self._m = InferenceModel.load_tf_keras(path_or_model)

    def predict(self, x, batch_size: Optional[int] = None):
        return self._m.predict(x, batch_size=batch_size)

    @classmethod
    def from_saved_model(cls, path: str, **kw) -> "TFNet":
        return cls(path, **kw)


class TFOptimizer:
    """Parity facade for the reference's TFOptimizer
    (tf_optimizer.py:336/441/556): wraps a compiled tf.keras model and an
    optional TFDataset; ``optimize()`` runs epochs on the TPU engine."""

    def __init__(self, keras_model: KerasModel, dataset: TFDataset):
        self.kmodel = keras_model
        self.dataset = dataset

    @classmethod
    def from_keras(cls, keras_model, dataset, **kw) -> "TFOptimizer":
        if not isinstance(keras_model, KerasModel):
            keras_model = KerasModel(keras_model, **kw)
        if not isinstance(dataset, TFDataset):
            dataset = TFDataset.from_ndarrays(dataset)
        return cls(keras_model, dataset)

    def optimize(self, end_trigger=None, epochs: int = 1):
        n_epochs = epochs
        if end_trigger is not None and hasattr(end_trigger, "max_epoch"):
            n_epochs = end_trigger.max_epoch
        return self.kmodel.fit(self.dataset, epochs=n_epochs)

    @classmethod
    def from_loss(cls, loss_fn, variables, optim_method=None, dataset=None,
                  clip_norm=None, clip_value=None,
                  metrics=None) -> "TFGraphOptimizer":
        """Train an ARBITRARY TensorFlow graph — not just the Keras layer
        vocabulary (reference tf_optimizer.py:479 ``from_loss``).

        ``loss_fn(*batch_tensors) -> scalar`` is any TF computation
        closing over ``variables`` (a list of ``tf.Variable`` or a
        ``tf.Module``).  Gradients stay inside TF (GradientTape over the
        user's own graph, like the reference kept grads in the TF
        session); the update rule is a zoo/optax optimizer applied on
        the JAX side, so schedules/clipping match native training.
        """
        return TFGraphOptimizer(loss_fn, variables,
                                optim_method=optim_method, dataset=dataset,
                                clip_norm=clip_norm, clip_value=clip_value,
                                metrics=metrics)

    @classmethod
    def from_train_op(cls, train_op, dataset=None,
                      metrics=None) -> "TFGraphOptimizer":
        """Drive a graph that owns its OWN update step (reference
        tf_optimizer.py:556): ``train_op(*batch_tensors)`` performs one
        parameter update (e.g. ``optimizer.apply_gradients`` inside) and
        returns the scalar loss."""
        return TFGraphOptimizer(None, None, train_op=train_op,
                                dataset=dataset, metrics=metrics)


class TFGraphOptimizer:
    """Training loop for arbitrary TF graphs (see ``TFOptimizer.from_loss``).

    The TF side runs as one compiled ``tf.function`` per step on the host
    (the reference ran the TF graph on CPU executors too); parameters are
    mirrored as JAX arrays so the optimizer is the same optax rule native
    models use, then assigned back to the variables after every step.
    """

    def __init__(self, loss_fn, variables, train_op=None, optim_method=None,
                 dataset=None, clip_norm=None, clip_value=None, metrics=None):
        import tensorflow as tf

        self._tf = tf
        self.dataset = dataset
        self.metrics = metrics or {}
        self.history: List[dict] = []
        self._train_op = train_op
        if train_op is not None:
            self._step = tf.function(train_op)
            return

        if hasattr(variables, "trainable_variables"):   # tf.Module / layer
            variables = list(variables.trainable_variables)
        if not variables:
            raise ValueError("from_loss needs a non-empty variable list")
        self.variables = list(variables)
        self.loss_fn = loss_fn

        from analytics_zoo_tpu.train.optimizers import Adam

        # strings lower through the same registry compile() uses
        from analytics_zoo_tpu.train import optimizers as _opts

        self.tx = (_opts.get(optim_method) if optim_method is not None
                   else Adam(1e-3))
        self._params = [jnp.asarray(v.numpy()) for v in self.variables]
        self._opt_state = self.tx.init(self._params)
        self._clip_norm, self._clip_value = clip_norm, clip_value

        @tf.function
        def tf_step(*batch):
            with tf.GradientTape() as tape:
                loss = loss_fn(*batch)
            grads = tape.gradient(loss, self.variables)
            return loss, grads

        self._step = tf_step

    # ------------------------------------------------------------------
    def _one_update(self, batch) -> float:
        import optax

        if self._train_op is not None:
            return float(np.asarray(self._step(*batch)))
        loss, grads = self._step(*batch)
        dead = [v.name for v, g in zip(self.variables, grads) if g is None]
        if dead:
            raise ValueError(
                f"loss_fn produces no gradient for variable(s) {dead} — "
                "they are not used in the loss; drop them from the "
                "variable list")
        tf = self._tf
        # embedding_lookup/gather grads arrive as tf.IndexedSlices
        gs = [jnp.asarray(np.asarray(tf.convert_to_tensor(g)))
              for g in grads]
        if self._clip_value is not None:
            c = float(self._clip_value)
            gs = [jnp.clip(g, -c, c) for g in gs]
        if self._clip_norm is not None:
            norm = jnp.sqrt(sum(jnp.sum(g * g) for g in gs))
            scale = jnp.minimum(1.0, self._clip_norm / (norm + 1e-12))
            gs = [g * scale for g in gs]
        updates, self._opt_state = self.tx.update(gs, self._opt_state,
                                                  self._params)
        self._params = optax.apply_updates(self._params, updates)
        for v, p in zip(self.variables, self._params):
            v.assign(np.asarray(p))
        return float(np.asarray(loss))

    def optimize(self, end_trigger=None, epochs: int = 1,
                 batch_size: Optional[int] = None, shuffle: bool = True,
                 seed: int = 0) -> List[dict]:
        """Run epochs over the dataset; returns per-epoch history rows
        (loss + any train-set metrics — the ``metrics`` fns are evaluated
        on the full TRAINING arrays after each epoch)."""
        if end_trigger is not None and hasattr(end_trigger, "max_epoch"):
            epochs = end_trigger.max_epoch
        ds = self.dataset
        if ds is None:
            raise ValueError("no dataset: pass one at construction")
        if not isinstance(ds, TFDataset):
            ds = TFDataset.from_ndarrays(ds,
                                         batch_size=batch_size or 32)
        b = batch_size or ds.batch_size
        arrays = list(ds.features) + list(ds.labels)
        n = arrays[0].shape[0]
        if n < b:
            raise ValueError(
                f"dataset ({n} rows) smaller than batch_size ({b}): "
                "no training step would run")
        rs = np.random.RandomState(seed)
        for _ in range(epochs):
            perm = rs.permutation(n) if shuffle else np.arange(n)
            losses = []
            for s in range(int(math.ceil(n / b))):
                idx = perm[s * b:(s + 1) * b]   # tail batch may be short
                losses.append(self._one_update([a[idx] for a in arrays]))
            rec = {"epoch": len(self.history) + 1,
                   "loss": float(np.mean(losses))}
            # train-set metrics: evaluated on the full TRAINING arrays
            # after the epoch (not a held-out validation set)
            for name, fn in self.metrics.items():
                rec[name] = float(np.asarray(fn(*arrays)))
            self.history.append(rec)
        return self.history


# ---------------------------------------------------------------------------
# torch ingestion (reference TorchNet trained torch modules under the zoo
# optimizer via JNI — TorchNet.scala:39,160)
# ---------------------------------------------------------------------------

class TorchModel:
    """Convert a simple ``torch.nn.Sequential`` into a natively trainable
    model (Linear/Conv2d/BatchNorm/ReLU/pool/Flatten/Dropout vocabulary).

    Weights are imported; training runs as pure JAX — torch is not in the
    step loop (unlike the reference's in-process libtorch).
    """

    def __init__(self, torch_module, optimizer=None, loss=None,
                 metrics=None):
        program = self._convert(torch_module)
        self._torch = torch_module
        self.model = FunctionModel(program)
        from analytics_zoo_tpu.train.optimizers import Adam

        self.model.compile(optimizer=optimizer or Adam(lr=1e-3),
                           loss=loss or "mse", metrics=metrics)

    def fit(self, x, y=None, batch_size: int = 32, epochs: int = 1, **kw):
        return self.model.fit(x, y, batch_size=batch_size, nb_epoch=epochs,
                              **kw)

    def evaluate(self, x, y=None, batch_size: int = 32):
        return self.model.evaluate(x, y, batch_size=batch_size)

    def predict(self, x, batch_size: int = 32):
        return self.model.predict(x, batch_size=batch_size)

    @staticmethod
    def _convert(module) -> GraphProgram:
        import jax
        import jax.numpy as jnp
        import torch

        from analytics_zoo_tpu.tfpark.converter import (
            UnsupportedLayerError, _stateless)

        if not isinstance(module, torch.nn.Sequential):
            raise UnsupportedLayerError(
                "TorchModel converts torch.nn.Sequential models; for "
                "arbitrary modules use deploy.InferenceModel.load_torch "
                "(inference) instead")
        nodes, params, state = [], {}, {}
        prev = "input"
        for i, sub in enumerate(module):
            name = f"torch_{i}_{type(sub).__name__.lower()}"
            t = type(sub).__name__
            if t == "Linear":
                p = {"kernel": sub.weight.detach().numpy().T.copy()}
                if sub.bias is not None:
                    p["bias"] = sub.bias.detach().numpy().copy()
                op = _stateless(lambda p, xs, tr, r: (
                    jnp.dot(xs[0], p["kernel"]) + p.get("bias", 0.0)))
            elif t == "Conv2d":
                if (tuple(sub.dilation) != (1, 1) or sub.groups != 1):
                    raise UnsupportedLayerError(
                        "Conv2d with dilation/groups is not converted")
                # Torch semantics are NCHW/OIHW — keep them verbatim so the
                # converted program consumes the exact tensors the torch
                # module does (and Flatten→Linear ordering stays C*H*W).
                # XLA lays out NCHW convs onto the MXU itself.
                p = {"kernel": sub.weight.detach().numpy().copy()}
                if sub.bias is not None:
                    p["bias"] = sub.bias.detach().numpy().copy()
                stride = tuple(sub.stride)
                pad = [(int(a), int(a)) for a in sub.padding] \
                    if not isinstance(sub.padding, str) else sub.padding.upper()

                def conv_fn(p, xs, tr, r, _s=stride, _pad=pad):
                    dn = jax.lax.conv_dimension_numbers(
                        xs[0].shape, p["kernel"].shape,
                        ("NCHW", "OIHW", "NCHW"))
                    y = jax.lax.conv_general_dilated(
                        xs[0], p["kernel"], _s, _pad, dimension_numbers=dn)
                    if "bias" in p:
                        y = y + p["bias"][None, :, None, None]
                    return y

                op = _stateless(conv_fn)
            elif t == "ReLU":
                p, op = {}, _stateless(lambda p, xs, tr, r: jax.nn.relu(xs[0]))
            elif t == "Sigmoid":
                p, op = {}, _stateless(
                    lambda p, xs, tr, r: jax.nn.sigmoid(xs[0]))
            elif t == "Tanh":
                p, op = {}, _stateless(lambda p, xs, tr, r: jnp.tanh(xs[0]))
            elif t == "Flatten":
                p, op = {}, _stateless(
                    lambda p, xs, tr, r: xs[0].reshape(xs[0].shape[0], -1))
            elif t == "Dropout":
                rate = float(sub.p)

                def drop_fn(p, xs, tr, r, _rate=rate):
                    x = xs[0]
                    if not tr or r is None or _rate <= 0:
                        return x
                    keep = jax.random.bernoulli(r, 1.0 - _rate, x.shape)
                    return jnp.where(keep, x / (1.0 - _rate), 0.0)

                p, op = {}, _stateless(drop_fn)
            elif t == "MaxPool2d":
                if sub.padding not in (0, (0, 0)) or sub.dilation not in (
                        1, (1, 1)):
                    raise UnsupportedLayerError(
                        "MaxPool2d with padding/dilation is not converted")
                ks = (sub.kernel_size if isinstance(sub.kernel_size, tuple)
                      else (sub.kernel_size,) * 2)
                st = (sub.stride if isinstance(sub.stride, tuple)
                      else (sub.stride,) * 2) if sub.stride else ks

                def pool_fn(p, xs, tr, r, _k=ks, _s=st):
                    # NCHW window to match the torch layout kept above
                    return jax.lax.reduce_window(
                        xs[0], -jnp.inf, jax.lax.max, (1, 1) + _k,
                        (1, 1) + _s, "VALID")

                p, op = {}, _stateless(pool_fn)
            else:
                raise UnsupportedLayerError(f"torch layer {t!r}")
            nodes.append((name, op, [prev]))
            if p:
                params[name] = p
            prev = name
        return GraphProgram(nodes, ["input"], [prev], params, state)


class TorchCriterion:
    """Use a torch loss module as the training objective
    (reference TorchCriterion.scala:130 ran libtorch in-process via JNI).

    TPU-native stance: the hot loop must stay one XLA program, so known
    torch losses are MAPPED to their native jax equivalents at
    construction (the loss itself is pure math — nothing torch-specific
    survives the translation).  Unknown custom losses raise rather than
    silently pulling torch into the step."""

    _TABLE = {
        "MSELoss": "mse",
        "L1Loss": "mae",
        "CrossEntropyLoss": "sparse_categorical_crossentropy_with_logits",
        "NLLLoss": "class_nll",
        "BCELoss": "binary_crossentropy",
        "BCEWithLogitsLoss": "binary_crossentropy_with_logits",
        "SmoothL1Loss": None,       # handled specially below
        # (HingeEmbeddingLoss deliberately unmapped: its distance-based
        # semantics differ from the keras margin hinge)
    }

    def __init__(self, torch_loss):
        name = type(torch_loss).__name__
        if name == "SmoothL1Loss":
            import jax.numpy as jnp

            def smooth_l1(y_true, y_pred):
                d = jnp.abs(y_pred - y_true)
                return jnp.mean(jnp.where(d < 1.0, 0.5 * d * d, d - 0.5))

            self.loss = smooth_l1
        elif name in self._TABLE:
            from analytics_zoo_tpu.nn import objectives

            self.loss = objectives.get(self._TABLE[name])
        else:
            raise UnsupportedLayerError(
                f"torch loss {name!r} has no native mapping; pass a jax "
                f"loss fn to compile() directly")
        self.name = name

    def __call__(self, y_true, y_pred):
        return self.loss(y_true, y_pred)
