"""BERT task estimators (reference pyzoo/zoo/tfpark/text/estimator/
bert_classifier.py / bert_ner.py / bert_squad.py — tf.estimator wrappers
over a TF BERT graph).

TPU-native redesign: the native BERT encoder (nn/layers/attention.py)
plus a task head is one Layer-protocol model trained by the standard
SPMD Estimator — same fit/evaluate/predict surface, no tf.estimator.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.nn import initializers
from analytics_zoo_tpu.nn.layers.attention import BERT
from analytics_zoo_tpu.nn.topology import KerasNet

__all__ = ["BERTClassifier", "BERTNER", "BERTSQuAD"]


class _BERTTask(KerasNet):
    """BERT encoder + task head over (ids, segments, mask) inputs."""

    head_on = "pooled"          # "pooled" | "sequence" | "qa"

    def __init__(self, num_classes: int, bert_config: Optional[Dict] = None,
                 **kw):
        super().__init__(**kw)
        cfg = dict(vocab=30522, hidden_size=128, n_block=2, nhead=2,
                   intermediate_size=512, max_position_len=512)
        cfg.update(bert_config or {})
        self.bert = BERT(name=f"{self.name}_bert", **cfg)
        self.num_classes = num_classes
        self.hidden_size = cfg["hidden_size"]
        self.initializer = initializers.get("glorot_uniform")

    @property
    def layers(self):
        return [self.bert]

    def build(self, rng, ids_shape, *rest):
        kb, kh = jax.random.split(rng)
        bert_params, bert_state = self.bert.init(kb, ids_shape, *rest)
        out = 2 if self.head_on == "qa" else self.num_classes
        params = {
            self.bert.name: bert_params,
            "head": {"kernel": self.initializer(
                kh, (self.hidden_size, out), jnp.float32),
                "bias": jnp.zeros((out,), jnp.float32)},
        }
        return params, {self.bert.name: bert_state}

    def call(self, params, state, ids, segments=None, mask=None, *,
             training=False, rng=None):
        # BERT layer positional order: ids, segments, positions, mask —
        # when a mask is given, positions must be filled with the
        # default 0..L-1 iota so the mask never lands in the pos slot
        inputs = [ids]
        if segments is not None or mask is not None:
            inputs.append(segments if segments is not None
                          else jnp.zeros_like(ids))
        if mask is not None:
            L = ids.shape[1]
            positions = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32),
                                         ids.shape)
            inputs.extend([positions, mask])
        (seq, pooled), _ = self.bert.call(
            params[self.bert.name], state.get(self.bert.name, {}), *inputs,
            training=training, rng=rng)
        h = params["head"]
        if self.head_on == "pooled":
            logits = pooled @ h["kernel"] + h["bias"]
        else:                               # per-token heads (ner / qa)
            logits = seq @ h["kernel"] + h["bias"]
            if self.head_on == "qa":
                # (B, L, 2) -> start/end logit pair
                logits = (logits[..., 0], logits[..., 1])
                return logits, state
        return logits, state


class BERTClassifier(_BERTTask):
    """Sequence classification on the pooled output (reference
    bert_classifier.py)."""

    head_on = "pooled"


class BERTNER(_BERTTask):
    """Token-level tagging on the sequence output (reference
    bert_ner.py)."""

    head_on = "sequence"


class BERTSQuAD(_BERTTask):
    """Extractive QA: start/end logits per token (reference
    bert_squad.py)."""

    head_on = "qa"

    def __init__(self, bert_config: Optional[Dict] = None, **kw):
        super().__init__(num_classes=2, bert_config=bert_config, **kw)
