"""TFEstimator — the model_fn-style custom-loop estimator
(reference pyzoo/zoo/tfpark/estimator.py:30,47,116: a tf.estimator
wrapper whose ``model_fn(features, labels, mode, params)`` returns an
``EstimatorSpec``, trained/evaluated/predicted from ``input_fn``s).

TPU-native redesign: no graph/session/ZooOptimizer dance — the model_fn
is plain Python that builds a Layer-protocol model and declares the
loss/optimizer for the requested mode; the spec lowers onto the SPMD
``train.Estimator`` (one jitted step, psum-fused gradients).  Custom
training logic lives in the spec's ``loss`` (any callable
``loss(y_true, y_pred) -> scalar``), custom prediction post-processing
in ``predictions_fn`` — the same degrees of freedom the reference's
EstimatorSpec train_op/predictions fields expose, minus the two-runtime
choreography (TFTrainingHelperV2.scala:53-98 is obsolete here).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


class ModeKeys:
    """tf.estimator.ModeKeys equivalent."""

    TRAIN = "train"
    EVAL = "eval"
    PREDICT = "predict"


class EstimatorSpec:
    """What a model_fn returns for a given mode.

    ``model``: a Layer-protocol model producing predictions from the
    features.  ``loss``: string or callable objective (TRAIN/EVAL).
    ``optimizer``: string or optimizer object (TRAIN).
    ``metrics``: metric names/objects (EVAL).  ``predictions_fn``:
    optional ``f(np.ndarray) -> np.ndarray`` applied to raw predictions
    (PREDICT).
    """

    def __init__(self, mode: str, model=None, loss=None, optimizer="adam",
                 metrics: Optional[Sequence] = None,
                 predictions_fn: Optional[Callable] = None,
                 grad_clip_norm: Optional[float] = None,
                 grad_accum_steps: int = 1):
        if model is None:
            raise ValueError("EstimatorSpec needs a model")
        if mode in (ModeKeys.TRAIN, ModeKeys.EVAL) and loss is None:
            raise ValueError(f"mode {mode!r} needs a loss")
        self.mode = mode
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = list(metrics or [])
        self.predictions_fn = predictions_fn
        self.grad_clip_norm = grad_clip_norm
        self.grad_accum_steps = grad_accum_steps


def _resolve_input(data) -> Tuple[List[np.ndarray], Optional[np.ndarray]]:
    """input_fn result → (features list, labels or None).  Accepts
    (x, y) tuples, bare arrays/lists (predict), or TFDataset."""
    from analytics_zoo_tpu.tfpark.tf_dataset import TFDataset

    if isinstance(data, TFDataset):
        feats = list(data.features)
        labels = data.labels[0] if data.labels else None
        return feats, labels
    if isinstance(data, tuple) and len(data) == 2:
        x, y = data
        xs = list(x) if isinstance(x, (list, tuple)) else [np.asarray(x)]
        return [np.asarray(a) for a in xs], np.asarray(y)
    xs = list(data) if isinstance(data, (list, tuple)) else [np.asarray(data)]
    return [np.asarray(a) for a in xs], None


class TFEstimator:
    """train/evaluate/predict driven by ``input_fn``s over a model_fn.

    ``model_fn(features, labels, mode, params) -> EstimatorSpec`` —
    ``features``/``labels`` are the arrays the input_fn produced (so the
    model_fn can shape itself on them), ``params`` the hyper-parameter
    dict given at construction (reference estimator.py:47-99 semantics).
    """

    def __init__(self, model_fn: Callable, model_dir: Optional[str] = None,
                 params: Optional[Dict[str, Any]] = None):
        self.model_fn = model_fn
        self.model_dir = model_dir
        self.params = dict(params or {})
        self._train_est = None      # the SPMD estimator (TRAIN spec)
        self._spec = None

    @classmethod
    def from_model_fn(cls, model_fn, model_dir=None, params=None):
        return cls(model_fn, model_dir=model_dir, params=params)

    # ------------------------------------------------------------------
    def _build(self, features, labels, mode) -> None:
        """Build (once) the underlying SPMD estimator from the TRAIN
        spec; EVAL/PREDICT reuse its weights like tf.estimator reuses
        the checkpoint."""
        if self._train_est is not None:
            return
        from analytics_zoo_tpu.train.estimator import Estimator

        spec = self.model_fn(features, labels, mode, self.params)
        if not isinstance(spec, EstimatorSpec):
            raise TypeError("model_fn must return an EstimatorSpec, got "
                            f"{type(spec).__name__}")
        self._spec = spec
        self._train_est = Estimator(
            spec.model, optimizer=spec.optimizer,
            # a PREDICT-only spec has no loss; the placeholder is never
            # evaluated on the predict path
            loss=spec.loss or "mse",
            metrics=spec.metrics, grad_clip_norm=spec.grad_clip_norm,
            grad_accum_steps=spec.grad_accum_steps)
        if self.model_dir:
            # tf.estimator semantics: model_dir checkpoints resume
            # training and serve predict-without-train
            self._train_est.set_checkpoint(self.model_dir)
            if self._train_est._ckpt_mgr.latest_step() is not None:
                self._train_est._restore_checkpoint()

    # ------------------------------------------------------------------
    def train(self, input_fn: Callable, steps: Optional[int] = None,
              batch_size: int = 32, epochs: int = 1):
        """Train from ``input_fn() -> (features, labels) | TFDataset``.
        ``steps`` caps the number of optimizer steps (reference
        train(input_fn, steps))."""
        data = input_fn()
        xs, y = _resolve_input(data)
        if y is None:
            raise ValueError("train input_fn must yield labels")
        self._build(xs, y, ModeKeys.TRAIN)
        est = self._train_est
        if steps is None:
            est.fit(xs, y, batch_size=batch_size,
                    epochs=est.finished_epochs + epochs, verbose=False)
            return self
        # exact step budget (tf.estimator train(steps) semantics): whole
        # epochs, then one trimmed pass for the remainder
        spe = max(1, len(y) // max(batch_size, 1))
        full, rem = divmod(steps, spe)
        if full:
            est.fit(xs, y, batch_size=batch_size,
                    epochs=est.finished_epochs + full, verbose=False)
        if rem:
            cut = rem * batch_size
            est.fit([a[:cut] for a in xs], y[:cut], batch_size=batch_size,
                    epochs=est.finished_epochs + 1, verbose=False)
        return self

    def evaluate(self, input_fn: Callable, eval_methods: Optional[Sequence] = None,
                 batch_size: int = 32) -> Dict[str, float]:
        data = input_fn()
        xs, y = _resolve_input(data)
        if y is None:
            raise ValueError("evaluate input_fn must yield labels")
        self._build(xs, y, ModeKeys.EVAL)
        # an EVAL-mode spec may carry extra metrics
        spec = self.model_fn(xs, y, ModeKeys.EVAL, self.params)
        if spec.metrics and not self._train_est.metrics:
            from analytics_zoo_tpu.nn import metrics as metrics_lib
            self._train_est.metrics = [metrics_lib.get(m)
                                       for m in spec.metrics]
            self._train_est._eval_step = None
        return self._train_est.evaluate(xs, y, batch_size=batch_size)

    def predict(self, input_fn: Callable, batch_size: int = 32) -> np.ndarray:
        data = input_fn()
        xs, _ = _resolve_input(data)
        if self._train_est is None:
            self._build(xs, None, ModeKeys.PREDICT)
        preds = self._train_est.predict(xs, batch_size=batch_size)
        spec = self.model_fn(xs, None, ModeKeys.PREDICT, self.params)
        if spec.predictions_fn is not None:
            preds = spec.predictions_fn(preds)
        return preds

    # ------------------------------------------------------------------
    @property
    def estimator(self):
        """The underlying SPMD train.Estimator (weights, checkpoints)."""
        if self._train_est is None:
            raise RuntimeError("call train()/evaluate()/predict() first")
        return self._train_est
