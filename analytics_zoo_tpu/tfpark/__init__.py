"""TFPark equivalent: foreign-model ingestion (L5).

Reference capability: pyzoo/zoo/tfpark/ — TFDataset (tf_dataset.py:115),
TFOptimizer (tf_optimizer.py:336), KerasModel (model.py:34), TFNet
(tfnet.py:51) — training and serving other frameworks' models under the
zoo engine.  Here ingestion means *conversion to native JAX* (see
converter.py) so the training hot loop is one XLA program.
"""

from analytics_zoo_tpu.tfpark.converter import (  # noqa: F401
    GraphProgram, UnsupportedLayerError, convert_keras_model)
from analytics_zoo_tpu.tfpark.estimator import (  # noqa: F401
    EstimatorSpec, ModeKeys, TFEstimator)
from analytics_zoo_tpu.tfpark.gan import GANEstimator  # noqa: F401
from analytics_zoo_tpu.tfpark.model import (  # noqa: F401
    FunctionModel, KerasModel, TFGraphOptimizer, TFNet, TFOptimizer,
    TorchCriterion,
    TorchModel)
from analytics_zoo_tpu.tfpark.text_estimators import (  # noqa: F401
    BERTNER, BERTSQuAD, BERTClassifier)
from analytics_zoo_tpu.tfpark.tf_dataset import TFDataset  # noqa: F401
