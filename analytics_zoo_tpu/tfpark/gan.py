"""GANEstimator — alternating generator/discriminator training.

Reference capability: ``GANEstimator`` (pyzoo/zoo/tfpark/gan/
gan_estimator.py) with ``GanOptimMethod`` (tfpark/GanOptimMethod.scala)
alternating D/G steps inside the BigDL optimizer.

TPU-native redesign: BOTH sub-steps are one jitted program each
(generator step donates G params/opt, discriminator step donates D's),
and the alternation schedule (d_steps : g_steps) is a host-side loop
over compiled steps — no optimizer subclassing.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.core.context import (explicit_prng_key,
                                             get_zoo_context)
from analytics_zoo_tpu.train import optimizers as optim_lib

__all__ = ["GANEstimator"]


def _bce_logits(logits, target: float):
    # the canonical stable implementation — one source of truth
    from analytics_zoo_tpu.nn.objectives import (
        binary_crossentropy_with_logits)

    return binary_crossentropy_with_logits(
        jnp.full(logits.shape, target, logits.dtype), logits)


class GANEstimator:
    """Train a generator/discriminator pair with alternating steps.

    ``generator`` / ``discriminator``: Layer-protocol models
    (Sequential/Model).  Default losses are the non-saturating GAN pair;
    override with ``generator_loss_fn(fake_logits)`` /
    ``discriminator_loss_fn(real_logits, fake_logits)``.
    """

    def __init__(self, generator, discriminator, noise_dim: int,
                 generator_optimizer="adam", discriminator_optimizer="adam",
                 generator_steps: int = 1, discriminator_steps: int = 1,
                 generator_loss_fn: Optional[Callable] = None,
                 discriminator_loss_fn: Optional[Callable] = None,
                 ctx=None):
        self.g = generator
        self.d = discriminator
        self.noise_dim = noise_dim
        if generator_steps < 1 or discriminator_steps < 1:
            raise ValueError("generator_steps and discriminator_steps must "
                             "be >= 1 (alternation needs both players)")
        self.g_tx = optim_lib.get(generator_optimizer)
        self.d_tx = optim_lib.get(discriminator_optimizer)
        self.g_steps = generator_steps
        self.d_steps = discriminator_steps
        self.g_loss_fn = generator_loss_fn or (
            lambda fake_logits: _bce_logits(fake_logits, 1.0))
        self.d_loss_fn = discriminator_loss_fn or (
            lambda real_logits, fake_logits:
            _bce_logits(real_logits, 1.0) + _bce_logits(fake_logits, 0.0))
        self.ctx = ctx or get_zoo_context()

        self.g_params = self.d_params = None
        self.g_state: Dict = {}
        self.d_state: Dict = {}
        self.history: List[Dict[str, float]] = []
        self._steps_built = False

    # ------------------------------------------------------------------
    def _build(self, batch_shape: Tuple[int, ...]):
        rng = explicit_prng_key(self.ctx.config.seed)
        kg, kd = jax.random.split(rng)
        noise_shape = (2, self.noise_dim)
        self.g_params, self.g_state = self.g.init(kg, noise_shape)
        fake_shape = self.g.output_shape(self.g_params, self.g_state,
                                         noise_shape)
        self.d_params, self.d_state = self.d.init(kd, tuple(fake_shape))
        self.g_opt = self.g_tx.init(self.g_params)
        self.d_opt = self.d_tx.init(self.d_params)

        g, d = self.g, self.d
        g_loss_fn, d_loss_fn = self.g_loss_fn, self.d_loss_fn
        g_tx, d_tx = self.g_tx, self.d_tx

        def d_step(gp, gs, dp, ds, d_opt, rng, real):
            rng, zk, gk, dk = jax.random.split(rng, 4)
            z = jax.random.normal(zk, (real.shape[0], self.noise_dim))
            fake, _ = g.call(gp, gs, z, training=True, rng=gk)

            def lossf(p):
                rl, nds = d.call(p, ds, real, training=True, rng=dk)
                fl, _ = d.call(p, ds, fake, training=True, rng=dk)
                return d_loss_fn(rl, fl), nds

            (loss, nds), grads = jax.value_and_grad(lossf, has_aux=True)(dp)
            updates, d_opt = d_tx.update(grads, d_opt, dp)
            import optax

            return optax.apply_updates(dp, updates), nds, d_opt, rng, loss

        def g_step(gp, gs, dp, ds, g_opt, rng, batch_size):
            rng, zk, gk, dk = jax.random.split(rng, 4)
            z = jax.random.normal(zk, (batch_size, self.noise_dim))

            def lossf(p):
                fake, ngs = g.call(p, gs, z, training=True, rng=gk)
                fl, _ = d.call(dp, ds, fake, training=True, rng=dk)
                return g_loss_fn(fl), ngs

            (loss, ngs), grads = jax.value_and_grad(lossf, has_aux=True)(gp)
            updates, g_opt = g_tx.update(grads, g_opt, gp)
            import optax

            return optax.apply_updates(gp, updates), ngs, g_opt, rng, loss

        self._d_step = jax.jit(d_step, donate_argnums=(2, 4, 5))
        self._g_step = jax.jit(g_step, donate_argnums=(0, 4, 5),
                               static_argnums=(6,))
        self._rng = explicit_prng_key(self.ctx.config.seed + 1)
        self._steps_built = True

    # ------------------------------------------------------------------
    def fit(self, real_data: np.ndarray, batch_size: int = 64,
            epochs: int = 1, verbose: bool = True) -> List[Dict[str, float]]:
        real_data = np.asarray(real_data, np.float32)
        if not self._steps_built:
            self._build(real_data.shape)
        n = len(real_data)
        steps = max(1, n // batch_size)
        rs = np.random.RandomState(self.ctx.config.seed)
        for epoch in range(epochs):
            perm = rs.permutation(n)
            d_losses, g_losses = [], []
            for s in range(steps):
                idx = perm[s * batch_size:(s + 1) * batch_size]
                real = jnp.asarray(real_data[idx])
                for _ in range(self.d_steps):
                    (self.d_params, self.d_state, self.d_opt, self._rng,
                     dl) = self._d_step(self.g_params, self.g_state,
                                        self.d_params, self.d_state,
                                        self.d_opt, self._rng, real)
                for _ in range(self.g_steps):
                    (self.g_params, self.g_state, self.g_opt, self._rng,
                     gl) = self._g_step(self.g_params, self.g_state,
                                        self.d_params, self.d_state,
                                        self.g_opt, self._rng,
                                        int(real.shape[0]))
                d_losses.append(dl)
                g_losses.append(gl)
            rec = {"epoch": epoch + 1,
                   "d_loss": float(jnp.mean(jnp.stack(d_losses))),
                   "g_loss": float(jnp.mean(jnp.stack(g_losses)))}
            self.history.append(rec)
            if verbose:
                print(f"epoch {rec['epoch']}: d_loss={rec['d_loss']:.4f} "
                      f"g_loss={rec['g_loss']:.4f}")
        return self.history

    def generate(self, n: int, seed: int = 0) -> np.ndarray:
        z = jax.random.normal(explicit_prng_key(seed), (n, self.noise_dim))
        out, _ = self.g.call(self.g_params, self.g_state, z,
                             training=False, rng=None)
        return np.asarray(out)
