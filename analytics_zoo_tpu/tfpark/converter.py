"""tf.keras → native JAX conversion (the heart of the TFPark equivalent).

Reference capability: TFPark trains *foreign* TF models under the zoo
engine by exporting the TF graph and running it via JNI per partition
(tf_optimizer.py:225-334, TFTrainingHelper.scala:32).  On TPU that
two-runtime trick would put host TF in the hot loop, so the redesign
*ingests* the model instead: the Keras layer graph is converted to a pure
JAX function + imported weight pytree, and then trains natively under the
SPMD Estimator — one fused XLA program, no TF at step time.

Supported: Sequential + single-node functional graphs over the common
layer vocabulary (Dense/Conv/BN/pool/merge/activations/...).  Anything
else raises ``UnsupportedLayerError`` — callers can fall back to
``deploy.InferenceModel.load_tf_keras`` (call_tf) for inference.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["convert_keras_model", "UnsupportedLayerError", "GraphProgram"]


class UnsupportedLayerError(ValueError):
    pass


_ACTS = {
    "linear": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": lambda x: jnp.clip(x, 0, 6),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
    # keras gelu defaults to the exact (erf) form; jax.nn.gelu defaults to
    # the tanh approximation — pin exact for parity
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "softplus": jax.nn.softplus,
    "swish": jax.nn.silu,
    "silu": jax.nn.silu,
    "hard_sigmoid": jax.nn.hard_sigmoid,
    "exponential": jnp.exp,
}


def _act(name: Optional[str]) -> Callable:
    if name is None:
        return _ACTS["linear"]
    if callable(name):
        raise UnsupportedLayerError("custom activation callables are not "
                                    "convertible; use a string activation")
    if name not in _ACTS:
        raise UnsupportedLayerError(f"activation {name!r}")
    return _ACTS[name]


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _require_channels_last(cfg: Dict, cn: str) -> None:
    """All converted spatial ops assume NHWC; channels_first models must
    not convert silently to wrong axes."""
    df = cfg.get("data_format", "channels_last")
    if df not in (None, "channels_last"):
        raise UnsupportedLayerError(
            f"{cn} with data_format={df!r} (only channels_last/NHWC "
            f"converts; transpose the model or use InferenceModel.load_tf)")


# ---------------------------------------------------------------------------
# per-layer converters: (config, weights) -> (params, op)
# op signature: op(p, xs: List[arr], training, rng, state_in) -> (out, state)
# ---------------------------------------------------------------------------

def _stateless(fn):
    def op(p, xs, training, rng, st):
        return fn(p, xs, training, rng), st
    return op


def _conv_dn(x_ndim):
    return jax.lax.conv_dimension_numbers(
        (1,) * x_ndim, (1,) * x_ndim,
        ("NHWC", "HWIO", "NHWC") if x_ndim == 4 else ("NWC", "WIO", "NWC"))


def _convert_dense(cfg, w):
    act = _act(cfg.get("activation"))
    p = {"kernel": w[0]}
    if cfg.get("use_bias", True):
        p["bias"] = w[1]

    def fn(p, xs, training, rng):
        y = jnp.dot(xs[0], p["kernel"])
        if "bias" in p:
            y = y + p["bias"]
        return act(y)

    return p, _stateless(fn)


def _convert_embedding(cfg, w):
    p = {"table": w[0]}

    def fn(p, xs, training, rng):
        return jnp.take(p["table"], xs[0].astype(jnp.int32), axis=0)

    return p, _stateless(fn)


def _make_conv(cfg, w, ndim, depthwise=False):
    strides = _pair(cfg.get("strides", 1)) if ndim == 4 else (
        (int(cfg.get("strides", [1])[0]
             if isinstance(cfg.get("strides", 1), (list, tuple))
             else cfg.get("strides", 1)),))
    dilation = cfg.get("dilation_rate", 1)
    dilation = (_pair(dilation) if ndim == 4 else
                ((int(dilation[0]) if isinstance(dilation, (list, tuple))
                  else int(dilation)),))
    padding = cfg.get("padding", "valid").upper()
    act = _act(cfg.get("activation"))
    use_bias = cfg.get("use_bias", True)
    p = {"kernel": w[0]}
    if use_bias:
        p["bias"] = w[1]

    def fn(p, xs, training, rng):
        x = xs[0]
        k = p["kernel"]
        if depthwise:
            # keras depthwise kernel (kh, kw, cin, mult) → HWIO with
            # feature_group_count=cin
            kh, kw, cin, mult = k.shape
            k = k.reshape(kh, kw, 1, cin * mult)
            y = jax.lax.conv_general_dilated(
                x, k, window_strides=strides, padding=padding,
                rhs_dilation=dilation, dimension_numbers=_conv_dn(4),
                feature_group_count=cin)
        else:
            y = jax.lax.conv_general_dilated(
                x, k, window_strides=strides, padding=padding,
                rhs_dilation=dilation, dimension_numbers=_conv_dn(x.ndim))
        if "bias" in p:
            y = y + p["bias"]
        return act(y)

    return p, _stateless(fn)


def _make_pool(cfg, reducer, init, ndim, average=False):
    pool = cfg.get("pool_size", 2)
    pool = _pair(pool) if ndim == 4 else (
        (int(pool[0]) if isinstance(pool, (list, tuple)) else int(pool)),)
    strides = cfg.get("strides") or pool
    strides = _pair(strides) if ndim == 4 else (
        (int(strides[0]) if isinstance(strides, (list, tuple))
         else int(strides)),)
    padding = cfg.get("padding", "valid").upper()

    def fn(p, xs, training, rng):
        x = xs[0]
        dims = (1,) + pool + (1,)
        strd = (1,) + strides + (1,)
        y = jax.lax.reduce_window(x, init, reducer, dims, strd, padding)
        if average:
            ones = jnp.ones(x.shape[1:-1], x.dtype)[None, ..., None]
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strd,
                                        padding)
            y = y / cnt
        return y

    return {}, _stateless(fn)


def _convert_batchnorm(cfg, w):
    eps = cfg.get("epsilon", 1e-3)
    momentum = cfg.get("momentum", 0.99)
    scale, center = cfg.get("scale", True), cfg.get("center", True)
    bn_axis = cfg.get("axis", -1)
    if isinstance(bn_axis, (list, tuple)):
        bn_axis = bn_axis[0] if bn_axis else -1
    bn_axis = int(bn_axis)
    i = 0
    p = {}
    if scale:
        p["gamma"] = w[i]; i += 1
    if center:
        p["beta"] = w[i]; i += 1
    moving_mean, moving_var = w[i], w[i + 1]

    def op(p, xs, training, rng, st):
        x = xs[0]
        # the op normalizes the LAST axis; ndim is static at trace time,
        # so a channels_first BN (axis=1 on 4D input) fails loudly here
        if bn_axis not in (-1, x.ndim - 1):
            raise UnsupportedLayerError(
                f"BatchNormalization axis={bn_axis} on rank-{x.ndim} input "
                f"(only last-axis / channels_last converts)")
        axes = tuple(range(x.ndim - 1))
        if training:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            new_st = {
                "mean": st["mean"] * momentum + mean * (1 - momentum),
                "var": st["var"] * momentum + var * (1 - momentum)}
        else:
            mean, var = st["mean"], st["var"]
            new_st = st
        y = (x - mean) / jnp.sqrt(var + eps)
        if "gamma" in p:
            y = y * p["gamma"]
        if "beta" in p:
            y = y + p["beta"]
        return y, new_st

    return p, op, {"mean": moving_mean, "var": moving_var}


def _convert_zeropad(cfg, w):
    pad = cfg.get("padding", 1)
    if isinstance(pad, int):
        pad = ((pad, pad), (pad, pad))
    else:
        pad = tuple((p, p) if isinstance(p, int) else tuple(p) for p in pad)

    def fn(p, xs, training, rng):
        return jnp.pad(xs[0], ((0, 0),) + pad + ((0, 0),))

    return {}, _stateless(fn)


def _convert_dropout(cfg, w):
    rate = cfg.get("rate", 0.5)

    def fn(p, xs, training, rng):
        x = xs[0]
        if not training or rng is None or rate <= 0:
            return x
        keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
        return jnp.where(keep, x / (1.0 - rate), 0.0)

    return {}, _stateless(fn)


def _convert_layernorm(cfg, w):
    eps = cfg.get("epsilon", 1e-3)
    i = 0
    p = {}
    if cfg.get("scale", True):
        p["gamma"] = w[i]; i += 1
    if cfg.get("center", True):
        p["beta"] = w[i]; i += 1

    def fn(p, xs, training, rng):
        x = xs[0]
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mean) / jnp.sqrt(var + eps)
        if "gamma" in p:
            y = y * p["gamma"]
        if "beta" in p:
            y = y + p["beta"]
        return y

    return p, _stateless(fn)


def _merge(fn2):
    def fn(p, xs, training, rng):
        out = xs[0]
        for x in xs[1:]:
            out = fn2(out, x)
        return out
    return {}, _stateless(fn)


_SPATIAL_LAYERS = frozenset({
    "Conv2D", "Convolution2D", "Conv1D", "Convolution1D", "DepthwiseConv2D",
    "MaxPooling2D", "AveragePooling2D", "MaxPooling1D", "AveragePooling1D",
    "GlobalAveragePooling2D", "GlobalMaxPooling2D",
    "GlobalAveragePooling1D", "GlobalMaxPooling1D",
    "ZeroPadding2D", "SpatialDropout2D", "UpSampling2D",
})


def _convert_rnn(cfg, w, kind: str):
    """LSTM/GRU → the native (golden-tested) recurrent layers; weights
    share keras's [kernel, recurrent_kernel, bias] layout and i,f,c,o /
    z,r,h gate order."""
    from analytics_zoo_tpu.nn.layers import recurrent as rc

    ra = cfg.get("recurrent_activation", "sigmoid")
    if ra == "hard_sigmoid":
        raise UnsupportedLayerError(
            "recurrent_activation='hard_sigmoid': keras 3's hard_sigmoid "
            "(relu6(x+3)/6) differs from the classic clip(0.2x+0.5,0,1) "
            "this framework implements — convert with 'sigmoid' instead")
    if not cfg.get("use_bias", True):
        raise UnsupportedLayerError(f"{kind} with use_bias=False")
    if (float(cfg.get("dropout", 0.0) or 0.0)
            or float(cfg.get("recurrent_dropout", 0.0) or 0.0)):
        raise UnsupportedLayerError(
            f"{kind} with dropout/recurrent_dropout — the converted layer "
            "would silently train unregularized; set both to 0 to convert")
    if cfg.get("stateful"):
        raise UnsupportedLayerError(f"stateful {kind}")
    if kind == "GRU" and cfg.get("reset_after", True):
        raise UnsupportedLayerError(
            "GRU reset_after=True (keras v2 formulation); rebuild the "
            "keras layer with reset_after=False (v1) to convert")
    common = dict(
        activation=cfg.get("activation", "tanh") or "linear",
        inner_activation=ra,
        return_sequences=cfg.get("return_sequences", False),
        go_backwards=cfg.get("go_backwards", False))
    layer = (rc.LSTM(cfg["units"], **common) if kind == "LSTM"
             else rc.GRU(cfg["units"], **common))
    p = {"kernel": w[0], "recurrent": w[1], "bias": w[2]}

    def fn(p, xs, training, rng):
        return layer.forward(p, xs[0], training=training, rng=rng)

    return p, _stateless(fn)


def _convert_layer(class_name: str, cfg: Dict, weights: List[np.ndarray]):
    """Returns (params, op, state) for one keras layer."""
    cn = class_name
    if cn in _SPATIAL_LAYERS:
        _require_channels_last(cfg, cn)
    if cn in ("LSTM", "GRU"):
        return (*_convert_rnn(cfg, weights, cn), {})
    if cn == "Dense":
        return (*_convert_dense(cfg, weights), {})
    if cn == "Embedding":
        return (*_convert_embedding(cfg, weights), {})
    if cn in ("Conv2D", "Convolution2D"):
        return (*_make_conv(cfg, weights, 4), {})
    if cn in ("Conv1D", "Convolution1D"):
        return (*_make_conv(cfg, weights, 3), {})
    if cn == "DepthwiseConv2D":
        return (*_make_conv(cfg, weights, 4, depthwise=True), {})
    if cn == "MaxPooling2D":
        return (*_make_pool(cfg, jax.lax.max, -jnp.inf, 4), {})
    if cn == "AveragePooling2D":
        return (*_make_pool(cfg, jax.lax.add, 0.0, 4, average=True), {})
    if cn == "MaxPooling1D":
        return (*_make_pool(cfg, jax.lax.max, -jnp.inf, 3), {})
    if cn == "AveragePooling1D":
        return (*_make_pool(cfg, jax.lax.add, 0.0, 3, average=True), {})
    if cn == "GlobalAveragePooling2D":
        return {}, _stateless(
            lambda p, xs, t, r: jnp.mean(xs[0], axis=(1, 2))), {}
    if cn == "GlobalMaxPooling2D":
        return {}, _stateless(
            lambda p, xs, t, r: jnp.max(xs[0], axis=(1, 2))), {}
    if cn == "GlobalAveragePooling1D":
        return {}, _stateless(
            lambda p, xs, t, r: jnp.mean(xs[0], axis=1)), {}
    if cn == "GlobalMaxPooling1D":
        return {}, _stateless(
            lambda p, xs, t, r: jnp.max(xs[0], axis=1)), {}
    if cn == "Flatten":
        return {}, _stateless(
            lambda p, xs, t, r: xs[0].reshape(xs[0].shape[0], -1)), {}
    if cn == "Reshape":
        shape = tuple(cfg["target_shape"])
        return {}, _stateless(
            lambda p, xs, t, r: xs[0].reshape((xs[0].shape[0],) + shape)), {}
    if cn == "Permute":
        dims = tuple(cfg["dims"])
        return {}, _stateless(
            lambda p, xs, t, r: jnp.transpose(xs[0], (0,) + dims)), {}
    if cn == "Activation":
        a = _act(cfg.get("activation"))
        return {}, _stateless(lambda p, xs, t, r: a(xs[0])), {}
    if cn == "ReLU":
        mx = cfg.get("max_value")
        neg = cfg.get("negative_slope", 0.0) or 0.0
        thr = cfg.get("threshold", 0.0) or 0.0

        def relu_fn(p, xs, t, r):
            x = xs[0]
            y = jnp.where(x >= thr, x, neg * (x - thr))
            if mx is not None:
                y = jnp.minimum(y, mx)
            return y

        return {}, _stateless(relu_fn), {}
    if cn == "LeakyReLU":
        alpha = cfg.get("negative_slope", cfg.get("alpha", 0.3))
        return {}, _stateless(
            lambda p, xs, t, r: jax.nn.leaky_relu(xs[0], alpha)), {}
    if cn == "Softmax":
        axis = cfg.get("axis", -1)
        return {}, _stateless(
            lambda p, xs, t, r: jax.nn.softmax(xs[0], axis=axis)), {}
    if cn == "BatchNormalization":
        return _convert_batchnorm(cfg, weights)
    if cn == "LayerNormalization":
        return (*_convert_layernorm(cfg, weights), {})
    if cn == "Dropout":
        return (*_convert_dropout(cfg, weights), {})
    if cn == "SpatialDropout2D":
        rate = cfg.get("rate", 0.5)

        def sdrop(p, xs, training, rng):
            x = xs[0]
            if not training or rng is None or rate <= 0:
                return x
            # drop whole feature maps: noise shape (N, 1, 1, C)
            keep = jax.random.bernoulli(
                rng, 1.0 - rate, (x.shape[0], 1, 1, x.shape[-1]))
            return jnp.where(keep, x / (1.0 - rate), 0.0)

        return {}, _stateless(sdrop), {}
    if cn == "ZeroPadding2D":
        return (*_convert_zeropad(cfg, weights), {})
    if cn == "Add":
        return (*_merge(jnp.add), {})
    if cn == "Subtract":
        return (*_merge(jnp.subtract), {})
    if cn == "Multiply":
        return (*_merge(jnp.multiply), {})
    if cn == "Maximum":
        return (*_merge(jnp.maximum), {})
    if cn == "Minimum":
        return (*_merge(jnp.minimum), {})
    if cn == "Average":
        p, op = _merge(jnp.add)

        def avg(p2, xs, training, rng, st):
            (y, st2) = op(p2, xs, training, rng, st)
            return y / len(xs), st2

        return p, avg, {}
    if cn == "Concatenate":
        axis = cfg.get("axis", -1)
        return {}, _stateless(
            lambda p, xs, t, r: jnp.concatenate(xs, axis=axis)), {}
    if cn in ("InputLayer",):
        return {}, _stateless(lambda p, xs, t, r: xs[0]), {}
    raise UnsupportedLayerError(f"keras layer {class_name!r}")


# ---------------------------------------------------------------------------
# graph walking
# ---------------------------------------------------------------------------

def _tensor_refs(obj, out: List[Tuple[str, int]]):
    """Recursively collect keras_history refs from serialized call args."""
    if isinstance(obj, dict):
        if obj.get("class_name") == "__keras_tensor__":
            name, node_idx, tensor_idx = obj["config"]["keras_history"]
            if int(tensor_idx) != 0 or int(node_idx) != 0:
                raise UnsupportedLayerError(
                    "multi-output / shared-layer graphs")
            out.append(name)
        else:
            for v in obj.values():
                _tensor_refs(v, out)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            _tensor_refs(v, out)


class GraphProgram:
    """A converted keras model: ordered ops over a name-keyed env.

    ``call(params, state, *inputs, training=, rng=)`` mirrors the native
    layer protocol so KerasModel can drop this into the Estimator.
    """

    def __init__(self, nodes, input_names, output_names, params, state):
        self.nodes = nodes              # [(name, op, parent_names)]
        self.input_names = input_names
        self.output_names = output_names
        self.params = params            # {layer_name: pytree}
        self.state = state              # {layer_name: pytree}

    def call(self, params, state, *inputs, training=False, rng=None):
        if len(inputs) != len(self.input_names):
            raise ValueError(f"expected {len(self.input_names)} inputs, "
                             f"got {len(inputs)}")
        env = dict(zip(self.input_names, inputs))
        new_state = dict(state)
        rngs = (jax.random.split(rng, len(self.nodes))
                if rng is not None else [None] * len(self.nodes))
        for (name, op, parents), r in zip(self.nodes, rngs):
            xs = [env[p] for p in parents]
            env[name], ns = op(params.get(name, {}), xs, training, r,
                               state.get(name, {}))
            if ns:  # only stateful nodes (BN) carry state — keeping the
                new_state[name] = ns  # pytree structure step-stable
        outs = [env[n] for n in self.output_names]
        return (outs[0] if len(outs) == 1 else outs), new_state


def convert_keras_model(model) -> GraphProgram:
    """Convert a tf.keras Sequential/functional model (Keras 3 config
    format) into a GraphProgram with imported weights."""
    cfg = model.get_config()
    layers_cfg = cfg["layers"]
    is_sequential = type(model).__name__ == "Sequential"

    params: Dict[str, Any] = {}
    state: Dict[str, Any] = {}
    nodes = []
    input_names: List[str] = []
    prev_name: Optional[str] = None

    for lc in layers_cfg:
        class_name = lc["class_name"]
        lcfg = lc.get("config", {})
        name = lcfg.get("name") or lc.get("name")
        if class_name == "InputLayer":
            input_names.append(name)
            prev_name = name
            continue
        try:
            klayer = model.get_layer(name)
            weights = [np.asarray(w) for w in klayer.get_weights()]
        except ValueError:
            weights = []
        p, op, st = _convert_layer(class_name, lcfg, weights)
        if is_sequential:
            if prev_name is None:  # no explicit InputLayer
                input_names.append("__seq_input__")
                prev_name = "__seq_input__"
            parents = [prev_name]
        else:
            refs: List[str] = []
            for node in lc.get("inbound_nodes", []):
                _tensor_refs(node, refs)
            if not refs:
                raise UnsupportedLayerError(
                    f"layer {name!r} has no inbound nodes")
            parents = refs
        nodes.append((name, op, parents))
        if p:
            params[name] = p
        if st:
            state[name] = st
        prev_name = name

    if is_sequential:
        output_names = [prev_name]
    else:
        def _names(spec):
            # ['name', 0, 0] or [['name',0,0], ...]
            if spec and isinstance(spec[0], (list, tuple)):
                return [s[0] for s in spec]
            return [spec[0]]

        input_names = _names(cfg["input_layers"])
        output_names = _names(cfg["output_layers"])
    return GraphProgram(nodes, input_names, output_names, params, state)
