from analytics_zoo_tpu.bigdl.loader import (  # noqa: F401
    BigDLModule,
    import_weights_by_name,
    load_bigdl_weights,
)
