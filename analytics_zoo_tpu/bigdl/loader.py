"""BigDL-format model reader (weights-only, pure python).

Reference capability: ``Net.load`` / ``Net.loadBigDL``
(zoo/src/main/scala/com/intel/analytics/zoo/pipeline/api/Net.scala:136-189)
load Analytics-Zoo/BigDL ``.model`` files — the format of the published
pretrained zoo (models/common/ZooModel.scala:183).  Those loaders
deserialize the full JVM module graph; here the GRAPH is rebuilt natively
(models/, nn/) and only the tensors are imported, so the reader decodes
just the protobuf weight payload.

Wire format (reverse-validated against the artifacts the reference
ships: pyzoo/test/zoo/resources/models/bigdl/bigdl_lenet.model and
zoo/src/test/resources/models/zoo_keras/small_*.model):

- The file is one ``BigDLModule`` message: name=1, subModules=2
  (recursive), weight=3, bias=4, preModules=5, nextModules=6,
  moduleType=7, attr map=8 (key=1/value=2 entries), version=9, train=10,
  namePostfix=11, id=12, parameters=16 (repeated tensor).
- ``BigDLTensor``: datatype=1, size=2 (packed), stride=3, offset=4
  (1-based), dimension=5, nElements=6, storage=8, id=9.
- ``TensorStorage``: datatype=1, float_data=2 (packed f32),
  double_data=3, id=9.
- Tensor data is DEDUPLICATED: in-tree tensors carry only ids; the root
  (or a container) attr map holds a ``"global_storage"`` entry — an
  AttrValue whose NameAttrList (field 14) maps tensor-id → AttrValue
  (tensorValue=10) holding the storage with actual data.

Environment note: no BigDL JVM runtime exists in this container (and no
network egress to fetch published zoo artifacts beyond the two shipped
test models), so golden checks assert exact tensor-level parity against
the committed reference artifacts rather than output parity against a
live BigDL process.
"""

from __future__ import annotations

import logging
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("analytics_zoo_tpu.bigdl")

# -- wire primitives (protobuf TLV) -----------------------------------------


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf: bytes):
    pos, n = 0, len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:
            val, pos = _read_varint(buf, pos)
        elif wtype == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wtype == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wtype == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        yield fnum, wtype, val


def _varints(val: bytes) -> List[int]:
    out, pos = [], 0
    while pos < len(val):
        v, pos = _read_varint(val, pos)
        out.append(v)
    return out


# -- decoded structures ------------------------------------------------------


@dataclass
class _Tensor:
    size: Tuple[int, ...] = ()
    offset: int = 1
    n_elements: int = 0
    storage_id: Optional[int] = None
    tensor_id: Optional[int] = None
    data: Optional[np.ndarray] = None       # present only in storage map


@dataclass
class BigDLModule:
    """One node of the decoded module tree (weights resolved)."""

    name: str = ""
    module_type: str = ""
    weight: Optional[np.ndarray] = None
    bias: Optional[np.ndarray] = None
    parameters: List[np.ndarray] = field(default_factory=list)
    children: List["BigDLModule"] = field(default_factory=list)

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()


def _decode_tensor(buf: bytes) -> _Tensor:
    t = _Tensor()
    for fnum, wtype, val in _fields(buf):
        if fnum == 2:
            t.size = tuple(_varints(val) if wtype == 2 else [val])
        elif fnum == 4:
            t.offset = val
        elif fnum == 6:
            t.n_elements = val
        elif fnum == 8:                      # TensorStorage
            # accumulate-and-concatenate (like onnx/proto.py's float
            # handling): a proto2-style unpacked writer emits one field
            # entry PER element, so overwriting t.data would keep only
            # the last one
            chunks: List[np.ndarray] = []
            for f2, w2, v2 in _fields(val):
                if f2 == 2:                  # float_data (packed or not)
                    chunks.append(
                        np.frombuffer(v2, np.float32) if w2 == 2
                        else np.asarray([struct.unpack("<f", v2)[0]],
                                        np.float32))
                elif f2 == 3:                # double_data (packed or not)
                    chunks.append(
                        (np.frombuffer(v2, np.float64) if w2 == 2
                         else np.asarray([struct.unpack("<d", v2)[0]],
                                         np.float64)).astype(np.float32))
                elif f2 == 9:
                    t.storage_id = v2
            if chunks:
                t.data = (chunks[0] if len(chunks) == 1
                          else np.concatenate(chunks))
        elif fnum == 9:
            t.tensor_id = val
    return t


def _decode_attr_storage_map(buf: bytes) -> Dict[int, _Tensor]:
    """AttrValue(nameAttrList=14) → {tensor_id: storage tensor}."""
    out: Dict[int, _Tensor] = {}
    for fnum, _, val in _fields(buf):
        if fnum != 14:
            continue
        for f2, _, v2 in _fields(val):
            if f2 != 2:                      # map entries
                continue
            key, av = None, None
            for f3, _, v3 in _fields(v2):
                if f3 == 1:
                    key = int(v3.decode())
                elif f3 == 2:
                    av = v3
            if key is None or av is None:
                continue
            for f4, _, v4 in _fields(av):
                if f4 == 10:                 # tensorValue
                    out[key] = _decode_tensor(v4)
    return out


def _decode_module(buf: bytes, storages: Dict[int, _Tensor]
                   ) -> BigDLModule:
    m = BigDLModule()
    raw: Dict[str, _Tensor] = {}
    params: List[_Tensor] = []
    for fnum, _, val in _fields(buf):
        if fnum == 1:
            m.name = val.decode()
        elif fnum == 2:
            m.children.append(_decode_module(val, storages))
        elif fnum == 3:
            raw["weight"] = _decode_tensor(val)
        elif fnum == 4:
            raw["bias"] = _decode_tensor(val)
        elif fnum == 7:
            m.module_type = val.decode()
        elif fnum == 8:                      # attr entry: global_storage?
            key, av = None, None
            for f2, _, v2 in _fields(val):
                if f2 == 1:
                    key = v2
                elif f2 == 2:
                    av = v2
            if key == b"global_storage" and av is not None:
                storages.update(_decode_attr_storage_map(av))
        elif fnum == 16:
            params.append(_decode_tensor(val))
    # resolve after the whole subtree parsed (global_storage is an attr
    # of the root/container and may decode after child tensors)
    m._raw, m._raw_params = raw, params      # type: ignore[attr-defined]
    return m


def _resolve(m: BigDLModule, storages: Dict[int, _Tensor],
             by_storage: Dict[int, np.ndarray]) -> None:
    def mat(t: Optional[_Tensor]) -> Optional[np.ndarray]:
        if t is None:
            return None
        data = None
        if t.data is not None:
            data = t.data
        elif t.tensor_id in storages:
            data = storages[t.tensor_id].data
        elif t.storage_id in by_storage:
            data = by_storage[t.storage_id]
        if data is None:
            return None
        if t.n_elements:
            n = t.n_elements
        elif t.size:
            n = int(np.prod(t.size))
        else:
            # a size-less view into (possibly shared) storage has no
            # defensible extent — taking the rest of the buffer is a
            # guess, so say so instead of silently decoding garbage
            logger.warning(
                "tensor without size or nElements (storage_id=%s, "
                "tensor_id=%s): taking the remaining %d storage elements",
                t.storage_id, t.tensor_id, data.size - (t.offset - 1))
            n = data.size
        arr = data[t.offset - 1:t.offset - 1 + n]
        return arr.reshape(t.size) if t.size else arr

    raw = getattr(m, "_raw", {})
    m.weight = mat(raw.get("weight"))
    m.bias = mat(raw.get("bias"))
    m.parameters = [a for a in (mat(t) for t in
                                getattr(m, "_raw_params", []))
                    if a is not None]
    for attr in ("_raw", "_raw_params"):
        if hasattr(m, attr):
            delattr(m, attr)
    for c in m.children:
        _resolve(c, storages, by_storage)


def load_bigdl_weights(path: str) -> BigDLModule:
    """Decode a BigDL/Analytics-Zoo ``.model`` file into a module tree
    with resolved weight/bias arrays (reference Net.scala:136-189,
    weights only — rebuild the graph natively and feed these in)."""
    with open(path, "rb") as f:
        buf = f.read()
    storages: Dict[int, _Tensor] = {}
    root = _decode_module(buf, storages)
    by_storage = {t.storage_id: t.data for t in storages.values()
                  if t.storage_id is not None and t.data is not None}
    _resolve(root, storages, by_storage)
    return root


def _short_type(module_type: str) -> str:
    return module_type.rsplit(".", 1)[-1]


def import_weights_by_name(model, path: str,
                           name_map: Optional[Dict[str, str]] = None,
                           strict: bool = True) -> Dict[str, int]:
    """Copy a ``.model`` file's tensors into a natively built Keras-style
    model, matched by layer name (``name_map`` renames artifact→native).

    Layout conversions applied per module type:
    - SpatialConvolution ``(group, out, in, kh, kw)`` → HWIO
    - Linear ``(out, in)`` → ``(in, out)``
    Returns ``{native_layer_name: tensors_copied}``; with ``strict`` an
    artifact layer with weights but no native counterpart raises.
    """
    root = load_bigdl_weights(path)
    name_map = name_map or {}
    native_names = {lay.name for lay in model.layers}
    seeded: Dict[str, dict] = {}
    copied: Dict[str, int] = {}
    for mod in root.walk():
        if mod.weight is None and not mod.parameters:
            continue
        target_name = name_map.get(mod.name, mod.name)
        if target_name not in native_names:
            if strict:
                raise KeyError(
                    f"artifact layer {mod.name!r} "
                    f"({_short_type(mod.module_type)}) has weights but no "
                    f"native layer named {target_name!r}; pass name_map")
            continue
        kind = _short_type(mod.module_type)
        w, b = mod.weight, mod.bias
        if kind == "SpatialConvolution":
            w = np.squeeze(w, axis=0) if w.ndim == 5 else w
            new = {"kernel": np.transpose(w, (2, 3, 1, 0))}  # → HWIO
            if b is not None:
                new["bias"] = b
        elif kind == "Linear":
            new = {"kernel": np.transpose(w, (1, 0))}
            if b is not None:
                new["bias"] = b
        else:
            raise NotImplementedError(
                f"BigDL module type {kind!r}: add a layout rule here "
                "(only tensors are imported; the graph is native)")
        seeded[target_name] = new
        copied[target_name] = len(new)
    # partial seeding by layer name: the estimator fills uncovered layers
    # from the initializer and warns (KerasNet.set_initial_weights)
    model.set_initial_weights(seeded)
    return copied
