"""InferenceModel: multi-backend, thread-safe serving model.

Reference capability: pipeline/inference/InferenceModel.scala:30-72 (a
LinkedBlockingQueue of cloned models provides request concurrency),
loaders for BigDL/Caffe/TF-frozen/TF-SavedModel/PyTorch/OpenVINO
(InferenceModelFactory.scala, ModelLoader.scala), int8 calibrated variants
(InferenceModel.scala:443), predict APIs (:762-830).

TPU-first redesign:
- No clone queue: an XLA-compiled function is immutable and thread-safe,
  so one jitted forward serves any number of threads.  Concurrency policy
  becomes *batching* policy (`DynamicBatcher`).
- Shape buckets: requests are padded up to the next bucket so the number
  of compiled programs stays bounded (replaces per-shape model clones).
- Foreign models: TF SavedModel / tf.keras ingested via
  ``jax2tf.call_tf`` (host TF executes the graph, JAX orchestrates) or —
  preferred — converted to a pure JAX program with imported weights by
  ``tfpark.convert_keras_model``; torch modules run in-process through
  torch (the reference ran libtorch via JNI in-process too).
- INT8: native weight quantization (per-channel symmetric) replacing the
  reference's OpenVINO calibration — int8 tables live in HBM, dequant is
  fused into the consuming matmul by XLA, halving weight bandwidth.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["InferenceModel", "DynamicBatcher", "quantize_pytree",
           "dequantize_pytree"]


def _as_tuple(x):
    return tuple(x) if isinstance(x, (list, tuple)) else (x,)


def imagenet_preprocess(scale: float = 1.0 / 127.5, offset: float = -1.0,
                        dtype=jnp.bfloat16):
    """On-device normalizer for uint8 image wire format: clients send
    raw uint8 HWC images (4x smaller than float32 on the host→device
    link); the chip casts + affine-normalizes inside the serving
    program.  Default maps [0,255] → [-1,1]."""
    def fn(x):
        return x.astype(dtype) * scale + offset

    return fn


def _next_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


# ---------------------------------------------------------------------------
# int8 weight quantization (reference InferenceModel.scala:443 — OpenVINO
# int8 calibration — replaced by a native AQT-style pass)
# ---------------------------------------------------------------------------

def quantize_pytree(params, min_size: int = 1024):
    """Per-channel symmetric int8 quantization of float leaves.

    Returns a pytree where each quantized leaf becomes
    ``{"q": int8 array, "scale": f32 per-last-axis-channel}``; small or
    non-float leaves pass through unchanged.
    """
    from analytics_zoo_tpu.ops.quantization import quantize_tensor

    def one(leaf):
        a = np.asarray(leaf)
        if a.dtype.kind != "f" or a.size < min_size or a.ndim == 0:
            return leaf
        # per-channel (last axis) for >=2-D; 1-D uses the same machinery
        # with its single axis (ONE shared int8 scheme — see
        # ops/quantization.quantize_tensor)
        if a.ndim >= 2:
            q, scale = quantize_tensor(a, axis=-1)
        else:
            amax = np.max(np.abs(a))
            scale = jnp.asarray([amax / 127.0 if amax > 0 else 1.0],
                                jnp.float32)
            q = jnp.clip(jnp.round(jnp.asarray(a) / scale), -127,
                         127).astype(jnp.int8)
        return {"q": np.asarray(q), "scale": np.asarray(scale, np.float32)}

    return jax.tree_util.tree_map(one, params)


def _is_qleaf(x) -> bool:
    return (isinstance(x, dict) and set(x) == {"q", "scale"})


def dequantize_pytree(qparams):
    """Inverse of quantize_pytree — runs inside jit so XLA fuses the
    int8→f32 dequant into the consuming matmul (weights stay int8 in HBM)."""
    def one(x):
        if _is_qleaf(x):
            return x["q"].astype(jnp.float32) * x["scale"]
        return x

    return jax.tree_util.tree_map(one, qparams, is_leaf=_is_qleaf)


# ---------------------------------------------------------------------------
# InferenceModel
# ---------------------------------------------------------------------------

class InferenceModel:
    """Thread-safe model for serving.

    Construct via one of the loaders::

        m = InferenceModel.load("/path/saved_by_save_model")   # native
        m = InferenceModel.from_keras_net(net, params, state)  # in-process
        m = InferenceModel.load_tf_saved_model(path)           # TF ingest
        m = InferenceModel.load_torch(path_or_module)          # torch

    then ``m.predict(inputs)`` from any number of threads.
    """

    def __init__(self, forward: Callable, batch_buckets: Sequence[int] =
                 (1, 8, 64, 256), dtype=None):
        """``forward``: fn(list_of_np_inputs_padded) -> np output(s) for a
        full padded batch.  Wrapped by bucket padding in predict()."""
        self._forward = forward
        self.batch_buckets = tuple(sorted(batch_buckets))
        self.dtype = dtype

    # -- loaders -----------------------------------------------------------
    @classmethod
    def load(cls, path: str, int8: bool = False, **kw) -> "InferenceModel":
        """Load the native format written by ``ZooModel.save_model`` (a dir
        with config.json + weights.npz) — reference doLoad
        (InferenceModel.scala:86)."""
        from analytics_zoo_tpu.models.common import ZooModel

        zm = ZooModel.load_model(path)
        net = zm.model
        tree = getattr(zm, "_pending_weights", None)
        if tree is None:
            raise FileNotFoundError(f"{path} has no weights.npz")
        return cls.from_keras_net(net, tree["params"], tree.get("state", {}),
                                  int8=int8, **kw)

    @classmethod
    def from_keras_net(cls, net, params, state=None, int8: bool = False,
                       preprocess: Optional[Callable] = None,
                       **kw) -> "InferenceModel":
        """Wrap a built KerasNet + weights as a serving model.

        ``preprocess``: optional jax fn run ON DEVICE inside the same
        compiled program as the forward pass (fn(*raw) -> model input(s)).
        Lets clients ship compact wire dtypes — e.g. uint8 images
        normalized on-chip — so the host→device link carries 4x fewer
        bytes than float32 (see ``deploy.imagenet_preprocess``)."""
        state = state or {}

        def _match_compute_dtype(p, s, xs):
            """A preprocess emitting bf16 (e.g. imagenet_preprocess's
            uint8→bf16 wire path) selects bf16 INFERENCE: float params
            AND state (BN stats) cast to the input dtype in-program (XLA
            folds the casts), outputs return as float32 for the client."""
            from analytics_zoo_tpu.train.estimator import _cast_floats

            floats = [x.dtype for x in xs
                      if jnp.issubdtype(x.dtype, jnp.floating)]
            cd = jnp.result_type(*floats) if floats else jnp.float32
            if cd != jnp.float32:
                p = _cast_floats(p, cd)
                s = _cast_floats(s, cd)
            return p, s

        def _f32_out(out):
            cast = (lambda o: o.astype(jnp.float32)
                    if jnp.issubdtype(o.dtype, jnp.floating) else o)
            return ([cast(o) for o in out]
                    if isinstance(out, (list, tuple)) else cast(out))

        if int8:
            qparams = quantize_pytree(params)

            @jax.jit
            def fwd(*xs):
                if preprocess is not None:
                    xs = _as_tuple(preprocess(*xs))
                p, s2 = _match_compute_dtype(dequantize_pytree(qparams),
                                             state, xs)
                out, _ = net.call(p, s2, *xs, training=False)
                return _f32_out(out)
        else:
            @jax.jit
            def fwd(*xs):
                if preprocess is not None:
                    xs = _as_tuple(preprocess(*xs))
                p, s2 = _match_compute_dtype(params, state, xs)
                out, _ = net.call(p, s2, *xs, training=False)
                return _f32_out(out)

        def forward(inputs: List[np.ndarray]):
            return fwd(*[jnp.asarray(x) for x in inputs])

        m = cls(forward, **kw)
        m._net, m._params, m._int8 = net, params, int8
        return m

    @classmethod
    def load_onnx(cls, path: str, int8: bool = False,
                  calibration_inputs=None, **kw) -> "InferenceModel":
        """Serve an .onnx file (onnx/loader.py).  ``int8=True`` runs
        post-training quantization: Gemm/MatMul nodes execute as int8
        MXU matmuls (ops/quantization.py) — with ``calibration_inputs``
        the activation scales are static (calibrated), otherwise dynamic.
        Replaces the reference's OpenVINO int8 path
        (InferenceModel.scala:443)."""
        from analytics_zoo_tpu.onnx import load_onnx

        program = load_onnx(path)
        if int8:
            from analytics_zoo_tpu.ops.quantization import quantize_program

            program = quantize_program(program, calibration_inputs)

        @jax.jit
        def fwd(*xs):
            out, _ = program.call(program.params, program.state, *xs,
                                  training=False)
            return out

        def forward(inputs: List[np.ndarray]):
            return fwd(*[jnp.asarray(x) for x in inputs])

        m = cls(forward, **kw)
        m._program, m._int8 = program, int8
        return m

    @classmethod
    def from_function(cls, fn: Callable, jit: bool = True,
                      **kw) -> "InferenceModel":
        """Serve an arbitrary jax function of the inputs."""
        jfn = jax.jit(fn) if jit else fn

        def forward(inputs: List[np.ndarray]):
            return jfn(*[jnp.asarray(x) for x in inputs])

        return cls(forward, **kw)

    @classmethod
    def load_tf_saved_model(cls, path: str, signature: str =
                            "serving_default", **kw) -> "InferenceModel":
        """Ingest a TF SavedModel via jax2tf.call_tf (reference
        doLoadTF/TFNet.fromSavedModel, TFNet.scala:654).  The TF graph
        executes on the host; JAX owns the calling side."""
        import tensorflow as tf  # gated: raises if TF absent
        from jax.experimental import jax2tf

        loaded = tf.saved_model.load(path)
        f = loaded.signatures[signature]
        call = jax2tf.call_tf(f)

        def forward(inputs: List[np.ndarray]):
            out = call(*[jnp.asarray(x) for x in inputs])
            if isinstance(out, dict):  # signature outputs are dicts
                vals = list(out.values())
                return vals[0] if len(vals) == 1 else vals
            return out

        m = cls(forward, **kw)
        m._tf_model = loaded  # keep alive
        return m

    @classmethod
    def load_tf_keras(cls, model_or_path, **kw) -> "InferenceModel":
        """Ingest a tf.keras model (object or .keras/.h5 path) —
        reference KerasModel serving (tfpark/model.py:34)."""
        import tensorflow as tf
        from jax.experimental import jax2tf

        model = (model_or_path if not isinstance(model_or_path, str)
                 else tf.keras.models.load_model(model_or_path))
        fn = tf.function(lambda *xs: model(*xs, training=False),
                         autograph=False)
        call = jax2tf.call_tf(fn)

        def forward(inputs: List[np.ndarray]):
            return call(*[jnp.asarray(x) for x in inputs])

        m = cls(forward, **kw)
        m._tf_model = model
        return m

    @classmethod
    def load_torch(cls, model_or_path, **kw) -> "InferenceModel":
        """Ingest a TorchScript file or torch.nn.Module (reference
        TorchNet.scala:39 — libtorch ran in-process via JNI; here torch
        runs in-process on the host CPU)."""
        import torch

        model = (torch.jit.load(model_or_path)
                 if isinstance(model_or_path, str) else model_or_path)
        model.eval()

        def forward(inputs: List[np.ndarray]):
            with torch.no_grad():
                out = model(*[torch.from_numpy(np.asarray(x))
                              for x in inputs])
            if isinstance(out, (tuple, list)):
                return [o.numpy() for o in out]
            return out.numpy()

        m = cls(forward, **kw)
        m._torch_model = model
        return m

    # -- predict -----------------------------------------------------------
    def predict(self, inputs, batch_size: Optional[int] = None):
        """Predict on one batch (list of arrays or a single array).

        Rows are padded up to the next batch bucket so repeated calls with
        ragged sizes reuse a bounded set of compiled programs (the
        reference bounded concurrency with a model-clone pool instead —
        InferenceModel.scala:67).  ``batch_size`` caps the per-program
        device batch (overrides the bucket for this call).
        """
        xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        xs = [np.asarray(x) for x in xs]
        n = xs[0].shape[0]
        bucket = (min(batch_size, _next_bucket(n, self.batch_buckets))
                  if batch_size else _next_bucket(n, self.batch_buckets))
        if bucket > n:
            xs = [np.concatenate(
                [x, np.repeat(x[-1:], bucket - n, axis=0)], axis=0)
                for x in xs]
        elif bucket < n:  # larger than biggest bucket (or capped): chunk
            outs = [self.predict([x[s:s + bucket] for x in xs],
                                 batch_size=bucket)
                    for s in range(0, n, bucket)]
            if isinstance(outs[0], list):
                return [np.concatenate([o[i] for o in outs], axis=0)
                        for i in range(len(outs[0]))]
            return np.concatenate(outs, axis=0)
        out = self._forward(xs)
        if isinstance(out, (list, tuple)):
            return [np.asarray(o)[:n] for o in out]
        return np.asarray(out)[:n]

    # reference predict-API aliases (InferenceModel.scala:762-830)
    do_predict = predict

    def predict_classes(self, inputs, **kw) -> np.ndarray:
        out = self.predict(inputs, **kw)
        if isinstance(out, list):
            out = out[0]
        return np.argmax(out, axis=-1)


# ---------------------------------------------------------------------------
# Dynamic batching — the TPU replacement for the model-clone queue
# ---------------------------------------------------------------------------

class DynamicBatcher:
    """Groups concurrent predict() calls into device batches.

    Reference InferenceModel served N threads with N model clones
    (InferenceModel.scala:30-72); on TPU one compiled program is already
    thread-safe, so the win is *coalescing* small requests into one MXU
    batch: requests wait at most ``max_latency_ms`` for peers.
    """

    def __init__(self, model: InferenceModel, max_batch: int = 64,
                 max_latency_ms: float = 5.0):
        self.model = model
        self.max_batch = max_batch
        self.max_latency = max_latency_ms / 1e3
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def predict(self, inputs) -> Any:
        """Enqueue one request (single example or small batch); blocks
        until its slice of the fused batch returns."""
        if self._stop.is_set():
            raise RuntimeError("DynamicBatcher is closed")
        xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        xs = [np.asarray(x) for x in xs]
        done = threading.Event()
        slot: Dict[str, Any] = {}
        self._q.put((xs, done, slot))
        while not done.wait(timeout=1.0):
            if self._stop.is_set() and not done.is_set():
                # raced with close(): the worker may have exited before
                # popping this request — close() drains, but don't hang
                raise RuntimeError("DynamicBatcher closed while waiting")
        if "error" in slot:
            raise slot["error"]
        return slot["out"]

    def close(self):
        self._stop.set()
        self._thread.join(timeout=2)
        # fail any requests still queued so no caller blocks forever
        while True:
            try:
                _, done, slot = self._q.get_nowait()
            except queue.Empty:
                break
            slot["error"] = RuntimeError("DynamicBatcher closed")
            done.set()

    def _loop(self):
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            batch = [first]
            deadline = time.monotonic() + self.max_latency
            rows = first[0][0].shape[0]
            while rows < self.max_batch:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                try:
                    req = self._q.get(timeout=timeout)
                except queue.Empty:
                    break
                batch.append(req)
                rows += req[0][0].shape[0]
            try:
                fused = [np.concatenate([b[0][i] for b in batch], axis=0)
                         for i in range(len(batch[0][0]))]
                out = self.model.predict(fused)
                outs = out if isinstance(out, list) else [out]
                s = 0
                for xs, done, slot in batch:
                    n = xs[0].shape[0]
                    sliced = [o[s:s + n] for o in outs]
                    slot["out"] = (sliced if isinstance(out, list)
                                   else sliced[0])
                    s += n
                    done.set()
            except Exception as e:  # surface errors to every waiter
                for _, done, slot in batch:
                    slot["error"] = e
                    done.set()
