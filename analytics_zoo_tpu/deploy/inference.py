"""InferenceModel: multi-backend, thread-safe serving model.

Reference capability: pipeline/inference/InferenceModel.scala:30-72 (a
LinkedBlockingQueue of cloned models provides request concurrency),
loaders for BigDL/Caffe/TF-frozen/TF-SavedModel/PyTorch/OpenVINO
(InferenceModelFactory.scala, ModelLoader.scala), int8 calibrated variants
(InferenceModel.scala:443), predict APIs (:762-830).

TPU-first redesign:
- No clone queue: an XLA-compiled function is immutable and thread-safe,
  so one jitted forward serves any number of threads.  Concurrency policy
  becomes *batching* policy (`DynamicBatcher`).
- Shape buckets: requests are padded up to the next bucket so the number
  of compiled programs stays bounded (replaces per-shape model clones).
- Foreign models: TF SavedModel / tf.keras ingested via
  ``jax2tf.call_tf`` (host TF executes the graph, JAX orchestrates) or —
  preferred — converted to a pure JAX program with imported weights by
  ``tfpark.convert_keras_model``; torch modules run in-process through
  torch (the reference ran libtorch via JNI in-process too).
- INT8: native weight quantization (per-channel symmetric) replacing the
  reference's OpenVINO calibration — int8 tables live in HBM, dequant is
  fused into the consuming matmul by XLA, halving weight bandwidth.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["InferenceModel", "DynamicBatcher", "BatchRequest",
           "ModelReplica", "scatter_batch_results", "quantize_pytree",
           "dequantize_pytree", "plan_buckets", "bucket_class",
           "LONG_DOC_TOKENS", "DEFAULT_MODEL"]

# the implicit model name for single-model serving paths; multi-model
# callers (ClusterServing with a dict of models) use their own names
DEFAULT_MODEL = "default"


def _as_tuple(x):
    return tuple(x) if isinstance(x, (list, tuple)) else (x,)


def imagenet_preprocess(scale: float = 1.0 / 127.5, offset: float = -1.0,
                        dtype=jnp.bfloat16):
    """On-device normalizer for uint8 image wire format: clients send
    raw uint8 HWC images (4x smaller than float32 on the host→device
    link); the chip casts + affine-normalizes inside the serving
    program.  Default maps [0,255] → [-1,1]."""
    def fn(x):
        return x.astype(dtype) * scale + offset

    return fn


def _next_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


# Requests at or past this many tokens belong to the "long_doc" bucket
# class: attention compute is O(L²)-dominated, so fusing rows into wide
# batch buckets only multiplies an already-saturating program.  Long-doc
# batches plan at the SMALLEST row bucket and the executor routes them
# to a mesh replica whose attention shards L ring-wise over the mesh
# (ops/ring_attention.py) — per-chip memory O(L/ways).
LONG_DOC_TOKENS = 32768


def bucket_class(tokens: Optional[int]) -> str:
    """Which bucket class a request of ``tokens`` sequence length falls
    in: ``"long_doc"`` (>= LONG_DOC_TOKENS) or ``"short"``."""
    return ("long_doc" if tokens is not None
            and int(tokens) >= LONG_DOC_TOKENS else "short")


def plan_buckets(n: int, buckets: Sequence[int],
                 tokens: Optional[int] = None) -> List[tuple]:
    """Split ``n`` rows into ``[(rows, bucket), ...]`` chunks.

    Full ``buckets[-1]``-row chunks first, then one tail chunk padded up
    to its nearest bucket.  This is THE bucket-overflow policy: both the
    compile-shape ledger (`InferenceModel.predict`) and the executor's
    replica dispatch (`serving.DeviceExecutor._dispatch`) plan through
    it, so the set of program shapes they produce can never disagree.

    ``tokens`` (the request's sequence length) selects the bucket class:
    in the ``"long_doc"`` class (>= LONG_DOC_TOKENS) every chunk is the
    SMALLEST row bucket — each sequence-saturated program owns the whole
    mesh replica, and the compiled-shape set stays one program per class
    instead of one per (rows × length) combination.
    """
    if bucket_class(tokens) == "long_doc":
        cap = buckets[0]
        return [(min(n - s, cap), cap) for s in range(0, n, cap)]
    out: List[tuple] = []
    cap = buckets[-1]
    s = 0
    while s < n:
        m = min(n - s, cap)
        out.append((m, _next_bucket(m, buckets)))
        s += m
    return out


def _match_compute_dtype(p, s, xs):
    """A preprocess emitting bf16 (e.g. imagenet_preprocess's uint8→bf16
    wire path) selects bf16 INFERENCE: float params AND state (BN stats)
    cast to the input dtype in-program (XLA folds the casts), outputs
    return as float32 for the client."""
    from analytics_zoo_tpu.train.estimator import _cast_floats

    floats = [x.dtype for x in xs
              if jnp.issubdtype(x.dtype, jnp.floating)]
    cd = jnp.result_type(*floats) if floats else jnp.float32
    if cd != jnp.float32:
        p = _cast_floats(p, cd)
        s = _cast_floats(s, cd)
    return p, s


def _f32_out(out):
    cast = (lambda o: o.astype(jnp.float32)
            if jnp.issubdtype(o.dtype, jnp.floating) else o)
    return ([cast(o) for o in out]
            if isinstance(out, (list, tuple)) else cast(out))


# ---------------------------------------------------------------------------
# int8 weight quantization (reference InferenceModel.scala:443 — OpenVINO
# int8 calibration — replaced by a native AQT-style pass)
# ---------------------------------------------------------------------------

def quantize_pytree(params, min_size: int = 1024, bits: int = 8):
    """Per-channel symmetric quantization of float leaves.

    ``bits=8``: each quantized leaf becomes ``{"q": int8 array, "scale":
    f32 per-last-axis-channel}``.  ``bits=4``: 2-D leaves with an even
    row count become ``{"q4": nibble-packed int8, "scale": f32}`` at 1/8
    the f32 footprint (ops/dequant_matmul.pack_int4); other leaves keep
    the int8 scheme (int4 packs along the contraction axis, which only
    a matmul weight has).  Small or non-float leaves pass through
    unchanged.  The leaf KEY ("q" vs "q4") carries the storage format —
    pytree structure stays static under jit, so the serving forward can
    route on it.
    """
    from analytics_zoo_tpu.ops.dequant_matmul import quantize_weights
    from analytics_zoo_tpu.ops.quantization import quantize_tensor

    def one(leaf):
        a = np.asarray(leaf)
        if a.dtype.kind != "f" or a.size < min_size or a.ndim == 0:
            return leaf
        if bits == 4 and a.ndim == 2 and a.shape[0] % 2 == 0:
            q4, scale = quantize_weights(a, bits=4)
            return {"q4": np.asarray(q4),
                    "scale": np.asarray(scale, np.float32)}
        # per-channel (last axis) for >=2-D; 1-D uses the same machinery
        # with its single axis (ONE shared int8 scheme — see
        # ops/quantization.quantize_tensor)
        if a.ndim >= 2:
            q, scale = quantize_tensor(a, axis=-1)
        else:
            amax = np.max(np.abs(a))
            scale = jnp.asarray([amax / 127.0 if amax > 0 else 1.0],
                                jnp.float32)
            q = jnp.clip(jnp.round(jnp.asarray(a) / scale), -127,
                         127).astype(jnp.int8)
        return {"q": np.asarray(q), "scale": np.asarray(scale, np.float32)}

    return jax.tree_util.tree_map(one, params)


def _is_qleaf(x) -> bool:
    return (isinstance(x, dict)
            and set(x) in ({"q", "scale"}, {"q4", "scale"}))


def dequantize_pytree(qparams):
    """Inverse of quantize_pytree — runs inside jit so XLA fuses the
    int8→f32 dequant into the consuming matmul (weights stay int8 in HBM)."""
    from analytics_zoo_tpu.ops.dequant_matmul import unpack_int4

    def one(x):
        if not _is_qleaf(x):
            return x
        if "q4" in x:  # zoolint: disable=JG-TRACED-BRANCH(dict-key membership is static pytree structure, not a traced value)
            q = unpack_int4(x["q4"], 2 * x["q4"].shape[0])
            return q.astype(jnp.float32) * x["scale"]
        return x["q"].astype(jnp.float32) * x["scale"]

    return jax.tree_util.tree_map(one, qparams, is_leaf=_is_qleaf)


def _dense_layer_names(net) -> set:
    """Names of Dense layers in a net — their quantized kernels stay
    packed through the serving forward (Dense fuses the dequant into the
    matmul via ops/dequant_matmul.py); every other quantized leaf is
    dequantized up front."""
    from analytics_zoo_tpu.nn.layers.core import Dense

    try:
        return {lyr.name for lyr in net.layers if isinstance(lyr, Dense)}
    except Exception:
        return set()


def _dequant_for_forward(qparams, dense_names):
    """Dequantize quantized leaves, EXCEPT Dense kernels, which pass
    through as q-leaves for the fused dequantize-matmul path."""
    if not isinstance(qparams, dict):
        return dequantize_pytree(qparams)
    out = {}
    for lname, sub in qparams.items():
        if lname in dense_names and isinstance(sub, dict):  # zoolint: disable=JG-TRACED-BRANCH(layer names are static python strings, not traced values)
            out[lname] = {
                k: (v if k == "kernel" and _is_qleaf(v)
                    else dequantize_pytree(v))
                for k, v in sub.items()}
        else:
            out[lname] = dequantize_pytree(sub)
    return out


# ---------------------------------------------------------------------------
# InferenceModel
# ---------------------------------------------------------------------------

class ModelReplica:
    """One serving replica: ``dispatch(xs)`` enqueues the computation and
    returns a handle immediately (device futures for native models);
    ``harvest(handle)`` performs the blocking readback and returns a list
    of np output arrays.  The split is what lets the device executor
    double-buffer: dispatch batch N+1 while N's readback is in flight."""

    def __init__(self, dispatch: Callable, harvest: Callable, device=None,
                 on_device_topn: bool = False, pads_input: bool = True):
        self.dispatch = dispatch
        self.harvest = harvest
        self.device = device
        self.on_device_topn = on_device_topn
        # False = dispatch() already handles buckets/slicing (the shared
        # predict() fallback); True = the executor pads to a bucket
        self.pads_input = pads_input

class InferenceModel:
    """Thread-safe model for serving.

    Construct via one of the loaders::

        m = InferenceModel.load("/path/saved_by_save_model")   # native
        m = InferenceModel.from_keras_net(net, params, state)  # in-process
        m = InferenceModel.load_tf_saved_model(path)           # TF ingest
        m = InferenceModel.load_torch(path_or_module)          # torch

    then ``m.predict(inputs)`` from any number of threads.
    """

    def __init__(self, forward: Callable, batch_buckets: Sequence[int] =
                 (1, 8, 64, 256), dtype=None, name: str = DEFAULT_MODEL):
        """``forward``: fn(list_of_np_inputs_padded) -> np output(s) for a
        full padded batch.  Wrapped by bucket padding in predict().
        ``name`` labels this model's series in every serving metric."""
        self._forward = forward
        self.batch_buckets = tuple(sorted(batch_buckets))
        self.dtype = dtype
        self.name = str(name)
        # program-shape ledger: one entry per distinct batch signature
        # actually dispatched that paid a LIVE XLA compile.  Tests assert
        # on it to prove the bounded-program contract (novel large
        # batches split into full-bucket programs instead of compiling
        # one-off shapes).  Signatures pre-installed from the persistent
        # compile cache land in ``_warm_shapes`` instead, so a warm
        # restart holds ``compile_count == 0`` — the warm-start proof.
        self._seen_shapes = set()
        self._warm_shapes = set()
        self._shape_lock = threading.Lock()
        self._net = None
        self._weight_dtype = "float32"
        # persistent AOT compile cache (deploy/compile_cache.py):
        # attached via attach_compile_cache(); _programs maps a JSON sig
        # key to a loaded/compiled executable
        self._cache = None
        self._fingerprint_cache: Optional[str] = None
        self._programs: Dict[str, Any] = {}
        self._param_fwds: Dict[Any, Any] = {}
        self._programs_lock = threading.Lock()
        self._pred_weights = None

    # expose the bucket lowering on the class (callers/tests reach it as
    # InferenceModel._next_bucket)
    _next_bucket = staticmethod(_next_bucket)

    def _note_shapes(self, xs, tag: str = "") -> bool:
        """Record the batch signature about to be dispatched; True (and a
        ``inference/novel_batch_shape`` counter bump) on first sight —
        i.e. when this dispatch pays an XLA compile.  Signatures the
        compile cache pre-installed (``warm()``) are not novel: their
        executable is already resident, no compile is paid."""
        sig = (tag,) + tuple((tuple(np.shape(x)),
                              str(getattr(x, "dtype", ""))) for x in xs)
        with self._shape_lock:
            if sig in self._seen_shapes or sig in self._warm_shapes:
                return False
            self._seen_shapes.add(sig)
            live = len(self._seen_shapes)
        from analytics_zoo_tpu.observe import metrics as obs

        obs.count("inference_novel_batch_shapes_total", model=self.name,
                  flat="inference/novel_batch_shape")
        obs.set_gauge("inference_compile_count", live, model=self.name)
        return True

    @property
    def compile_count(self) -> int:
        """Number of distinct program shapes that paid a live compile
        (cache-warmed shapes excluded)."""
        with self._shape_lock:
            return len(self._seen_shapes)

    @property
    def warm_count(self) -> int:
        """Number of program shapes pre-installed from the compile cache."""
        with self._shape_lock:
            return len(self._warm_shapes)

    # -- persistent AOT compile cache --------------------------------------
    def fingerprint(self) -> str:
        """Content hash of this model's weights: net class + weight dtype
        + per-leaf (path, shape, dtype, CRC32 of the bytes).  The compile
        cache keys on it so an executable can never be replayed against
        different weights/architecture than it was compiled for."""
        if self._fingerprint_cache is not None:
            return self._fingerprint_cache
        import hashlib
        import struct
        import zlib

        h = hashlib.sha256()
        h.update((type(self._net).__name__ if self._net is not None
                  else "<fn>").encode())
        h.update(self._weight_dtype.encode())
        weights = (self._qparams if getattr(self, "_int8", False)
                   else getattr(self, "_params", None))
        for tree in (weights, getattr(self, "_state", None)):
            if tree is None:
                continue
            for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
                a = np.asarray(leaf)
                h.update(jax.tree_util.keystr(path).encode())
                h.update(str(a.shape).encode())
                h.update(str(a.dtype).encode())
                h.update(struct.pack(
                    "<I", zlib.crc32(a.tobytes()) & 0xFFFFFFFF))
        self._fingerprint_cache = h.hexdigest()[:16]
        return self._fingerprint_cache

    def weight_nbytes(self) -> int:
        """Per-replica HBM weight footprint — what the multi-model HBM
        budget (`serving_hbm_budget_bytes`) charges per replica slot.
        Function/foreign models have no explicit weight tree: 0."""
        weights = (self._qparams if getattr(self, "_int8", False)
                   else getattr(self, "_params", None))
        if weights is None:
            return 0
        total = 0
        for leaf in jax.tree_util.tree_leaves(weights):
            total += np.asarray(leaf).nbytes
        for leaf in jax.tree_util.tree_leaves(getattr(self, "_state", None)
                                              or {}):
            total += np.asarray(leaf).nbytes
        return total

    def attach_compile_cache(self, cache, name: Optional[str] = None
                             ) -> "InferenceModel":
        """Wire a ``deploy.compile_cache.CompileCache`` into the dispatch
        path: every bucketed program is AOT-lowered
        (``fwd.lower(...).compile()``), persisted on first compile, and
        reloaded from disk on the next process start (``warm()``).

        Only models with a native net qualify — foreign forwards
        (TF/torch/function) have no param-explicit program to serialize.
        """
        if self._net is None:
            raise ValueError(
                "attach_compile_cache needs a native net (from_keras_net/"
                "load); TF/torch/function models have no param-explicit "
                "XLA program to serialize")
        self._cache = cache
        if name:
            self.name = str(name)
        return self

    @staticmethod
    def _aot_sig(xs, device, top_n) -> Dict[str, Any]:
        """JSON-able program signature: input shapes/dtypes + target
        device + fused top-N.  Joined with ``fingerprint()`` (and the
        mesh descriptor, added by the cache) it addresses one executable."""
        return {"in": [[list(np.shape(x)), str(getattr(x, "dtype", ""))]
                       for x in xs],
                "dev": str(device) if device is not None else "",
                "top_n": int(top_n or 0)}

    @staticmethod
    def _warm_sig(sig: Dict[str, Any]):
        """The ``_note_shapes`` ledger key a cached sig corresponds to."""
        return ((sig.get("dev", ""),)
                + tuple((tuple(s), d) for s, d in sig["in"]))

    def _param_forward_for(self, top_n):
        with self._programs_lock:
            fwd = self._param_fwds.get(top_n)
            if fwd is None:
                fwd = self._build_param_forward(top_n=top_n)
                self._param_fwds[top_n] = fwd
        return fwd

    def _aot_program(self, p, s, xs, device=None, top_n=None, fwd=None):
        """The executable for one program signature: in-memory table →
        disk cache → live ``lower().compile()`` (which is then persisted
        so the NEXT process start skips it).  ``fwd`` overrides which
        jitted forward lowers on a miss (the sharded mesh-replica path
        traces with its table mode baked in; its ``device`` descriptor
        keeps the cache entries distinct)."""
        sig = self._aot_sig(xs, device, top_n)
        import json
        key = json.dumps(sig, sort_keys=True)
        with self._programs_lock:
            prog = self._programs.get(key)
        if prog is not None:
            return prog
        prog = self._cache.load(self.fingerprint(), sig, model=self.name)
        if prog is None:
            if fwd is None:
                fwd = self._param_forward_for(top_n)
            prog = fwd.lower(p, s, *xs).compile()
            self._cache.store(self.fingerprint(), sig, prog,
                              model=self.name)
        with self._programs_lock:
            self._programs[key] = prog
        return prog

    def warm(self) -> int:
        """Pre-install every cached executable for this model's
        fingerprint.  A restarted process reaches full bucket coverage
        here, in deserialization time, instead of after N live compiles
        — and ``compile_count`` stays 0 for every warmed shape (the
        acceptance proof for the ``serving_restart_to_slo`` bench).
        Returns the number of programs installed."""
        if self._cache is None:
            return 0
        import json
        n = 0
        for sig, prog in self._cache.load_all(self.fingerprint(),
                                              model=self.name):
            key = json.dumps(sig, sort_keys=True)
            with self._programs_lock:
                self._programs[key] = prog
            with self._shape_lock:
                self._warm_shapes.add(self._warm_sig(sig))
            n += 1
        return n

    # -- loaders -----------------------------------------------------------
    @classmethod
    def load(cls, path: str, int8: bool = False,
             weight_dtype: Optional[str] = None, **kw) -> "InferenceModel":
        """Load the native format written by ``ZooModel.save_model`` (a dir
        with config.json + weights.npz) — reference doLoad
        (InferenceModel.scala:86)."""
        from analytics_zoo_tpu.models.common import ZooModel

        zm = ZooModel.load_model(path)
        net = zm.model
        tree = getattr(zm, "_pending_weights", None)
        if tree is None:
            raise FileNotFoundError(f"{path} has no weights.npz")
        return cls.from_keras_net(net, tree["params"], tree.get("state", {}),
                                  int8=int8, weight_dtype=weight_dtype, **kw)

    @staticmethod
    def _resolve_weight_dtype(weight_dtype: Optional[str],
                              int8: bool) -> str:
        """None defers to the legacy ``int8`` flag, then to the global
        ``serving_weight_dtype`` knob (no context = float32)."""
        if weight_dtype is None:
            if int8:
                return "int8"
            from analytics_zoo_tpu.ops.dispatch import config_knob

            weight_dtype = config_knob("serving_weight_dtype", "float32")
        if weight_dtype not in ("float32", "int8", "int4"):
            raise ValueError(
                f"serving weight_dtype must be float32|int8|int4, got "
                f"{weight_dtype!r}")
        return weight_dtype

    @classmethod
    def from_keras_net(cls, net, params, state=None, int8: bool = False,
                       preprocess: Optional[Callable] = None,
                       weight_dtype: Optional[str] = None,
                       **kw) -> "InferenceModel":
        """Wrap a built KerasNet + weights as a serving model.

        ``preprocess``: optional jax fn run ON DEVICE inside the same
        compiled program as the forward pass (fn(*raw) -> model input(s)).
        Lets clients ship compact wire dtypes — e.g. uint8 images
        normalized on-chip — so the host→device link carries 4x fewer
        bytes than float32 (see ``deploy.imagenet_preprocess``).

        ``weight_dtype``: replica weight storage — "float32", "int8"
        (1/4 HBM footprint) or "int4" (1/8); ``None`` resolves the
        legacy ``int8`` flag, then the ``serving_weight_dtype`` config
        knob.  Quantized Dense kernels stay packed end-to-end: the
        forward dequantizes them inside the matmul
        (ops/dequant_matmul.py — the fused Pallas kernel on TPU)."""
        state = state or {}
        weight_dtype = cls._resolve_weight_dtype(weight_dtype, int8)
        quantized = weight_dtype != "float32"
        qparams = (quantize_pytree(params,
                                   bits=4 if weight_dtype == "int4" else 8)
                   if quantized else None)
        dense_names = _dense_layer_names(net) if quantized else set()

        if quantized:
            @jax.jit
            def fwd(*xs):
                if preprocess is not None:
                    xs = _as_tuple(preprocess(*xs))
                p, s2 = _match_compute_dtype(
                    _dequant_for_forward(qparams, dense_names), state, xs)
                out, _ = net.call(p, s2, *xs, training=False)
                return _f32_out(out)
        else:
            @jax.jit
            def fwd(*xs):
                if preprocess is not None:
                    xs = _as_tuple(preprocess(*xs))
                p, s2 = _match_compute_dtype(params, state, xs)
                out, _ = net.call(p, s2, *xs, training=False)
                return _f32_out(out)

        def forward(inputs: List[np.ndarray]):
            return fwd(*[jnp.asarray(x) for x in inputs])

        m = cls(forward, **kw)
        m._net, m._params, m._int8 = net, params, quantized
        m._weight_dtype = weight_dtype
        m._state, m._preprocess, m._qparams = state, preprocess, qparams
        return m

    # -- replicas ----------------------------------------------------------
    def _build_param_forward(self, top_n: Optional[int] = None,
                             table_shard=None):
        """One jitted forward taking (params, state, *xs) explicitly, so
        the same traced program runs on whichever device its arguments
        live on — the building block for per-device serving replicas.
        ``top_n`` fuses top-k into the program (scores never leave the
        chip: the readback is 2*top_n scalars per row, not the logits).
        ``table_shard`` (a ``parallel.mode.TableShardMode``) is entered
        INSIDE the traced body, so the listed embedding tables lower to
        the ``shard_map`` local-bag + psum exchange at trace time —
        the mesh-replica forward for row-sharded giant tables."""
        import contextlib

        net, pre, int8 = self._net, self._preprocess, self._int8
        dense_names = _dense_layer_names(net) if int8 else set()
        if table_shard is not None:
            from analytics_zoo_tpu.parallel.mode import table_mode
        else:
            table_mode = None

        @jax.jit
        def fwd(p, s, *xs):
            ctx = (table_mode(table_shard) if table_shard is not None
                   else contextlib.nullcontext())
            with ctx:
                if pre is not None:
                    xs = _as_tuple(pre(*xs))
                if int8:
                    p = _dequant_for_forward(p, dense_names)
                p2, s2 = _match_compute_dtype(p, s, xs)
                out, _ = net.call(p2, s2, *xs, training=False)
                out = _f32_out(out)
                if top_n:
                    o = out[0] if isinstance(out, (list, tuple)) else out
                    v, i = jax.lax.top_k(o, top_n)
                    return i.astype(jnp.int32), v
                return out

        return fwd

    def replica_forwards(self, n: int = 1, devices=None,
                         top_n: Optional[int] = None
                         ) -> List["ModelReplica"]:
        """``n`` per-device serving replicas with *async* dispatch.

        Models built from a native net (``from_keras_net`` / ``load``)
        get true replicas: the weights are placed once per device and
        each dispatch runs on its own chip, so a round-robin executor
        keeps every chip busy.  Foreign loaders (TF/torch/ONNX/function)
        fall back to sharing the base forward — it is thread-safe, just
        not multi-device.
        """
        if devices is None:
            from analytics_zoo_tpu.parallel.sharding import replica_devices

            try:
                from analytics_zoo_tpu.core.context import _GLOBAL_CONTEXT
                devices = (replica_devices(_GLOBAL_CONTEXT.mesh)
                           if _GLOBAL_CONTEXT is not None else jax.devices())
            except Exception:
                devices = jax.devices()
        devices = list(devices)[:max(1, int(n))]
        if self._net is None:
            # shared-forward fallback: predict() handles buckets/top-N
            model = self

            def dispatch(xs, _m=model):
                return _m.predict(xs)

            def harvest(h):
                return h if isinstance(h, list) else [h]

            return [ModelReplica(dispatch, harvest, device=None,
                                 on_device_topn=False, pads_input=False)
                    for _ in devices]
        fwd = self._build_param_forward(top_n=top_n)
        weights = self._qparams if self._int8 else self._params
        out = []
        for dev in devices:
            p_i = jax.device_put(weights, dev)
            s_i = jax.device_put(self._state, dev)

            def dispatch(xs, _p=p_i, _s=s_i, _d=dev):
                # async: device_put and the jitted call both return
                # immediately with future-backed arrays — readback (the
                # only blocking part) happens in harvest()
                self._note_shapes(xs, tag=str(_d))
                xd = [jax.device_put(jnp.asarray(x), _d) for x in xs]
                if self._cache is not None:
                    prog = self._aot_program(_p, _s, xd, device=_d,
                                             top_n=top_n)
                    return prog(_p, _s, *xd)
                return fwd(_p, _s, *xd)

            def harvest(h):
                hs = h if isinstance(h, (list, tuple)) else [h]
                return [np.asarray(o) for o in hs]

            out.append(ModelReplica(dispatch, harvest, device=dev,
                                    on_device_topn=bool(top_n),
                                    pads_input=True))
        return out

    def mesh_replica(self, mesh, top_n: Optional[int] = None
                     ) -> "ModelReplica":
        """One serving replica spanning a whole ``Mesh`` — the
        long-document executor slot (docs/SERVING.md "Long-document
        bucket class").  Weights are placed replicated over the mesh
        once; each dispatch runs the forward with all mesh devices
        cooperating, so a net whose attention shards the sequence axis
        (``seq_shards`` → ops/ring_attention.py) holds only O(L/ways)
        of K/V per chip instead of the full 32k–128k context.  The AOT
        compile-cache signature carries a device descriptor, so the
        mesh program warms independently of the single-chip buckets.
        """
        if self._net is None:
            raise ValueError(
                "mesh_replica needs a native net (from_keras_net/load); "
                "foreign forwards have no mesh-placeable param tree")
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(mesh, PartitionSpec())
        fwd = self._build_param_forward(top_n=top_n)
        weights = self._qparams if self._int8 else self._params
        p_i = jax.device_put(weights, rep)
        s_i = jax.device_put(self._state, rep)
        desc = "mesh:" + "x".join(
            f"{k}={v}" for k, v in mesh.shape.items())

        def dispatch(xs):
            self._note_shapes(xs, tag=desc)
            xd = [jax.device_put(jnp.asarray(x), rep) for x in xs]
            if self._cache is not None:
                prog = self._aot_program(p_i, s_i, xd, device=desc,
                                         top_n=top_n)
                return prog(p_i, s_i, *xd)
            return fwd(p_i, s_i, *xd)

        def harvest(h):
            hs = h if isinstance(h, (list, tuple)) else [h]
            return [np.asarray(o) for o in hs]

        return ModelReplica(dispatch, harvest, device=desc,
                            on_device_topn=bool(top_n), pads_input=True)

    def sharded_tables(self) -> tuple:
        """The net's row-shardable table manifest (layer names set by
        ``table_placement="sharded"`` model builders), or () for nets
        without one."""
        return tuple(getattr(self._net, "_sharded_tables", None) or ())

    def weight_nbytes_per_chip(self, mesh, axis: str = "model") -> int:
        """PER-CHIP HBM weight footprint when this model serves as a
        mesh replica over ``mesh``: listed tables charge
        ``nbytes / ways`` (they row-shard over ``axis``), everything
        else charges full bytes.  This is what the executor's HBM
        budget planner charges a mesh-replica slot — the whole point of
        the sharded serving path is that a table bigger than one chip's
        budget still fits per-chip."""
        tables = self.sharded_tables()
        weights = self._qparams if getattr(self, "_int8", False) \
            else getattr(self, "_params", None)
        if weights is None:
            return 0
        if not tables or mesh is None:
            return self.weight_nbytes()
        from analytics_zoo_tpu.parallel.table_sharding import \
            per_chip_weight_nbytes
        total = per_chip_weight_nbytes(weights, tables, mesh, axis=axis)
        total += per_chip_weight_nbytes(
            getattr(self, "_state", None) or {}, tables, mesh, axis=axis)
        return total

    # -- hot-row replication caches (ISSUE 19) -----------------------------
    def _table_leaf(self, tname: str):
        """The authoritative ``<tname>/table`` param leaf, or None."""
        from analytics_zoo_tpu.parallel.sharding import path_str
        from analytics_zoo_tpu.parallel.table_sharding import \
            table_leaf_patterns

        pats = table_leaf_patterns((tname,))
        found = [None]

        def one(path, leaf):
            if any(p.search(path_str(path)) for p in pats):
                found[0] = leaf
            return leaf

        jax.tree_util.tree_map_with_path(
            one, getattr(self, "_params", None) or {})
        return found[0]

    def _table_id_field_indices(self, tname: str,
                                id_fields=None) -> Optional[tuple]:
        """Input positions whose arrays carry ``tname``'s id stream:
        an explicit ``id_fields`` entry wins, then the net's
        ``_table_id_fields`` manifest, then the graph-ancestor trace
        (``Model.input_ancestors``).  None means "unknown" — the
        caller falls back to every integer input."""
        names = None
        if id_fields and tname in id_fields:
            names = tuple(id_fields[tname])
        else:
            manifest = getattr(self._net, "_table_id_fields", None) or {}
            if tname in manifest:
                names = tuple(manifest[tname])
            elif hasattr(self._net, "input_ancestors"):
                # an empty trace means the manifest names a layer the
                # graph doesn't apply — treat as unknown, not as "no
                # id stream", so the cache still fills
                names = self._net.input_ancestors(tname) or None
        if names is None:
            return None
        inputs = [v.name for v in getattr(self._net, "inputs", [])]
        return tuple(i for i, n in enumerate(inputs) if n in names)

    def enable_hot_caches(self, mesh=None, *, axis: str = "model",
                          capacity: Optional[int] = None,
                          refresh_period_s: Optional[float] = None,
                          id_fields: Optional[Dict[str, Any]] = None,
                          clock=time.monotonic) -> Dict[str, Any]:
        """Build one :class:`~analytics_zoo_tpu.parallel.hot_cache.
        HotRowCache` per entry of the net's ``_sharded_tables`` manifest
        (the ``table_hot_cache`` knob gates this: ``"off"`` builds
        none).  The caches are SERVING-side and read-only: frequency
        fills from the dispatch id streams (``record_hot_ids``), values
        come only from ``refresh_hot_caches`` re-reading the
        authoritative params, and ``invalidate_hot_caches`` runs on
        every ``swap_replicas`` / hot reload.  ``clock`` is injectable
        for the staleness tests.

        Each cache records only its OWN table's id streams: the input
        fields feeding a table come from ``id_fields`` (table name ->
        input-field names), the net's ``_table_id_fields`` manifest, or
        the graph-ancestor trace — so a multi-table model's caches
        never cross-pollute, and integer non-id inputs (lengths,
        offsets, positions) never skew a ranking."""
        from analytics_zoo_tpu.ops.dispatch import config_knob
        from analytics_zoo_tpu.parallel.hot_cache import HotRowCache

        if config_knob("table_hot_cache", "auto") == "off":
            self._hot_caches: Dict[str, Any] = {}
            self._hot_cache_fields: Dict[str, Any] = {}
            return {}
        if capacity is None:
            capacity = int(config_knob("table_hot_cache_capacity", 1024))
        if refresh_period_s is None:
            refresh_period_s = float(
                config_knob("table_hot_cache_refresh_s", 30.0))
        caches: Dict[str, Any] = {}
        fields: Dict[str, Any] = {}
        for tname in self.sharded_tables():
            leaf = self._table_leaf(tname)
            if leaf is None or len(getattr(leaf, "shape", ())) != 2:
                continue
            caches[tname] = HotRowCache(
                f"{self.name}/{tname}", capacity,
                dim=int(leaf.shape[1]),
                refresh_period_s=refresh_period_s, clock=clock,
                mesh=mesh,
                dtype=np.dtype(str(getattr(leaf, "dtype", "float32"))))
            fields[tname] = self._table_id_field_indices(
                tname, id_fields)
        self._hot_caches = caches
        self._hot_cache_fields = fields
        return dict(caches)

    def hot_caches(self) -> Dict[str, Any]:
        return dict(getattr(self, "_hot_caches", None) or {})

    def record_hot_ids(self, xs) -> None:
        """Fold a dispatch batch's id streams into the table caches'
        frequency counts — each cache sees only the input positions
        mapped to ITS table (``enable_hot_caches``); a table with no
        known mapping falls back to every integer array."""
        caches = getattr(self, "_hot_caches", None)
        if not caches:
            return
        fields = getattr(self, "_hot_cache_fields", None) or {}
        arrays = [np.asarray(x) for x in xs]
        int_idx = [i for i, a in enumerate(arrays)
                   if a.dtype.kind in "iu"]
        for tname, c in caches.items():
            idx = fields.get(tname)
            for i in (int_idx if idx is None
                      else [i for i in idx if i in int_idx]):
                c.record(arrays[i])

    def refresh_hot_caches(self, force: bool = False) -> int:
        """Re-rank + re-read every cache from the authoritative table
        leaves; ``force`` skips the period check (used right after a
        weight swap).  Returns the number of caches refreshed."""
        from analytics_zoo_tpu.parallel.hot_cache import table_row_reader

        done = 0
        for tname, cache in self.hot_caches().items():
            leaf = self._table_leaf(tname)
            if leaf is None:
                continue
            reader = table_row_reader(leaf)
            if force:
                cache.refresh(reader)
                done += 1
            elif cache.maybe_refresh(reader):
                done += 1
        return done

    def invalidate_hot_caches(self, reason: str = "swap") -> None:
        """Drop every cache's replica rows (all ids miss until the next
        refresh) — the weight-swap safety hook: a hot-reloaded model
        must never serve pre-swap rows."""
        for cache in self.hot_caches().values():
            cache.invalidate(reason)

    def shard_replica(self, mesh, top_n: Optional[int] = None,
                      axis: str = "model") -> "ModelReplica":
        """One serving replica spanning a whole ``Mesh`` with the net's
        ``_sharded_tables`` row-sharded ``P(axis, None)`` over it — the
        giant-embedding serving path (docs/SERVING.md "Pod-scale
        serving").

        Each listed table leaf is placed once with ``rows/ways`` rows
        per chip; every other leaf replicates.  The forward traces with
        the table-shard mode active, so ``ShardedEmbeddingTable``
        lowers to ``parallel.table_sharding.sharded_bag`` — the local
        fused lookup plus ONE ``(B, D)`` psum per table; the gathered
        rows never leave their owning shard.  The AOT compile-cache
        signature carries a ``shard_mesh:...`` device descriptor (and
        the cache env already folds in the mesh), so a rebuilt mesh
        replica warm-starts with zero live compiles.
        """
        if self._net is None:
            raise ValueError(
                "shard_replica needs a native net (from_keras_net/load); "
                "foreign forwards have no mesh-placeable param tree")
        from jax.sharding import NamedSharding, PartitionSpec

        from analytics_zoo_tpu.parallel.mode import TableShardMode
        from analytics_zoo_tpu.parallel.sharding import path_str
        from analytics_zoo_tpu.parallel.table_sharding import (
            resolve_table_ways, table_leaf_patterns)

        tables = self.sharded_tables()
        mode = TableShardMode(mesh, axis, tables)
        rep = NamedSharding(mesh, PartitionSpec())
        row_sh = NamedSharding(mesh, PartitionSpec(axis, None))
        pats = table_leaf_patterns(tables)

        def placement(path, leaf):
            shape = getattr(leaf, "shape", ())
            if (any(p.search(path_str(path)) for p in pats)
                    and len(shape) == 2
                    and resolve_table_ways(mesh, axis,
                                           int(shape[0])) > 1):
                return row_sh
            return rep

        fwd = self._build_param_forward(top_n=top_n, table_shard=mode)
        weights = self._qparams if self._int8 else self._params
        shardings = jax.tree_util.tree_map_with_path(placement, weights)
        p_i = jax.device_put(weights, shardings)
        s_i = jax.device_put(self._state, rep)
        desc = ("shard_mesh:" + "x".join(
            f"{k}={v}" for k, v in mesh.shape.items()) + f":{axis}")

        def dispatch(xs):
            self._note_shapes(xs, tag=desc)
            # hot-row cache frequency tap: the fused id streams passing
            # through here ARE the batcher's traffic (host numpy still)
            self.record_hot_ids(xs)
            xd = [jax.device_put(jnp.asarray(x), rep) for x in xs]
            if self._cache is not None:
                prog = self._aot_program(p_i, s_i, xd, device=desc,
                                         top_n=top_n, fwd=fwd)
                return prog(p_i, s_i, *xd)
            return fwd(p_i, s_i, *xd)

        def harvest(h):
            hs = h if isinstance(h, (list, tuple)) else [h]
            return [np.asarray(o) for o in hs]

        return ModelReplica(dispatch, harvest, device=desc,
                            on_device_topn=bool(top_n), pads_input=True)

    @classmethod
    def load_onnx(cls, path: str, int8: bool = False,
                  calibration_inputs=None, **kw) -> "InferenceModel":
        """Serve an .onnx file (onnx/loader.py).  ``int8=True`` runs
        post-training quantization: Gemm/MatMul nodes execute as int8
        MXU matmuls (ops/quantization.py) — with ``calibration_inputs``
        the activation scales are static (calibrated), otherwise dynamic.
        Replaces the reference's OpenVINO int8 path
        (InferenceModel.scala:443)."""
        from analytics_zoo_tpu.onnx import load_onnx

        program = load_onnx(path)
        if int8:
            from analytics_zoo_tpu.ops.quantization import quantize_program

            program = quantize_program(program, calibration_inputs)

        @jax.jit
        def fwd(*xs):
            out, _ = program.call(program.params, program.state, *xs,
                                  training=False)
            return out

        def forward(inputs: List[np.ndarray]):
            return fwd(*[jnp.asarray(x) for x in inputs])

        m = cls(forward, **kw)
        m._program, m._int8 = program, int8
        return m

    @classmethod
    def from_function(cls, fn: Callable, jit: bool = True,
                      **kw) -> "InferenceModel":
        """Serve an arbitrary jax function of the inputs."""
        jfn = jax.jit(fn) if jit else fn

        def forward(inputs: List[np.ndarray]):
            return jfn(*[jnp.asarray(x) for x in inputs])

        return cls(forward, **kw)

    @classmethod
    def load_tf_saved_model(cls, path: str, signature: str =
                            "serving_default", **kw) -> "InferenceModel":
        """Ingest a TF SavedModel via jax2tf.call_tf (reference
        doLoadTF/TFNet.fromSavedModel, TFNet.scala:654).  The TF graph
        executes on the host; JAX owns the calling side."""
        import tensorflow as tf  # gated: raises if TF absent
        from jax.experimental import jax2tf

        loaded = tf.saved_model.load(path)
        f = loaded.signatures[signature]
        call = jax2tf.call_tf(f)

        def forward(inputs: List[np.ndarray]):
            out = call(*[jnp.asarray(x) for x in inputs])
            if isinstance(out, dict):  # signature outputs are dicts
                vals = list(out.values())
                return vals[0] if len(vals) == 1 else vals
            return out

        m = cls(forward, **kw)
        m._tf_model = loaded  # keep alive
        return m

    @classmethod
    def load_tf_keras(cls, model_or_path, **kw) -> "InferenceModel":
        """Ingest a tf.keras model (object or .keras/.h5 path) —
        reference KerasModel serving (tfpark/model.py:34)."""
        import tensorflow as tf
        from jax.experimental import jax2tf

        model = (model_or_path if not isinstance(model_or_path, str)
                 else tf.keras.models.load_model(model_or_path))
        fn = tf.function(lambda *xs: model(*xs, training=False),
                         autograph=False)
        call = jax2tf.call_tf(fn)

        def forward(inputs: List[np.ndarray]):
            return call(*[jnp.asarray(x) for x in inputs])

        m = cls(forward, **kw)
        m._tf_model = model
        return m

    @classmethod
    def load_torch(cls, model_or_path, **kw) -> "InferenceModel":
        """Ingest a TorchScript file or torch.nn.Module (reference
        TorchNet.scala:39 — libtorch ran in-process via JNI; here torch
        runs in-process on the host CPU)."""
        import torch

        model = (torch.jit.load(model_or_path)
                 if isinstance(model_or_path, str) else model_or_path)
        model.eval()

        def forward(inputs: List[np.ndarray]):
            with torch.no_grad():
                out = model(*[torch.from_numpy(np.asarray(x))
                              for x in inputs])
            if isinstance(out, (tuple, list)):
                return [o.numpy() for o in out]
            return out.numpy()

        m = cls(forward, **kw)
        m._torch_model = model
        return m

    # -- predict -----------------------------------------------------------
    def predict(self, inputs, batch_size: Optional[int] = None):
        """Predict on one batch (list of arrays or a single array).

        Rows are padded up to the next batch bucket so repeated calls with
        ragged sizes reuse a bounded set of compiled programs (the
        reference bounded concurrency with a model-clone pool instead —
        InferenceModel.scala:67).  ``batch_size`` caps the per-program
        device batch (overrides the bucket for this call).
        """
        xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        xs = [np.asarray(x) for x in xs]
        n = xs[0].shape[0]
        bucket = _next_bucket(n, self.batch_buckets)
        if batch_size:
            # snap an explicit cap DOWN to the nearest bucket: a cap
            # between buckets (say 40 with buckets (8, 64)) would
            # otherwise compile a fresh one-off 40-row program per novel
            # cap — chunking into full-bucket programs keeps the compiled
            # set bounded.  A cap below the smallest bucket is honored
            # as-is (the caller explicitly chose that program shape).
            eff = max((b for b in self.batch_buckets if b <= batch_size),
                      default=batch_size)
            bucket = min(eff, bucket)
        if bucket > n:
            xs = [np.concatenate(
                [x, np.repeat(x[-1:], bucket - n, axis=0)], axis=0)
                for x in xs]
        elif bucket < n:  # larger than biggest bucket (or capped): chunk
            eff = (tuple(b for b in self.batch_buckets if b <= bucket)
                   or (bucket,))
            outs, s = [], 0
            for m, b in plan_buckets(n, eff):
                outs.append(self.predict([x[s:s + m] for x in xs],
                                         batch_size=b))
                s += m
            if isinstance(outs[0], list):
                return [np.concatenate([o[i] for o in outs], axis=0)
                        for i in range(len(outs[0]))]
            return np.concatenate(outs, axis=0)
        self._note_shapes(xs)
        if self._cache is not None and self._net is not None:
            out = self._aot_forward(xs)
        else:
            out = self._forward(xs)
        if isinstance(out, (list, tuple)):
            return [np.asarray(o)[:n] for o in out]
        return np.asarray(out)[:n]

    def _aot_forward(self, xs):
        """Cache-backed predict() forward: same program as the closure-
        jitted ``_forward`` but param-explicit, so it routes through the
        persistent AOT table (warm shapes execute with zero live
        compiles)."""
        if self._pred_weights is None:
            w = self._qparams if self._int8 else self._params
            self._pred_weights = (w, self._state)
        p, s = self._pred_weights
        xj = [jnp.asarray(x) for x in xs]
        prog = self._aot_program(p, s, xj, device=None, top_n=None)
        return prog(p, s, *xj)

    # reference predict-API aliases (InferenceModel.scala:762-830)
    do_predict = predict

    def predict_classes(self, inputs, **kw) -> np.ndarray:
        out = self.predict(inputs, **kw)
        if isinstance(out, list):
            out = out[0]
        return np.argmax(out, axis=-1)


# ---------------------------------------------------------------------------
# Dynamic batching — the TPU replacement for the model-clone queue
# ---------------------------------------------------------------------------

class BatchRequest:
    """One queued request inside the DynamicBatcher: ``xs`` keep their
    leading batch dim (``n`` rows); ``callback(out, error)`` fires with
    the request's slice of the fused output (or the batch error).
    ``deadline`` (monotonic seconds, optional) is the record's client
    TTL: a request still unflushed past it is shed with a typed
    ``DeadlineExpired`` instead of wasting a device slot.  ``span``
    (optional observe.Span) is the record's batch_wait leg — the
    batcher ends it when the request flushes, sheds, or the batcher
    closes, so the request's timeline never dangles.  ``model`` names
    the target model in a multi-model pipeline (None = single-model
    legacy path); it rides into the bucket key so two models' requests
    never fuse, and into every per-request metric as a label."""

    __slots__ = ("xs", "n", "callback", "t_submit", "deadline", "span",
                 "model")

    def __init__(self, xs, callback, deadline=None, span=None, model=None):
        self.xs = xs
        self.n = xs[0].shape[0]
        self.callback = callback
        self.t_submit = time.monotonic()
        self.deadline = deadline
        self.span = span
        self.model = model


def scatter_batch_results(out, reqs: List[BatchRequest]) -> None:
    """Slice one fused model output back to the requests that formed it."""
    outs = out if isinstance(out, list) else [out]
    s = 0
    for r in reqs:
        sliced = [np.asarray(o)[s:s + r.n] for o in outs]
        r.callback(sliced if isinstance(out, list) else sliced[0], None)
        s += r.n


class DynamicBatcher:
    """Shape-bucketed continuous batching: stage 2 of the serving pipeline.

    Reference InferenceModel served N threads with N model clones
    (InferenceModel.scala:30-72); on TPU one compiled program is already
    thread-safe, so the win is *coalescing* small requests into one MXU
    batch.  Requests group by row shape/dtype (mixed-shape traffic never
    fuses — each shape is its own bucket feeding its own compiled
    program) and a bucket dispatches on whichever comes first:

    - **batch-full** — ``max_batch`` rows accumulated (preempts the
      deadline: a hot bucket never waits);
    - **deadline** — ``max_latency_ms`` since the bucket's oldest
      request (trickle traffic is never stranded).

    Two front doors: blocking ``predict`` (drop-in concurrency helper)
    and async ``submit(xs, callback)`` (the serving pipeline's path).
    ``dispatch_fn(key, fused, reqs)`` hands full batches to an external
    executor (the serving DeviceExecutor); without one, batches run
    inline through ``model.predict``.
    """

    def __init__(self, model: Optional[InferenceModel] = None,
                 max_batch: int = 64, max_latency_ms: float = 5.0,
                 dispatch_fn: Optional[Callable] = None,
                 name: str = "serving",
                 heartbeat: Optional[Callable[[], None]] = None):
        if model is None and dispatch_fn is None:
            raise ValueError("DynamicBatcher needs a model or a "
                             "dispatch_fn")
        self.model = model
        self.max_batch = max_batch
        self.max_latency = max_latency_ms / 1e3
        self.name = name
        self._dispatch_fn = dispatch_fn
        self._heartbeat = heartbeat
        self._cv = threading.Condition()
        self._buckets: Dict[Any, List[BatchRequest]] = {}
        self._rows: Dict[Any, int] = {}
        self._deadline: Dict[Any, float] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    @staticmethod
    def _key(xs) -> Any:
        return tuple((tuple(x.shape[1:]), str(x.dtype)) for x in xs)

    # -- front doors -------------------------------------------------------
    def submit(self, inputs, callback: Callable,
               deadline: Optional[float] = None, span=None,
               model: Optional[str] = None) -> None:
        """Async enqueue; ``callback(out, error)`` fires from the
        dispatch side when this request's slice is ready.  ``deadline``
        (monotonic) sheds the request with ``DeadlineExpired`` if it is
        still queued when the bucket flushes past it.  ``span`` is the
        caller's batch_wait span, ended by the batcher at flush/shed.
        ``model`` scopes the bucket: requests for different models never
        fuse into one device batch."""
        if self._stop.is_set():
            raise RuntimeError("DynamicBatcher is closed")
        xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        xs = [np.asarray(x) for x in xs]
        req = BatchRequest(xs, callback, deadline=deadline, span=span,
                           model=model)
        key = self._key(xs) if model is None else (model,) + self._key(xs)
        full_reqs = None
        with self._cv:
            self._buckets.setdefault(key, []).append(req)
            self._rows[key] = self._rows.get(key, 0) + req.n
            self._deadline.setdefault(key, req.t_submit + self.max_latency)
            if self._rows[key] >= self.max_batch:
                # batch-full preempts the dispatcher thread: flush from
                # the submitting thread NOW rather than after the loop's
                # next GIL slot, so the device starts on batch N while
                # later requests are still being decoded/submitted
                full_reqs = self._buckets.pop(key)
                self._rows.pop(key, None)
                self._deadline.pop(key, None)
            self._cv.notify_all()
        if full_reqs is None:
            return
        groups, leftover = self._take(full_reqs, False)
        if leftover:
            with self._cv:
                self._buckets.setdefault(key, [])[:0] = leftover
                self._rows[key] = self._rows.get(key, 0) + sum(
                    r.n for r in leftover)
                self._deadline[key] = min(
                    self._deadline.get(key, float("inf")),
                    leftover[0].t_submit + self.max_latency)
        for g, full in groups:
            self._flush(key, g, full)

    def predict(self, inputs) -> Any:
        """Enqueue one request (single example or small batch); blocks
        until its slice of the fused batch returns."""
        done = threading.Event()
        slot: Dict[str, Any] = {}

        def cb(out, err):
            if err is not None:
                slot["error"] = err
            else:
                slot["out"] = out
            done.set()

        self.submit(inputs, cb)
        while not done.wait(timeout=1.0):
            if self._stop.is_set() and not done.is_set():
                # raced with close(): the worker may have exited before
                # popping this request — close() drains, but don't hang
                raise RuntimeError("DynamicBatcher closed while waiting")
        if "error" in slot:
            raise slot["error"]
        return slot["out"]

    def close(self, flush: bool = False):
        """Stop the dispatcher.  ``flush=True`` dispatches whatever is
        buffered first (graceful pipeline drain); pending requests left
        after that fail with RuntimeError so no caller blocks forever."""
        if flush and not self._stop.is_set():
            with self._cv:
                groups = [(k, self._buckets.pop(k))
                          for k in list(self._buckets)]
                self._rows.clear()
                self._deadline.clear()
            for key, reqs in groups:
                self._flush(key, reqs, full=False)
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        self._thread.join(timeout=2)
        with self._cv:
            pending = [r for reqs in self._buckets.values() for r in reqs]
            self._buckets.clear()
            self._rows.clear()
            self._deadline.clear()
        for r in pending:
            if r.span is not None:
                r.span.end(status="closed")
            r.callback(None, RuntimeError("DynamicBatcher closed"))

    # -- dispatcher --------------------------------------------------------
    def _ready(self, now: float) -> List[Any]:
        full = [k for k, r in self._rows.items() if r >= self.max_batch]
        due = [k for k, d in self._deadline.items()
               if k not in full and d <= now and self._rows.get(k)]
        return full + due

    def _loop(self):
        while not self._stop.is_set():
            if self._heartbeat is not None:
                self._heartbeat()
            flushes = []
            with self._cv:
                now = time.monotonic()
                ready = self._ready(now)
                if not ready:
                    timeout = 0.05
                    if self._deadline:
                        timeout = min(timeout, max(
                            1e-4, min(self._deadline.values()) - now))
                    self._cv.wait(timeout=timeout)
                    now = time.monotonic()
                    ready = self._ready(now)
                for key in ready:
                    reqs = self._buckets.pop(key, [])
                    self._rows.pop(key, None)
                    deadline_hit = self._deadline.pop(key, now) <= now
                    if not reqs:
                        continue
                    groups, leftover = self._take(reqs, deadline_hit)
                    flushes.extend((key, g, f) for g, f in groups)
                    if leftover:
                        # a full-flush leaves the partial tail batching
                        # toward its own (original-arrival) deadline
                        self._buckets[key] = leftover
                        self._rows[key] = sum(r.n for r in leftover)
                        self._deadline[key] = (leftover[0].t_submit
                                               + self.max_latency)
            for key, reqs, full in flushes:
                self._flush(key, reqs, full)

    def _take(self, reqs, deadline_hit):
        """Pack requests into ≤max_batch-row groups (request boundaries
        respected; a single oversized request flushes alone)."""
        groups, cur, rows = [], [], 0
        for r in reqs:
            if cur and rows + r.n > self.max_batch:
                groups.append((cur, True))
                cur, rows = [], 0
            cur.append(r)
            rows += r.n
        leftover = []
        if cur:
            if rows >= self.max_batch or deadline_hit:
                groups.append((cur, rows >= self.max_batch))
            else:
                leftover = cur
        return groups, leftover

    def _flush(self, key, reqs: List[BatchRequest], full: bool) -> None:
        from analytics_zoo_tpu.core.profiling import TIMERS
        from analytics_zoo_tpu.observe import metrics as obs
        from analytics_zoo_tpu.robust.errors import DeadlineExpired

        now = time.monotonic()
        expired = [r for r in reqs
                   if r.deadline is not None and now > r.deadline]
        if expired:
            # shed before paying the dispatch: the client's TTL already
            # elapsed while the request batched, so answer the typed
            # error now and keep the device slot for live work
            for r in expired:
                obs.count("serving_shed_total", code="expired",
                          model=r.model or DEFAULT_MODEL,
                          flat=f"{self.name}/shed_expired")
            err = DeadlineExpired(
                "client TTL expired while the request batched")
            for r in expired:
                if r.span is not None:
                    r.span.end(status="expired")
                r.callback(None, err)
            reqs = [r for r in reqs if r not in expired]
            if not reqs:
                return
        TIMERS.incr(f"{self.name}/flush_full" if full
                    else f"{self.name}/flush_deadline")
        for r in reqs:
            obs.observe("serving_stage_seconds", now - r.t_submit,
                        stage="batch_wait", model=r.model or DEFAULT_MODEL,
                        flat=f"{self.name}/batch_wait")
            if r.span is not None:
                r.span.end(rows=r.n, full=full)
        try:
            if len(reqs) == 1:
                # single-request batch: hand the arrays through as-is —
                # on the shm backend these are views into the slot, and
                # this is the last place a host copy could sneak in
                # before device_put
                fused = list(reqs[0].xs)
            else:
                TIMERS.incr(f"{self.name}/batch_fuse_copies")
                fused = [np.concatenate([r.xs[i] for r in reqs], axis=0)
                         for i in range(len(reqs[0].xs))]
            if self._dispatch_fn is not None:
                self._dispatch_fn(key, fused, reqs)
                return
            out = self.model.predict(fused)
            scatter_batch_results(out, reqs)
        except Exception as e:  # surface errors to every waiter
            for r in reqs:
                r.callback(None, e)
