"""Metrics-driven autoscaler for the serving pipeline.

A control loop over the stage gauges the pipeline already publishes
(docs/OBSERVABILITY.md): decode-queue depth, executor inflight,
per-model observed e2e p99 vs its SLO, and replica health.  Each tick
(``Autoscaler.check`` — ridden by the serving supervisor at the
``serving_autoscale_interval_s`` cadence) it may move one of three
actuators on :class:`~analytics_zoo_tpu.deploy.serving.ClusterServing`:

- **decode_workers** (``resize_decode_pool``): queue pressure grows the
  decode pool toward ``max_decode_workers``; a drained queue shrinks it.
- **replicas** (``resize_model_replicas``, per model): a model whose
  observed p99 crowds its SLO gets more replicas (HBM budget
  permitting); a model far under SLO with idle capacity gives them back.
- **batch_deadline** (``set_batch_deadline_ms``): sustained queue
  pressure *without* SLO pressure raises the batcher deadline (bigger
  fused batches, better device efficiency); SLO pressure lowers it
  (latency beats batching).

Two dampers keep the loop from flapping (docs/SERVING.md "Warm start &
multi-model" — hysteresis rules): a decision only fires after
``hysteresis`` CONSECUTIVE ticks agree on the same (model, resource,
direction), and each (model, resource) then enters a ``cooldown_s``
quiet period.  Every applied action is counted in
``serving_autoscale_actions_total{model,resource,direction}`` and kept
in the ``actions`` audit list the chaos soak asserts over.

The reference scaled by adding Spark executors to the ClusterServing
job (PAPER.md §L1); this is the TPU-native, in-process equivalent.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

from analytics_zoo_tpu.observe import metrics as obs

__all__ = ["AutoscalePolicy", "Autoscaler", "audit_actions"]

logger = logging.getLogger("analytics_zoo_tpu.deploy")

# model label for actions that concern the whole pipeline, not one model
PIPELINE = "_pipeline"
ALL_MODELS = "_all"


class AutoscalePolicy:
    """Bounds + watermarks for the control loop.  Defaults are sized
    for the single-host pipeline; the chaos soak and the bench override
    them to act fast."""

    def __init__(self,
                 min_decode_workers: int = 1,
                 max_decode_workers: int = 16,
                 min_replicas: int = 1,
                 max_replicas: int = 8,
                 min_batch_delay_ms: float = 1.0,
                 max_batch_delay_ms: float = 50.0,
                 queue_high: int = 64,
                 queue_low: int = 2,
                 slo_high_frac: float = 1.0,
                 slo_low_frac: float = 0.3,
                 hysteresis: int = 2,
                 cooldown_s: float = 5.0):
        self.min_decode_workers = max(1, int(min_decode_workers))
        self.max_decode_workers = max(self.min_decode_workers,
                                      int(max_decode_workers))
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.min_batch_delay_ms = float(min_batch_delay_ms)
        self.max_batch_delay_ms = float(max_batch_delay_ms)
        self.queue_high = int(queue_high)
        self.queue_low = int(queue_low)
        # replica pressure thresholds as fractions of the model's SLO:
        # p99 >= slo * high_frac -> grow; p99 <= slo * low_frac -> shrink
        self.slo_high_frac = float(slo_high_frac)
        self.slo_low_frac = float(slo_low_frac)
        self.hysteresis = max(1, int(hysteresis))
        self.cooldown_s = float(cooldown_s)


class Autoscaler:
    """One instance per :class:`ClusterServing`; driven by its
    supervisor (``sup.add_check("autoscale", scaler.check, every=k)``)
    or directly by tests with fabricated signals."""

    def __init__(self, serving, policy: Optional[AutoscalePolicy] = None,
                 clock=time.monotonic):
        self.serving = serving
        self.policy = policy or AutoscalePolicy(
            cooldown_s=serving.cfg.autoscale_cooldown_s)
        self._clock = clock
        # (model, resource, direction) -> consecutive agreeing ticks
        self._streak: Dict[tuple, int] = {}
        # (model, resource) -> time of last applied action
        self._last: Dict[tuple, float] = {}
        self.actions: List[Dict[str, Any]] = []

    # -- signals -----------------------------------------------------------

    def signals(self) -> Dict[str, Any]:
        """One coherent snapshot of the gauges the loop decides from."""
        srv = self.serving
        ex = srv._executor
        decode_q = getattr(srv, "_decode_q", None)
        sig: Dict[str, Any] = {
            "queue_depth": decode_q.qsize() if decode_q is not None else 0,
            "inflight": ex.inflight if ex is not None else 0,
            "max_inflight": srv.cfg.max_inflight,
            "decode_workers": srv._decode_target,
            "models": {},
        }
        for m in srv.models:
            sig["models"][m] = {
                "replicas": ex.group_size(m) if ex is not None else 0,
                "healthy": ex.healthy_replicas(m) if ex is not None else 0,
                # pod-scale mesh replicas are capacity too: a shed mesh
                # replica shows up here as lost headroom, and the freed
                # per-chip budget lets a single-chip grow pass
                # _budget_allows (docs/SERVING.md "Pod-scale serving")
                "mesh_replicas": (ex.mesh_group_size(m)
                                  if ex is not None else 0),
                "mesh_healthy": (ex.healthy_mesh_replicas(m)
                                 if ex is not None else 0),
                "slo_ms": srv.cfg.slo_for(m),
                "p99_ms": srv._admission.p99(m),
            }
        return sig

    # -- dampers -----------------------------------------------------------

    def _breach(self, key: tuple, breached: bool) -> bool:
        """Consecutive-tick hysteresis: True only once the same (model,
        resource, direction) has been signalled ``hysteresis`` ticks in
        a row.  A tick that doesn't signal resets the streak."""
        if not breached:
            self._streak.pop(key, None)
            return False
        n = self._streak.get(key, 0) + 1
        self._streak[key] = n
        return n >= self.policy.hysteresis

    def _cooled(self, model: str, resource: str) -> bool:
        t = self._last.get((model, resource))
        return t is None or self._clock() - t >= self.policy.cooldown_s

    def _act(self, model: str, resource: str, direction: str,
             apply_fn, detail: str) -> None:
        value = apply_fn()
        self._last[(model, resource)] = self._clock()
        self._streak.pop((model, resource, direction), None)
        obs.count("serving_autoscale_actions_total", model=model,
                  resource=resource, direction=direction,
                  flat=f"serving/autoscale_{resource}_{direction}")
        self.actions.append({"t": self._clock(), "model": model,
                             "resource": resource, "direction": direction,
                             "value": value, "detail": detail})
        logger.info("autoscale: %s %s %s -> %s (%s)", model, resource,
                    direction, value, detail)

    # -- the control loop --------------------------------------------------

    def check(self, signals: Optional[Dict[str, Any]] = None) -> None:
        """One control tick.  Tests pass fabricated ``signals``; the
        supervisor passes none and the live gauges are read."""
        sig = signals if signals is not None else self.signals()
        self._scale_decode(sig)
        for m in list(sig["models"]):
            self._scale_replicas(m, sig)
        self._scale_deadline(sig)

    def _scale_decode(self, sig: Dict[str, Any]) -> None:
        pol = self.policy
        cur = sig["decode_workers"]
        depth = sig["queue_depth"]
        up = depth >= pol.queue_high and cur < pol.max_decode_workers
        down = depth <= pol.queue_low and cur > pol.min_decode_workers
        if self._breach((PIPELINE, "decode_workers", "up"), up) \
                and self._cooled(PIPELINE, "decode_workers"):
            n = min(pol.max_decode_workers, max(cur + 1, cur * 2))
            self._act(PIPELINE, "decode_workers", "up",
                      lambda: self.serving.resize_decode_pool(n),
                      f"queue depth {depth} >= {pol.queue_high}")
        elif self._breach((PIPELINE, "decode_workers", "down"), down) \
                and self._cooled(PIPELINE, "decode_workers"):
            n = max(pol.min_decode_workers, cur - 1)
            self._act(PIPELINE, "decode_workers", "down",
                      lambda: self.serving.resize_decode_pool(n),
                      f"queue depth {depth} <= {pol.queue_low}")

    def _scale_replicas(self, model: str, sig: Dict[str, Any]) -> None:
        pol = self.policy
        ms = sig["models"][model]
        cur = ms["replicas"]
        slo, p99 = ms["slo_ms"], ms["p99_ms"]
        if slo > 0 and p99 > 0:
            up = (p99 >= slo * pol.slo_high_frac
                  and cur < pol.max_replicas)
            down = (p99 <= slo * pol.slo_low_frac
                    and cur > pol.min_replicas)
            why_up = f"p99 {p99:.0f}ms >= SLO {slo:.0f}ms"
            why_down = f"p99 {p99:.0f}ms << SLO {slo:.0f}ms"
        else:
            # no SLO for this model: fall back to saturation signals —
            # the executor pegged at max_inflight with a deep queue
            saturated = (sig["inflight"] >= sig["max_inflight"]
                         and sig["queue_depth"] >= pol.queue_high)
            up = saturated and cur < pol.max_replicas
            down = (sig["queue_depth"] <= pol.queue_low
                    and sig["inflight"] == 0 and cur > pol.min_replicas)
            why_up = (f"saturated (inflight {sig['inflight']}, "
                      f"queue {sig['queue_depth']})")
            why_down = "idle"
        if self._breach((model, "replicas", "up"), up) \
                and self._cooled(model, "replicas"):
            self._act(model, "replicas", "up",
                      lambda: self.serving.resize_model_replicas(
                          model, cur + 1), why_up)
        elif self._breach((model, "replicas", "down"), down) \
                and self._cooled(model, "replicas"):
            self._act(model, "replicas", "down",
                      lambda: self.serving.resize_model_replicas(
                          model, cur - 1), why_down)

    def _scale_deadline(self, sig: Dict[str, Any]) -> None:
        pol = self.policy
        batcher = getattr(self.serving, "_batcher", None)
        if batcher is None:
            return
        cur_ms = batcher.max_latency * 1e3
        over_slo = any(m["slo_ms"] > 0 and m["p99_ms"] > m["slo_ms"]
                       for m in sig["models"].values())
        up = (sig["queue_depth"] >= pol.queue_high and not over_slo
              and cur_ms < pol.max_batch_delay_ms)
        down = over_slo and cur_ms > pol.min_batch_delay_ms
        if self._breach((ALL_MODELS, "batch_deadline", "up"), up) \
                and self._cooled(ALL_MODELS, "batch_deadline"):
            ms = min(pol.max_batch_delay_ms, cur_ms * 2)
            self._act(ALL_MODELS, "batch_deadline", "up",
                      lambda: self.serving.set_batch_deadline_ms(ms),
                      f"queue deep ({sig['queue_depth']}), SLOs met — "
                      "batch harder")
        elif self._breach((ALL_MODELS, "batch_deadline", "down"), down) \
                and self._cooled(ALL_MODELS, "batch_deadline"):
            ms = max(pol.min_batch_delay_ms, cur_ms / 2)
            self._act(ALL_MODELS, "batch_deadline", "down",
                      lambda: self.serving.set_batch_deadline_ms(ms),
                      "over SLO — flush sooner")

    def stats(self) -> Dict[str, Any]:
        return {"actions": len(self.actions),
                "last": self.actions[-1] if self.actions else None}

    # -- audited-action export (the loadgen convergence assertions) --------

    def export_actions(self) -> List[Dict[str, Any]]:
        """Deep-copied audit list, safe to hold across further ticks."""
        return [dict(a) for a in list(self.actions)]

    def audit(self, flap_window_s: Optional[float] = None) -> Dict[str, Any]:
        """Convergence audit over the applied-action ledger — see
        :func:`audit_actions`.  The flap window defaults to twice the
        policy cooldown: a reversal inside it means the dampers lost."""
        return audit_actions(self.export_actions(),
                             cooldown_s=self.policy.cooldown_s,
                             now=self._clock(),
                             flap_window_s=flap_window_s)


def audit_actions(actions: List[Dict[str, Any]], cooldown_s: float,
                  now: Optional[float] = None,
                  flap_window_s: Optional[float] = None) -> Dict[str, Any]:
    """Hysteresis audit over an action ledger (pure — tests feed
    fabricated ledgers).

    A **flap** is a direction reversal on the same (model, resource)
    within ``flap_window_s`` (default ``2 * cooldown_s``) of the
    previous action: up→down→up churn the hysteresis + cooldown
    dampers exist to prevent.  ``quiet_s`` is the time since the last
    action (None with no ``now``); the soak's convergence assertion is
    ``flaps == 0`` plus a long-enough quiet tail.
    """
    window = float(flap_window_s if flap_window_s is not None
                   else 2.0 * cooldown_s)
    flaps: List[Dict[str, Any]] = []
    last_by_key: Dict[tuple, Dict[str, Any]] = {}
    by_label: Dict[str, int] = {}
    for a in actions:
        key = (a["model"], a["resource"])
        label = f"{a['model']}/{a['resource']}/{a['direction']}"
        by_label[label] = by_label.get(label, 0) + 1
        prev = last_by_key.get(key)
        if prev is not None and prev["direction"] != a["direction"] \
                and a["t"] - prev["t"] < window:
            flaps.append({"model": a["model"], "resource": a["resource"],
                          "from": prev["direction"], "to": a["direction"],
                          "gap_s": a["t"] - prev["t"]})
        last_by_key[key] = a
    last_t = actions[-1]["t"] if actions else None
    return {
        "total": len(actions),
        "by_label": by_label,
        "flap_window_s": window,
        "flaps": len(flaps),
        "flap_events": flaps,
        "last_t": last_t,
        "quiet_s": (None if now is None or last_t is None
                    else max(0.0, now - last_t)),
    }
