"""ShmQueue: the zero-copy serving transport over a
``multiprocessing.shared_memory`` ring buffer.

The Memory/File/Redis backends all pay the string-codec tax: tensors
cross as base64 inside JSON, and the worker re-materializes every
payload at least twice.  ShmQueue moves raw bytes instead — records are
packed by :mod:`analytics_zoo_tpu.deploy.codec` straight into a
fixed-size slot arena inside one shared-memory segment, and
``pop_batch`` hands back ``np.frombuffer`` *views* into the slot, which
feed ``jax.device_put`` with no intermediate host copy at all.

Segment layout (one ``SharedMemory``, sized at construction)::

    geometry header | gseq u64 | slot state[u8 * slots]
    | slot seq[u64 * slots] | slot len[u32 * slots] | slot rid[96 * slots]
    | slot owner-pid[u32 * slots]
    | result state[u8] / len[u32] / rid[96] / owner-pid[u32] arrays
    | 4096-aligned request arena  (slots x slot_bytes)
    | 4096-aligned result arena   (result_slots x result_slot_bytes)

Slot protocol (lock-light by construction): the queue condition is held
only to *claim* a slot — scan the state flags, flip ``FREE → WRITING``
(push) or ``READY → READING`` (pop), bump the shared ``gseq`` cursor.
The payload memcpy happens outside the lock (the claimed state makes
the slot single-owner), and publishing is one byte store
(``WRITING → READY``) followed by a notify.  FIFO order rides the
``gseq`` stamps: pop sorts its claims by sequence number, so
single-producer order is exact and multi-producer order is
claim-order (the same guarantee the Redis stream gives concurrent
``xadd`` callers).

Slot lifetime is reference-counted, not copied: each popped record
leases its slot through a ctypes window over the shm buffer, and a
``weakref.finalize`` on that window returns the slot to ``FREE`` when
the last tensor view dies (after ``device_put`` upload, typically).
The release path is deliberately **lock-free** — finalizers can fire
during GC at any point, including while the releasing thread already
holds the queue lock, so they only append to a ``deque``; push/pop
drain it under the condition, and blocked pushers poll on a short wait
timeout.  ``serving/shm_backpressure_waits`` counts pushers that found
the arena full (slot exhaustion == backpressure, bounded by
``push_timeout_s``).

Cross-process leases: every ``push`` stamps the caller's pid into a
shared per-slot owner array; ``pop_batch`` carries it to the result
slot when the worker publishes.  A READY result slot whose owner pid no
longer exists will never be consumed (the waiter died between push and
``get_result``) — ``reclaim_dead_result_leases`` frees those slots and
counts ``serving_shm_lease_reclaims_total``; the serving supervisor
runs it every tick so a SIGKILL-ed client cannot strand result
capacity.

Lifecycle: the segment is ``unlink``-ed the moment ``stop()`` runs
(POSIX keeps live mappings valid after unlink, so in-flight leases
finish safely), outstanding leases defer only the ``close()``, and an
``atexit`` registry warns about — and unlinks — any queue whose owner
never called ``stop()``, so a crashed test run cannot strand segments
in ``/dev/shm``.

Scope: coordination (condition variables, the freed-deque) is
in-process — one serving worker, many threads.  Cross-process /
cross-host serving stays on the File/Redis backends (the distributed
fallback); this backend exists to make the single-host hot path as
fast as the memory bus.
"""

from __future__ import annotations

import atexit
import ctypes
import logging
import os
import threading
import time
import uuid
import weakref
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.core.profiling import TIMERS
from analytics_zoo_tpu.deploy import codec
from analytics_zoo_tpu.observe import metrics as obs
from analytics_zoo_tpu.robust.errors import (MalformedRecordError,
                                             ServingOverloaded)

__all__ = ["ShmQueue", "live_segments", "shm_available"]

_log = logging.getLogger("analytics_zoo_tpu.deploy")

FREE, WRITING, READY, READING = 0, 1, 2, 3
_RID_CAP = 94            # rid bytes per slot (2-byte length prefix)
_ARENA_ALIGN = 4096

# segment name -> queue, for leak warnings at interpreter exit
_LIVE: Dict[str, "ShmQueue"] = {}


def shm_available() -> bool:
    """True when POSIX shared memory actually works here (containers
    can mount /dev/shm noexec/ro or not at all)."""
    try:
        from multiprocessing import shared_memory

        seg = shared_memory.SharedMemory(create=True, size=64)
        seg.buf[0] = 1
        seg.close()
        seg.unlink()
        return True
    except Exception:
        return False


def live_segments() -> List[str]:
    """Names of segments created and not yet stopped (leak probe)."""
    return sorted(_LIVE)


@atexit.register
def _warn_leaked_segments() -> None:
    for seg, q in list(_LIVE.items()):
        _log.warning("ShmQueue segment %s leaked (stop() was never "
                     "called); unlinking at exit", seg)
        try:
            q.stop(timeout=0.5)
        except Exception:
            pass


def _align(n: int, a: int) -> int:
    return (n + a - 1) & ~(a - 1)


def _pid_alive(pid: int) -> bool:
    """Signal-0 probe; EPERM means the pid exists under another uid."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:        # PermissionError included: process exists
        return True
    return True


class ShmQueue:
    """Shared-memory ring-buffer stream + result store (see module
    docstring for the slot protocol and lifecycle contract)."""

    wire = "binary"

    def __init__(self, name: str = "serving_stream", slots: int = 256,
                 slot_bytes: int = 1 << 20,
                 result_slots: Optional[int] = None,
                 result_slot_bytes: Optional[int] = None,
                 push_timeout_s: float = 5.0):
        from multiprocessing import shared_memory

        self.name = name
        self.slots = max(2, int(slots))
        self.slot_bytes = int(slot_bytes)
        self.result_slots = int(result_slots or self.slots)
        self.result_slot_bytes = int(result_slot_bytes or self.slot_bytes)
        self.push_timeout_s = float(push_timeout_s)
        self.segment = f"azs-{name[:32]}-{uuid.uuid4().hex[:8]}"

        off = 64                                  # geometry header
        self._gseq_off = off
        off += 8
        self._state_off = off
        off += self.slots
        off = _align(off, 8)
        self._seq_off = off
        off += 8 * self.slots
        self._len_off = off
        off += 4 * self.slots
        self._rid_off = off
        off += (2 + _RID_CAP) * self.slots
        off = _align(off, 4)
        self._pid_off = off
        off += 4 * self.slots
        self._rstate_off = off
        off += self.result_slots
        off = _align(off, 4)
        self._rlen_off = off
        off += 4 * self.result_slots
        self._rrid_off = off
        off += (2 + _RID_CAP) * self.result_slots
        off = _align(off, 4)
        self._rpid_off = off
        off += 4 * self.result_slots
        self._arena_off = _align(off, _ARENA_ALIGN)
        self._rarena_off = _align(
            self._arena_off + self.slots * self.slot_bytes, _ARENA_ALIGN)
        total = self._rarena_off + self.result_slots * self.result_slot_bytes

        self._shm = shared_memory.SharedMemory(create=True, size=total,
                                               name=self.segment)
        buf = self._shm.buf
        self._gseq = np.frombuffer(buf, np.uint64, 1, self._gseq_off)
        self._st = np.frombuffer(buf, np.uint8, self.slots, self._state_off)
        self._seq = np.frombuffer(buf, np.uint64, self.slots, self._seq_off)
        self._ln = np.frombuffer(buf, np.uint32, self.slots, self._len_off)
        self._rid = np.frombuffer(buf, np.uint8,
                                  (2 + _RID_CAP) * self.slots,
                                  self._rid_off).reshape(self.slots, -1)
        self._pid = np.frombuffer(buf, np.uint32, self.slots,
                                  self._pid_off)
        self._rst = np.frombuffer(buf, np.uint8, self.result_slots,
                                  self._rstate_off)
        self._rln = np.frombuffer(buf, np.uint32, self.result_slots,
                                  self._rlen_off)
        self._rrid = np.frombuffer(
            buf, np.uint8, (2 + _RID_CAP) * self.result_slots,
            self._rrid_off).reshape(self.result_slots, -1)
        self._rpid = np.frombuffer(buf, np.uint32, self.result_slots,
                                   self._rpid_off)
        self._gseq[0] = 0
        self._st[:] = FREE
        self._rst[:] = FREE
        self._pid[:] = 0
        self._rpid[:] = 0

        self._cond = threading.Condition()    # request-slot claims
        self._rcond = threading.Condition()   # result-slot claims
        # slots whose last lease died; appended lock-free by finalizers,
        # drained under _cond (see module docstring: GC-reentrancy)
        self._freed: "deque[int]" = deque()
        # rid -> pusher pid, carried from the request slot at pop_batch
        # so set_result_many can stamp the result-slot owner.  Worker-
        # process local (only the popping side consults it).
        self._owner: Dict[str, int] = {}
        self.lease_reclaims = 0
        self._closed = False
        _LIVE[self.segment] = self

    # -- slot bookkeeping ---------------------------------------------------

    def _slot_off(self, idx: int) -> int:
        return self._arena_off + idx * self.slot_bytes

    def _rslot_off(self, idx: int) -> int:
        return self._rarena_off + idx * self.result_slot_bytes

    @staticmethod
    def _put_rid(arr: np.ndarray, idx: int, rid: str) -> None:
        b = rid.encode("utf-8")[:_RID_CAP]
        arr[idx, 0] = len(b) & 0xFF
        arr[idx, 1] = len(b) >> 8
        arr[idx, 2:2 + len(b)] = np.frombuffer(b, np.uint8)

    @staticmethod
    def _get_rid(arr: np.ndarray, idx: int) -> str:
        n = int(arr[idx, 0]) | (int(arr[idx, 1]) << 8)
        return bytes(arr[idx, 2:2 + n]).decode("utf-8")

    def _drain_freed_locked(self) -> None:
        while True:
            try:
                idx = self._freed.popleft()
            except IndexError:
                return
            self._st[idx] = FREE

    def _check_open(self) -> None:
        if self._closed:
            raise RuntimeError(f"ShmQueue[{self.name}] is stopped")

    # -- stream: request direction -----------------------------------------

    def push(self, record: Dict) -> str:
        self._check_open()
        rid = record.get("uri") or uuid.uuid4().hex
        prepared = codec.prepare_record(record)
        need = prepared[2]
        if need > self.slot_bytes:
            raise MalformedRecordError(
                f"record packs to {need} bytes > slot_bytes="
                f"{self.slot_bytes}; raise serving_shm_slot_bytes or "
                "shrink the payload")
        deadline = time.monotonic() + self.push_timeout_s
        with self._cond:
            while True:
                self._drain_freed_locked()
                free = np.flatnonzero(self._st == FREE)
                if free.size:
                    idx = int(free[0])
                    self._st[idx] = WRITING
                    self._gseq[0] += 1
                    seq = int(self._gseq[0])
                    break
                if time.monotonic() >= deadline:
                    raise ServingOverloaded(
                        f"ShmQueue[{self.name}]: all {self.slots} slots "
                        f"busy for {self.push_timeout_s:.1f}s "
                        "(slot-exhaustion backpressure)")
                TIMERS.incr("serving/shm_backpressure_waits")
                # short timeout: finalizer-freed slots arrive without a
                # notify (the release path is lock-free)
                self._cond.wait(0.05)
        n = codec.pack_record_into(record, self._shm.buf,
                                   self._slot_off(idx), codec="shm",
                                   prepared=prepared)
        self._ln[idx] = n
        self._seq[idx] = seq
        self._put_rid(self._rid, idx, rid)
        self._pid[idx] = os.getpid()
        self._st[idx] = READY       # publish: single byte store
        with self._cond:
            self._cond.notify_all()
        return rid

    def pop_batch(self, n: int, timeout: float = 0.1
                  ) -> List[Tuple[str, Dict]]:
        self._check_open()
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                self._drain_freed_locked()
                ready = np.flatnonzero(self._st == READY)
                if ready.size:
                    take = ready[np.argsort(self._seq[ready],
                                            kind="stable")][:n]
                    self._st[take] = READING
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    return []
                self._cond.wait(min(left, 0.05))
        out: List[Tuple[str, Dict]] = []
        for idx in (int(i) for i in take):
            ln = int(self._ln[idx])
            # the lease: a ctypes window over the slot.  Tensor views
            # produced by unpack_record keep it alive through their
            # .base chain; when the last one dies the finalizer returns
            # the slot — append only, no locks (GC-safe).
            lease = (ctypes.c_char * ln).from_buffer(
                self._shm.buf, self._slot_off(idx))
            weakref.finalize(lease, self._freed.append, idx)
            rec = codec.unpack_record(lease, codec="shm")
            rid = self._get_rid(self._rid, idx)
            self._owner[rid] = int(self._pid[idx])
            out.append((rid, rec))
            del lease  # the record's tensor views now own the slot
        return out

    def __len__(self) -> int:
        if self._closed:
            return 0
        with self._cond:
            return int((self._st == READY).sum())

    def trim(self, maxlen: int) -> int:
        """Drop oldest undelivered records beyond maxlen (XTRIM-style
        backpressure, same contract as the other backends)."""
        self._check_open()
        with self._cond:
            self._drain_freed_locked()
            ready = np.flatnonzero(self._st == READY)
            drop = max(0, int(ready.size) - int(maxlen))
            if drop:
                oldest = ready[np.argsort(self._seq[ready],
                                          kind="stable")][:drop]
                self._st[oldest] = FREE
                self._cond.notify_all()
            return drop

    # -- result direction ---------------------------------------------------

    def set_result(self, rid: str, value: Any) -> None:
        self.set_result_many([(rid, value)])

    def set_result_many(self, pairs: List[Tuple[str, Any]]) -> None:
        """Batched result writes: the respond pool drains its queue and
        publishes every ready result under ONE claim round."""
        self._check_open()
        blobs = []
        for rid, value in pairs:
            data = codec.pack_result(value, codec="shm")
            if len(data) > self.result_slot_bytes:
                from analytics_zoo_tpu.deploy.serving import error_payload

                data = codec.pack_result(error_payload(
                    "internal",
                    f"result of {len(data)} bytes exceeds "
                    f"result_slot_bytes={self.result_slot_bytes}",
                    uri=rid), codec="shm")
            blobs.append((rid, data))
        deadline = time.monotonic() + self.push_timeout_s
        with self._rcond:
            for rid, data in blobs:
                while True:
                    free = np.flatnonzero(self._rst == FREE)
                    if free.size:
                        idx = int(free[0])
                        break
                    if time.monotonic() >= deadline:
                        raise ServingOverloaded(
                            f"ShmQueue[{self.name}]: all "
                            f"{self.result_slots} result slots busy "
                            "(results not being consumed?)")
                    self._rcond.wait(0.05)
                off = self._rslot_off(idx)
                self._shm.buf[off:off + len(data)] = data
                self._rln[idx] = len(data)
                self._put_rid(self._rrid, idx, rid)
                self._rpid[idx] = self._owner.pop(rid, 0)
                self._rst[idx] = READY
            self._rcond.notify_all()

    def get_result(self, rid: str, timeout: float = 10.0) -> Any:
        self._check_open()
        deadline = time.monotonic() + timeout
        with self._rcond:
            while True:
                for idx in np.flatnonzero(self._rst == READY):
                    idx = int(idx)
                    if self._get_rid(self._rrid, idx) == rid:
                        off = self._rslot_off(idx)
                        ln = int(self._rln[idx])
                        data = bytes(self._shm.buf[off:off + ln])
                        self._rst[idx] = FREE
                        self._rcond.notify_all()
                        return codec.unpack_result(data, copy=False,
                                                   codec="shm")
                left = deadline - time.monotonic()
                if left <= 0:
                    from analytics_zoo_tpu.deploy.serving import _timeout_msg

                    raise TimeoutError(_timeout_msg(self, rid, timeout))
                self._rcond.wait(min(left, 0.05))

    def reclaim_dead_result_leases(self) -> int:
        """Free READY result slots whose owner process is gone.

        A result slot stays READY until the pusher that owns the rid
        calls :meth:`get_result`; if that process was SIGKILL-ed the
        slot would otherwise leak until the segment dies.  The serving
        supervisor runs this every tick — each reclaim counts
        ``serving_shm_lease_reclaims_total``.  Slots with no stamped
        owner (pid 0: results published for rids this worker never
        popped, e.g. decode-stage error payloads) are left alone.
        """
        if self._closed:
            return 0
        freed = 0
        with self._rcond:
            for idx in np.flatnonzero(self._rst == READY):
                idx = int(idx)
                pid = int(self._rpid[idx])
                if pid > 0 and not _pid_alive(pid):
                    self._rst[idx] = FREE
                    self._rpid[idx] = 0
                    freed += 1
            if freed:
                self._rcond.notify_all()
        # prune owner stamps whose waiter died before the result was
        # ever published (the respond pool would stamp a dead pid and
        # the next tick frees it; dropping the map entry here keeps the
        # worker-local map bounded)
        for rid, pid in list(self._owner.items()):
            if pid > 0 and not _pid_alive(pid):
                self._owner.pop(rid, None)
        if freed:
            self.lease_reclaims += freed
            obs.count("serving_shm_lease_reclaims_total", freed,
                      flat="serving/shm_lease_reclaims")
            _log.warning("ShmQueue[%s]: reclaimed %d result lease(s) "
                         "whose owner process died", self.name, freed)
        return freed

    def pending_results(self) -> List[str]:
        if self._closed:
            return []
        with self._rcond:
            return [self._get_rid(self._rrid, int(i))
                    for i in np.flatnonzero(self._rst == READY)]

    # -- health / lifecycle -------------------------------------------------

    def leased_slots(self) -> int:
        """Records popped whose tensor views are still alive (test and
        leak-probe surface)."""
        if self._closed:
            return 0
        with self._cond:
            self._drain_freed_locked()
            return int((self._st == READING).sum())

    def health(self) -> Dict[str, Any]:
        if self._closed:
            return {"ok": False, "backend": "shm", "closed": True,
                    "segment": self.segment}
        with self._cond:
            self._drain_freed_locked()
            return {"ok": True, "backend": "shm",
                    "segment": self.segment,
                    "depth": int((self._st == READY).sum()),
                    "slots_free": int((self._st == FREE).sum()),
                    "slots_leased": int((self._st == READING).sum()),
                    "pending_results": int((self._rst == READY).sum())}

    def stop(self, timeout: float = 2.0) -> None:
        """Unlink the segment (immediately — live leases keep their
        mappings valid), wait briefly for outstanding leases, drop our
        views, close the mapping.  Idempotent; leak-warns instead of
        hanging when a consumer still holds record views."""
        if self._closed:
            return
        self._closed = True
        _LIVE.pop(self.segment, None)
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._cond:
                self._drain_freed_locked()
                leased = int((self._st == READING).sum())
            if not leased:
                break
            time.sleep(0.01)
        else:
            _log.warning(
                "ShmQueue[%s]: %d leased record view(s) still alive "
                "after %.1fs at stop — mapping close deferred until "
                "they are garbage-collected (segment already unlinked)",
                self.name, leased, timeout)
        # our metadata views are buffer exports too; drop them so
        # close() can release the mapping
        self._gseq = self._st = self._seq = self._ln = self._rid = None
        self._rst = self._rln = self._rrid = None
        self._pid = self._rpid = None
        try:
            self._shm.close()
        except BufferError:
            # outstanding leases still export the buffer.  __del__ would
            # retry close() and raise the same BufferError unraisably at
            # GC time, so neuter it: the segment is already unlinked and
            # the mapping is reclaimed when the process (or the last
            # view) dies — nothing leaks in /dev/shm either way.
            self._shm.close = lambda: None
