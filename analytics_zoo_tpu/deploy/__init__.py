"""Deployment layer: InferenceModel + Cluster-Serving equivalent.

Reference capability: L7 — pipeline/inference/ (InferenceModel.scala:30,
multi-backend thread-safe serving) and serving/ (ClusterServing.scala:46,
Redis-stream streaming inference), plus the Python client
(pyzoo/zoo/serving/client.py:58-150).
"""

from analytics_zoo_tpu.deploy.inference import (  # noqa: F401
    LONG_DOC_TOKENS, BatchRequest, DynamicBatcher, InferenceModel,
    ModelReplica, bucket_class, dequantize_pytree, imagenet_preprocess,
    plan_buckets, quantize_pytree, scatter_batch_results)
from analytics_zoo_tpu.deploy.autoscale import (  # noqa: F401
    AutoscalePolicy, Autoscaler)
from analytics_zoo_tpu.deploy.compile_cache import (  # noqa: F401
    CompileCache, CompileCacheCorrupt)
from analytics_zoo_tpu.deploy.codec import (  # noqa: F401
    pack_record, pack_result, packed_nbytes, unpack_record, unpack_result)
from analytics_zoo_tpu.deploy.serving import (  # noqa: F401
    ClusterServing, DeviceExecutor, FileQueue, InputQueue, MemoryQueue,
    OutputQueue, RedisQueue, ServingConfig, decode_image, decode_tensor,
    encode_image, encode_tensor, error_payload, make_queue,
    make_queue_from_zoo)
from analytics_zoo_tpu.deploy.shmqueue import (  # noqa: F401
    ShmQueue, shm_available)
