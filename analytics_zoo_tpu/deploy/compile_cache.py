"""Persistent AOT compile cache: serialized XLA executables on disk.

A serving process pays one XLA compile per (model, bucket shape, dtype,
device) program signature.  On a restart every one of those compiles is
paid again before the worker reaches full bucket coverage — the
dominant term in restart-to-SLO time (docs/PERFORMANCE.md
``serving_restart_to_slo``).  The reference stack dodged this by
loading pre-built OpenVINO engine blobs (PAPER.md §L0); the TPU-native
equivalent is ``jax.jit(fwd).lower(...).compile()`` +
``jax.experimental.serialize_executable``: the compiled executable
serializes to bytes, and a restarted process deserializes it back in
milliseconds instead of re-tracing and re-compiling.

Entry layout (one file per program, content-addressed)::

    <digest>.xc := MAGIC("AZXC") | u32 header_len | header_json
                   | u32 crc32(payload) | u64 payload_len | payload

``digest = sha256(fingerprint, sig)`` where ``sig`` carries the input
shapes/dtypes, target device, fused top-N and the mesh descriptor
(platform x device count).  The jax/jaxlib versions live in the HEADER,
not the digest: a version mismatch is *detected* at load
(``version_skew``) and the caller's recompile overwrites the same file
in place — an invisible miss would leave stale executables pinned on
disk forever.

Failure semantics mirror ``train/checkpoint.py`` snapshots: payload CRC
verified on every load; a torn/truncated/unparseable entry is
quarantined to ``<file>.corrupt`` and the caller falls back to a clean
recompile.  Writes are atomic (tmp + ``os.replace``) so a crash
mid-store never leaves a half-written entry under the real name.

Every outcome is counted in
``serving_compile_cache_events_total{event=hit|miss|corrupt|version_skew}``
with the owning model as a ``model`` label (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import struct
import tempfile
import threading
import time
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = ["CompileCache", "CompileCacheCorrupt", "cache_env"]

logger = logging.getLogger("analytics_zoo_tpu.deploy")

_MAGIC = b"AZXC"
_HDR = struct.Struct("<I")      # header_len
_PAY = struct.Struct("<IQ")     # crc32(payload), payload_len


class CompileCacheCorrupt(Exception):
    """A cache entry failed structural validation (magic/CRC/length)."""


def cache_env() -> Dict[str, str]:
    """The toolchain identity an executable is only valid under.

    ``jax``/``jaxlib`` versions gate deserialization (an executable
    serialized by one XLA build is not guaranteed loadable by another);
    ``mesh`` (platform x visible device count) joins the *digest* so a
    4-chip cache never collides with an 8-chip one.
    """
    import jax
    import jaxlib

    devs = jax.devices()
    return {
        "jax": jax.__version__,
        "jaxlib": getattr(jaxlib, "__version__", "unknown"),
        "mesh": f"{devs[0].platform}x{len(devs)}",
    }


class CompileCache:
    """Content-addressed on-disk store of serialized XLA executables.

    One instance may be shared by every model in a multi-model worker;
    the in-memory ledger (``_index``) and event counts are guarded by
    ``_lock`` — loads/stores arrive concurrently from replica dispatch
    threads and the warm() path.
    """

    SUFFIX = ".xc"

    def __init__(self, root: str, max_entries: int = 512):
        self.root = str(root)
        self.max_entries = max(1, int(max_entries))
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        # digest -> header of entries this process has seen intact
        self._index: Dict[str, Dict[str, Any]] = {}
        self._events: Dict[str, int] = {}

    # -- keying ------------------------------------------------------------

    @staticmethod
    def digest(fingerprint: str, sig: Dict[str, Any]) -> str:
        """Content address for one program: model fingerprint + program
        signature + mesh descriptor (NOT the jax version — see module
        docstring)."""
        blob = json.dumps({"fp": fingerprint, "sig": sig,
                           "mesh": cache_env()["mesh"]}, sort_keys=True)
        return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:40]

    def path_for(self, fingerprint: str, sig: Dict[str, Any]) -> str:
        return os.path.join(self.root,
                            self.digest(fingerprint, sig) + self.SUFFIX)

    # -- events ------------------------------------------------------------

    def _event(self, event: str, model: str) -> None:
        from analytics_zoo_tpu.observe import metrics as obs

        with self._lock:
            self._events[event] = self._events.get(event, 0) + 1
        obs.count("serving_compile_cache_events_total", event=event,
                  model=model, flat=f"serving/compile_cache_{event}")

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            events = dict(self._events)
            indexed = len(self._index)
        return {"root": self.root, "events": events, "indexed": indexed,
                "entries": len(self._entry_files())}

    # -- store -------------------------------------------------------------

    def store(self, fingerprint: str, sig: Dict[str, Any], compiled,
              model: str = "default") -> str:
        """Serialize one compiled executable; atomic overwrite-in-place
        (version-skewed or stale entries at the same digest are simply
        replaced).  Returns the entry path."""
        from jax.experimental import serialize_executable

        blob, in_tree, out_tree = serialize_executable.serialize(compiled)
        payload = pickle.dumps((blob, in_tree, out_tree),
                               protocol=pickle.HIGHEST_PROTOCOL)
        header = dict(fingerprint=fingerprint, sig=sig, model=model,
                      created=time.time(), **cache_env())
        hdr = json.dumps(header, sort_keys=True).encode("utf-8")
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        path = self.path_for(fingerprint, sig)
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(_MAGIC)
                f.write(_HDR.pack(len(hdr)))
                f.write(hdr)
                f.write(_PAY.pack(crc, len(payload)))
                f.write(payload)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        with self._lock:
            self._index[os.path.basename(path)[:-len(self.SUFFIX)]] = header
        self.gc()
        return path

    # -- load --------------------------------------------------------------

    def _read_entry(self, path: str) -> Tuple[Dict[str, Any], bytes]:
        """Parse + CRC-check one entry; raises CompileCacheCorrupt on any
        structural damage (torn write, truncation, bit rot)."""
        with open(path, "rb") as f:
            data = f.read()
        if len(data) < len(_MAGIC) + _HDR.size or \
                data[:len(_MAGIC)] != _MAGIC:
            raise CompileCacheCorrupt(f"{path}: bad magic")
        off = len(_MAGIC)
        (hlen,) = _HDR.unpack_from(data, off)
        off += _HDR.size
        if off + hlen + _PAY.size > len(data):
            raise CompileCacheCorrupt(f"{path}: truncated header")
        try:
            header = json.loads(data[off:off + hlen].decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise CompileCacheCorrupt(f"{path}: unparseable header: {e}")
        off += hlen
        crc, plen = _PAY.unpack_from(data, off)
        off += _PAY.size
        payload = data[off:off + plen]
        if len(payload) != plen:
            raise CompileCacheCorrupt(
                f"{path}: truncated payload ({len(payload)}/{plen} bytes)")
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            raise CompileCacheCorrupt(f"{path}: payload CRC mismatch")
        return header, payload

    def _quarantine(self, path: str, model: str, why: str) -> None:
        self._event("corrupt", model)
        logger.warning("compile cache: quarantining %s (%s)", path, why)
        try:
            os.replace(path, path + ".corrupt")
        except OSError:
            pass
        with self._lock:
            self._index.pop(
                os.path.basename(path)[:-len(self.SUFFIX)], None)

    @staticmethod
    def _version_ok(header: Dict[str, Any]) -> bool:
        env = cache_env()
        return (header.get("jax") == env["jax"]
                and header.get("jaxlib") == env["jaxlib"])

    def _deserialize(self, payload: bytes):
        from jax.experimental import serialize_executable

        blob, in_tree, out_tree = pickle.loads(payload)
        return serialize_executable.deserialize_and_load(
            blob, in_tree, out_tree)

    def load(self, fingerprint: str, sig: Dict[str, Any],
             model: str = "default"):
        """One executable, or None (caller compiles + ``store``\\ s).

        Counts exactly one of ``hit`` / ``miss`` / ``corrupt`` /
        ``version_skew``.  A skewed entry stays on disk: the caller's
        recompile stores to the same digest and overwrites it."""
        path = self.path_for(fingerprint, sig)
        if not os.path.exists(path):
            self._event("miss", model)
            return None
        try:
            header, payload = self._read_entry(path)
        except CompileCacheCorrupt as e:
            self._quarantine(path, model, str(e))
            return None
        if not self._version_ok(header):
            self._event("version_skew", model)
            logger.warning(
                "compile cache: %s built under jax %s/jaxlib %s; current "
                "is %s — recompiling and overwriting", path,
                header.get("jax"), header.get("jaxlib"),
                cache_env()["jax"])
            return None
        try:
            compiled = self._deserialize(payload)
        except Exception as e:
            # structurally intact but undeserializable (e.g. an XLA
            # build mismatch the version header didn't capture)
            self._quarantine(path, model, f"deserialize failed: {e}")
            return None
        with self._lock:
            self._index[os.path.basename(path)[:-len(self.SUFFIX)]] = header
        self._event("hit", model)
        return compiled

    def load_all(self, fingerprint: str, model: str = "default"
                 ) -> Iterator[Tuple[Dict[str, Any], Any]]:
        """Every intact, version-compatible entry for one model
        fingerprint — the warm() path: a restarted worker pre-installs
        full bucket coverage without needing to see a single request.
        Yields ``(sig, compiled)``; each successful load counts ``hit``."""
        for path in self._entry_files():
            try:
                header, payload = self._read_entry(path)
            except CompileCacheCorrupt as e:
                self._quarantine(path, model, str(e))
                continue
            if header.get("fingerprint") != fingerprint:
                continue
            if not self._version_ok(header):
                self._event("version_skew", model)
                continue
            try:
                compiled = self._deserialize(payload)
            except Exception as e:
                self._quarantine(path, model, f"deserialize failed: {e}")
                continue
            with self._lock:
                self._index[os.path.basename(path)[:-len(self.SUFFIX)]] = \
                    header
            self._event("hit", model)
            yield header["sig"], compiled

    # -- housekeeping ------------------------------------------------------

    def _entry_files(self) -> List[str]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(os.path.join(self.root, fn) for fn in names
                      if fn.endswith(self.SUFFIX))

    def entries(self) -> List[Dict[str, Any]]:
        """Headers of every intact entry (corrupt ones skipped, not
        quarantined — this is a read-only listing)."""
        out = []
        for path in self._entry_files():
            try:
                header, _ = self._read_entry(path)
            except CompileCacheCorrupt:
                continue
            out.append(header)
        return out

    def gc(self, max_entries: Optional[int] = None) -> int:
        """Evict oldest-mtime entries beyond the cap (docs/SERVING.md
        "Warm start & multi-model" — eviction is LRU-by-mtime because a
        warm() sweep re-reads, and thereby touches, every live entry).
        Returns the number evicted."""
        cap = max_entries if max_entries is not None else self.max_entries
        files = self._entry_files()
        if len(files) <= cap:
            return 0
        def _mtime(p):
            try:
                return os.path.getmtime(p)
            except OSError:
                return 0.0
        files.sort(key=_mtime)
        evicted = 0
        for path in files[:len(files) - cap]:
            try:
                os.unlink(path)
                evicted += 1
            except OSError:
                continue
            with self._lock:
                self._index.pop(
                    os.path.basename(path)[:-len(self.SUFFIX)], None)
        return evicted
