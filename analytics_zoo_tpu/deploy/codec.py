"""Binary tensor wire codec: framed ``dtype/shape/bytes`` records.

The legacy serving wire ships every tensor as
``ndarray → tobytes → base64 → JSON string`` and decodes it with the
mirror-image chain — ~2.7x the bytes on the wire and two full passes
over the payload in pure Python (the r05 bench measured the full queue
path at 27 imgs/s while the device side of the same model did
thousands).  This module replaces it with a length-prefixed binary
frame that moves raw bytes:

    AZB1 | u32 meta_len | meta-JSON | u32 n_tensors |
      [ u16 name_len | name | u16 dtype_len | dtype | u8 ndim |
        u64*ndim shape | u64 nbytes | pad→64 | raw bytes ] * n

``meta`` is the record minus its tensor fields (uri/ts/ttl_ms/fmt plus
any legacy JSON-safe payloads — backward-compat base64 dicts ride
through untouched).  Tensor payloads are 64-byte aligned so
:func:`unpack_record` can hand back ``np.frombuffer`` *views* into the
source buffer — decode is zero-copy: off a shared-memory slot the view
feeds ``jax.device_put`` without the bytes ever being duplicated on the
host.  Views are read-only by design (copy-on-write is explicit via
``copy=True`` / ``decode_tensor(writable=True)``); the
``serving/codec_tensor_copies`` counter makes every materialized copy
visible, which is how the zero-copy claim is test-verified rather than
asserted.

dtype fidelity: the dtype crosses the wire as its numpy name, with an
``ml_dtypes`` fallback so ``uint8``/``bfloat16`` records stay
``uint8``/``bfloat16`` end-to-end and any normalize/cast happens
on-device (``imagenet_preprocess``), never in the codec.

Every pack/unpack reports ``serving_wire_bytes_total{codec=...}`` and
``serving_codec_seconds{codec,op}`` into the observe CATALOG so the
bench breakdown can attribute the wire share per codec.
"""

from __future__ import annotations

import json
import struct
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.core.profiling import TIMERS
from analytics_zoo_tpu.observe import metrics as obs

__all__ = ["MAGIC", "pack_record", "pack_record_into", "packed_nbytes",
           "prepare_record", "unpack_record", "is_packed", "pack_result",
           "unpack_result", "wire_dtype"]

MAGIC = b"AZB1"
_ALIGN = 64
_HDR = struct.Struct("<4sI")       # magic, meta_len
_NT = struct.Struct("<I")          # n_tensors
_U16 = struct.Struct("<H")
_U64 = struct.Struct("<Q")


def wire_dtype(name: str) -> np.dtype:
    """Resolve a wire dtype name, including the ml_dtypes families
    (``bfloat16`` etc.) numpy itself cannot spell."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # ships with jax

        return np.dtype(getattr(ml_dtypes, name))


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def _split(rec: Dict[str, Any]) -> Tuple[Dict[str, Any],
                                         List[Tuple[str, np.ndarray]]]:
    """Record → (JSON-safe meta, [(name, ndarray)]).  Only genuine
    ndarray values ride the binary frames; legacy ``{"b64": ...}``
    payloads and plain scalars stay in the meta JSON."""
    meta: Dict[str, Any] = {}
    tensors: List[Tuple[str, np.ndarray]] = []
    for k, v in rec.items():
        if k.startswith("_"):       # worker-side handles (spans etc.)
            continue
        if isinstance(v, np.ndarray):
            if v.dtype.hasobject:
                raise ValueError(f"field {k!r}: object dtype is not "
                                 "wire-encodable")
            tensors.append((k, v))
        else:
            meta[k] = v
    return meta, tensors


def _frame_sizes(meta_blob: bytes,
                 tensors: List[Tuple[str, np.ndarray]]) -> int:
    n = _HDR.size + len(meta_blob) + _NT.size
    for name, a in tensors:
        nb = name.encode("utf-8")
        n += _U16.size + len(nb) + _U16.size + len(str(a.dtype))
        n += 1 + 8 * a.ndim + 8
        n = _align(n)
        n += a.nbytes
    return n


def prepare_record(rec: Dict[str, Any]
                   ) -> Tuple[bytes, List[Tuple[str, np.ndarray]], int]:
    """Split + size a record once: ``(meta_blob, tensors, nbytes)``.
    Callers that need the size before packing (slot-fit prechecks) hand
    the triple back to :func:`pack_record_into` so the split and the
    meta JSON dump are not paid twice on the hot path."""
    meta, tensors = _split(rec)
    blob = json.dumps(meta).encode("utf-8")
    return blob, tensors, _frame_sizes(blob, tensors)


def packed_nbytes(rec: Dict[str, Any]) -> int:
    """Exact wire size of ``pack_record(rec)`` (slot-fit precheck)."""
    return prepare_record(rec)[2]


def pack_record_into(rec: Dict[str, Any], buf, offset: int = 0,
                     codec: str = "binary",
                     prepared: Optional[Tuple] = None) -> int:
    """Serialize ``rec`` directly into a writable buffer (a shm slot, a
    bytearray) at ``offset``.  Returns bytes written.  Tensor bytes are
    memcpy'd exactly once — array memory → wire — with no base64 and no
    intermediate ``tobytes()`` allocation.  Pass a
    :func:`prepare_record` triple as ``prepared`` to reuse the
    split/size work already done for the slot-fit check."""
    t0 = time.perf_counter()
    blob, tensors, _ = prepared or prepare_record(rec)
    dst = np.frombuffer(buf, dtype=np.uint8)
    o = offset
    dst[o:o + _HDR.size] = np.frombuffer(
        _HDR.pack(MAGIC, len(blob)), np.uint8)
    o += _HDR.size
    dst[o:o + len(blob)] = np.frombuffer(blob, np.uint8)
    o += len(blob)
    dst[o:o + _NT.size] = np.frombuffer(_NT.pack(len(tensors)), np.uint8)
    o += _NT.size
    for name, a in tensors:
        a = np.ascontiguousarray(a)
        hdr = bytearray()
        nb = name.encode("utf-8")
        dt = str(a.dtype).encode("ascii")
        hdr += _U16.pack(len(nb)) + nb
        hdr += _U16.pack(len(dt)) + dt
        hdr += bytes([a.ndim])
        for s in a.shape:
            hdr += _U64.pack(s)
        hdr += _U64.pack(a.nbytes)
        dst[o:o + len(hdr)] = np.frombuffer(bytes(hdr), np.uint8)
        o += len(hdr)
        o = offset + _align(o - offset)
        if a.nbytes:
            dst[o:o + a.nbytes] = a.reshape(-1).view(np.uint8)
        o += a.nbytes
    total = o - offset
    obs.count("serving_wire_bytes_total", total, codec=codec,
              flat=f"serving/wire_bytes_{codec}")
    obs.observe("serving_codec_seconds", time.perf_counter() - t0,
                codec=codec, op="encode")
    return total


def pack_record(rec: Dict[str, Any], codec: str = "binary") -> bytearray:
    """Serialize ``rec`` to a fresh buffer (File/network backends)."""
    prepared = prepare_record(rec)
    out = bytearray(prepared[2])
    pack_record_into(rec, out, 0, codec=codec, prepared=prepared)
    return out


def is_packed(buf) -> bool:
    mv = memoryview(buf)
    return len(mv) >= 4 and bytes(mv[:4]) == MAGIC


def unpack_record(buf, offset: int = 0, copy: bool = False,
                  codec: str = "binary") -> Dict[str, Any]:
    """Deserialize one packed record.  Tensor fields come back as
    ``np.frombuffer`` views into ``buf`` — zero-copy, read-only, and
    holding a reference to ``buf`` (so a shm slot stays leased exactly
    as long as any view of it is alive).  ``copy=True`` materializes
    writable copies instead (counted: ``serving/codec_tensor_copies``)."""
    t0 = time.perf_counter()
    mv = memoryview(buf).cast("B")
    magic, meta_len = _HDR.unpack_from(mv, offset)
    if magic != MAGIC:
        raise ValueError("not a packed record (bad magic)")
    o = offset + _HDR.size
    rec: Dict[str, Any] = json.loads(bytes(mv[o:o + meta_len]))
    o += meta_len
    (n_tensors,) = _NT.unpack_from(mv, o)
    o += _NT.size
    for _ in range(n_tensors):
        (nlen,) = _U16.unpack_from(mv, o)
        o += _U16.size
        name = bytes(mv[o:o + nlen]).decode("utf-8")
        o += nlen
        (dlen,) = _U16.unpack_from(mv, o)
        o += _U16.size
        dt = wire_dtype(bytes(mv[o:o + dlen]).decode("ascii"))
        o += dlen
        ndim = mv[o]
        o += 1
        shape = tuple(_U64.unpack_from(mv, o + 8 * i)[0]
                      for i in range(ndim))
        o += 8 * ndim
        (nbytes,) = _U64.unpack_from(mv, o)
        o += 8
        o = offset + _align(o - offset)
        count = nbytes // dt.itemsize if dt.itemsize else 0
        # frombuffer on `buf` itself (not the memoryview) so the view's
        # .base chain pins the original buffer object — the shm slot
        # lease rides that refcount
        a = np.frombuffer(buf, dtype=dt, count=count,
                          offset=o).reshape(shape)
        if copy:
            TIMERS.incr("serving/codec_tensor_copies")
            a = a.copy()
        else:
            a.setflags(write=False)
        rec[name] = a
        o += nbytes
    obs.observe("serving_codec_seconds", time.perf_counter() - t0,
                codec=codec, op="decode")
    return rec


# -- result direction -------------------------------------------------------

def pack_result(value: Any, codec: str = "binary") -> bytes:
    """Result value → wire bytes.  Dicts carrying ndarrays (the native
    ``{"tensor": row}`` envelope) take the binary frame; everything else
    (error payloads, top-N pairs, reference-wire lists) is plain JSON
    utf-8 — the magic prefix discriminates on the way back."""
    if isinstance(value, dict) and any(
            isinstance(v, np.ndarray) for v in value.values()):
        return bytes(pack_record(value, codec=codec))
    return json.dumps(value).encode("utf-8")


def unpack_result(buf, copy: bool = True, codec: str = "binary") -> Any:
    if is_packed(buf):
        return unpack_record(buf, copy=copy, codec=codec)
    return json.loads(bytes(memoryview(buf)))
