"""Cluster Serving: streaming inference worker + client queues.

Reference capability: serving/ClusterServing.scala:46 (Spark Structured
Streaming over a Redis stream ``image_stream``: read → base64-decode →
batch → broadcast InferenceModel predict → write results to Redis hashes,
with XTRIM backpressure at :123-138) and the Python client
pyzoo/zoo/serving/client.py:58-150 (InputQueue.enqueue_image / xadd,
OutputQueue.dequeue / query).

TPU-first redesign: the streaming engine is a plain worker loop around one
compiled forward (no Spark, no model broadcast — the XLA executable IS the
broadcast).  The transport is pluggable:

- ``MemoryQueue``   — in-process (tests, single-process apps);
- ``FileQueue``     — spool directory with atomic renames (cross-process
                      on one host / shared FS, zero extra deps);
- ``RedisQueue``    — wire-compatible with the reference client
                      (xadd/hset), used when ``redis`` is importable.

Client API parity: ``InputQueue.enqueue`` / ``enqueue_image`` (base64) and
``OutputQueue.dequeue`` / ``query`` keep the reference semantics.

Robustness contract shared by all three backends (docs/ROBUSTNESS.md):
``get_result`` raises :class:`TimeoutError` with a uniform message once
the deadline passes, ``health()`` returns a ``{"ok": bool, ...}`` probe
(writability for FileQueue, PING for RedisQueue), and persistent-backend
I/O runs under a :class:`~analytics_zoo_tpu.robust.RetryPolicy`
(transient filesystem/connection blips are retried with backoff; the
``queue.io`` fault-injection site exercises exactly those paths).
"""

from __future__ import annotations

import base64
import json
import os
import tempfile
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.robust import RetryPolicy, faults

__all__ = ["MemoryQueue", "FileQueue", "RedisQueue", "make_queue",
           "InputQueue", "OutputQueue", "ServingConfig", "ClusterServing",
           "encode_image", "decode_image"]


# ---------------------------------------------------------------------------
# image payload codec (reference serving/utils/ImageProcessing base64→BGR,
# client.py:83-110 enqueue_image)
# ---------------------------------------------------------------------------

def encode_tensor(a) -> Dict[str, Any]:
    """ndarray → JSON-safe payload (the single raw-array wire codec)."""
    a = np.asarray(a)
    return {"b64": base64.b64encode(a.tobytes()).decode("ascii"),
            "shape": list(a.shape), "dtype": str(a.dtype)}


def decode_tensor(payload: Dict[str, Any]) -> np.ndarray:
    return np.frombuffer(
        base64.b64decode(payload["b64"]),
        dtype=np.dtype(payload["dtype"])).reshape(payload["shape"]).copy()


def encode_image(image) -> Dict[str, Any]:
    """ndarray (H, W, C) float/uint8 or a path → JSON-safe payload."""
    if isinstance(image, str):
        with open(image, "rb") as f:
            return {"image": base64.b64encode(f.read()).decode("ascii"),
                    "codec": "file"}
    return {"codec": "raw", "image": encode_tensor(image)}


def decode_image(payload: Dict[str, Any]) -> np.ndarray:
    if payload.get("codec") == "raw":
        return decode_tensor(payload["image"])
    raw = base64.b64decode(payload["image"])
    import cv2  # compressed file bytes (jpg/png)
    img = cv2.imdecode(np.frombuffer(raw, np.uint8), cv2.IMREAD_COLOR)
    if img is None:
        raise ValueError("undecodable image payload")
    return img


# ---------------------------------------------------------------------------
# queue backends
# ---------------------------------------------------------------------------

def _timeout_msg(q, rid: str, timeout: float) -> str:
    """One TimeoutError message shape across every backend, so callers
    (and tests) never have to care which transport is underneath."""
    return (f"{type(q).__name__}[{q.name}]: no result for {rid!r} "
            f"within {timeout:.1f}s")


def _io_retry(name: str, retry_on) -> RetryPolicy:
    """Default retry for persistent-backend I/O: 3 quick attempts —
    enough to absorb a transient fs/connection blip without turning a
    dead backend into a multi-second client hang."""
    return RetryPolicy(max_attempts=3, base_delay_s=0.02, max_delay_s=0.5,
                       retry_on=retry_on, name=name)


class MemoryQueue:
    """In-process stream + result store (single-process serving/tests)."""

    def __init__(self, name: str = "serving_stream"):
        self.name = name
        self._items: List[Tuple[str, Dict]] = []
        self._results: Dict[str, Any] = {}
        self._cv = threading.Condition()

    def push(self, record: Dict) -> str:
        rid = record.get("uri") or uuid.uuid4().hex
        with self._cv:
            self._items.append((rid, record))
            self._cv.notify_all()
        return rid

    def pop_batch(self, n: int, timeout: float = 0.1
                  ) -> List[Tuple[str, Dict]]:
        deadline = time.monotonic() + timeout
        with self._cv:
            while not self._items and time.monotonic() < deadline:
                self._cv.wait(timeout=deadline - time.monotonic())
            out, self._items = self._items[:n], self._items[n:]
            return out

    def __len__(self) -> int:
        with self._cv:
            return len(self._items)

    def trim(self, maxlen: int) -> int:
        """Drop oldest items beyond maxlen (reference XTRIM backpressure,
        ClusterServing.scala:132-138).  Returns number dropped."""
        with self._cv:
            drop = max(0, len(self._items) - maxlen)
            if drop:
                self._items = self._items[drop:]
            return drop

    def set_result(self, rid: str, value: Any) -> None:
        with self._cv:
            self._results[rid] = value
            self._cv.notify_all()

    def get_result(self, rid: str, timeout: float = 10.0) -> Any:
        deadline = time.monotonic() + timeout
        with self._cv:
            while rid not in self._results:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(_timeout_msg(self, rid, timeout))
                self._cv.wait(timeout=left)
            return self._results.pop(rid)

    def pending_results(self) -> List[str]:
        with self._cv:
            return list(self._results)

    def health(self) -> Dict[str, Any]:
        with self._cv:
            return {"ok": True, "backend": "memory",
                    "depth": len(self._items),
                    "pending_results": len(self._results)}


class FileQueue:
    """Spool-directory stream: cross-process on one host or a shared FS.

    Records are JSON files; atomic rename makes push/claim race-free
    without locks (rename(2) is atomic on POSIX).  Plays the role the
    Redis server plays for the reference when no Redis is available.
    """

    def __init__(self, root: str, name: str = "serving_stream",
                 retry: Optional[RetryPolicy] = None):
        self.name = name
        self.root = os.path.join(root, name)
        self.in_dir = os.path.join(self.root, "in")
        self.out_dir = os.path.join(self.root, "out")
        for d in (self.in_dir, self.out_dir):
            os.makedirs(d, exist_ok=True)
        self._seq = 0
        self._retry = retry or _io_retry("filequeue_io", (OSError,))

    def push(self, record: Dict) -> str:
        rid = record.get("uri") or uuid.uuid4().hex
        self._seq += 1
        fn = f"{time.time_ns():020d}_{self._seq:06d}_{rid}.json"

        def _write():
            faults.inject("queue.io")
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            with os.fdopen(fd, "w") as f:
                json.dump({"rid": rid, "record": record}, f)
            os.replace(tmp, os.path.join(self.in_dir, fn))

        self._retry.call(_write)
        return rid

    # claims older than this are from a crashed worker and get requeued
    STALE_CLAIM_S = 60.0

    def pop_batch(self, n: int, timeout: float = 0.1
                  ) -> List[Tuple[str, Dict]]:
        deadline = time.monotonic() + timeout
        while True:
            out = []
            for fn in sorted(os.listdir(self.in_dir)):
                if len(out) >= n:
                    break
                path = os.path.join(self.in_dir, fn)
                if fn.endswith(".claimed"):
                    # recover claims orphaned by a crashed worker
                    try:
                        if (time.time() - os.path.getmtime(path)
                                > self.STALE_CLAIM_S):
                            os.rename(path, path[: -len(".claimed")])
                    except OSError:
                        pass
                    continue
                if not fn.endswith(".json"):
                    continue
                claimed = path + ".claimed"
                try:
                    os.rename(path, claimed)  # atomic claim
                except OSError:
                    continue  # another worker won
                with open(claimed) as f:
                    blob = json.load(f)
                os.unlink(claimed)
                out.append((blob["rid"], blob["record"]))
            if out or time.monotonic() >= deadline:
                return out
            time.sleep(0.005)

    def __len__(self) -> int:
        return sum(1 for fn in os.listdir(self.in_dir)
                   if fn.endswith(".json"))

    def trim(self, maxlen: int) -> int:
        files = sorted(fn for fn in os.listdir(self.in_dir)
                       if fn.endswith(".json"))
        drop = max(0, len(files) - maxlen)
        for fn in files[:drop]:
            try:
                os.unlink(os.path.join(self.in_dir, fn))
            except OSError:
                pass
        return drop

    def set_result(self, rid: str, value: Any) -> None:
        def _write():
            faults.inject("queue.io")
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            with os.fdopen(fd, "w") as f:
                json.dump(value, f)
            os.replace(tmp, os.path.join(self.out_dir, rid + ".json"))

        self._retry.call(_write)

    def get_result(self, rid: str, timeout: float = 10.0) -> Any:
        path = os.path.join(self.out_dir, rid + ".json")
        deadline = time.monotonic() + timeout

        def _read():
            faults.inject("queue.io")
            with open(path) as f:
                val = json.load(f)
            os.unlink(path)
            return val

        while True:
            if os.path.exists(path):
                return self._retry.call(_read)
            if time.monotonic() >= deadline:
                raise TimeoutError(_timeout_msg(self, rid, timeout))
            time.sleep(0.005)

    def pending_results(self) -> List[str]:
        return [fn[:-5] for fn in os.listdir(self.out_dir)
                if fn.endswith(".json")]

    def health(self) -> Dict[str, Any]:
        """Probe: the spool directories must exist and be writable."""
        try:
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".probe")
            os.close(fd)
            os.unlink(tmp)
            return {"ok": True, "backend": "file", "root": self.root,
                    "depth": len(self)}
        except OSError as e:
            return {"ok": False, "backend": "file", "root": self.root,
                    "error": str(e)}


class RedisQueue:
    """Redis-stream backend, wire-shaped like the reference
    (xadd to the stream, results to hashes ``result:{uri}``) —
    client.py:83-150 / ClusterServing.scala:107-138.  Requires the
    ``redis`` package and a live server.

    Reads go through a consumer group (XREADGROUP + XACK), so N workers
    on one queue each claim disjoint records — the same exactly-one-
    claimer contract as FileQueue."""

    GROUP = "serving_workers"

    def __init__(self, host: str = "localhost", port: int = 6379,
                 name: str = "serving_stream",
                 retry: Optional[RetryPolicy] = None):
        import redis  # gated import

        self.name = name
        self._r = redis.Redis(host=host, port=port, decode_responses=True)
        self._consumer = uuid.uuid4().hex
        self._retry = retry or _io_retry(
            "redisqueue_io",
            (getattr(redis, "ConnectionError", OSError),
             getattr(redis, "TimeoutError", OSError), OSError))
        try:
            self._r.xgroup_create(self.name, self.GROUP, id="0",
                                  mkstream=True)
        except redis.ResponseError as e:  # BUSYGROUP = already exists
            if "BUSYGROUP" not in str(e):
                raise

    def push(self, record: Dict) -> str:
        rid = record.get("uri") or uuid.uuid4().hex

        def _write():
            faults.inject("queue.io")
            self._r.xadd(self.name, {"blob": json.dumps(
                {"rid": rid, "record": record})})

        self._retry.call(_write)
        return rid

    def pop_batch(self, n: int, timeout: float = 0.1
                  ) -> List[Tuple[str, Dict]]:
        resp = self._r.xreadgroup(self.GROUP, self._consumer,
                                  {self.name: ">"}, count=n,
                                  block=int(timeout * 1000))
        out = []
        for _, entries in resp or []:
            for eid, fields in entries:
                if "blob" in fields:
                    # native client envelope (json)
                    blob = json.loads(fields["blob"])
                    out.append((blob["rid"], blob["record"]))
                else:
                    # reference-client wire shape: flat fields
                    # {uri, image: b64(jpg bytes)} (client.py:102-110) —
                    # lift into the worker's record schema (the b64 file
                    # codec is exactly decode_image's "file" path)
                    rec = dict(fields)
                    rid = rec.get("uri") or eid
                    if "image" in rec and not isinstance(rec["image"],
                                                         dict):
                        rec = {"uri": rid, "codec": "file",
                               "image": rec["image"]}
                    out.append((rid, rec))
                self._r.xack(self.name, self.GROUP, eid)
        return out

    def __len__(self) -> int:
        return self._r.xlen(self.name)

    def trim(self, maxlen: int) -> int:
        before = self._r.xlen(self.name)
        self._r.xtrim(self.name, maxlen=maxlen)
        return max(0, before - self._r.xlen(self.name))

    def set_result(self, rid: str, value: Any) -> None:
        def _write():
            faults.inject("queue.io")
            self._r.hset(f"result:{rid}", "value", json.dumps(value))

        self._retry.call(_write)

    def get_result(self, rid: str, timeout: float = 10.0) -> Any:
        deadline = time.monotonic() + timeout

        def _read():
            faults.inject("queue.io")
            return self._r.hget(f"result:{rid}", "value")

        while True:
            v = self._retry.call(_read)
            if v is not None:
                self._r.delete(f"result:{rid}")
                return json.loads(v)
            if time.monotonic() >= deadline:
                raise TimeoutError(_timeout_msg(self, rid, timeout))
            time.sleep(0.01)

    def pending_results(self) -> List[str]:
        return [k.split(":", 1)[1] for k in self._r.keys("result:*")]

    def health(self) -> Dict[str, Any]:
        """Probe: PING the server (the reference serving stack's startup
        does the same liveness check before starting the stream)."""
        try:
            self._r.ping()
            return {"ok": True, "backend": "redis", "depth": len(self)}
        except Exception as e:
            return {"ok": False, "backend": "redis", "error": str(e)}


def make_queue(backend: str = "memory", **kw):
    """String lowering for queue backends."""
    b = backend.lower()
    if b in ("memory", "mem"):
        return MemoryQueue(**kw)
    if b in ("file", "spool"):
        return FileQueue(**kw)
    if b in ("redis",):
        return RedisQueue(**kw)
    raise ValueError(f"unknown queue backend {backend!r}; "
                     "known: memory, file, redis")


# ---------------------------------------------------------------------------
# client (reference pyzoo/zoo/serving/client.py:58-150)
# ---------------------------------------------------------------------------

class InputQueue:
    """Producer side: enqueue records for the serving worker."""

    def __init__(self, queue):
        self.queue = queue

    def enqueue(self, uri: Optional[str] = None, **data) -> str:
        """Enqueue arbitrary named arrays (reference enqueue:58)."""
        rec: Dict[str, Any] = {"uri": uri or uuid.uuid4().hex}
        for k, v in data.items():
            rec[k] = encode_tensor(v)
        return self.queue.push(rec)

    def enqueue_image(self, uri: Optional[str] = None, image=None) -> str:
        """Enqueue one image (path or ndarray) — reference
        enqueue_image:83 (base64 xadd)."""
        rec = {"uri": uri or uuid.uuid4().hex, **encode_image(image)}
        return self.queue.push(rec)


class OutputQueue:
    """Consumer side: fetch prediction results."""

    def __init__(self, queue):
        self.queue = queue

    def query(self, uri: str, timeout: float = 10.0) -> Any:
        """Result for one uri (reference query:140)."""
        return self.queue.get_result(uri, timeout=timeout)

    def dequeue(self, timeout: float = 10.0) -> Dict[str, Any]:
        """Drain all currently-available results (reference dequeue:127)."""
        deadline = time.monotonic() + timeout
        while True:
            pend = self.queue.pending_results()
            if pend:
                return {rid: self.queue.get_result(rid, timeout=1.0)
                        for rid in pend}
            if time.monotonic() >= deadline:
                return {}
            time.sleep(0.01)


# ---------------------------------------------------------------------------
# the serving worker (reference ClusterServing.scala main loop)
# ---------------------------------------------------------------------------

class ServingConfig:
    """YAML/dict config (reference ClusterServingHelper.scala:104-170)."""

    def __init__(self, model_path: Optional[str] = None, batch_size: int = 32,
                 backpressure_maxlen: int = 10_000, poll_timeout_s: float = 0.1,
                 postprocess_top_n: Optional[int] = None, int8: bool = False,
                 tensorboard_dir: Optional[str] = None):
        self.model_path = model_path
        self.batch_size = batch_size
        self.backpressure_maxlen = backpressure_maxlen
        self.poll_timeout_s = poll_timeout_s
        self.postprocess_top_n = postprocess_top_n
        self.int8 = int8
        self.tensorboard_dir = tensorboard_dir

    @classmethod
    def from_yaml(cls, path: str) -> "ServingConfig":
        import yaml

        with open(path) as f:
            blob = yaml.safe_load(f) or {}
        return cls(**blob)


def _decode_record(rec: Dict) -> Dict[str, np.ndarray]:
    out = {}
    if "image" in rec:
        out["image"] = decode_image(rec)
    for k, v in rec.items():
        if k != "image" and isinstance(v, dict) and "b64" in v:
            out[k] = decode_tensor(v)
    return out


class ClusterServing:
    """The worker loop: pop batch → decode → predict → write results.

    One process per TPU chip/slice; scale out by running more workers on
    the same queue (FileQueue/RedisQueue hand each record to exactly one
    claimer).  Backpressure trims the input stream like the reference's
    XTRIM-at-memory-threshold (ClusterServing.scala:123-138).
    """

    def __init__(self, model, queue, config: Optional[ServingConfig] = None,
                 preprocess: Optional[Callable] = None):
        self.model = model  # InferenceModel
        self.queue = queue
        self.cfg = config or ServingConfig()
        self.preprocess = preprocess
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.records_served = 0
        self._tb = None
        if self.cfg.tensorboard_dir:
            from analytics_zoo_tpu.core.summary import SummaryWriter
            self._tb = SummaryWriter(self.cfg.tensorboard_dir)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ClusterServing":
        self._thread = threading.Thread(target=self.run_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    # -- model hot reload (reference ClusterServingHelper.scala:185-193:
    # the config/model path is re-checked periodically and the serving
    # model swapped in place without stopping the stream) ----------------
    def enable_hot_reload(self, model_path: str,
                          check_interval_s: float = 10.0
                          ) -> "ClusterServing":
        self._reload_path = model_path
        self._reload_interval = check_interval_s
        self._reload_last_check = 0.0
        self._reload_mtime = self._path_mtime(model_path)
        return self

    @staticmethod
    def _path_mtime(path: str) -> float:
        if os.path.isdir(path):
            return max((os.path.getmtime(os.path.join(path, f))
                        for f in os.listdir(path)), default=0.0)
        return os.path.getmtime(path) if os.path.exists(path) else 0.0

    def _maybe_reload(self) -> bool:
        path = getattr(self, "_reload_path", None)
        if path is None:
            return False
        now = time.time()
        if now - self._reload_last_check < self._reload_interval:
            return False
        self._reload_last_check = now
        mtime = self._path_mtime(path)
        if mtime <= self._reload_mtime:
            return False
        # save_model writes config.json + weights.npz non-atomically:
        # only reload once the mtime has been STABLE for a full check
        # interval, so a mid-write snapshot (new config + old weights,
        # or a truncated npz) is never loaded
        if mtime != getattr(self, "_reload_pending_mtime", None):
            self._reload_pending_mtime = mtime
            return False
        from analytics_zoo_tpu.deploy.inference import InferenceModel

        import logging
        logging.getLogger("analytics_zoo_tpu.deploy").info(
            "model at %s changed (mtime %.0f); hot-reloading", path, mtime)
        self.model = InferenceModel.load(path)
        self._reload_mtime = mtime
        self._reload_pending_mtime = None
        return True

    def run_forever(self) -> None:
        import logging

        log = logging.getLogger("analytics_zoo_tpu.deploy")
        while not self._stop.is_set():
            try:
                self._maybe_reload()
                self.serve_once()
            except Exception:  # keep serving: one bad batch must not
                log.exception("serving batch failed; worker continues")
                time.sleep(0.05)  # kill the worker (reference keeps its
                #                   streaming query alive the same way)

    # -- one scheduling quantum -------------------------------------------
    def serve_once(self) -> int:
        """Serve up to one batch; returns number of records served."""
        dropped = self.queue.trim(self.cfg.backpressure_maxlen)
        if dropped:
            import logging
            logging.getLogger("analytics_zoo_tpu.deploy").warning(
                "backpressure: dropped %d queued records", dropped)
        batch = self.queue.pop_batch(self.cfg.batch_size,
                                     timeout=self.cfg.poll_timeout_s)
        if not batch:
            return 0
        t0 = time.perf_counter()
        rids, arrays = [], []
        for rid, rec in batch:
            try:
                decoded = _decode_record(rec)
                x = decoded.get("image")
                if x is None:  # first non-image tensor
                    x = next(iter(decoded.values()))
                if self.preprocess is not None:
                    x = self.preprocess(x)
                x = np.asarray(x)
                if arrays and x.shape != arrays[0].shape:
                    raise ValueError(
                        f"record shape {x.shape} != batch {arrays[0].shape}")
            except Exception as e:
                # a bad record answers with an error instead of poisoning
                # the batch (clients see it in query() rather than a hang)
                self.queue.set_result(rid, {"error": str(e)})
                continue
            rids.append(rid)
            arrays.append(x)
        if not arrays:
            return 0
        x = np.stack(arrays, axis=0)
        try:
            out = self.model.predict(x)
        except Exception as e:
            # records are already destructively popped from the queue —
            # answer every one with the error rather than losing them
            for rid in rids:
                self.queue.set_result(rid, {"error": str(e)})
            return 0
        outs = out[0] if isinstance(out, list) else out
        for i, rid in enumerate(rids):
            row = np.asarray(outs[i])
            if self.cfg.postprocess_top_n and row.ndim == 1:
                # top-N (class, prob) pairs — reference PostProcessing topN
                idx = np.argsort(row)[::-1][: self.cfg.postprocess_top_n]
                val = [[int(j), float(row[j])] for j in idx]
            else:
                val = row.tolist()
            self.queue.set_result(rid, val)
        dt = time.perf_counter() - t0
        self.records_served += len(rids)
        if self._tb is not None:
            # reference "Serving Throughput"/"Total Records Number" scalars
            self._tb.add_scalar("serving_throughput", len(rids) / dt,
                                self.records_served)
            self._tb.add_scalar("total_records", self.records_served,
                                self.records_served)
        return len(rids)
