"""Cluster Serving: streaming inference worker + client queues.

Reference capability: serving/ClusterServing.scala:46 (Spark Structured
Streaming over a Redis stream ``image_stream``: read → base64-decode →
batch → broadcast InferenceModel predict → write results to Redis hashes,
with XTRIM backpressure at :123-138) and the Python client
pyzoo/zoo/serving/client.py:58-150 (InputQueue.enqueue_image / xadd,
OutputQueue.dequeue / query).

TPU-first redesign: the streaming engine is a multi-stage async pipeline
around compiled forwards (no Spark, no model broadcast — the XLA
executable IS the broadcast; see docs/SERVING.md):

    poller → decode pool → DynamicBatcher → DeviceExecutor → respond pool

Decode/preprocess runs concurrently with device compute, the batcher
groups requests by shape and flushes on batch-full or a deadline, and
the executor double-buffers async dispatches round-robined over
per-device model replicas.  Every stage reports into
``core.profiling.TIMERS`` (``serving/queue_wait`` / ``decode`` /
``batch_wait`` / ``device`` / ``respond`` / ``e2e``) with p50/p99
rollups surfaced by :meth:`ClusterServing.health`.  The transport is
pluggable:

- ``MemoryQueue``   — in-process (tests, single-process apps);
- ``FileQueue``     — spool directory with atomic renames (cross-process
                      on one host / shared FS, zero extra deps);
- ``RedisQueue``    — wire-compatible with the reference client
                      (xadd/hset), used when ``redis`` is importable;
- ``ShmQueue``      — shared-memory ring buffer + binary tensor codec,
                      the zero-copy single-host hot path
                      (``deploy/shmqueue.py``; docs/SERVING.md "Wire
                      format & queue backends").

Wire format is a per-backend property (``queue.wire``): ``"binary"``
backends move framed raw tensor bytes (:mod:`deploy.codec` — no base64,
no JSON for tensor payloads), ``"json"`` backends keep the legacy
base64-in-JSON codec for compatibility with the reference client.  The
worker decodes BOTH on every backend, so old producers keep working
against new workers.

Client API parity: ``InputQueue.enqueue`` / ``enqueue_image`` (base64) and
``OutputQueue.dequeue`` / ``query`` keep the reference semantics.

Robustness contract shared by all three backends (docs/ROBUSTNESS.md):
``get_result`` raises :class:`TimeoutError` with a uniform message once
the deadline passes, ``health()`` returns a ``{"ok": bool, ...}`` probe
(writability for FileQueue, PING for RedisQueue), and persistent-backend
I/O runs under a :class:`~analytics_zoo_tpu.robust.RetryPolicy`
(transient filesystem/connection blips are retried with backoff; the
``queue.io`` fault-injection site exercises exactly those paths).
"""

from __future__ import annotations

import base64
import json
import logging
import os
import queue as pyqueue
import tempfile
import threading
import time
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.core.profiling import TIMERS
from analytics_zoo_tpu.deploy import codec as wire_codec
from analytics_zoo_tpu.deploy.inference import (
    DEFAULT_MODEL, DynamicBatcher, bucket_class, plan_buckets,
    scatter_batch_results)
from analytics_zoo_tpu.observe import metrics as obs
from analytics_zoo_tpu.observe.export import JsonlEventLog, to_prometheus
from analytics_zoo_tpu.observe.recorder import SLO, FlightRecorder
from analytics_zoo_tpu.observe.trace import TRACER
from analytics_zoo_tpu.robust import (CircuitBreaker, Heartbeat,
                                      QuarantineBroadcast, RetryPolicy,
                                      Supervisor, faults)
from analytics_zoo_tpu.robust.errors import (DeadlineExpired, HostLostError,
                                             MalformedRecordError,
                                             MeshReplicaLostError,
                                             ServingError, ServingOverloaded)

__all__ = ["MemoryQueue", "FileQueue", "RedisQueue", "make_queue",
           "make_queue_from_zoo", "InputQueue", "OutputQueue",
           "ServingConfig", "ClusterServing", "DeviceExecutor",
           "PodCoordinator", "encode_tensor", "decode_tensor",
           "encode_image", "decode_image", "error_payload",
           "MalformedRecordError"]


def error_payload(code: str, message: Any, uri: Optional[str] = None
                  ) -> Dict[str, Any]:
    """The structured error result (docs/SERVING.md "Failure semantics").

    Every record the pipeline cannot serve terminates with one of these
    on the OutputQueue — never a silent drop: ``error`` is the human
    message, ``code`` the stable machine class (``expired`` /
    ``overloaded`` / ``malformed`` / ``decode_error`` / ``model_error``
    / ``internal``), ``uri`` echoes the record id, ``ts`` stamps when
    the error was written."""
    return {"error": str(message), "code": str(code), "uri": uri,
            "ts": time.time()}


# ---------------------------------------------------------------------------
# image payload codec (reference serving/utils/ImageProcessing base64→BGR,
# client.py:83-110 enqueue_image)
# ---------------------------------------------------------------------------

def encode_tensor(a) -> Dict[str, Any]:
    """ndarray → JSON-safe payload (the LEGACY base64 wire codec).

    Binary-wire backends (``queue.wire == "binary"``) skip this entirely
    and ship raw ndarrays through :mod:`deploy.codec`; this stays the
    reference-compatible fallback for Memory/Redis and old producers.
    Instrumented so the bench can attribute the base64 tax:
    ``serving/codec_b64_encode`` counts calls,
    ``serving_wire_bytes_total{codec="json_b64"}`` the on-wire bytes."""
    t0 = time.perf_counter()
    a = np.asarray(a)
    payload = {"b64": base64.b64encode(a.tobytes()).decode("ascii"),
               "shape": list(a.shape), "dtype": str(a.dtype)}
    TIMERS.incr("serving/codec_b64_encode")
    obs.count("serving_wire_bytes_total", len(payload["b64"]),
              codec="json_b64", flat="serving/wire_bytes_json_b64")
    obs.observe("serving_codec_seconds", time.perf_counter() - t0,
                codec="json_b64", op="encode")
    return payload


def decode_tensor(payload, writable: bool = False) -> np.ndarray:
    """Wire payload → ndarray.

    Accepts the legacy ``{"b64", "shape", "dtype"}`` dict AND a raw
    ndarray (the binary wire hands tensors through already decoded —
    possibly as a read-only view into a shared-memory slot).

    Writability is explicit: the default is a zero-copy READ-ONLY array
    (``np.frombuffer`` views are non-writable by nature; hiding that
    behind an implicit copy is exactly the hot-path tax this module
    removes).  Pass ``writable=True`` to get a private mutable copy —
    counted in ``serving/codec_tensor_copies`` so the zero-copy claim
    stays test-verifiable."""
    if isinstance(payload, np.ndarray):
        if writable and not payload.flags.writeable:
            TIMERS.incr("serving/codec_tensor_copies")
            return payload.copy()
        return payload
    t0 = time.perf_counter()
    TIMERS.incr("serving/codec_b64_decode")
    a = np.frombuffer(
        base64.b64decode(payload["b64"]),
        dtype=wire_codec.wire_dtype(payload["dtype"])
    ).reshape(payload["shape"])
    if writable:
        TIMERS.incr("serving/codec_tensor_copies")
        a = a.copy()
    obs.observe("serving_codec_seconds", time.perf_counter() - t0,
                codec="json_b64", op="decode")
    return a


def encode_image(image, wire: str = "json") -> Dict[str, Any]:
    """ndarray (H, W, C) float/uint8 or a path → wire payload."""
    if isinstance(image, str):
        with open(image, "rb") as f:
            return {"image": base64.b64encode(f.read()).decode("ascii"),
                    "codec": "file"}
    if wire == "binary":
        return {"codec": "raw", "image": np.asarray(image)}
    return {"codec": "raw", "image": encode_tensor(image)}


def decode_image(payload: Dict[str, Any]) -> np.ndarray:
    img = payload.get("image")
    if isinstance(img, np.ndarray):  # binary wire: already decoded
        return img
    if payload.get("codec") == "raw":
        return decode_tensor(payload["image"])
    raw = base64.b64decode(img)
    import cv2  # compressed file bytes (jpg/png)
    img = cv2.imdecode(np.frombuffer(raw, np.uint8), cv2.IMREAD_COLOR)
    if img is None:
        raise ValueError("undecodable image payload")
    return img


# ---------------------------------------------------------------------------
# queue backends
# ---------------------------------------------------------------------------

def _timeout_msg(q, rid: str, timeout: float) -> str:
    """One TimeoutError message shape across every backend, so callers
    (and tests) never have to care which transport is underneath."""
    return (f"{type(q).__name__}[{q.name}]: no result for {rid!r} "
            f"within {timeout:.1f}s")


def _io_retry(name: str, retry_on) -> RetryPolicy:
    """Default retry for persistent-backend I/O: 3 quick attempts —
    enough to absorb a transient fs/connection blip without turning a
    dead backend into a multi-second client hang."""
    return RetryPolicy(max_attempts=3, base_delay_s=0.02, max_delay_s=0.5,
                       retry_on=retry_on, name=name)


class MemoryQueue:
    """In-process stream + result store (single-process serving/tests)."""

    def __init__(self, name: str = "serving_stream"):
        self.name = name
        self._items: List[Tuple[str, Dict]] = []
        self._results: Dict[str, Any] = {}
        self._cv = threading.Condition()

    def push(self, record: Dict) -> str:
        rid = record.get("uri") or uuid.uuid4().hex
        with self._cv:
            self._items.append((rid, record))
            self._cv.notify_all()
        return rid

    def pop_batch(self, n: int, timeout: float = 0.1
                  ) -> List[Tuple[str, Dict]]:
        deadline = time.monotonic() + timeout
        with self._cv:
            while not self._items and time.monotonic() < deadline:
                self._cv.wait(timeout=deadline - time.monotonic())
            out, self._items = self._items[:n], self._items[n:]
            return out

    def __len__(self) -> int:
        with self._cv:
            return len(self._items)

    def trim(self, maxlen: int) -> int:
        """Drop oldest items beyond maxlen (reference XTRIM backpressure,
        ClusterServing.scala:132-138).  Returns number dropped."""
        with self._cv:
            drop = max(0, len(self._items) - maxlen)
            if drop:
                self._items = self._items[drop:]
            return drop

    def set_result(self, rid: str, value: Any) -> None:
        with self._cv:
            self._results[rid] = value
            self._cv.notify_all()

    def get_result(self, rid: str, timeout: float = 10.0) -> Any:
        deadline = time.monotonic() + timeout
        with self._cv:
            while rid not in self._results:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(_timeout_msg(self, rid, timeout))
                self._cv.wait(timeout=left)
            return self._results.pop(rid)

    def pending_results(self) -> List[str]:
        with self._cv:
            return list(self._results)

    def health(self) -> Dict[str, Any]:
        with self._cv:
            return {"ok": True, "backend": "memory",
                    "depth": len(self._items),
                    "pending_results": len(self._results)}


class FileQueue:
    """Spool-directory stream: cross-process on one host or a shared FS.

    Records are one file each; atomic rename makes push/claim race-free
    without locks (rename(2) is atomic on POSIX).  Plays the role the
    Redis server plays for the reference when no Redis is available.

    ``codec="binary"`` (the default) spools records as ``.bin`` framed
    tensor files (:mod:`deploy.codec` — raw bytes, no base64);
    ``codec="json"`` keeps the legacy one-JSON-per-record format.
    ``pop_batch`` reads BOTH extensions, so mixed producers coexist.

    Depth bookkeeping is cached: ``__len__``/``trim`` answer from a
    counter maintained under ``_lock`` (push +1, pop refreshes it from
    the directory scan it does anyway) and only fall back to a full
    ``os.listdir`` on a cache miss — the poller calls ``trim`` every
    loop, so an O(queue) scan per loop was a measurable tax.
    """

    def __init__(self, root: str, name: str = "serving_stream",
                 retry: Optional[RetryPolicy] = None,
                 codec: str = "binary"):
        self.name = name
        self.codec = codec
        self.wire = "binary" if codec == "binary" else "json"
        self.root = os.path.join(root, name)
        self.in_dir = os.path.join(self.root, "in")
        self.out_dir = os.path.join(self.root, "out")
        for d in (self.in_dir, self.out_dir):
            os.makedirs(d, exist_ok=True)
        self._seq = 0
        self._retry = retry or _io_retry("filequeue_io", (OSError,))
        self._lock = threading.Lock()
        self._n: Optional[int] = None  # None = miss → rescan

    _EXTS = (".json", ".bin")

    def push(self, record: Dict) -> str:
        rid = record.get("uri") or uuid.uuid4().hex
        self._seq += 1
        ext = ".bin" if self.codec == "binary" else ".json"
        fn = f"{time.time_ns():020d}_{self._seq:06d}_{rid}{ext}"

        def _write():
            faults.inject("queue.io")
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            if ext == ".bin":
                with os.fdopen(fd, "wb") as f:
                    f.write(wire_codec.pack_record(record, codec="file"))
            else:
                with os.fdopen(fd, "w") as f:
                    json.dump({"rid": rid, "record": record}, f)
            os.replace(tmp, os.path.join(self.in_dir, fn))

        self._retry.call(_write)
        with self._lock:
            if self._n is not None:
                self._n += 1
        return rid

    # claims older than this are from a crashed worker and get requeued
    STALE_CLAIM_S = 60.0

    @classmethod
    def _is_record(cls, fn: str) -> bool:
        return fn.endswith(cls._EXTS)

    @staticmethod
    def _rid_of(fn: str) -> str:
        # {time_ns}_{seq}_{rid}.{ext}: rid may itself contain "_"
        return fn.rsplit(".", 1)[0].split("_", 2)[2]

    def _read_record(self, path: str) -> Tuple[str, Dict]:
        if path.endswith(".bin.claimed") or path.endswith(".bin"):
            with open(path, "rb") as f:
                data = f.read()
            fn = os.path.basename(path)
            if fn.endswith(".claimed"):
                fn = fn[: -len(".claimed")]
            # copy=True: the backing file is deleted after the claim, so
            # views must not outlive this function
            return (self._rid_of(fn),
                    wire_codec.unpack_record(data, copy=True,
                                             codec="file"))
        with open(path) as f:
            blob = json.load(f)
        return blob["rid"], blob["record"]

    def pop_batch(self, n: int, timeout: float = 0.1
                  ) -> List[Tuple[str, Dict]]:
        deadline = time.monotonic() + timeout
        while True:
            out = []
            seen = 0
            for fn in sorted(os.listdir(self.in_dir)):
                path = os.path.join(self.in_dir, fn)
                if fn.endswith(".claimed"):
                    # recover claims orphaned by a crashed worker
                    try:
                        if (time.time() - os.path.getmtime(path)
                                > self.STALE_CLAIM_S):
                            os.rename(path, path[: -len(".claimed")])
                            seen += 1
                    except OSError:
                        pass
                    continue
                if not self._is_record(fn):
                    continue
                if len(out) >= n:
                    seen += 1  # stays queued; count for the cache
                    continue
                claimed = path + ".claimed"
                try:
                    os.rename(path, claimed)  # atomic claim
                except OSError:
                    continue  # another worker won
                blob = self._read_record(claimed)
                os.unlink(claimed)
                out.append(blob)
            with self._lock:
                # the scan just walked the whole directory — refresh the
                # cached depth for free (also heals cross-process drift)
                self._n = seen
            if out or time.monotonic() >= deadline:
                return out
            time.sleep(0.005)

    def __len__(self) -> int:
        with self._lock:
            if self._n is None:  # cache miss: rescan once
                self._n = sum(1 for fn in os.listdir(self.in_dir)
                              if self._is_record(fn))
            return self._n

    def trim(self, maxlen: int) -> int:
        with self._lock:
            if self._n is not None and self._n <= maxlen:
                return 0  # fast path: no listdir under the limit
        files = sorted(fn for fn in os.listdir(self.in_dir)
                       if self._is_record(fn))
        drop = max(0, len(files) - maxlen)
        for fn in files[:drop]:
            try:
                os.unlink(os.path.join(self.in_dir, fn))
            except OSError:
                pass
        with self._lock:
            self._n = len(files) - drop
        return drop

    def set_result(self, rid: str, value: Any) -> None:
        binary = self.codec == "binary"

        def _write():
            faults.inject("queue.io")
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            if binary:
                with os.fdopen(fd, "wb") as f:
                    f.write(wire_codec.pack_result(value, codec="file"))
                os.replace(tmp, os.path.join(self.out_dir, rid + ".bin"))
            else:
                with os.fdopen(fd, "w") as f:
                    json.dump(value, f)
                os.replace(tmp, os.path.join(self.out_dir, rid + ".json"))

        self._retry.call(_write)

    def get_result(self, rid: str, timeout: float = 10.0) -> Any:
        paths = [os.path.join(self.out_dir, rid + ext)
                 for ext in (".bin", ".json")]
        deadline = time.monotonic() + timeout

        def _read(path):
            faults.inject("queue.io")
            if path.endswith(".bin"):
                with open(path, "rb") as f:
                    val = wire_codec.unpack_result(f.read(), copy=True,
                                                   codec="file")
            else:
                with open(path) as f:
                    val = json.load(f)
            os.unlink(path)
            return val

        while True:
            for path in paths:
                if os.path.exists(path):
                    return self._retry.call(lambda p=path: _read(p))
            if time.monotonic() >= deadline:
                raise TimeoutError(_timeout_msg(self, rid, timeout))
            time.sleep(0.005)

    def pending_results(self) -> List[str]:
        return [fn.rsplit(".", 1)[0] for fn in os.listdir(self.out_dir)
                if fn.endswith(self._EXTS)]

    def health(self) -> Dict[str, Any]:
        """Probe: the spool directories must exist and be writable."""
        try:
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".probe")
            os.close(fd)
            os.unlink(tmp)
            return {"ok": True, "backend": "file", "root": self.root,
                    "depth": len(self)}
        except OSError as e:
            return {"ok": False, "backend": "file", "root": self.root,
                    "error": str(e)}


class RedisQueue:
    """Redis-stream backend, wire-shaped like the reference
    (xadd to the stream, results to hashes ``result:{uri}``) —
    client.py:83-150 / ClusterServing.scala:107-138.  Requires the
    ``redis`` package and a live server.

    Reads go through a consumer group (XREADGROUP + XACK), so N workers
    on one queue each claim disjoint records — the same exactly-one-
    claimer contract as FileQueue."""

    GROUP = "serving_workers"

    def __init__(self, host: str = "localhost", port: int = 6379,
                 name: str = "serving_stream",
                 retry: Optional[RetryPolicy] = None):
        import redis  # gated import

        self.name = name
        self._r = redis.Redis(host=host, port=port, decode_responses=True)
        self._consumer = uuid.uuid4().hex
        self._retry = retry or _io_retry(
            "redisqueue_io",
            (getattr(redis, "ConnectionError", OSError),
             getattr(redis, "TimeoutError", OSError), OSError))
        try:
            self._r.xgroup_create(self.name, self.GROUP, id="0",
                                  mkstream=True)
        except redis.ResponseError as e:  # BUSYGROUP = already exists
            if "BUSYGROUP" not in str(e):
                raise

    def push(self, record: Dict) -> str:
        rid = record.get("uri") or uuid.uuid4().hex

        def _write():
            faults.inject("queue.io")
            self._r.xadd(self.name, {"blob": json.dumps(
                {"rid": rid, "record": record})})

        self._retry.call(_write)
        return rid

    def pop_batch(self, n: int, timeout: float = 0.1
                  ) -> List[Tuple[str, Dict]]:
        resp = self._r.xreadgroup(self.GROUP, self._consumer,
                                  {self.name: ">"}, count=n,
                                  block=int(timeout * 1000))
        out = []
        for _, entries in resp or []:
            for eid, fields in entries:
                if "blob" in fields:
                    # native client envelope (json)
                    blob = json.loads(fields["blob"])
                    out.append((blob["rid"], blob["record"]))
                else:
                    # reference-client wire shape: flat fields
                    # {uri, image: b64(jpg bytes)} (client.py:102-110) —
                    # lift into the worker's record schema (the b64 file
                    # codec is exactly decode_image's "file" path)
                    rec = dict(fields)
                    rid = rec.get("uri") or eid
                    if "image" in rec and not isinstance(rec["image"],
                                                         dict):
                        rec = {"uri": rid, "codec": "file",
                               "image": rec["image"]}
                    out.append((rid, rec))
                self._r.xack(self.name, self.GROUP, eid)
        return out

    def __len__(self) -> int:
        return self._r.xlen(self.name)

    def trim(self, maxlen: int) -> int:
        before = self._r.xlen(self.name)
        self._r.xtrim(self.name, maxlen=maxlen)
        return max(0, before - self._r.xlen(self.name))

    def set_result(self, rid: str, value: Any) -> None:
        def _write():
            faults.inject("queue.io")
            self._r.hset(f"result:{rid}", "value", json.dumps(value))

        self._retry.call(_write)

    def get_result(self, rid: str, timeout: float = 10.0) -> Any:
        deadline = time.monotonic() + timeout

        def _read():
            faults.inject("queue.io")
            return self._r.hget(f"result:{rid}", "value")

        while True:
            v = self._retry.call(_read)
            if v is not None:
                self._r.delete(f"result:{rid}")
                return json.loads(v)
            if time.monotonic() >= deadline:
                raise TimeoutError(_timeout_msg(self, rid, timeout))
            time.sleep(0.01)

    def pending_results(self) -> List[str]:
        return [k.split(":", 1)[1] for k in self._r.keys("result:*")]

    def health(self) -> Dict[str, Any]:
        """Probe: PING the server (the reference serving stack's startup
        does the same liveness check before starting the stream)."""
        try:
            self._r.ping()
            return {"ok": True, "backend": "redis", "depth": len(self)}
        except Exception as e:
            return {"ok": False, "backend": "redis", "error": str(e)}


def make_queue(backend: str = "memory", **kw):
    """String lowering for queue backends."""
    b = backend.lower()
    if b in ("memory", "mem"):
        return MemoryQueue(**kw)
    if b in ("file", "spool"):
        return FileQueue(**kw)
    if b in ("redis",):
        return RedisQueue(**kw)
    if b in ("shm", "shared_memory"):
        from analytics_zoo_tpu.deploy.shmqueue import ShmQueue

        return ShmQueue(**kw)
    raise ValueError(f"unknown queue backend {backend!r}; "
                     "known: memory, file, redis, shm")


def make_queue_from_zoo(zoo_cfg, **kw):
    """Queue from the global config: ``serving_queue_backend`` picks the
    transport (``ZOO_SERVING_QUEUE_BACKEND=shm`` env-selects the
    zero-copy path) and the ``serving_shm_*`` knobs size the arena."""
    backend = kw.pop("backend", None) or zoo_cfg.serving_queue_backend
    if backend.lower() in ("shm", "shared_memory"):
        kw.setdefault("slots", zoo_cfg.serving_shm_slots)
        kw.setdefault("slot_bytes", zoo_cfg.serving_shm_slot_bytes)
        kw.setdefault("result_slot_bytes",
                      zoo_cfg.serving_shm_result_slot_bytes)
    return make_queue(backend, **kw)


# ---------------------------------------------------------------------------
# client (reference pyzoo/zoo/serving/client.py:58-150)
# ---------------------------------------------------------------------------

class InputQueue:
    """Producer side: enqueue records for the serving worker.

    The tensor wire format follows the queue: binary backends
    (``queue.wire == "binary"``) get raw ndarrays (framed by the backend,
    zero base64), JSON backends get the legacy ``encode_tensor``
    payloads."""

    def __init__(self, queue):
        self.queue = queue
        self.wire = getattr(queue, "wire", "json")

    @staticmethod
    def _validated_ttl(ttl_ms) -> Optional[float]:
        if ttl_ms is None:
            return None
        if (not isinstance(ttl_ms, (int, float))
                or isinstance(ttl_ms, bool)
                or not np.isfinite(ttl_ms) or ttl_ms <= 0):
            raise MalformedRecordError(
                f"ttl_ms must be a positive finite number, got {ttl_ms!r}")
        return float(ttl_ms)

    def enqueue(self, uri: Optional[str] = None,
                ttl_ms: Optional[float] = None,
                model: Optional[str] = None, **data) -> str:
        """Enqueue arbitrary named arrays (reference enqueue:58).

        Native-client records carry ``ts`` (enqueue wall-clock, feeding
        the ``serving/queue_wait`` / ``serving/e2e`` stage timers) and
        ``fmt: "tensor"`` — the worker answers them with the lossless
        tensor codec instead of ``tolist()`` (OutputQueue decodes
        transparently; reference-wire records keep plain JSON lists).

        ``ttl_ms`` is the client deadline: the worker sheds the record
        with a structured ``expired``/``overloaded`` error instead of
        serving it after the client has given up (docs/SERVING.md).

        Malformed input (no tensors, non-encodable dtype, bad TTL)
        raises :class:`MalformedRecordError` BEFORE anything is pushed —
        a typed client-side rejection, never a poisoned queue."""
        rec: Dict[str, Any] = {"uri": uri or uuid.uuid4().hex,
                               "ts": time.time(), "fmt": "tensor"}
        ttl = self._validated_ttl(ttl_ms)
        if ttl is not None:
            rec["ttl_ms"] = ttl
        if model is not None:
            # routes the record to one named model in a multi-model
            # worker; rides the record meta (str, not a tensor field)
            rec["model"] = str(model)
        if not data:
            raise MalformedRecordError("record carries no tensor fields")
        for k, v in data.items():
            try:
                a = np.asarray(v)
                if a.dtype.hasobject:
                    raise ValueError(
                        f"dtype {a.dtype} is not wire-encodable")
                rec[k] = a if self.wire == "binary" else encode_tensor(a)
            except MalformedRecordError:
                raise
            except Exception as e:
                raise MalformedRecordError(
                    f"field {k!r} is not tensor-encodable: {e}") from e
        return self.queue.push(rec)

    def enqueue_image(self, uri: Optional[str] = None, image=None,
                      ttl_ms: Optional[float] = None) -> str:
        """Enqueue one image (path or ndarray) — reference
        enqueue_image:83 (base64 xadd)."""
        rec = {"uri": uri or uuid.uuid4().hex, "ts": time.time(),
               "fmt": "tensor", **encode_image(image, wire=self.wire)}
        ttl = self._validated_ttl(ttl_ms)
        if ttl is not None:
            rec["ttl_ms"] = ttl
        return self.queue.push(rec)


class OutputQueue:
    """Consumer side: fetch prediction results."""

    def __init__(self, queue):
        self.queue = queue

    @staticmethod
    def _decode_result(val: Any) -> Any:
        # native-client results ride the tensor codec (lossless, typed);
        # everything else (top-N pairs, errors, reference-wire lists)
        # passes through as-is.  Clients get a WRITABLE array either
        # way — results left the slot/spool already, so this copy (if
        # any) is off the serving hot path.
        if isinstance(val, dict) and "tensor" in val:
            return decode_tensor(val["tensor"], writable=True)
        return val

    def query(self, uri: str, timeout: float = 10.0) -> Any:
        """Result for one uri (reference query:140)."""
        return self._decode_result(self.queue.get_result(uri,
                                                         timeout=timeout))

    def dequeue(self, timeout: float = 10.0) -> Dict[str, Any]:
        """Drain all currently-available results (reference dequeue:127)."""
        deadline = time.monotonic() + timeout
        while True:
            pend = self.queue.pending_results()
            if pend:
                return {rid: self._decode_result(
                    self.queue.get_result(rid, timeout=1.0))
                    for rid in pend}
            if time.monotonic() >= deadline:
                return {}
            time.sleep(0.01)


# ---------------------------------------------------------------------------
# the serving worker (reference ClusterServing.scala main loop)
# ---------------------------------------------------------------------------

class ServingConfig:
    """YAML/dict config (reference ClusterServingHelper.scala:104-170).

    Pipeline knobs (docs/SERVING.md): ``max_batch_delay_ms`` is the
    DynamicBatcher's deadline (oldest queued request never waits longer
    for peers), ``decode_workers`` sizes the decode pool, ``replicas``
    the per-device model copies the executor round-robins over, and
    ``max_inflight`` bounds concurrently-dispatched device batches
    (2 = double buffering).  ``pipeline=False`` falls back to the
    synchronous one-thread worker (the bench's ``serving_sync_baseline``
    leg measures exactly that).

    Self-healing knobs (docs/SERVING.md "Failure semantics"):
    ``breaker_threshold`` consecutive failures quarantine a replica,
    ``breaker_cooldown_s`` gates the half-open probe and the
    supervisor's rebuild, ``supervisor_interval_s`` paces the repair
    checks, ``stage_stall_s`` is the stage-heartbeat watchdog deadline,
    ``harvest_deadline_s`` bounds one device readback before the
    replica counts as hung, ``default_ttl_ms`` applies to records with
    no client TTL of their own, and ``supervise=False`` turns the whole
    supervision layer off (bare pipeline, PR-4 behaviour)."""

    def __init__(self, model_path: Optional[str] = None, batch_size: int = 32,
                 backpressure_maxlen: int = 10_000, poll_timeout_s: float = 0.1,
                 postprocess_top_n: Optional[int] = None, int8: bool = False,
                 tensorboard_dir: Optional[str] = None,
                 max_batch_delay_ms: float = 5.0, decode_workers: int = 4,
                 replicas: int = 1, max_inflight: int = 2,
                 pipeline: bool = True, breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 2.0,
                 supervisor_interval_s: float = 0.25,
                 stage_stall_s: float = 10.0,
                 harvest_deadline_s: float = 30.0,
                 default_ttl_ms: Optional[float] = None,
                 supervise: bool = True,
                 slo_p99_ms=0.0,
                 slo_window_s: float = 5.0,
                 flight_dir: Optional[str] = None,
                 jsonl_path: Optional[str] = None,
                 profile_on_breach: bool = False,
                 span_ring: Optional[int] = None,
                 compile_cache_dir: Optional[str] = None,
                 compile_cache_entries: int = 512,
                 hbm_budget_bytes: int = 0,
                 autoscale: bool = False,
                 autoscale_cooldown_s: float = 5.0,
                 autoscale_interval_s: float = 1.0,
                 autoscale_policy=None,
                 mesh_replicas: int = 0,
                 mesh_axis: str = "model",
                 mesh_shed_after_s: float = 30.0):
        self.model_path = model_path
        self.batch_size = batch_size
        self.backpressure_maxlen = backpressure_maxlen
        self.poll_timeout_s = poll_timeout_s
        self.postprocess_top_n = postprocess_top_n
        self.int8 = int8
        self.tensorboard_dir = tensorboard_dir
        self.max_batch_delay_ms = max_batch_delay_ms
        self.decode_workers = max(1, int(decode_workers))
        self.replicas = max(1, int(replicas))
        self.max_inflight = max(1, int(max_inflight))
        self.pipeline = pipeline
        self.breaker_threshold = max(1, int(breaker_threshold))
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.supervisor_interval_s = float(supervisor_interval_s)
        self.stage_stall_s = float(stage_stall_s)
        self.harvest_deadline_s = float(harvest_deadline_s)
        self.default_ttl_ms = default_ttl_ms
        self.supervise = supervise
        # observability (docs/OBSERVABILITY.md): slo_p99_ms > 0 arms the
        # flight recorder's e2e-p99 SLO; breaker trips are watched
        # regardless whenever supervision is on.  Multi-model workers
        # pass a dict {model: p99_ms} — each model gets its own SLO
        # series and admission weight (docs/SERVING.md).
        if isinstance(slo_p99_ms, dict):
            self.slo_p99_ms = {str(k): float(v)
                               for k, v in slo_p99_ms.items()}
        else:
            self.slo_p99_ms = float(slo_p99_ms)
        self.slo_window_s = float(slo_window_s)
        self.flight_dir = flight_dir
        self.jsonl_path = jsonl_path
        self.profile_on_breach = bool(profile_on_breach)
        self.span_ring = span_ring
        # warm start + capacity control (docs/SERVING.md "Warm start &
        # multi-model")
        self.compile_cache_dir = compile_cache_dir or None
        self.compile_cache_entries = max(1, int(compile_cache_entries))
        self.hbm_budget_bytes = max(0, int(hbm_budget_bytes or 0))
        self.autoscale = bool(autoscale)
        self.autoscale_cooldown_s = float(autoscale_cooldown_s)
        self.autoscale_interval_s = float(autoscale_interval_s)
        self.autoscale_policy = autoscale_policy
        # pod-scale serving (docs/SERVING.md "Pod-scale serving"): a
        # mesh replica is one shard_replica forward over the context
        # mesh — a first-class replica slot AND a first-class failure
        # domain.  ``mesh_shed_after_s`` bounds how long a quarantined
        # mesh replica waits for the host roster to heal before the
        # supervisor sheds it and re-plans the HBM budget without it.
        self.mesh_replicas = max(0, int(mesh_replicas))
        self.mesh_axis = str(mesh_axis)
        self.mesh_shed_after_s = float(mesh_shed_after_s)

    def slo_for(self, model: str) -> float:
        """The e2e-p99 SLO (ms) for one model: its dict entry, or the
        scalar applied to every model; 0.0 = unbounded."""
        if isinstance(self.slo_p99_ms, dict):
            return float(self.slo_p99_ms.get(model, 0.0))
        return float(self.slo_p99_ms)

    def slo_models(self) -> Dict[str, float]:
        """Every model with a nonzero SLO (empty for scalar configs —
        the scalar arms the legacy unlabeled watcher instead)."""
        if isinstance(self.slo_p99_ms, dict):
            return {m: v for m, v in self.slo_p99_ms.items() if v > 0}
        return {}

    @classmethod
    def from_yaml(cls, path: str) -> "ServingConfig":
        import yaml

        with open(path) as f:
            blob = yaml.safe_load(f) or {}
        return cls(**blob)

    @classmethod
    def from_zoo(cls, zoo_cfg, **overrides: Any) -> "ServingConfig":
        """Lift the global ``ZooConfig.serving_*`` knobs (ZOO_SERVING_*
        env vars included) into a ServingConfig."""
        kw: Dict[str, Any] = dict(
            batch_size=zoo_cfg.serving_batch_size,
            max_batch_delay_ms=zoo_cfg.serving_max_batch_delay_ms,
            decode_workers=zoo_cfg.serving_decode_workers,
            replicas=zoo_cfg.serving_replicas,
            max_inflight=zoo_cfg.serving_max_inflight,
            breaker_threshold=zoo_cfg.serving_breaker_threshold,
            breaker_cooldown_s=zoo_cfg.serving_breaker_cooldown_s,
            supervisor_interval_s=zoo_cfg.serving_supervisor_interval_s,
            stage_stall_s=zoo_cfg.serving_stage_stall_s,
            harvest_deadline_s=zoo_cfg.serving_harvest_deadline_s,
            default_ttl_ms=zoo_cfg.serving_default_ttl_ms,
            slo_p99_ms=zoo_cfg.serving_slo_p99_ms,
            slo_window_s=zoo_cfg.serving_slo_window_s,
            flight_dir=zoo_cfg.observe_flight_dir or None,
            jsonl_path=zoo_cfg.observe_jsonl_path or None,
            profile_on_breach=zoo_cfg.observe_profile_on_breach,
            span_ring=zoo_cfg.observe_span_ring,
            tensorboard_dir=zoo_cfg.tensorboard_dir,
            compile_cache_dir=zoo_cfg.serving_compile_cache_dir or None,
            hbm_budget_bytes=zoo_cfg.serving_hbm_budget_bytes,
            autoscale=zoo_cfg.serving_autoscale,
            autoscale_cooldown_s=zoo_cfg.serving_autoscale_cooldown_s,
            autoscale_interval_s=zoo_cfg.serving_autoscale_interval_s)
        kw.update(overrides)
        return cls(**kw)


def _decode_record(rec: Dict) -> Dict[str, np.ndarray]:
    """Tensor fields of a claimed record, whatever wire they rode:
    binary-backend ndarrays pass through untouched (zero-copy views on
    shm), legacy ``{"b64": ...}`` payloads decode read-only."""
    out = {}
    if "image" in rec:
        out["image"] = decode_image(rec)
    for k, v in rec.items():
        if k == "image" or k.startswith("_"):
            continue
        if isinstance(v, np.ndarray):
            out[k] = v
        elif isinstance(v, dict) and "b64" in v:
            out[k] = decode_tensor(v)
    return out


class _ReplicaSlot:
    """One supervised replica position: the replica object, its circuit
    breaker, the owning model's name, and the rebuild bookkeeping."""

    __slots__ = ("replica", "breaker", "index", "rebuilt", "model",
                 "kind")

    def __init__(self, replica, breaker, index, model=DEFAULT_MODEL,
                 kind="replica"):
        self.replica = replica
        self.breaker = breaker
        self.index = index
        self.model = model
        self.kind = kind    # "replica" | "longdoc_replica"
        self.rebuilt = False    # set by rebuild_slot; cleared (and
        #                         counted as restored) on first success


class _Batch:
    """One fused batch moving through the executor.  ``claimed`` is the
    single-ownership flag between the harvest thread and the watchdog:
    whoever sets it (under the executor lock) answers/requeues the
    requests; the other side discards.  A requeue always builds a FRESH
    _Batch so a late readback from an abandoned harvest can never
    double-answer."""

    __slots__ = ("key", "fused", "reqs", "attempt", "slot", "handles",
                 "t_dispatch", "t_harvest", "claimed", "first_blocked_t",
                 "span", "model")

    def __init__(self, key, fused, reqs, attempt=0, model=DEFAULT_MODEL):
        self.key = key
        self.fused = fused
        self.reqs = reqs
        self.attempt = attempt
        self.model = model
        self.slot = None
        self.handles = None
        self.t_dispatch = None
        self.t_harvest = None
        self.claimed = False
        self.first_blocked_t = None
        self.span = None  # device-batch span linking member traces


class _ModelGroup:
    """One named model's executor state: its replica slots, round-robin
    cursor, shape buckets and (optional) sync fallback.  The executor
    multiplexes every group over the same dispatch/harvest threads and
    inflight budget — the chips don't care which model a batch belongs
    to, only the slots and ledgers are per-model.

    ``long_slots`` holds the long-document mesh-replica slots
    (``InferenceModel.mesh_replica``): batches at or past
    ``LONG_DOC_TOKENS`` sequence tokens route there with their own
    round-robin cursor, so a 128k-token request never occupies (and
    never OOMs) a single-chip slot.

    ``mesh_slots`` holds the pod-scale sharded mesh replicas
    (``InferenceModel.shard_replica`` — docs/SERVING.md "Pod-scale
    serving"): each one is a whole mesh slice serving as ONE replica.
    They join the normal round-robin (first-class capacity) but stay a
    separate list because they plan under per-chip shard bytes, heal
    against the host roster, and quarantine atomically as a group."""

    __slots__ = ("name", "slots", "rr", "buckets", "fallback",
                 "long_slots", "long_rr", "mesh_slots")

    def __init__(self, name, slots, buckets, fallback=None,
                 long_slots=None, mesh_slots=None):
        self.name = name
        self.slots = slots
        self.rr = 0
        self.buckets = tuple(sorted(buckets))
        self.fallback = fallback
        self.long_slots = list(long_slots or [])
        self.long_rr = 0
        self.mesh_slots = list(mesh_slots or [])

    def all_slots(self):
        return (list(self.slots) + list(self.long_slots)
                + list(self.mesh_slots))


class DeviceExecutor:
    """Stage 3: keeps the chips busy with double-buffered async dispatch.

    Multi-model (docs/SERVING.md "Warm start & multi-model"): the
    ``replicas`` / ``buckets`` / ``fallback`` ctor arguments accept
    either the legacy single-model shapes (a list / a tuple / one
    callable — they become the ``"default"`` model) or dicts keyed by
    model name.  One executor then multiplexes N models over the same
    dispatch+harvest threads and ``max_inflight`` budget, with
    *per-model* replica slots, breaker quarantine, round-robin cursors
    and bucket sets; every batch carries its model name into the
    ``{model}`` label of the serving metrics.

    A dispatch thread pulls full batches off a bounded inbox, pads them
    to the model's shape buckets, round-robins them over per-device
    :class:`~analytics_zoo_tpu.deploy.inference.ModelReplica`\\ s, and
    enqueues the *handle* (future-backed device arrays — JAX's async
    dispatch returns before the TPU finishes) onto a pending queue whose
    ``maxsize=max_inflight`` IS the double-buffering bound: with 2 in
    flight, batch N+1 is transferring/queueing while N computes.  A
    separate harvest thread performs the only blocking readback.

    Overlap is counter-verified, not eyeballed: ``serving/device_idle_events``
    counts dispatches that found the device quiet for more than
    ``IDLE_EPS_S`` since the previous harvest (saturated load must keep
    it ~flat), and ``busy()`` lets the decode pool prove it decodes
    while the device computes (``serving/decode_overlap``).

    Self-healing (docs/SERVING.md "Failure semantics"): every replica
    sits in a :class:`_ReplicaSlot` behind a
    :class:`~analytics_zoo_tpu.robust.CircuitBreaker`.  The round-robin
    skips quarantined slots; a failed dispatch/harvest requeues the
    batch (fresh :class:`_Batch`, ``max_retries`` bound) onto healthy
    replicas before any request sees an error.  With every slot
    quarantined the executor degrades to the synchronous ``fallback``
    forward (the ``serve_once`` predict path) instead of hanging, and
    ``check_harvest`` — driven by the supervisor — abandons a readback
    stuck past its deadline: quarantine the replica, requeue the
    in-flight records, respawn the harvest stage.
    """

    IDLE_EPS_S = 0.005  # harvest→dispatch gaps above this count as idle

    def __init__(self, replicas, buckets=(1, 32),
                 max_inflight: int = 2, name: str = "serving",
                 breaker_threshold: int = 3, breaker_cooldown_s: float = 2.0,
                 fallback=None, max_retries: int = 2,
                 long_doc_replicas=None, mesh_replicas=None):
        rep_map = (dict(replicas) if isinstance(replicas, dict)
                   else {DEFAULT_MODEL: list(replicas or [])})
        if not rep_map or not all(rep_map.values()):
            raise ValueError("DeviceExecutor needs at least one replica "
                             "per model")
        # long_doc_replicas: mesh replicas for the >= LONG_DOC_TOKENS
        # bucket class — a list (default model) or dict keyed by model
        long_map = (dict(long_doc_replicas)
                    if isinstance(long_doc_replicas, dict)
                    else {DEFAULT_MODEL: list(long_doc_replicas or [])})
        # mesh_replicas: pod-scale sharded mesh replicas
        # (InferenceModel.shard_replica) — first-class round-robin
        # capacity, quarantined atomically as one failure domain
        mesh_map = (dict(mesh_replicas) if isinstance(mesh_replicas, dict)
                    else {DEFAULT_MODEL: list(mesh_replicas or [])})
        self.max_inflight = max(1, int(max_inflight))
        self.name = name
        self.breaker_threshold = max(1, int(breaker_threshold))
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.max_retries = max(0, int(max_retries))
        self._heartbeat: Optional[Callable[[], None]] = None
        # swap listeners: fn(model_name) called on every swap_replicas —
        # how the hot-row caches (ISSUE 19) learn the weights changed
        self._swap_listeners: List[Callable[[str], None]] = []
        self._inbox: "pyqueue.Queue" = pyqueue.Queue(
            maxsize=max(2, self.max_inflight * 4))
        self._pending: "pyqueue.Queue" = pyqueue.Queue(
            maxsize=self.max_inflight)
        self._retryq: "deque[_Batch]" = deque()
        self._lock = threading.Lock()
        bucket_map = buckets if isinstance(buckets, dict) else {}
        fb_map = fallback if isinstance(fallback, dict) else {}
        self._groups: Dict[str, _ModelGroup] = {}
        for mname, reps in rep_map.items():
            longs = long_map.get(mname) or []
            self._groups[mname] = _ModelGroup(
                mname, self._make_slots(reps, mname),
                bucket_map.get(mname, buckets if not isinstance(
                    buckets, dict) else (1, 32)),
                fb_map.get(mname) if isinstance(fallback, dict)
                else fallback,
                long_slots=self._make_slots(
                    longs, mname, kind="longdoc_replica",
                    start=len(reps)),
                mesh_slots=self._make_slots(
                    mesh_map.get(mname) or [], mname,
                    kind="mesh_replica", start=len(reps) + len(longs)))
        self._default_model = next(iter(self._groups))
        # one epoch ledger per executor: a host-loss epoch quarantines
        # every mesh slot of the affected model exactly once, however
        # many threads observe the same loss
        self.mesh_quarantine = QuarantineBroadcast(name=f"{name}_mesh")
        self._inflight = 0
        self._last_harvest_t: Optional[float] = None
        self._harvesting: Optional[_Batch] = None
        self._harvest_epoch = 0
        self._swap: Optional[Dict[str, List]] = None
        self._stop = threading.Event()
        self._log = logging.getLogger("analytics_zoo_tpu.deploy")
        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop, daemon=True, name="srv-dispatch")
        self._harvest_thread = threading.Thread(
            target=self._harvest_loop, args=(0,), daemon=True,
            name="srv-harvest")
        self._dispatch_thread.start()
        self._harvest_thread.start()

    def _make_slots(self, replicas: List, model: str = DEFAULT_MODEL,
                    kind: str = "replica", start: int = 0
                    ) -> List["_ReplicaSlot"]:
        # long-doc / mesh slot indices continue after the single-chip
        # ones so rebuild_slot/metrics address every slot of a model
        # uniquely
        prefix = (f"{self.name}_{kind}" if model == DEFAULT_MODEL
                  else f"{self.name}_{model}_{kind}")
        return [_ReplicaSlot(
            rep, CircuitBreaker(failure_threshold=self.breaker_threshold,
                                cooldown_s=self.breaker_cooldown_s,
                                name=f"{prefix}{i}"), i, model=model,
            kind=kind)
            for i, rep in enumerate(replicas, start)]

    # -- legacy single-model views (tests/callers from before multi-model
    # address the default group through these) -----------------------------
    @property
    def _slots(self) -> List["_ReplicaSlot"]:
        return self._groups[self._default_model].slots

    @property
    def buckets(self) -> tuple:
        return self._groups[self._default_model].buckets

    @property
    def _fallback(self):
        return self._groups[self._default_model].fallback

    def models(self) -> List[str]:
        return list(self._groups)

    def group_size(self, model: str) -> int:
        with self._lock:
            g = self._groups.get(model)
            return len(g.slots) if g is not None else 0

    @property
    def replicas(self) -> List:
        """The live replica objects (compat view over the slots; every
        group's slots flattened in insertion order)."""
        with self._lock:
            return [s.replica for g in self._groups.values()
                    for s in g.slots]

    # -- producer side -----------------------------------------------------
    def submit(self, key, fused: List[np.ndarray], reqs: List) -> None:
        """DynamicBatcher ``dispatch_fn``: hand over one fused batch.
        Blocks when ``max_inflight`` batches are already queued — the
        pipeline's backpressure toward the batcher/decoders."""
        if self._stop.is_set():
            raise RuntimeError("DeviceExecutor is stopped")
        model = (getattr(reqs[0], "model", None) if reqs else None) \
            or self._default_model
        self._inbox.put(_Batch(key, fused, reqs, model=model))

    def busy(self) -> bool:
        """True while any batch is dispatched-but-not-harvested."""
        with self._lock:
            return self._inflight > 0

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def swap_replicas(self, replicas, model: Optional[str] = None) -> None:
        """Hot reload: the new replica set takes over at the next
        dispatch (in-flight batches finish on the old weights).  The new
        slots start with fresh (closed) breakers.  ``replicas`` may be a
        list (the default — or the named — model) or a dict of per-model
        lists; partial swaps merge into one pending swap."""
        if isinstance(replicas, dict):
            swap = {str(k): list(v) for k, v in replicas.items()}
        else:
            swap = {model or self._default_model: list(replicas)}
        with self._lock:
            if self._swap is None:
                self._swap = swap
            else:
                self._swap.update(swap)
            listeners = list(self._swap_listeners)
        # weight-swap hooks outside the lock: hot-row caches invalidate
        # here so a swapped model can never serve pre-swap rows
        for fn in listeners:
            for mname in swap:
                try:
                    fn(mname)
                except Exception:
                    logging.getLogger("analytics_zoo_tpu.deploy") \
                        .exception("swap listener failed for %r", mname)

    def add_swap_listener(self, fn: Callable[[str], None]) -> None:
        """Register ``fn(model_name)`` to run on every
        :meth:`swap_replicas` (hot reload / resize / rebuild)."""
        with self._lock:
            self._swap_listeners.append(fn)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._dispatch_thread.join(timeout=timeout)
        self._harvest_thread.join(timeout=timeout)

    def is_alive(self) -> bool:
        return (self._dispatch_thread.is_alive()
                or self._harvest_thread.is_alive())

    # -- supervision surface ----------------------------------------------
    def replica_states(self) -> List[Dict[str, Any]]:
        """Per-slot health for ``health()``: breaker state machine plus
        device identity and owning model."""
        with self._lock:
            slots = [s for g in self._groups.values()
                     for s in g.all_slots()]
        return [dict(slot=s.index, model=s.model, kind=s.kind,
                     device=str(getattr(s.replica, "device", "host")),
                     rebuilt_pending_probe=s.rebuilt,
                     **s.breaker.snapshot())
                for s in slots]

    def healthy_replicas(self, model: Optional[str] = None) -> int:
        with self._lock:
            if model is not None:
                g = self._groups.get(model)
                slots = g.all_slots() if g is not None else []
            else:
                slots = [s for g in self._groups.values()
                         for s in g.all_slots()]
        return sum(1 for s in slots if s.breaker.health != "quarantined")

    def quarantined_slots(self, min_open_s: float = 0.0
                          ) -> List["_ReplicaSlot"]:
        """Slots whose breaker is open and (open long enough OR already
        failed a probe) — the supervisor's rebuild candidates.  The
        ``opens >= 2`` clause matters under load: the hot dispatch loop
        flips open → half-open at exactly the cooldown, so a
        persistently-bad replica cycles probes without ever *aging* in
        the open state."""
        with self._lock:
            slots = [s for g in self._groups.values()
                     for s in g.all_slots()]
        out = []
        for s in slots:
            snap = s.breaker.snapshot()
            if snap["state"] == "open" and (
                    snap["open_age_s"] >= min_open_s or snap["opens"] >= 2):
                out.append(s)
        return out

    def rebuild_slot(self, index: int, replica,
                     model: Optional[str] = None) -> None:
        """Supervisor repair: swap a fresh replica into one slot.  The
        breaker resets to closed; the first successful harvest through
        the slot counts ``<name>/replica_restored``."""
        model = model or self._default_model
        kind = "replica"
        with self._lock:
            group = self._groups.get(model)
            if group is None:
                return
            for s in group.all_slots():
                if s.index == index:
                    s.replica = replica
                    s.breaker.reset()
                    s.rebuilt = True
                    kind = s.kind
                    break
            else:
                return
        obs.count("serving_replica_events_total", event="rebuilt",
                  replica=index, model=model,
                  flat=f"{self.name}/replica_rebuilt")
        if kind == "mesh_replica":
            obs.count("serving_mesh_replica_events_total", event="rebuilt",
                      model=model, flat=f"{self.name}/mesh_replica_rebuilt")
        self._log.warning("%s: replica %d (%s) rebuilt and swapped in",
                          self.name, index, model)

    # -- mesh replicas (docs/SERVING.md "Pod-scale serving") ---------------
    def mesh_slots_of(self, model: Optional[str] = None
                      ) -> List["_ReplicaSlot"]:
        with self._lock:
            g = self._groups.get(model or self._default_model)
            return list(g.mesh_slots) if g is not None else []

    def mesh_group_size(self, model: Optional[str] = None) -> int:
        return len(self.mesh_slots_of(model))

    def healthy_mesh_replicas(self, model: Optional[str] = None) -> int:
        return sum(1 for s in self.mesh_slots_of(model)
                   if s.breaker.health != "quarantined")

    def quarantine_mesh_replica(self, epoch: int,
                                model: Optional[str] = None) -> bool:
        """Atomically quarantine EVERY mesh-replica slot of ``model``
        for host-loss ``epoch``.  A mesh replica is one failure domain:
        a dead member host (barrier timeout, harvest watchdog, peer
        notification) invalidates the whole slice, so all its breakers
        trip together — exactly once per epoch, however many threads
        observe the same loss (docs/SERVING.md "Pod-scale serving").
        Returns True when THIS call performed the trip."""
        model = model or self._default_model
        slots = self.mesh_slots_of(model)
        if not slots:
            return False
        if not self.mesh_quarantine.trip(epoch,
                                         [s.breaker for s in slots]):
            return False
        obs.count("serving_mesh_replica_events_total", event="quarantined",
                  model=model, flat=f"{self.name}/mesh_replica_quarantined")
        self._log.warning(
            "%s: mesh replica(s) of %r quarantined atomically at host-loss "
            "epoch %d (%d slot(s))", self.name, model, epoch, len(slots))
        return True

    def shed_mesh_replicas(self, model: Optional[str] = None) -> int:
        """Drop every mesh-replica slot of ``model`` (the roster did not
        heal in time — docs/SERVING.md "Pod-scale serving").  In-flight
        batches on the shed slots still answer through the normal
        requeue path; the freed per-chip budget lets the autoscaler
        re-plan with one fewer replica.  Returns slots shed."""
        model = model or self._default_model
        with self._lock:
            g = self._groups.get(model)
            if g is None or not g.mesh_slots:
                return 0
            shed, g.mesh_slots = list(g.mesh_slots), []
            g.rr = 0
        obs.count("serving_mesh_replica_events_total", len(shed),
                  event="shed", model=model,
                  flat=f"{self.name}/mesh_replica_shed")
        self._log.warning("%s: shed %d mesh replica slot(s) of %r",
                          self.name, len(shed), model)
        return len(shed)

    def add_mesh_replicas(self, replicas: List,
                          model: Optional[str] = None) -> int:
        """Install fresh mesh-replica slots (supervisor rebuild after a
        shed, or a late roster heal).  Indices continue after every
        existing slot of the group."""
        model = model or self._default_model
        with self._lock:
            g = self._groups.get(model)
            if g is None or not replicas:
                return 0
            start = max((s.index for s in g.all_slots()), default=-1) + 1
            g.mesh_slots.extend(self._make_slots(
                list(replicas), model, kind="mesh_replica", start=start))
            n = len(g.mesh_slots)
        obs.count("serving_mesh_replica_events_total", len(replicas),
                  event="rebuilt", model=model,
                  flat=f"{self.name}/mesh_replica_rebuilt")
        return n

    def ensure_threads(self) -> None:
        """Supervisor repair: respawn a dead executor thread (the loops
        are exception-proof, so death is unexpected — but the healer
        assumes nothing)."""
        if self._stop.is_set():
            return
        if not self._dispatch_thread.is_alive():
            obs.count("serving_stage_restarts_total", stage="dispatch",
                      flat=f"{self.name}/stage_restarted")
            self._log.warning("%s: dispatch thread died; restarting",
                              self.name)
            self._dispatch_thread = threading.Thread(
                target=self._dispatch_loop, daemon=True, name="srv-dispatch")
            self._dispatch_thread.start()
        if not self._harvest_thread.is_alive():
            with self._lock:
                self._harvest_epoch += 1
                epoch = self._harvest_epoch
            obs.count("serving_stage_restarts_total", stage="harvest",
                      flat=f"{self.name}/stage_restarted")
            self._log.warning("%s: harvest thread died; restarting",
                              self.name)
            self._harvest_thread = threading.Thread(
                target=self._harvest_loop, args=(epoch,), daemon=True,
                name=f"srv-harvest-{epoch}")
            self._harvest_thread.start()

    def check_harvest(self, deadline_s: float) -> bool:
        """Supervisor watchdog: a readback blocked past ``deadline_s``
        means the replica (or its device stream) is hung.  Claim the
        batch away from the stuck thread, quarantine the replica,
        requeue the records, and respawn the harvest stage.  The stuck
        thread eventually unblocks, sees its batch claimed and its epoch
        superseded, and exits without answering anything."""
        with self._lock:
            batch = self._harvesting
            now = time.monotonic()
            if (batch is None or batch.claimed or batch.t_harvest is None
                    or now - batch.t_harvest <= deadline_s):
                return False
            batch.claimed = True
            self._harvesting = None
            self._inflight -= 1
            self._last_harvest_t = now
            slot = batch.slot
            self._harvest_epoch += 1
            epoch = self._harvest_epoch
        TIMERS.incr(f"{self.name}/harvest_abandoned")
        if batch.span is not None:
            batch.span.end(status="abandoned",
                           error=f"harvest exceeded {deadline_s:.1f}s")
        self._log.warning(
            "%s: harvest readback exceeded %.1fs deadline on replica %s — "
            "abandoning, quarantining, requeueing %d request(s)",
            self.name, deadline_s,
            slot.index if slot is not None else "?", len(batch.reqs))
        if slot is not None and slot.breaker.force_open():
            obs.count("serving_replica_events_total", event="quarantined",
                      replica=slot.index, model=slot.model,
                      flat=f"{self.name}/replica_quarantined")
        if slot is not None and slot.kind == "mesh_replica":
            # a wedged mesh readback is indistinguishable from a lost
            # member host — quarantine the whole slice (synthesized
            # epoch; the roster-driven path supplies real ones)
            self.quarantine_mesh_replica(
                self.mesh_quarantine.last_epoch + 1, model=slot.model)
        self._requeue_or_fail(
            batch, ServingError("device harvest exceeded "
                                f"{deadline_s:.1f}s deadline",
                                code="model_error"))
        self._harvest_thread = threading.Thread(
            target=self._harvest_loop, args=(epoch,), daemon=True,
            name=f"srv-harvest-{epoch}")
        self._harvest_thread.start()
        return True

    # -- failure plumbing --------------------------------------------------
    def _fail_batch(self, batch: "_Batch", exc: BaseException) -> None:
        if not isinstance(exc, ServingError):
            try:
                exc.code = getattr(exc, "code", "model_error")
            except Exception:
                pass
        if batch.span is not None:  # no-op if already terminal
            batch.span.end(status=getattr(exc, "code", None) or "error",
                           error=str(exc))
        for r in batch.reqs:
            r.callback(None, exc)

    def _requeue_or_fail(self, batch: "_Batch", exc: BaseException) -> None:
        """Retry the batch on another replica (fresh _Batch — the old
        object stays claimed so a late abandoned readback is inert), or
        answer typed errors once retries are spent."""
        if batch.attempt < self.max_retries:
            obs.count("serving_batch_retries_total", model=batch.model,
                      flat=f"{self.name}/batch_retries")
            if batch.span is not None:
                batch.span.end(status="retry", error=str(exc))
            fresh = _Batch(batch.key, batch.fused, batch.reqs,
                           attempt=batch.attempt + 1, model=batch.model)
            self._retryq.append(fresh)
        else:
            self._fail_batch(batch, exc)

    def _replica_failed(self, slot: "_ReplicaSlot", batch: "_Batch",
                        exc: BaseException) -> None:
        if (slot.kind == "mesh_replica"
                and isinstance(exc, MeshReplicaLostError)):
            # a lost member host invalidates the WHOLE mesh slice: trip
            # every mesh slot of the group at the loss epoch (idempotent
            # — concurrent observers collapse into one quarantine), then
            # let the requeue retry on the surviving single-chip slots
            self.quarantine_mesh_replica(exc.epoch, model=slot.model)
        elif slot.breaker.record_failure():
            obs.count("serving_replica_events_total", event="quarantined",
                      replica=slot.index, model=slot.model,
                      flat=f"{self.name}/replica_quarantined")
            self._log.warning(
                "%s: replica %d quarantined after %d consecutive "
                "failure(s); last error: %s", self.name, slot.index,
                slot.breaker.failure_threshold, exc)
        self._requeue_or_fail(batch, exc)

    # -- dispatch ----------------------------------------------------------
    def _next_batch(self) -> Optional["_Batch"]:
        try:
            return self._retryq.popleft()
        except IndexError:
            pass
        try:
            return self._inbox.get(timeout=0.05)
        except pyqueue.Empty:
            return None

    def _pick_slot_locked(self, group: "_ModelGroup", long_doc: bool = False
                          ) -> Optional["_ReplicaSlot"]:
        # mesh slots are first-class capacity: they share the normal
        # round-robin cursor with the single-chip slots
        slots = (group.long_slots if long_doc
                 else list(group.slots) + list(group.mesh_slots))
        rr = group.long_rr if long_doc else group.rr
        n = len(slots)
        for k in range(n):
            s = slots[(rr + k) % n]
            if s.breaker.allow():
                if long_doc:
                    group.long_rr = (rr + k + 1) % n
                else:
                    group.rr = (rr + k + 1) % n
                return s
        return None

    def _dispatch_loop(self) -> None:
        while True:
            if self._heartbeat is not None:
                self._heartbeat()
            batch = self._next_batch()
            if batch is None:
                if self._stop.is_set():
                    return  # inbox drained after stop
                continue
            try:
                self._dispatch_one(batch)
            except Exception:
                # the loop must outlive any single batch: answer it and
                # keep dispatching
                self._log.exception("%s: dispatch loop error", self.name)
                self._fail_batch(batch, ServingError(
                    "internal dispatch error", code="internal"))

    def _dispatch_one(self, batch: "_Batch") -> None:
        with self._lock:
            if self._swap is not None:
                for mname, reps in self._swap.items():
                    g = self._groups.get(mname)
                    if g is None:
                        self._groups[mname] = _ModelGroup(
                            mname, self._make_slots(reps, mname),
                            self._groups[self._default_model].buckets)
                    else:
                        g.slots = self._make_slots(reps, mname)
                        g.rr = 0
                self._swap = None
            group = self._groups.get(batch.model)
            # bucket class: the token axis (dim 1) of the fused input
            # decides whether this batch belongs on a long-document
            # mesh replica (>= LONG_DOC_TOKENS) or a single-chip slot
            x0 = batch.fused[0]
            tokens = (int(x0.shape[1])
                      if getattr(x0, "ndim", 0) >= 2 else None)
            long_doc = bool(group is not None and group.long_slots
                            and bucket_class(tokens) == "long_doc")
            slot = (self._pick_slot_locked(group, long_doc=long_doc)
                    if group is not None else None)
            if slot is None and long_doc:
                # every long-doc slot quarantined: degrade onto the
                # normal slots (latency over dropped requests) and let
                # their breakers arbitrate from here
                slot = self._pick_slot_locked(group)
                long_doc = False
            if group is None:
                pass
            elif slot is not None:
                now = time.monotonic()
                if (self._inflight == 0 and self._last_harvest_t is not None
                        and now - self._last_harvest_t > self.IDLE_EPS_S):
                    # the device drained before new work arrived — under
                    # saturated load this must stay ~0 (warmup/drain gaps
                    # are excluded: no previous harvest / no next dispatch)
                    TIMERS.incr(f"{self.name}/device_idle_events")
                    TIMERS.observe(f"{self.name}/device_idle",
                                   now - self._last_harvest_t)
                # count the batch in-flight BEFORE dispatching so even a
                # synchronous fallback forward reads busy() == True while
                # it computes
                self._inflight += 1
        if group is None:
            # a record named a model this executor doesn't host —
            # answer typed, don't poison the dispatch loop
            self._fail_batch(batch, ServingError(
                f"unknown model {batch.model!r}", code="malformed"))
            return
        if slot is None:
            self._no_healthy_replica(batch, group)
            return
        # the batch span links its member record spans: each request's
        # batch_wait span carries the record's trace id
        if batch.span is None:
            batch.span = TRACER.start(
                "serving/device_batch", replica=slot.index,
                model=batch.model,
                rows=batch.fused[0].shape[0], attempt=batch.attempt,
                members=[r.span.trace for r in batch.reqs
                         if getattr(r, "span", None) is not None])
        try:
            plan = faults.fire(f"{self.name}.replica_crash")
            if plan is not None and plan.exc is not None:
                raise plan.exc
            batch.handles = self._dispatch(slot.replica, batch.fused,
                                           group.buckets, tokens=tokens)
        except Exception as e:
            with self._lock:
                self._inflight -= 1
            self._replica_failed(slot, batch, e)
            return
        batch.slot = slot
        batch.t_dispatch = time.monotonic()
        obs.count("serving_batches_total", replica=slot.index,
                  model=batch.model, flat=f"{self.name}/device_batches")
        obs.count("serving_batch_rows_total", batch.fused[0].shape[0],
                  replica=slot.index, model=batch.model,
                  flat=f"{self.name}/device_rows")
        if long_doc:
            obs.count("serving_long_doc_batches_total", model=batch.model,
                      flat=f"{self.name}/long_doc_batches")
        self._pending.put(batch)

    def _no_healthy_replica(self, batch: "_Batch",
                            group: "_ModelGroup") -> None:
        """Every replica is quarantined.  With a ``fallback`` (the
        owning worker's sync predict — the ``serve_once`` path) the
        batch still serves, synchronously, while the supervisor rebuilds
        replicas; without one, the batch waits for a half-open probe
        window and eventually fails typed rather than hanging."""
        if group.fallback is not None:
            with self._lock:
                self._inflight += 1
            try:
                out = group.fallback(batch.fused)
                obs.count("serving_batches_total", replica="fallback",
                          model=batch.model,
                          flat=f"{self.name}/sync_fallback_batches")
                TIMERS.incr(f"{self.name}/device_batches")
                obs.count("serving_batch_rows_total",
                          batch.fused[0].shape[0], replica="fallback",
                          model=batch.model,
                          flat=f"{self.name}/device_rows")
                if batch.span is not None:
                    batch.span.end(fallback=True)
                scatter_batch_results(out, batch.reqs)
            except Exception as e:
                self._requeue_or_fail(batch, e)
            finally:
                with self._lock:
                    self._inflight -= 1
                    self._last_harvest_t = time.monotonic()
            return
        now = time.monotonic()
        if batch.first_blocked_t is None:
            batch.first_blocked_t = now
        if (now - batch.first_blocked_t
                > max(1.0, 4.0 * self.breaker_cooldown_s)):
            self._fail_batch(batch, ServingError(
                "no healthy replica available", code="model_error"))
            return
        time.sleep(0.01)  # wait for a probe window / supervisor rebuild
        self._retryq.append(batch)

    def _dispatch(self, rep, fused: List[np.ndarray], buckets,
                  tokens: Optional[int] = None):
        """Pad to the bucket set and dispatch; a batch larger than the
        biggest bucket splits into full-bucket programs (never compiles
        a one-off shape).  The split/pad plan comes from the SAME
        ``plan_buckets`` the predict path uses, so the executor and the
        compile-shape ledger can never disagree.  ``tokens`` carries the
        batch's sequence length into the bucket-class decision: the
        long-document class plans at the smallest row bucket.
        Returns [(handle, rows), ...]."""
        n = fused[0].shape[0]
        if not rep.pads_input:  # fallback replica: predict() pads itself
            return [(rep.dispatch(fused), n)]
        out, s = [], 0
        for m, bucket in plan_buckets(n, buckets, tokens=tokens):
            chunk = [x[s:s + m] for x in fused]
            if bucket > m:
                chunk = [np.concatenate(
                    [c, np.repeat(c[-1:], bucket - m, axis=0)], axis=0)
                    for c in chunk]
            out.append((rep.dispatch(chunk), m))
            s += m
        return out

    # -- harvest -----------------------------------------------------------
    def _harvest_loop(self, my_epoch: int) -> None:
        while True:
            with self._lock:
                if self._harvest_epoch != my_epoch:
                    return  # superseded by the watchdog's respawn
            try:
                batch = self._pending.get(timeout=0.05)
            except pyqueue.Empty:
                if (self._stop.is_set()
                        and not self._dispatch_thread.is_alive()
                        and self._pending.empty()):
                    return
                continue
            self._harvest_one(batch)

    def _harvest_one(self, batch: "_Batch") -> None:
        slot = batch.slot
        with self._lock:
            self._harvesting = batch
            batch.t_harvest = time.monotonic()
        err: Optional[BaseException] = None
        out = None
        try:
            plan = faults.fire(f"{self.name}.replica_hang")
            if plan is not None:  # simulated wedged readback
                time.sleep(float(plan.payload or 0.5))
                if plan.exc is not None:
                    raise plan.exc
            parts = []
            for h, m in batch.handles:
                outs = slot.replica.harvest(h)  # the one blocking readback
                parts.append([np.asarray(o)[:m] for o in outs])
            outs = (parts[0] if len(parts) == 1 else
                    [np.concatenate([p[i] for p in parts], axis=0)
                     for i in range(len(parts[0]))])
            out = outs if len(outs) > 1 else outs[0]
        except Exception as e:
            err = e
        # claim the batch: exactly one of {this thread, the watchdog}
        # answers it
        with self._lock:
            if self._harvesting is batch:
                self._harvesting = None
            if batch.claimed:
                return  # the watchdog took it while we were stuck
            batch.claimed = True
            self._inflight -= 1
            self._last_harvest_t = time.monotonic()
        if err is not None:
            self._replica_failed(slot, batch, err)
            return
        dt = time.monotonic() - batch.t_dispatch
        obs.observe("serving_stage_seconds", dt, stage="device",
                    model=batch.model, flat=f"{self.name}/device")
        if batch.span is not None:
            batch.span.end(device_s=dt)
        scatter_batch_results(out, batch.reqs)
        if slot.breaker.record_success():
            obs.count("serving_replica_events_total", event="restored",
                      replica=slot.index, model=slot.model,
                      flat=f"{self.name}/replica_restored")
        if slot.rebuilt:
            slot.rebuilt = False
            obs.count("serving_replica_events_total", event="restored",
                      replica=slot.index, model=slot.model,
                      flat=f"{self.name}/replica_restored")


class _PodReplica:
    """A mesh replica whose dispatch is gated by the pod's deadline
    barrier (:meth:`PodCoordinator.dispatch_barrier`): every member
    host enters the barrier before compute, so a dead member surfaces
    as :class:`MeshReplicaLostError` on all survivors within the
    barrier timeout instead of a silent hang."""

    def __init__(self, inner, coord: "PodCoordinator"):
        self._inner = inner
        self._coord = coord
        self.device = (f"pod{coord.replica_id}:"
                       f"{getattr(inner, 'device', 'mesh')}")
        self.on_device_topn = bool(getattr(inner, "on_device_topn", False))
        self.pads_input = bool(getattr(inner, "pads_input", True))

    def dispatch(self, xs):
        self._coord.dispatch_barrier()
        return self._inner.dispatch(xs)

    def harvest(self, handle):
        return self._inner.harvest(handle)


class PodCoordinator:
    """Cross-host coordination for one mesh replica (docs/SERVING.md
    "Pod-scale serving").

    Every serving process of a pod holds one coordinator over the
    shared :class:`~analytics_zoo_tpu.core.context.HostRoster`.  The
    dispatch path synchronizes the members with a deadline barrier
    (``zoo_pod_dispatch_{name}_{seq}`` — the serving mirror of the data
    loader's ``zoo_data_shard_*`` barriers): a member that dies or
    wedges times the barrier out on EVERY survivor within
    ``dist_barrier_timeout_s``, and each survivor converts the timeout
    into the same epoch-tagged :class:`MeshReplicaLostError` — so the
    executor's :class:`~analytics_zoo_tpu.robust.QuarantineBroadcast`
    trips the whole replica exactly once per loss epoch, atomically, on
    every surviving host.

    ``faults.inject("serving.host_lost")`` sits on the barrier path so
    chaos tests drive the full loss→quarantine→heal cycle without a
    real multi-host pod (docs/ROBUSTNESS.md fault-site table).
    """

    def __init__(self, roster, process_id: int, *, replica_id: int = 0,
                 name: str = "pod",
                 barrier_timeout_s: Optional[float] = None):
        self.roster = roster
        self.process_id = int(process_id)
        self.replica_id = int(replica_id)
        self.name = name
        self.barrier_timeout_s = barrier_timeout_s
        self._seq = 0
        self._seq_lock = threading.Lock()

    def wrap_replica(self, replica) -> "_PodReplica":
        """Gate one ``shard_replica`` forward behind the pod barrier."""
        return _PodReplica(replica, self)

    def dispatch_barrier(self) -> None:
        """One barrier round before a mesh dispatch.  Raises
        :class:`MeshReplicaLostError` (epoch-tagged, roster already
        marked) when any member is gone."""
        from analytics_zoo_tpu.core.context import dist_barrier

        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        try:
            faults.inject("serving.host_lost")
            dist_barrier(f"zoo_pod_dispatch_{self.name}_{seq}",
                         timeout_s=self.barrier_timeout_s,
                         phase="dispatch")
        except MeshReplicaLostError:
            raise
        except HostLostError as e:
            raise self.host_lost(
                barrier=getattr(e, "barrier", "") or "",
                timeout_s=getattr(e, "timeout_s", None)) from e

    def host_lost(self, lost_process_id: int = -1, barrier: str = "",
                  timeout_s: Optional[float] = None
                  ) -> MeshReplicaLostError:
        """Mark the loss on the roster and build the typed error.  A
        barrier timeout cannot name the dead member, so without an
        explicit ``lost_process_id`` every peer is marked lost — the
        replica is unusable either way, and a healed peer re-registers
        through :meth:`heal`."""
        peers = [p for p in self.roster.expected
                 if p != self.process_id]
        lost = ([int(lost_process_id)] if lost_process_id >= 0
                else list(peers))
        epoch = self.roster.epoch
        for pid in lost:
            epoch = self.roster.mark_lost(pid)
        obs.count("serving_mesh_replica_events_total", event="host_lost",
                  model=self.name, flat="serving/pod_host_lost")
        # fan the loss out to every registered peer-loss hook so ONE
        # barrier deadline quarantines every model's mesh replicas, not
        # just the model whose dispatch tripped it
        from analytics_zoo_tpu.core.context import report_peer_loss
        report_peer_loss(
            lost, reason=(f"pod {self.name!r} replica {self.replica_id} "
                          f"barrier deadline"))
        msg = (f"pod {self.name!r} replica {self.replica_id}: member "
               f"host(s) {lost} lost at roster epoch {epoch}")
        if barrier:
            msg += (f" (barrier {barrier!r} timed out"
                    + (f" after {timeout_s:.1f}s" if timeout_s else "")
                    + ")")
        return MeshReplicaLostError(
            msg, replica_id=self.replica_id,
            lost_process_id=lost[0] if lost else -1, epoch=epoch,
            barrier=barrier, timeout_s=timeout_s)

    def heal(self, process_id: int) -> int:
        """A member came back: re-register it on the roster.  Returns
        the new roster epoch (the supervisor rebuilds the replica once
        ``roster.healed()``)."""
        return self.roster.mark_alive(int(process_id))


class _SloAdmission:
    """Weighted per-model admission (docs/SERVING.md "Warm start &
    multi-model").  Each model with a nonzero SLO gets a sliding window
    of recent e2e latencies; while its observed p99 exceeds its SLO the
    poller admits only a ``slo/p99`` fraction of that model's incoming
    records (deterministic fractional accumulator, not a coin flip) and
    sheds the rest with a typed ``overloaded`` error — the over-SLO
    model's queue pressure never starves its neighbours."""

    WINDOW = 256        # samples kept per model
    MIN_SAMPLES = 20    # below this, always admit (cold start)
    MIN_FRACTION = 0.05  # never shed more than 95%

    def __init__(self, slos: Dict[str, float]):
        self._slos = {m: float(v) for m, v in slos.items() if v > 0}
        self._lock = threading.Lock()
        self._win: Dict[str, deque] = {
            m: deque(maxlen=self.WINDOW) for m in self._slos}
        self._acc: Dict[str, float] = {m: 0.0 for m in self._slos}

    @property
    def active(self) -> bool:
        return bool(self._slos)

    def note(self, model: str, e2e_s: float) -> None:
        win = self._win.get(model)
        if win is None:
            return
        with self._lock:
            win.append(float(e2e_s))

    def p99(self, model: str) -> float:
        """Observed e2e p99 (ms) over the window; 0.0 = not enough
        samples yet."""
        win = self._win.get(model)
        if win is None:
            return 0.0
        with self._lock:
            xs = sorted(win)
        if len(xs) < self.MIN_SAMPLES:
            return 0.0
        return xs[min(len(xs) - 1, int(0.99 * len(xs)))] * 1e3

    def admit(self, model: str) -> bool:
        slo = self._slos.get(model)
        if slo is None:
            return True
        p99 = self.p99(model)
        if p99 <= slo:
            return True
        frac = max(self.MIN_FRACTION, slo / p99)
        with self._lock:
            self._acc[model] += frac
            if self._acc[model] >= 1.0:
                self._acc[model] -= 1.0
                return True
        return False


class ClusterServing:
    """The serving worker (reference ClusterServing.scala main loop).

    Default mode is the async pipeline (``ServingConfig.pipeline``)::

        poller ─→ decode pool ─→ DynamicBatcher ─→ DeviceExecutor ─→ respond pool
        (claim,    (base64/JSON    (shape buckets,   (pad, round-robin   (codec,
         trim,      + preprocess,   full-or-deadline   replicas, async     set_result,
         reload)    concurrent)     flush)             double-buffer)      metrics)

    ``pipeline=False`` (or calling :meth:`serve_once` directly) runs the
    original synchronous quantum.  One process per TPU chip/slice; scale
    out by running more workers on the same queue (FileQueue/RedisQueue
    hand each record to exactly one claimer).  Backpressure trims the
    input stream like the reference's XTRIM-at-memory-threshold
    (ClusterServing.scala:123-138).
    """

    def __init__(self, model, queue, config: Optional[ServingConfig] = None,
                 preprocess: Optional[Callable] = None, mesh=None,
                 roster=None, pod: Optional[PodCoordinator] = None):
        # ``model`` is one InferenceModel (legacy) or a dict of named
        # models multiplexed by one executor under a shared HBM budget
        # (docs/SERVING.md "Warm start & multi-model").  ``self.model``
        # stays the single/default model for existing callers.
        # ``mesh`` (+ ``cfg.mesh_replicas``) turns on pod-scale mesh
        # replicas; ``roster``/``pod`` wire the cross-host failure
        # domain (docs/SERVING.md "Pod-scale serving").
        if isinstance(model, dict):
            if not model:
                raise ValueError("ClusterServing needs at least one model")
            self.models: Dict[str, Any] = dict(model)
            for mname, m in self.models.items():
                if getattr(m, "name", None) != mname:
                    m.name = mname
        else:
            self.models = {getattr(model, "name", None)
                           or DEFAULT_MODEL: model}
        self._default_model = next(iter(self.models))
        self.model = self.models[self._default_model]
        self.queue = queue
        self._wire = getattr(queue, "wire", "json")
        self.cfg = config or ServingConfig()
        self.preprocess = preprocess
        self._stop = threading.Event()
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self._threads: List[threading.Thread] = []
        self._executor: Optional[DeviceExecutor] = None
        self._batcher: Optional[DynamicBatcher] = None
        self._hb: Optional[Heartbeat] = None
        self._supervisor: Optional[Supervisor] = None
        self._topn_on_device = False
        self._topn_by_model: Dict[str, bool] = {}
        self.records_served = 0
        self._count_lock = threading.Lock()
        # warm start: one shared CompileCache for every hosted model
        self._compile_cache = None
        if self.cfg.compile_cache_dir:
            from analytics_zoo_tpu.deploy.compile_cache import CompileCache
            self._compile_cache = CompileCache(
                self.cfg.compile_cache_dir,
                max_entries=self.cfg.compile_cache_entries)
            for mname, m in self.models.items():
                if getattr(m, "_net", None) is not None:
                    m.attach_compile_cache(self._compile_cache)
        # per-model SLO admission + autoscaler actuator state
        self._admission = _SloAdmission(
            {m: self.cfg.slo_for(m) for m in self.models})
        self._autoscaler = None
        self._scale_lock = threading.Lock()
        self._decode_target = self.cfg.decode_workers
        self._replica_plan: Dict[str, int] = {}
        # pod-scale mesh replicas (docs/SERVING.md "Pod-scale serving")
        self._mesh = mesh
        self.roster = roster
        self.pod = pod
        self._mesh_plan: Dict[str, int] = {}
        self._peer_loss_hook = None
        self._tb = None
        self._tb_last_t = time.monotonic()
        self._tb_last_n = 0
        if self.cfg.tensorboard_dir:
            from analytics_zoo_tpu.core.summary import SummaryWriter
            self._tb = SummaryWriter(self.cfg.tensorboard_dir)
        # observability wiring (docs/OBSERVABILITY.md): spans always on
        # (a dict append per stage hop), event log / flight recorder by
        # config
        self.flight_recorder: Optional[FlightRecorder] = None
        self._event_log: Optional[JsonlEventLog] = None
        if self.cfg.span_ring:
            TRACER.resize(self.cfg.span_ring)
        if self.cfg.jsonl_path:
            self._event_log = JsonlEventLog(self.cfg.jsonl_path)
            self._event_log.attach(TRACER)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ClusterServing":
        if self.is_alive():
            return self
        self._stop.clear()
        self._stopped = False
        if self.cfg.pipeline:
            self._start_pipeline()
        else:
            self._thread = threading.Thread(target=self.run_forever,
                                            daemon=True, name="srv-sync")
            self._thread.start()
        if self.pod is not None:
            # the coordination service's heartbeat detector observes a
            # member death whether or not a dispatch barrier is in
            # flight — route it into the same quarantine entry point
            from analytics_zoo_tpu.core import context as _ctx
            _ctx.on_peer_loss(self.notify_host_lost)
            self._peer_loss_hook = self.notify_host_lost
        return self

    def _build_replicas(self, model: Optional[str] = None,
                        n: Optional[int] = None) -> List:
        mname = model or self._default_model
        if n is None:
            n = self._replica_plan.get(mname, self.cfg.replicas)
        return self.models[mname].replica_forwards(
            n=n, top_n=self.cfg.postprocess_top_n)

    def _plan_replicas(self) -> Dict[str, int]:
        """Per-model replica counts under the shared HBM budget: every
        model starts at ``cfg.replicas``; while the summed weight bytes
        exceed ``hbm_budget_bytes`` the heaviest group sheds one replica
        (never below 1 — the budget bounds *copies*, not presence)."""
        plan = {m: self.cfg.replicas for m in self.models}
        budget = self.cfg.hbm_budget_bytes
        if not budget:
            return plan
        sizes = {m: max(1, int(getattr(mdl, "weight_nbytes",
                                       lambda: 0)() or 1))
                 for m, mdl in self.models.items()}
        def cost(p):
            return sum(sizes[m] * p[m] for m in p)
        while cost(plan) > budget and any(v > 1 for v in plan.values()):
            heavy = max((m for m in plan if plan[m] > 1),
                        key=lambda m: sizes[m] * plan[m])
            plan[heavy] -= 1
        if cost(plan) > budget:
            logging.getLogger("analytics_zoo_tpu.deploy").warning(
                "serving: even one replica per model (%d bytes) exceeds "
                "the HBM budget (%d bytes); proceeding at 1 each",
                cost(plan), budget)
        return plan

    def _mesh_eligible(self, mname: str) -> bool:
        m = self.models[mname]
        return (getattr(m, "_net", None) is not None
                and hasattr(m, "shard_replica"))

    def _mesh_chip_nbytes(self, mname: str) -> int:
        """Per-chip bytes of ONE mesh replica of ``mname``: sharded
        table leaves charge ``nbytes / ways``, everything else full —
        the reason an over-per-chip-budget sharded-table model still
        fits a mesh replica (docs/SERVING.md "Pod-scale serving")."""
        m = self.models[mname]
        try:
            return max(1, int(m.weight_nbytes_per_chip(
                self._mesh, axis=self.cfg.mesh_axis)))
        except Exception:
            return max(1, int(getattr(m, "weight_nbytes",
                                      lambda: 0)() or 1))

    def _plan_mesh_replicas(self) -> Dict[str, int]:
        """Mesh-replica counts under what the single-chip plan left of
        the shared HBM budget.  A mesh replica is charged its PER-CHIP
        shard bytes (the budget is per chip; the slice spreads the
        table rows); over budget the heaviest model sheds mesh replicas
        first — all the way to 0, mesh capacity is optional."""
        if self._mesh is None or not self.cfg.mesh_replicas:
            return {m: 0 for m in self.models}
        plan = {m: (self.cfg.mesh_replicas if self._mesh_eligible(m)
                    else 0) for m in self.models}
        budget = self.cfg.hbm_budget_bytes
        if not budget:
            return plan
        sizes = {m: max(1, int(getattr(mdl, "weight_nbytes",
                                       lambda: 0)() or 1))
                 for m, mdl in self.models.items()}
        chip = {m: self._mesh_chip_nbytes(m) for m in self.models}
        used = sum(sizes[m] * self._replica_plan.get(m, self.cfg.replicas)
                   for m in self.models)
        def cost(p):
            return used + sum(chip[m] * p[m] for m in p)
        while cost(plan) > budget and any(v > 0 for v in plan.values()):
            heavy = max((m for m in plan if plan[m] > 0),
                        key=lambda m: chip[m] * plan[m])
            plan[heavy] -= 1
        return plan

    def _build_mesh_replicas(self, model: Optional[str] = None,
                             n: Optional[int] = None) -> List:
        """``n`` fresh sharded mesh forwards (each one whole-mesh-as-
        one-replica), pod-barrier-gated when a :class:`PodCoordinator`
        is attached.  Warm-start note: the PR 15 compile-cache digest
        already folds in the mesh, so a rebuilt mesh replica re-loads
        its programs instead of compiling (``warm_compile_count == 0``
        in the chaos soak)."""
        mname = model or self._default_model
        if n is None:
            n = self._mesh_plan.get(mname, self.cfg.mesh_replicas)
        reps = [self.models[mname].shard_replica(
                    self._mesh, top_n=self.cfg.postprocess_top_n,
                    axis=self.cfg.mesh_axis)
                for _ in range(max(0, int(n)))]
        if self.pod is not None:
            reps = [self.pod.wrap_replica(r) for r in reps]
        return reps

    def _warm_models(self) -> None:
        """Pre-install every cached executable before replica build, so
        a restarted worker's first request hits full bucket coverage
        with ZERO live compiles (counter-proven: ``compile_count`` stays
        0, cache ``hit`` events >= bucket count)."""
        if self._compile_cache is None:
            return
        log = logging.getLogger("analytics_zoo_tpu.deploy")
        t0 = time.perf_counter()
        for mname, m in self.models.items():
            if getattr(m, "_net", None) is None:
                continue
            n = m.warm()
            if n:
                log.info("serving: model %r warm-started %d program(s) "
                         "from %s in %.2fs", mname, n,
                         self.cfg.compile_cache_dir,
                         time.perf_counter() - t0)

    def _start_pipeline(self) -> None:
        self._warm_models()
        self._replica_plan = self._plan_replicas()
        self._mesh_plan = self._plan_mesh_replicas()
        rep_map: Dict[str, List] = {}
        bucket_map: Dict[str, tuple] = {}
        fb_map: Dict[str, Callable] = {}
        mesh_map: Dict[str, List] = {}
        for mname, m in self.models.items():
            reps = self._build_replicas(mname)
            rep_map[mname] = reps
            self._topn_by_model[mname] = bool(reps[0].on_device_topn)
            bucket_map[mname] = tuple(
                getattr(m, "batch_buckets", None)
                or (1, self.cfg.batch_size))
            fb_map[mname] = (lambda fused, _m=m: _m.predict(
                fused[0] if len(fused) == 1 else fused))
            if self._mesh_plan.get(mname):
                mesh_map[mname] = self._build_mesh_replicas(mname)
        self._topn_on_device = self._topn_by_model[self._default_model]
        self._hb = Heartbeat()
        self._executor = DeviceExecutor(
            rep_map, buckets=bucket_map,
            max_inflight=self.cfg.max_inflight,
            breaker_threshold=self.cfg.breaker_threshold,
            breaker_cooldown_s=self.cfg.breaker_cooldown_s,
            fallback=fb_map, mesh_replicas=mesh_map or None)
        self._executor._heartbeat = lambda: self._hb.beat("device")
        # hot-row replication caches (ISSUE 19): models serving sharded
        # tables through mesh replicas get a per-table top-K cache; a
        # replica swap (hot reload / resize / rebuild) invalidates it
        for mname in mesh_map:
            m = self.models[mname]
            if getattr(m, "sharded_tables", lambda: ())():
                m.enable_hot_caches(self._mesh, axis=self.cfg.mesh_axis)
        self._executor.add_swap_listener(self._on_replica_swap)
        self._batcher = DynamicBatcher(
            max_batch=self.cfg.batch_size,
            max_latency_ms=self.cfg.max_batch_delay_ms,
            dispatch_fn=self._executor.submit,
            heartbeat=lambda: self._hb.beat("batcher"))
        self._decode_q: "pyqueue.Queue" = pyqueue.Queue(
            maxsize=max(64, self.cfg.batch_size * 4))
        self._respond_q: "pyqueue.Queue" = pyqueue.Queue()
        self._poller = threading.Thread(target=self._poll_loop, daemon=True,
                                        name="srv-poll")
        with self._scale_lock:      # vs a concurrent resize_decode_pool
            self._decode_workers = [
                threading.Thread(target=self._decode_loop, daemon=True,
                                 name=f"srv-decode-{i}")
                for i in range(self._decode_target)]
            decode_workers = list(self._decode_workers)
        self._respond_workers = [
            threading.Thread(target=self._respond_loop, daemon=True,
                             name=f"srv-respond-{i}")
            for i in range(max(1, self.cfg.decode_workers // 2))]
        self._threads = ([self._poller] + decode_workers
                         + self._respond_workers)
        for t in self._threads:
            t.start()
        if self.cfg.supervise:
            self._start_supervisor()

    # -- supervision -------------------------------------------------------
    def _start_supervisor(self) -> None:
        """Background healer: replica rebuilds, the harvest watchdog,
        stage restarts, and health gauges (docs/SERVING.md)."""
        sup = Supervisor(interval_s=self.cfg.supervisor_interval_s,
                         name="serving_supervisor")
        sup.add_check("harvest_watchdog", lambda: self._executor
                      .check_harvest(self.cfg.harvest_deadline_s))
        sup.add_check("heal_replicas", self._heal_replicas)
        sup.add_check("heal_mesh_replicas", self._heal_mesh_replicas)
        reclaim = getattr(self.queue, "reclaim_dead_result_leases", None)
        if callable(reclaim):
            # shm result slots leased to a client that was SIGKILL-ed
            # would otherwise stay READY forever (nobody left to call
            # get_result) — harvest them every tick
            sup.add_check("shm_lease_reclaim", reclaim)
        sup.add_check("stages", self._check_stages)
        sup.add_check("gauges", self._publish_gauges)
        # hot-row cache upkeep rides the supervisor cadence: each tick
        # asks every model's caches to refresh iff their period elapsed
        # (or they were invalidated by a swap) — staleness stays bounded
        # by table_hot_cache_refresh_s without a dedicated thread
        sup.add_check("hot_cache_refresh", self._refresh_hot_caches)
        # the flight recorder rides the supervisor cadence: e2e-p99
        # SLOs (per model — e2e series carry a {model} label) plus
        # breaker trips always
        slos = []
        slo_map = self.cfg.slo_models()
        if not slo_map and not isinstance(self.cfg.slo_p99_ms, dict) \
                and self.cfg.slo_p99_ms > 0:
            # scalar config: one shared bound applied to every model
            slo_map = {m: self.cfg.slo_p99_ms for m in self.models}
        for mname, p99_ms in slo_map.items():
            suffix = "" if mname == self._default_model else f"_{mname}"
            slos.append(SLO(f"serving_e2e_p99{suffix}",
                            "serving_stage_seconds",
                            labels={"stage": "e2e", "model": mname},
                            p99_ms=p99_ms, min_count=10))
        profile_dir = None
        if self.cfg.profile_on_breach and self.cfg.flight_dir:
            profile_dir = os.path.join(self.cfg.flight_dir, "profile")
        self.flight_recorder = FlightRecorder(
            slos=slos,
            watch_counters=[("breaker_transitions_total", {"to": "open"})],
            window_s=self.cfg.slo_window_s,
            out_dir=self.cfg.flight_dir or None,
            profile_dir=profile_dir,
            cooldown_s=max(1.0, 2.0 * self.cfg.slo_window_s))
        sup.add_check("flight_recorder", self.flight_recorder.check)
        if self.cfg.autoscale:
            from analytics_zoo_tpu.deploy.autoscale import Autoscaler
            self._autoscaler = Autoscaler(
                self, policy=self.cfg.autoscale_policy)
            every = max(1, int(round(
                self.cfg.autoscale_interval_s
                / self.cfg.supervisor_interval_s)))
            sup.add_check("autoscale", self._autoscaler.check, every=every)
        self._supervisor = sup
        sup.start()

    def _heal_replicas(self) -> None:
        """Rebuild quarantined replicas: a breaker still open after its
        cooldown (or re-opened by a failed probe) gets a FRESH replica —
        new program + weights on the same device — hot-swapped into its
        slot, mirroring the ``swap_replicas`` reload path but per-slot."""
        ex = self._executor
        if ex is None:
            return
        stale = ex.quarantined_slots(min_open_s=self.cfg.breaker_cooldown_s)
        if not stale:
            return
        # one replica_forwards call per affected model rebuilds its full
        # set; pick out the slots that need one (cheap for
        # function-models, and for jitted forwards the compile cache
        # makes the extra copies ~free)
        by_model: Dict[str, List] = {}
        for slot in stale:
            by_model.setdefault(slot.model, []).append(slot)
        for mname, slots in by_model.items():
            if mname not in self.models:
                continue
            fresh = self._build_replicas(mname, n=ex.group_size(mname))
            for slot in slots:
                if slot.index < len(fresh):
                    ex.rebuild_slot(slot.index, fresh[slot.index],
                                    model=mname)

    def notify_host_lost(self, process_id: int = -1) -> int:
        """Cross-host quarantine entry point (docs/SERVING.md
        "Pod-scale serving"): a host death was observed — by THIS
        process's barrier timeout, by a peer's notification, or by the
        pod supervisor.  Marks the loss on the roster (bumping its
        epoch) and trips every model's mesh replicas at that epoch.
        Idempotent per epoch: every survivor can call this for the same
        loss and the breakers trip exactly once."""
        ex = self._executor
        if self.roster is not None and process_id >= 0:
            epoch = self.roster.mark_lost(process_id)
        elif self.roster is not None:
            epoch = max(1, self.roster.epoch)
        else:
            epoch = (ex.mesh_quarantine.last_epoch + 1
                     if ex is not None else 1)
        if ex is not None:
            for mname in ex.models():
                ex.quarantine_mesh_replica(epoch, model=mname)
        return epoch

    def _heal_mesh_replicas(self) -> None:
        """Mesh-replica lifecycle (docs/SERVING.md "Pod-scale serving"):
        a quarantined mesh replica waits for the host roster to heal,
        then rebuilds through the compile cache (zero live compiles —
        the cache digest covers the mesh); a roster broken past
        ``mesh_shed_after_s`` sheds the replica instead, freeing its
        per-chip budget so the autoscaler re-plans with one fewer
        replica.  Without a roster (single-host pods, tests) the
        breaker cooldown paces the rebuild like ``_heal_replicas``."""
        ex = self._executor
        if ex is None or self._mesh is None:
            return
        roster = self.roster
        for mname in list(ex.models()):
            slots = ex.mesh_slots_of(mname)
            if not slots:
                continue
            quar = [s for s in slots
                    if s.breaker.snapshot()["state"] == "open"]
            if not quar:
                continue
            if roster is not None and not roster.healed():
                if roster.lost_age_s() > self.cfg.mesh_shed_after_s:
                    ex.shed_mesh_replicas(mname)
                    self._mesh_plan[mname] = 0
                continue  # roster still broken: wait for heal or shed
            if roster is None:
                cd = self.cfg.breaker_cooldown_s
                quar = [s for s in quar
                        if s.breaker.open_age_s() >= cd
                        or s.breaker.snapshot()["opens"] >= 2]
                if not quar:
                    continue
            fresh = self._build_mesh_replicas(mname, n=len(quar))
            for slot, rep in zip(quar, fresh):
                ex.rebuild_slot(slot.index, rep, model=mname)

    def _check_stages(self) -> None:
        """Watchdog for wedged/dead stage threads.  A dead thread is
        restarted outright; a live thread whose heartbeat is stale past
        ``stage_stall_s`` is only *flagged* (``serving/stage_stalled``)
        — killing a live Python thread isn't possible, and the harvest
        watchdog already covers the one stage that can block on a
        device."""
        if self._stop.is_set():
            return
        ex = self._executor
        if ex is not None:
            ex.ensure_threads()
        log = logging.getLogger("analytics_zoo_tpu.deploy")
        if self._poller is not None and not self._poller.is_alive():
            obs.count("serving_stage_restarts_total", stage="poller",
                      flat="serving/stage_restarted")
            log.warning("serving poller died; restarting")
            self._poller = threading.Thread(
                target=self._poll_loop, daemon=True, name="srv-poll")
            self._threads.append(self._poller)
            self._poller.start()
        with self._scale_lock:
            # prune dead workers, then top up only to the AUTOSCALER'S
            # target — a shrink retires workers via sentinel, and those
            # intentional deaths must not be resurrected here
            alive = [t for t in self._decode_workers if t.is_alive()]
            pruned = len(self._decode_workers) - len(alive)
            self._decode_workers = alive
            deficit = self._decode_target - len(alive)
            for _ in range(max(0, deficit)):
                obs.count("serving_stage_restarts_total", stage="decode",
                          flat="serving/stage_restarted")
                log.warning("decode pool below target (%d/%d); restarting",
                            len(self._decode_workers), self._decode_target)
                nt = threading.Thread(
                    target=self._decode_loop, daemon=True,
                    name=f"srv-decode-{len(self._decode_workers)}")
                self._decode_workers.append(nt)
                self._threads.append(nt)
                nt.start()
            if pruned and deficit <= 0:
                log.info("decode pool pruned %d retired worker(s) "
                         "(target %d)", pruned, self._decode_target)
        for i, t in enumerate(self._respond_workers):
            if not t.is_alive():
                obs.count("serving_stage_restarts_total", stage="respond",
                          flat="serving/stage_restarted")
                log.warning("respond worker %d died; restarting", i)
                nt = threading.Thread(target=self._respond_loop, daemon=True,
                                      name=f"srv-respond-{i}")
                self._respond_workers[i] = nt
                self._threads.append(nt)
                nt.start()
        if self._hb is not None:
            # an idle stage blocks on its queue with an aging heartbeat —
            # only a stale beat WITH work pending means wedged
            busy = (self._decode_q.qsize() > 0
                    or self._respond_q.qsize() > 0
                    or (ex is not None and ex.inflight > 0))
            if busy:
                for stage, age in self._hb.ages().items():
                    if age > self.cfg.stage_stall_s:
                        TIMERS.incr(f"serving/stage_stalled/{stage}")

    # -- autoscaler actuators (deploy/autoscale.py drives these) -----------
    def resize_decode_pool(self, n: int) -> int:
        """Grow/shrink the decode pool to ``n`` threads.  Growth spawns
        immediately; shrink retires workers with ``None`` sentinels (a
        worker finishes its current record, then exits) and
        ``_check_stages`` prunes the dead threads next tick."""
        n = max(1, int(n))
        with self._scale_lock:
            cur = self._decode_target
            self._decode_target = n
            if n > cur:
                for i in range(n - cur):
                    nt = threading.Thread(
                        target=self._decode_loop, daemon=True,
                        name=f"srv-decode-{len(self._decode_workers) + i}")
                    self._decode_workers.append(nt)
                    self._threads.append(nt)
                    nt.start()
            else:
                for _ in range(cur - n):
                    self._decode_q.put(None)
        return n

    def _budget_allows(self, model: str, extra: int) -> bool:
        """True if ``extra`` more replicas of ``model`` fit the shared
        HBM budget (0/unset = unlimited)."""
        budget = self.cfg.hbm_budget_bytes
        if not budget or self._executor is None:
            return True
        used = 0
        for mname, m in self.models.items():
            nb = int(getattr(m, "weight_nbytes", lambda: 0)() or 0)
            used += nb * self._executor.group_size(mname)
            # live mesh replicas charge per-chip shard bytes; a shed
            # mesh replica frees exactly this much for re-planning
            mesh_n = self._executor.mesh_group_size(mname)
            if mesh_n and self._mesh is not None:
                used += self._mesh_chip_nbytes(mname) * mesh_n
        add = int(getattr(self.models[model], "weight_nbytes",
                          lambda: 0)() or 0) * extra
        return used + add <= budget

    def resize_model_replicas(self, model: str, n: int) -> int:
        """Rebuild one model's replica group at ``n`` copies (hot swap —
        in-flight batches finish on the old set).  A grow that would
        bust the HBM budget is refused (returns the current size)."""
        n = max(1, int(n))
        ex = self._executor
        if ex is None or model not in self.models:
            return 0
        cur = ex.group_size(model)
        if n == cur:
            return cur
        if n > cur and not self._budget_allows(model, n - cur):
            logging.getLogger("analytics_zoo_tpu.deploy").warning(
                "serving: replica grow %s -> %d refused (HBM budget)",
                model, n)
            return cur
        reps = self._build_replicas(model, n=n)
        ex.swap_replicas(reps, model=model)
        self._replica_plan[model] = n
        return n

    def set_batch_deadline_ms(self, ms: float) -> float:
        """Retune the DynamicBatcher's flush deadline in place."""
        ms = max(0.1, float(ms))
        if self._batcher is not None:
            self._batcher.max_latency = ms / 1e3
        return ms

    # -- scenario hooks (the loadgen harness rides these) ------------------
    def add_scenario_check(self, name: str, fn, every: int = 1) -> bool:
        """Register an extra periodic check on the serving supervisor —
        the loadgen harness uses it to export status snapshots and to
        script mid-run events at the supervisor cadence.  Returns False
        when there is no supervisor to ride (``supervise=False`` or the
        sync engine)."""
        if self._supervisor is None:
            return False
        self._supervisor.add_check(name, fn, every=every)
        return True

    def autoscale_actions(self) -> List[Dict[str, Any]]:
        """The autoscaler's applied-action audit ledger (deep copies;
        empty when autoscaling is off) — the convergence assertions in
        the loadgen soak read this, not internals."""
        if self._autoscaler is None:
            return []
        return self._autoscaler.export_actions()

    def autoscale_audit(self) -> Optional[Dict[str, Any]]:
        """Hysteresis audit over the action ledger (flap detection —
        :func:`deploy.autoscale.audit_actions`); None when off."""
        if self._autoscaler is None:
            return None
        return self._autoscaler.audit()

    def _publish_gauges(self) -> None:
        ex = self._executor
        if ex is not None:
            obs.set_gauge("serving_replicas_healthy",
                          ex.healthy_replicas(),
                          flat="serving/replicas_healthy")
            for mname in ex.models():
                obs.set_gauge("serving_replicas_healthy",
                              ex.healthy_replicas(mname), model=mname,
                              flat=f"serving/replicas_healthy/{mname}")
            obs.set_gauge("serving_inflight", ex.inflight,
                          flat="serving/inflight")
        if self._hb is not None:
            for stage, age in self._hb.ages().items():
                obs.set_gauge("serving_heartbeat_age_seconds", age,
                              stage=stage,
                              flat=f"serving/heartbeat_age_s/{stage}")

    def is_alive(self) -> bool:
        """True while any worker thread (pipeline stage or sync loop) is
        running — mirror of ``PrefetchIterator``'s liveness probe."""
        threads = list(self._threads)
        if self._thread is not None:
            threads.append(self._thread)
        if self._executor is not None and self._executor.is_alive():
            return True
        return any(t.is_alive() for t in threads)

    def stop(self, timeout: float = 5.0) -> None:
        """Graceful, idempotent shutdown: stages drain in pipeline order
        (claimed records are answered, not lost).  A thread that
        outlives ``timeout`` is logged as leaked — mirroring
        ``PrefetchIterator.close()`` — instead of silently abandoned."""
        if self._stopped:
            return
        self._stopped = True
        self._stop.set()
        if self._peer_loss_hook is not None:
            from analytics_zoo_tpu.core import context as _ctx
            _ctx.remove_peer_loss_hook(self._peer_loss_hook)
            self._peer_loss_hook = None
        log = logging.getLogger("analytics_zoo_tpu.deploy")
        if self._supervisor is not None:
            # the healer goes down FIRST so it can't resurrect stages
            # that are draining on purpose
            self._supervisor.stop(timeout=timeout)
        if self._threads:  # pipeline mode
            self._poller.join(timeout=timeout)
            with self._scale_lock:  # snapshot vs a late autoscaler tick
                decode_workers = list(self._decode_workers)
            for _ in decode_workers:
                self._decode_q.put(None)
            for t in decode_workers:
                t.join(timeout=timeout)
            if self._batcher is not None:
                self._batcher.close(flush=True)
            if self._executor is not None:
                self._executor.stop(timeout=timeout)
            for _ in self._respond_workers:
                self._respond_q.put(None)
            for t in self._respond_workers:
                t.join(timeout=timeout)
        elif self._thread is not None:
            self._thread.join(timeout=timeout)
        if self.is_alive():
            leaked = [t.name for t in self._threads + (
                [self._thread] if self._thread else []) if t.is_alive()]
            log.warning(
                "ClusterServing.stop(): worker thread(s) %s still alive "
                "after %.1fs — leaked (likely stuck in model forward or "
                "backend I/O)", leaked or ["device-executor"], timeout)
        if self._event_log is not None:
            # one final metrics dump so the log tail always carries the
            # end-of-run registry state
            self._event_log.detach(TRACER)
            self._event_log.metrics_dump()
            self._event_log.close()

    # -- deadline-aware admission (docs/SERVING.md "Failure semantics") ----
    def _record_ttl_s(self, rec: Dict) -> Optional[float]:
        """Remaining time budget in seconds for a claimed record, from
        its enqueue timestamp + client TTL (or the config default).
        None = no deadline; <= 0 = already expired."""
        ttl_ms = rec.get("ttl_ms")
        if ttl_ms is None:
            ttl_ms = self.cfg.default_ttl_ms
        if ttl_ms is None:
            return None
        try:
            ttl_ms = float(ttl_ms)
        except (TypeError, ValueError):
            return None
        ts = rec.get("ts")
        age = (time.time() - ts) if isinstance(ts, (int, float)) else 0.0
        return ttl_ms / 1e3 - age

    def _shed(self, rid: str, rec: Dict, code: str, msg: str) -> None:
        """Answer a shed record with a structured error — every claimed
        record terminates in a result or a typed error payload, never
        silence.  The record's root span (started at claim, or here for
        the sync path) ends with the shed code as its terminal status."""
        model = rec.get("model") or self._default_model
        obs.count("serving_shed_total", code=code, model=model,
                  flat=f"serving/shed_{'expired' if code == 'expired' else 'early'}")
        obs.count("serving_errors_total", code=code, model=model,
                  flat="serving/errors_returned")
        sp = rec.pop("_span", None)
        if sp is None:
            sp = TRACER.start("serving/request", uri=rec.get("uri") or rid)
        sp.end(status=code, error=msg)
        try:
            self.queue.set_result(
                rid, error_payload(code, msg, uri=rec.get("uri")))
        except Exception:
            logging.getLogger("analytics_zoo_tpu.deploy").exception(
                "failed to write shed-error result for %r", rid)

    # -- pipeline stages ---------------------------------------------------
    def _poll_loop(self) -> None:
        """Stage 1: claim records, account queue-wait, shed expired /
        hopeless work before it costs decode+dispatch, apply
        backpressure and hot reload, feed the decode pool."""
        log = logging.getLogger("analytics_zoo_tpu.deploy")
        while not self._stop.is_set():
            try:
                self._hb.beat("poller")
                if self._maybe_reload():
                    self._executor.swap_replicas(self._build_replicas())
                dropped = self.queue.trim(self.cfg.backpressure_maxlen)
                if dropped:
                    TIMERS.incr("serving/backpressure_dropped", dropped)
                    log.warning("backpressure: dropped %d queued records",
                                dropped)
                batch = self.queue.pop_batch(self.cfg.batch_size,
                                             timeout=self.cfg.poll_timeout_s)
                now = time.time()
                for rid, rec in batch:
                    # root span: trace id is fresh per claim (rids may
                    # repeat across runs); the rid rides as the uri attr
                    rec["_span"] = TRACER.start("serving/request",
                                                uri=rec.get("uri") or rid)
                    # multi-model routing + weighted admission: resolve
                    # the target model, reject unknown names typed, and
                    # shed a fraction of an over-SLO model's traffic
                    # BEFORE it costs decode/dispatch
                    model = rec.get("model") or self._default_model
                    if model not in self.models:
                        self._shed(rid, rec, "malformed",
                                   f"unknown model {model!r}")
                        continue
                    rec["model"] = model
                    if not self._admission.admit(model):
                        self._shed(
                            rid, rec, "overloaded",
                            f"model {model!r} over its p99 SLO "
                            f"({self._admission.p99(model):.0f}ms > "
                            f"{self.cfg.slo_for(model):.0f}ms); "
                            "weighted admission shed")
                        continue
                    ts = rec.get("ts")
                    if isinstance(ts, (int, float)):
                        obs.observe("serving_stage_seconds",
                                    max(0.0, now - ts), stage="queue_wait",
                                    model=model, flat="serving/queue_wait")
                    remaining = self._record_ttl_s(rec)
                    if remaining is not None:
                        if remaining <= 0:
                            self._shed(rid, rec, "expired",
                                       "client TTL expired before decode")
                            continue
                        # estimated time-to-answer from recent e2e p50:
                        # if the pipeline can't plausibly make the
                        # deadline, failing fast beats a late answer
                        est = TIMERS.percentile("serving/e2e", 50)
                        if est > 0 and est > remaining:
                            self._shed(
                                rid, rec, "overloaded",
                                f"estimated service time {est * 1e3:.0f}ms "
                                f"exceeds remaining TTL "
                                f"{remaining * 1e3:.0f}ms")
                            continue
                        rec["_deadline_mono"] = time.monotonic() + remaining
                    while not self._stop.is_set():
                        try:
                            self._decode_q.put((rid, rec), timeout=0.1)
                            break
                        except pyqueue.Full:
                            continue
            except Exception:
                log.exception("serving poller failed; worker continues")
                time.sleep(0.05)

    def _decode_loop(self) -> None:
        """Stage 2a: base64/JSON decode + host preprocess, concurrent
        with device compute (``serving/decode_overlap`` proves it)."""
        while True:
            item = self._decode_q.get()
            if item is None:
                return
            self._hb.beat("decode")
            rid, rec = item
            deadline = rec.get("_deadline_mono")
            model = rec.get("model") or self._default_model
            root = rec.get("_span")
            dsp = None
            try:
                faults.inject("serving.decode_error")
                if root is not None:
                    dsp = TRACER.start("serving/decode", trace=root.trace,
                                       parent=root.sid, model=model)
                with obs.time_stage("serving_stage_seconds",
                                    stage="decode", model=model,
                                    flat="serving/decode"):
                    decoded = _decode_record(rec)
                    x = decoded.get("image")
                    if x is None:  # first non-image tensor
                        it = iter(decoded.values())
                        x = next(it, None)
                    if x is None:
                        raise MalformedRecordError(
                            "record decoded to no tensor fields")
                    if self.preprocess is not None:
                        x = self.preprocess(x)
                    x = np.asarray(x)
                if dsp is not None:
                    dsp.end()
                # the decode itself may have eaten the rest of the budget
                if deadline is not None and time.monotonic() > deadline:
                    raise DeadlineExpired(
                        "client TTL expired during decode")
                if self._executor.busy():
                    TIMERS.incr("serving/decode_overlap")
                wsp = None
                if root is not None:
                    # ended by the DynamicBatcher at flush/shed time —
                    # the batch_wait leg of the record's timeline
                    wsp = TRACER.start("serving/batch_wait",
                                       trace=root.trace, parent=root.sid)
                self._batcher.submit(
                    [x[None]],
                    lambda out, err, _rid=rid, _rec=rec:
                        self._respond_q.put((_rid, _rec, out, err)),
                    deadline=deadline, span=wsp,
                    model=rec.get("model"))
            except Exception as e:
                # a bad record answers with an error instead of poisoning
                # the pipeline (clients see it in query(), not a hang)
                if isinstance(e, DeadlineExpired):
                    obs.count("serving_shed_total", code="expired",
                              model=model, flat="serving/shed_expired")
                elif not isinstance(e, ServingError):
                    try:
                        e.code = getattr(e, "code", "decode_error")
                    except Exception:
                        pass
                if dsp is not None:
                    dsp.end(status=getattr(e, "code", None) or "error",
                            error=str(e))
                self._respond_q.put((rid, rec, None, e))

    def _respond_loop(self) -> None:
        """Stage 4: format + write results, close the e2e span, emit
        TensorBoard scalars.  Writes are BATCHED: the worker greedily
        drains whatever is already queued (up to one device batch) and
        publishes the whole group through one ``set_result_many`` round
        — on ShmQueue that is one lock claim for N results instead of N.
        Transient result-store failures retry (above the backend's own
        I/O retries); a formatting failure degrades to a typed
        internal-error payload — the record still terminates."""
        log = logging.getLogger("analytics_zoo_tpu.deploy")
        retry = _io_retry("serving_respond", retry_on=(Exception,))
        cap = max(8, self.cfg.batch_size)
        while True:
            item = self._respond_q.get()
            if item is None:
                return
            items = [item]
            while len(items) < cap:
                try:
                    nxt = self._respond_q.get_nowait()
                except pyqueue.Empty:
                    break
                if nxt is None:
                    # hand the stop sentinel on (ours arrives at the
                    # next blocking get) and publish what we have
                    self._respond_q.put(None)
                    break
                items.append(nxt)
            self._hb.beat("respond")
            self._respond_many(items, retry, log)

    def _respond_many(self, items: List, retry, log) -> None:
        t0 = time.perf_counter()
        prepared: List[Tuple] = []  # (rid, rec, val, root, rsp)
        for rid, rec, out, err in items:
            root = rec.pop("_span", None)
            rsp = None
            if root is not None:
                rsp = TRACER.start("serving/respond", trace=root.trace,
                                   parent=root.sid)
            try:
                faults.inject("serving.respond_error")
                val = self._format_result(out, err, rec)
            except Exception as fe:
                log.exception("result formatting failed for %r", rid)
                val = error_payload(
                    "internal", f"result formatting failed: {fe}",
                    uri=rec.get("uri"))
            if isinstance(val, dict) and "error" in val:
                obs.count("serving_errors_total",
                          code=val.get("code") or "internal",
                          model=rec.get("model") or self._default_model,
                          flat="serving/errors_returned")
            prepared.append((rid, rec, val, root, rsp))

        def _write():
            pairs = []
            for _rid, _rec, _val, _root, _rsp in prepared:
                # keep the per-record fault cadence the chaos plans
                # target, batched write or not
                faults.inject("serving.queue_io")
                pairs.append((_rid, _val))
            many = getattr(self.queue, "set_result_many", None)
            if many is not None:
                many(pairs)
            else:
                for _rid, _val in pairs:
                    self.queue.set_result(_rid, _val)

        try:
            retry.call(_write)
        except Exception:
            TIMERS.incr("serving/respond_failed", len(prepared))
            log.exception("serving respond failed for %d record(s)",
                          len(prepared))
            for _rid, _rec, _val, root, rsp in prepared:
                if rsp is not None:
                    rsp.end(status="error", error="respond failed")
                if root is not None:
                    root.end(status="internal", error="respond failed")
            return
        if len(prepared) > 1:
            TIMERS.incr("serving/respond_batched_writes")
        # per-record stage time: the batch wall time amortized over its
        # members, so breakdown math (total / records) stays honest
        per = (time.perf_counter() - t0) / len(prepared)
        now = time.time()
        for rid, rec, val, root, rsp in prepared:
            model = rec.get("model") or self._default_model
            obs.observe("serving_stage_seconds", per, stage="respond",
                        model=model, flat="serving/respond")
            # terminal spans: the respond leg, then the root with the
            # typed outcome — the span chain is now reconstructable
            outcome_code = (val.get("code") or "internal") \
                if isinstance(val, dict) and "error" in val else "ok"
            if rsp is not None:
                rsp.end()
            if root is not None:
                root.end(status=outcome_code)
            obs.count("serving_records_total", model=model,
                      outcome="ok" if outcome_code == "ok" else "error")
            ts = rec.get("ts")
            if isinstance(ts, (int, float)):
                e2e = max(0.0, now - ts)
                obs.observe("serving_stage_seconds", e2e, stage="e2e",
                            model=model, flat="serving/e2e")
                # feed the per-model admission window (only models with
                # an SLO keep one)
                self._admission.note(model, e2e)
        with self._count_lock:
            self.records_served += len(prepared)
        self._maybe_tb_flush()

    def _format_result(self, out, err, rec: Dict) -> Any:
        """One result value for the wire: typed error payload, top-N
        pairs, or the raw row (tensor-codec envelope for native clients,
        ``tolist()`` for reference-wire records)."""
        if err is not None:
            code = getattr(err, "code", None) or "internal"
            return error_payload(code, err, uri=rec.get("uri"))
        top_n = self.cfg.postprocess_top_n
        outs = out if isinstance(out, list) else [out]
        topn_on_device = self._topn_by_model.get(
            rec.get("model") or self._default_model, self._topn_on_device)
        if top_n and topn_on_device and len(outs) == 2:
            # the jitted forward already ran lax.top_k: outs = (idx, val)
            idx, vals = np.asarray(outs[0])[0], np.asarray(outs[1])[0]
            return [[int(i), float(v)] for i, v in zip(idx, vals)]
        row = np.asarray(outs[0])
        # pipeline requests are single-row: drop the leading batch axis so
        # the wire value matches what serve_once returns per record
        if row.ndim > 1 or (row.ndim == 1 and row.dtype.kind in "OUS"
                            and row.shape[0] == 1):
            row = row[0] if row.shape[0] == 1 else row
        row = np.asarray(row)
        return self._format_row(row, native=rec.get("fmt") == "tensor")

    def _format_row(self, row: np.ndarray, native: bool) -> Any:
        top_n = self.cfg.postprocess_top_n
        if top_n and row.ndim == 1 and row.dtype.kind in "biufc":
            # top-N (class, prob) pairs — reference PostProcessing topN
            idx = np.argsort(row)[::-1][:top_n]
            return [[int(j), float(row[j])] for j in idx]
        if native and row.dtype.kind in "biufc":
            if self._wire == "binary":
                # the backend frames the raw array itself — no base64
                return {"tensor": row}
            return {"tensor": encode_tensor(row)}
        # object/str rows (e.g. a detector forward returning JSON blobs)
        # can't ride the tensor codec — hand the value through as-is
        return row.tolist()

    def _maybe_tb_flush(self) -> None:
        if self._tb is None:
            return
        now = time.monotonic()
        with self._count_lock:
            n, dt = self.records_served, now - self._tb_last_t
            if n - self._tb_last_n < 32 and dt < 1.0:
                return
            delta = n - self._tb_last_n
            self._tb_last_t, self._tb_last_n = now, n
        # reference "Serving Throughput"/"Total Records Number" scalars,
        # plus per-stage p99 rollups so latency regressions attribute
        self._tb.add_scalar("serving_throughput",
                            delta / dt if dt > 0 else 0.0, n)
        self._tb.add_scalar("total_records", n, n)
        for stage in ("queue_wait", "decode", "batch_wait", "device",
                      "respond", "e2e"):
            p99 = TIMERS.percentile(f"serving/{stage}", 99)
            if p99:
                self._tb.add_scalar(f"serving_{stage}_p99_ms", p99 * 1e3, n)
        if self._executor is not None:
            self._tb.add_scalar("serving_replicas_healthy",
                                self._executor.healthy_replicas(), n)

    def health(self) -> Dict[str, Any]:
        """Liveness + per-stage latency rollups + pipeline counters."""
        qh = (self.queue.health() if hasattr(self.queue, "health")
              else {"ok": True})
        stages = {}
        for k, v in TIMERS.stats().items():
            if k.startswith("serving/"):
                stages[k.split("/", 1)[1]] = {
                    "count": v["count"],
                    "mean_ms": v["mean_s"] * 1e3,
                    "p50_ms": v["p50_s"] * 1e3,
                    "p99_ms": v["p99_s"] * 1e3}
        with self._count_lock:
            records_served = self.records_served
        h: Dict[str, Any] = {
            "ok": bool(qh.get("ok", True)),
            "running": self.is_alive(),
            "records_served": records_served,
            "queue": qh,
            "stages": stages,
            "counters": {k: n for k, n in TIMERS.counts().items()
                         if k.startswith(("serving/", "inference/"))},
        }
        if self._executor is not None:
            h["inflight"] = self._executor.inflight
            h["replicas"] = len(self._executor.replicas)
            h["replicas_healthy"] = self._executor.healthy_replicas()
            h["replica_states"] = self._executor.replica_states()
            h["models"] = {
                m: {"replicas": self._executor.group_size(m),
                    "replicas_healthy": self._executor.healthy_replicas(m),
                    "mesh_replicas": self._executor.mesh_group_size(m),
                    "mesh_replicas_healthy":
                        self._executor.healthy_mesh_replicas(m),
                    "slo_p99_ms": self.cfg.slo_for(m),
                    "observed_p99_ms": self._admission.p99(m)}
                for m in self._executor.models()}
            if any(self._executor.mesh_group_size(m)
                   for m in self._executor.models()) or self._mesh_plan:
                mesh: Dict[str, Any] = {
                    "plan": dict(self._mesh_plan),
                    "quarantine_epoch":
                        self._executor.mesh_quarantine.last_epoch}
                if self.roster is not None:
                    mesh["roster"] = self.roster.snapshot()
                h["mesh"] = mesh
        if self._compile_cache is not None:
            h["compile_cache"] = self._compile_cache.stats()
        if self._autoscaler is not None:
            h["autoscale"] = self._autoscaler.stats()
            # convergence at a glance (full flap events via autoscale_audit)
            audit = self._autoscaler.audit()
            h["autoscale"]["flaps"] = audit["flaps"]
            h["autoscale"]["quiet_s"] = audit["quiet_s"]
        with self._scale_lock:
            h["decode_target"] = self._decode_target
        if self._hb is not None:
            h["stage_heartbeat_age_s"] = self._hb.ages()
        if self._supervisor is not None:
            h["supervisor"] = self._supervisor.is_alive()
        gauges = {k: v for k, v in TIMERS.gauges().items()
                  if k.startswith("serving/")}
        if gauges:
            h["gauges"] = gauges
        observe: Dict[str, Any] = {
            "span_ring": TRACER.ring_size(),
            "spans_completed": TRACER.completed_count(),
            "spans_active": TRACER.active_count(),
            "metric_series": obs.METRICS.series_count(),
        }
        if self.flight_recorder is not None:
            observe["flight_recorder"] = self.flight_recorder.stats()
        h["observe"] = observe
        return h

    def metrics_text(self) -> str:
        """The labeled metric registry in Prometheus text format —
        scrape endpoint payload (``parse_prometheus`` round-trips it)."""
        return to_prometheus(obs.METRICS)

    # -- hot-row replication caches (ISSUE 19) ----------------------------
    def _on_replica_swap(self, model: str) -> None:
        """DeviceExecutor swap listener: a replica swap means the served
        weights (may have) changed — drop the model's hot-row replicas
        so no post-swap request is answered from pre-swap rows.  The
        supervisor's ``hot_cache_refresh`` check rebuilds them from the
        authoritative shards on its next tick."""
        m = self.models.get(model)
        if m is not None and hasattr(m, "invalidate_hot_caches"):
            m.invalidate_hot_caches("swap")

    def _refresh_hot_caches(self) -> None:
        for m in self.models.values():
            if hasattr(m, "refresh_hot_caches") and m.hot_caches():
                m.refresh_hot_caches()

    def hot_cache_stats(self) -> Dict[str, Any]:
        """Per-table cache stats across models (ops dashboards/tests)."""
        out: Dict[str, Any] = {}
        for mname, m in self.models.items():
            for tname, cache in getattr(m, "hot_caches", dict)().items():
                out[f"{mname}/{tname}"] = cache.stats()
        return out

    # -- model hot reload (reference ClusterServingHelper.scala:185-193:
    # the config/model path is re-checked periodically and the serving
    # model swapped in place without stopping the stream) ----------------
    def enable_hot_reload(self, model_path: str,
                          check_interval_s: float = 10.0
                          ) -> "ClusterServing":
        self._reload_path = model_path
        self._reload_interval = check_interval_s
        self._reload_last_check = 0.0
        self._reload_mtime = self._path_mtime(model_path)
        return self

    @staticmethod
    def _path_mtime(path: str) -> float:
        if os.path.isdir(path):
            return max((os.path.getmtime(os.path.join(path, f))
                        for f in os.listdir(path)), default=0.0)
        return os.path.getmtime(path) if os.path.exists(path) else 0.0

    def _maybe_reload(self) -> bool:
        path = getattr(self, "_reload_path", None)
        if path is None:
            return False
        now = time.time()
        if now - self._reload_last_check < self._reload_interval:
            return False
        self._reload_last_check = now
        mtime = self._path_mtime(path)
        if mtime <= self._reload_mtime:
            return False
        # save_model writes config.json + weights.npz non-atomically:
        # only reload once the mtime has been STABLE for a full check
        # interval, so a mid-write snapshot (new config + old weights,
        # or a truncated npz) is never loaded
        if mtime != getattr(self, "_reload_pending_mtime", None):
            self._reload_pending_mtime = mtime
            return False
        from analytics_zoo_tpu.deploy.inference import InferenceModel

        import logging
        logging.getLogger("analytics_zoo_tpu.deploy").info(
            "model at %s changed (mtime %.0f); hot-reloading", path, mtime)
        old = self.models.get(self._default_model)
        self.model = InferenceModel.load(path)
        self.model.name = self._default_model
        self.models[self._default_model] = self.model
        # the reloaded model starts with EMPTY hot caches (every id
        # misses until the first refresh) — carried-over rows would be
        # pre-reload weights; the old model's caches die with it
        if old is not None and getattr(old, "hot_caches", dict)():
            old.invalidate_hot_caches("reload")
            self.model.enable_hot_caches(self._mesh,
                                         axis=self.cfg.mesh_axis)
        if (self._compile_cache is not None
                and getattr(self.model, "_net", None) is not None):
            self.model.attach_compile_cache(self._compile_cache)
        self._reload_mtime = mtime
        self._reload_pending_mtime = None
        return True

    def run_forever(self) -> None:
        import logging

        log = logging.getLogger("analytics_zoo_tpu.deploy")
        while not self._stop.is_set():
            try:
                self._maybe_reload()
                self.serve_once()
            except Exception:  # keep serving: one bad batch must not
                log.exception("serving batch failed; worker continues")
                time.sleep(0.05)  # kill the worker (reference keeps its
                #                   streaming query alive the same way)

    # -- one scheduling quantum (sync mode / tests / bench baseline) ------
    def serve_once(self) -> int:
        """Serve up to one batch; returns number of records served.

        Records are grouped by decoded shape/dtype and each group served
        as its own (bucket-padded) batch — a record whose shape differs
        from its neighbors is servable, not an error (mixed 224/299
        traffic in one poll just becomes two programs)."""
        dropped = self.queue.trim(self.cfg.backpressure_maxlen)
        if dropped:
            logging.getLogger("analytics_zoo_tpu.deploy").warning(
                "backpressure: dropped %d queued records", dropped)
        batch = self.queue.pop_batch(self.cfg.batch_size,
                                     timeout=self.cfg.poll_timeout_s)
        if not batch:
            return 0
        t0 = time.perf_counter()
        groups: Dict[Any, List] = {}  # (shape, dtype) -> [(rid, x, native)]
        for rid, rec in batch:
            # root span for the sync path too: _shed/error/success all
            # terminate it, so span chains reconstruct either way
            rec["_span"] = TRACER.start("serving/request", sync=True,
                                        uri=rec.get("uri") or rid)
            remaining = self._record_ttl_s(rec)
            if remaining is not None and remaining <= 0:
                self._shed(rid, rec, "expired",
                           "client TTL expired before decode")
                continue
            model = rec.get("model") or self._default_model
            if model not in self.models:
                self._shed(rid, rec, "malformed",
                           f"unknown model {model!r}")
                continue
            rec["model"] = model
            try:
                decoded = _decode_record(rec)
                x = decoded.get("image")
                if x is None:  # first non-image tensor
                    x = next(iter(decoded.values()), None)
                if x is None:
                    raise MalformedRecordError(
                        "record decoded to no tensor fields")
                if self.preprocess is not None:
                    x = self.preprocess(x)
                x = np.asarray(x)
            except Exception as e:
                # a bad record answers with an error instead of poisoning
                # the batch (clients see it in query() rather than a hang)
                code = getattr(e, "code", None) or "decode_error"
                obs.count("serving_errors_total", code=code, model=model,
                          flat="serving/errors_returned")
                sp = rec.pop("_span", None)
                if sp is not None:
                    sp.end(status=code, error=str(e))
                self.queue.set_result(
                    rid, error_payload(code, e, uri=rec.get("uri")))
                continue
            groups.setdefault((model, x.shape, str(x.dtype)), []).append(
                (rid, x, rec.get("fmt") == "tensor", rec))
        served = 0
        for (model, _shape, _dt), entries in groups.items():
            x = np.stack([e[1] for e in entries], axis=0)
            try:
                out = self.models[model].predict(x)
            except Exception as e:
                # records are already destructively popped from the queue —
                # answer every one with the error rather than losing them
                for rid, _, _, rec in entries:
                    obs.count("serving_errors_total", code="model_error",
                              model=model, flat="serving/errors_returned")
                    sp = rec.pop("_span", None)
                    if sp is not None:
                        sp.end(status="model_error", error=str(e))
                    self.queue.set_result(rid, error_payload(
                        "model_error", e, uri=rec.get("uri")))
                continue
            outs = out[0] if isinstance(out, list) else out
            for i, (rid, _, native, _rec) in enumerate(entries):
                self.queue.set_result(
                    rid, self._format_row(np.asarray(outs[i]), native))
                sp = _rec.pop("_span", None)
                if sp is not None:
                    sp.end()
            obs.count("serving_records_total", len(entries),
                      model=model, outcome="ok")
            served += len(entries)
        dt = time.perf_counter() - t0
        # serve_once can run concurrently with a started pipeline's
        # respond pool, which bumps this counter under _count_lock —
        # an unlocked += here would lose increments (THR-GUARD)
        with self._count_lock:
            self.records_served += served
            total = self.records_served
        if self._tb is not None and served:
            # reference "Serving Throughput"/"Total Records Number" scalars
            self._tb.add_scalar("serving_throughput", served / dt, total)
            self._tb.add_scalar("total_records", total, total)
        return served
