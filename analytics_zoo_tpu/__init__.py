"""Analytics Zoo TPU — a TPU-native deep-learning framework.

A from-scratch re-design of Analytics Zoo's capabilities
(reference: /root/reference, Scala/Spark/BigDL) as an idiomatic
JAX/XLA/Pallas framework:

- ``core``     — context/mesh init, config, triggers, TensorBoard writer
                 (replaces NNContext / ZooTrigger / zoo.tensorboard).
- ``data``     — FeatureSet-style host datasets with memory tiers, image &
                 text preprocessing (replaces zoo.feature.*).
- ``nn``       — Keras-style Sequential/Model + autograd Variable DSL,
                 layers, objectives, metrics (replaces
                 zoo.pipeline.api.keras / autograd).
- ``train``    — Estimator: one jitted SPMD train step with XLA collectives
                 (replaces InternalDistriOptimizer / AllReduceParameter).
- ``parallel`` — mesh construction, sharding rules, ring attention
                 (replaces the Spark block-manager allreduce backend).
- ``ops``      — Pallas TPU kernels (flash attention, NMS, ...).
- ``models``   — built-in model zoo (NCF, WideAndDeep, AnomalyDetector,
                 TextClassifier, Seq2seq, KNRM, SSD, BERT ...).
- ``deploy``   — InferenceModel multi-backend serving + cluster serving.
"""

__version__ = "0.1.0"

from analytics_zoo_tpu.core.context import init_zoo_context, ZooContext  # noqa: F401
