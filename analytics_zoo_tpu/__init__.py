"""Analytics Zoo TPU — a TPU-native deep-learning framework.

A from-scratch re-design of Analytics Zoo's capabilities
(reference: /root/reference, Scala/Spark/BigDL) as an idiomatic
JAX/XLA/Pallas framework:

- ``core``     — context/mesh init, config, triggers, TensorBoard writer
                 (replaces NNContext / ZooTrigger / zoo.tensorboard).
- ``data``     — FeatureSet-style host datasets with memory tiers, image &
                 text preprocessing (replaces zoo.feature.*).
- ``nn``       — Keras-style Sequential/Model + autograd Variable DSL,
                 layers, objectives, metrics (replaces
                 zoo.pipeline.api.keras / autograd).
- ``train``    — Estimator: one jitted SPMD train step with XLA collectives
                 (replaces InternalDistriOptimizer / AllReduceParameter).
- ``parallel`` — mesh construction, sharding rules, ring attention
                 (replaces the Spark block-manager allreduce backend).
- ``ops``      — Pallas TPU kernels (flash attention, NMS, ...).
- ``models``   — built-in model zoo (NCF, WideAndDeep, AnomalyDetector,
                 TextClassifier, Seq2seq, KNRM, SSD, BERT ...).
- ``deploy``   — InferenceModel multi-backend serving + cluster serving.
- ``tfpark``   — foreign-model ingestion: tf.keras/torch converted to
                 native JAX, TFDataset facades, GAN + BERT estimators.
- ``onnx``     — ONNX import without the onnx package (wire codec +
                 jax/lax op lowering); imported graphs train and serve.
- ``nnframes`` — Spark-ML-style NNEstimator/NNClassifier over DataFrames.
- ``automl``   — TimeSequencePredictor + in-process search engine.
- ``native``   — C++ host data-plane (crc32c, parallel gather) via ctypes.
- ``utils``    — nest flatten/pack + file helpers.
"""

__version__ = "0.2.0"

from analytics_zoo_tpu.core.config import ZooConfig  # noqa: F401
from analytics_zoo_tpu.core.context import (  # noqa: F401
    ZooContext,
    get_zoo_context,
    init_zoo_context,
)
