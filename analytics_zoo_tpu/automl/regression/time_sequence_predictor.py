"""TimeSequencePredictor — AutoML entry point for time-series forecasting
(reference automl/regression/time_sequence_predictor.py:335-586).

``fit(input_df)`` searches feature + model hyper-parameters (per recipe)
and returns a fitted ``TimeSequencePipeline``.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional, Sequence

import pandas as pd

from analytics_zoo_tpu.automl.common.metrics import Evaluator
from analytics_zoo_tpu.automl.feature.time_sequence import (
    TimeSequenceFeatureTransformer)
from analytics_zoo_tpu.automl.model.time_sequence import (Seq2SeqForecaster,
                                                          VanillaLSTM)
from analytics_zoo_tpu.automl.pipeline.time_sequence import (
    TimeSequencePipeline)
from analytics_zoo_tpu.automl.search import (Recipe, SearchEngine,
                                             SmokeRecipe)

logger = logging.getLogger("analytics_zoo_tpu.automl")


class TimeSequencePredictor:
    """Search + train a forecaster for a univariate target with extra
    features.  future_seq_len == 1 -> VanillaLSTM; > 1 -> multi-horizon
    forecaster (reference picks Seq2Seq there)."""

    def __init__(self, name: str = "automl", logs_dir: str = "~/zoo_automl",
                 future_seq_len: int = 1, dt_col: str = "datetime",
                 target_col: str = "value",
                 extra_features_col: Optional[Sequence[str]] = None,
                 drop_missing: bool = True):
        self.name = name
        self.logs_dir = logs_dir
        self.future_seq_len = future_seq_len
        self.dt_col = dt_col
        self.target_col = target_col
        self.extra_features_col = extra_features_col
        self.drop_missing = drop_missing
        self.pipeline: Optional[TimeSequencePipeline] = None

    def _check_input(self, input_df, validation_df, metric):
        for df in (input_df, validation_df):
            if df is None:
                continue
            for col in (self.dt_col, self.target_col):
                if col not in df.columns:
                    raise ValueError(f"column {col!r} missing from frame")
        Evaluator.evaluate(metric, [0.0], [0.0])   # validates metric name

    def _make_model(self, config: Dict):
        """Model selection via the config's ``model`` key (reference
        recipes carry "model": "LSTM"|"Seq2seq"|"MTNet",
        time_sequence_predictor.py:70,99,162)."""
        name = str(config.get("model", "")).lower()
        if name == "mtnet":
            from analytics_zoo_tpu.automl.model.mtnet import MTNet
            return MTNet(future_seq_len=self.future_seq_len)
        if name in ("seq2seq", "seq2seqforecaster"):
            return Seq2SeqForecaster(max(self.future_seq_len, 1))
        if name in ("lstm", "vanillalstm"):
            return VanillaLSTM()
        # default: horizon decides (the pre-"model"-key behavior)
        return (VanillaLSTM() if self.future_seq_len == 1
                else Seq2SeqForecaster(self.future_seq_len))

    def fit(self, input_df: pd.DataFrame,
            validation_df: Optional[pd.DataFrame] = None,
            metric: str = "mse", recipe: Optional[Recipe] = None,
            max_parallel: int = 1) -> TimeSequencePipeline:
        recipe = recipe or SmokeRecipe()
        self._check_input(input_df, validation_df, metric)

        probe = TimeSequenceFeatureTransformer(
            future_seq_len=self.future_seq_len, dt_col=self.dt_col,
            target_col=self.target_col,
            extra_features_col=self.extra_features_col,
            drop_missing=self.drop_missing)
        feature_list = probe.get_feature_list(input_df)
        space = recipe.search_space(feature_list)
        mode = Evaluator.get_metric_mode(metric)

        def trainable(config: Dict):
            ft = TimeSequenceFeatureTransformer(
                future_seq_len=self.future_seq_len, dt_col=self.dt_col,
                target_col=self.target_col,
                extra_features_col=self.extra_features_col,
                drop_missing=self.drop_missing)
            x, y = ft.fit_transform(input_df, **config)
            if validation_df is not None:
                vx, vy = ft.transform(validation_df, is_train=True)
                val = (vx, vy)
            else:
                split = max(1, int(len(x) * 0.9))
                val = (x[split:], y[split:]) if split < len(x) else None
                x, y = x[:split], y[:split]
            model = self._make_model(config)
            score = model.fit_eval(x, y, validation_data=val, metric=metric,
                                   **config)
            return score, {"ft": ft, "model": model}

        engine = SearchEngine(
            space, metric_mode=mode, num_samples=recipe.num_samples,
            max_parallel=max_parallel,
            search_alg=getattr(recipe, "search_alg", "random"),
            n_startup=getattr(recipe, "n_startup", None))
        engine.run(trainable)
        best = engine.best()
        logger.info("best config %s -> %s=%.6g", best.config, metric,
                    best.metric)
        self.pipeline = TimeSequencePipeline(
            best.extra["ft"], best.extra["model"], best.config)
        return self.pipeline

    def predict(self, input_df: pd.DataFrame) -> pd.DataFrame:
        if self.pipeline is None:
            raise RuntimeError("fit first")
        return self.pipeline.predict(input_df)

    def evaluate(self, input_df: pd.DataFrame, metric: str = "mse") -> float:
        if self.pipeline is None:
            raise RuntimeError("fit first")
        return self.pipeline.evaluate(input_df, metric)
