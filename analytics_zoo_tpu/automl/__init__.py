"""AutoML for time series (reference pyzoo/zoo/automl, 4.7k LoC).

Capability parity, TPU-native design:
- ``TimeSequencePredictor.fit(df)`` drives a hyper-parameter search over
  rolling-window feature configs + model configs and returns a fitted
  ``TimeSequencePipeline`` (reference regression/time_sequence_predictor.py).
- The search engine is **in-process** with a ray.tune-shaped API
  (search/__init__.py): the reference bootstraps a second Ray runtime on
  Spark executors (RayOnSpark) because its training is JVM-cluster-bound;
  here every trial is a jitted JAX program on the local mesh, so trials
  run concurrently in a thread/process pool and ray is not required.
  ``search_alg="tpe"`` / ``BayesRecipe`` give BayesOpt-style sequential
  model-based search (reference RayTuneSearchEngine.py:25 BayesOptSearch).
- Feature engineering (rolling windows, datetime features, scaling) in
  feature/time_sequence.py (reference feature/time_sequence.py:30-540).
- Models: VanillaLSTM, encoder-decoder Seq2Seq (future_seq_len>1), and
  MTNet (model/mtnet.py — the reference's flagship, MTNet_keras.py),
  selectable via the config's ``model`` key.
"""

from analytics_zoo_tpu.automl.common.metrics import Evaluator
from analytics_zoo_tpu.automl.feature.time_sequence import (
    TimeSequenceFeatureTransformer)
from analytics_zoo_tpu.automl.model.mtnet import MTNet, MTNetBlock
from analytics_zoo_tpu.automl.pipeline.time_sequence import (
    TimeSequencePipeline, load_ts_pipeline)
from analytics_zoo_tpu.automl.regression.time_sequence_predictor import (
    TimeSequencePredictor)
from analytics_zoo_tpu.automl.search import (BayesRecipe, GridRandomRecipe,
                                             MTNetGridRandomRecipe,
                                             MTNetSmokeRecipe, RandomRecipe,
                                             Recipe, SearchEngine,
                                             SmokeRecipe, TPESampler)

__all__ = ["TimeSequencePredictor", "TimeSequencePipeline",
           "load_ts_pipeline", "TimeSequenceFeatureTransformer",
           "Evaluator", "SearchEngine", "Recipe", "SmokeRecipe",
           "RandomRecipe", "GridRandomRecipe", "BayesRecipe",
           "MTNetSmokeRecipe", "MTNetGridRandomRecipe", "TPESampler",
           "MTNet", "MTNetBlock"]
