"""AutoML for time series (reference pyzoo/zoo/automl, 4.7k LoC).

Capability parity, TPU-native design:
- ``TimeSequencePredictor.fit(df)`` drives a hyper-parameter search over
  rolling-window feature configs + model configs and returns a fitted
  ``TimeSequencePipeline`` (reference regression/time_sequence_predictor.py).
- The search engine is **in-process** with a ray.tune-shaped API
  (search/__init__.py): the reference bootstraps a second Ray runtime on
  Spark executors (RayOnSpark) because its training is JVM-cluster-bound;
  here every trial is a jitted JAX program on the local mesh, so trials
  run in a thread pool and ray is not required (used if installed).
- Feature engineering (rolling windows, datetime features, scaling) in
  feature/time_sequence.py (reference feature/time_sequence.py:30-540).
- Models: VanillaLSTM (future_seq_len==1) and Seq2Seq (>1) on the native
  nn stack (reference automl/model/VanillaLSTM.py, Seq2Seq.py).
"""

from analytics_zoo_tpu.automl.common.metrics import Evaluator
from analytics_zoo_tpu.automl.feature.time_sequence import (
    TimeSequenceFeatureTransformer)
from analytics_zoo_tpu.automl.pipeline.time_sequence import (
    TimeSequencePipeline, load_ts_pipeline)
from analytics_zoo_tpu.automl.regression.time_sequence_predictor import (
    TimeSequencePredictor)
from analytics_zoo_tpu.automl.search import (GridRandomRecipe, RandomRecipe,
                                             Recipe, SearchEngine,
                                             SmokeRecipe)

__all__ = ["TimeSequencePredictor", "TimeSequencePipeline",
           "load_ts_pipeline", "TimeSequenceFeatureTransformer",
           "Evaluator", "SearchEngine", "Recipe", "SmokeRecipe",
           "RandomRecipe", "GridRandomRecipe"]
