"""TimeSequencePipeline — fitted transformer + model, save/load
(reference automl/pipeline/time_sequence.py)."""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import numpy as np
import pandas as pd

from analytics_zoo_tpu.automl.common.metrics import Evaluator
from analytics_zoo_tpu.automl.feature.time_sequence import (
    TimeSequenceFeatureTransformer)
from analytics_zoo_tpu.automl.model.time_sequence import VanillaLSTM


class TimeSequencePipeline:
    """Predict/evaluate on raw DataFrames with the best found config."""

    def __init__(self, feature_transformer: TimeSequenceFeatureTransformer,
                 model, config: Dict):
        self.feature_transformer = feature_transformer
        self.model = model
        self.config = dict(config)

    def predict(self, input_df: pd.DataFrame) -> pd.DataFrame:
        x, _ = self.feature_transformer.transform(input_df, is_train=False)
        y = self.model.predict(x)
        return self.feature_transformer.post_processing(input_df, y,
                                                        is_train=False)

    def evaluate(self, input_df: pd.DataFrame, metric: str = "mse") -> float:
        x, y = self.feature_transformer.transform(input_df, is_train=True)
        pred = self.model.predict(x)
        y_true = self.feature_transformer._unscale_y(y)
        y_pred = self.feature_transformer._unscale_y(np.asarray(pred))
        return Evaluator.evaluate(metric, y_true, y_pred)

    # -- persistence -------------------------------------------------------
    def save(self, pipeline_dir: str) -> None:
        os.makedirs(pipeline_dir, exist_ok=True)
        self.feature_transformer.save(
            os.path.join(pipeline_dir, "feature_transformer.json"))
        self.model.save(os.path.join(pipeline_dir, "model.npz"))
        meta = {"config": {k: (list(v) if isinstance(v, (list, tuple))
                               else v) for k, v in self.config.items()},
                "future_seq_len": self.feature_transformer.future_seq_len,
                "model_class": type(self.model).__name__}
        with open(os.path.join(pipeline_dir, "pipeline.json"), "w") as f:
            json.dump(meta, f)


def load_ts_pipeline(pipeline_dir: str) -> TimeSequencePipeline:
    with open(os.path.join(pipeline_dir, "pipeline.json")) as f:
        meta = json.load(f)
    ft = TimeSequenceFeatureTransformer.load(
        os.path.join(pipeline_dir, "feature_transformer.json"))
    config = meta["config"]
    fsl = int(meta.get("future_seq_len", 1))
    cls_name = meta.get("model_class", "VanillaLSTM")
    if cls_name == "MTNet":
        from analytics_zoo_tpu.automl.model.mtnet import MTNet
        model = MTNet(future_seq_len=fsl)
    elif cls_name == "Seq2SeqForecaster":
        from analytics_zoo_tpu.automl.model.time_sequence import (
            Seq2SeqForecaster)
        model = Seq2SeqForecaster(fsl)
    else:
        model = VanillaLSTM()
    # the transformer's config holds the RESOLVED feature selection and
    # window length (fit_transform persists them), so the model input
    # width is reconstructed exactly
    past = int(ft.config.get("past_seq_len", 2))
    n_feat = 1 + len(ft.config["selected_features"])
    model.restore(os.path.join(pipeline_dir, "model.npz"),
                  (past, n_feat), meta["future_seq_len"], config)
    return TimeSequencePipeline(ft, model, config)
