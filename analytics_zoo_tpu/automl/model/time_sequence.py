"""Time-series models for AutoML trials
(reference automl/model/VanillaLSTM.py, Seq2Seq.py — keras and pytorch
variants collapse into one JAX-native implementation each).

``fit_eval(x, y, validation_data, **config) -> val_metric`` is the
trainable contract the search engine scores.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from analytics_zoo_tpu.automl.common.metrics import Evaluator


def _build_lstm(input_shape, config, out_dim):
    from analytics_zoo_tpu.nn import reset_name_scope
    from analytics_zoo_tpu.nn.layers.core import Dense, Dropout
    from analytics_zoo_tpu.nn.layers.recurrent import LSTM
    from analytics_zoo_tpu.nn.topology import Sequential

    reset_name_scope()
    m = Sequential()
    m.add(LSTM(int(config.get("lstm_1_units", 32)), return_sequences=True,
               input_shape=tuple(input_shape)))
    m.add(Dropout(float(config.get("dropout", 0.2))))
    m.add(LSTM(int(config.get("lstm_2_units", 32))))
    m.add(Dropout(float(config.get("dropout", 0.2))))
    m.add(Dense(out_dim))
    return m


class VanillaLSTM:
    """2-layer LSTM regressor (future_seq_len == 1)."""

    out_is_seq = False

    def __init__(self, check_optional_config: bool = False):
        self.model = None
        self.config: Dict = {}

    def _ensure(self, x, y, config):
        out_dim = y.shape[1] if y.ndim > 1 else 1
        self.config = dict(config)
        self.model = _build_lstm(x.shape[1:], config, out_dim)
        from analytics_zoo_tpu.train.optimizers import Adam

        self.model.compile(
            optimizer=Adam(lr=float(config.get("lr", 1e-3))), loss="mse")

    def fit_eval(self, x, y, validation_data=None, metric: str = "mse",
                 **config) -> float:
        if y.ndim == 1:
            y = y[:, None]
        self._ensure(x, y, config)
        vx, vy = validation_data if validation_data is not None else (x, y)
        if vy.ndim == 1:
            vy = vy[:, None]
        self.model.fit(x, y, batch_size=int(config.get("batch_size", 32)),
                       nb_epoch=int(config.get("epochs", 1)), verbose=False)
        pred = self.model.predict(vx, batch_size=1024)
        return Evaluator.evaluate(metric, vy, pred)

    def predict(self, x) -> np.ndarray:
        return self.model.predict(x, batch_size=1024)

    def evaluate(self, x, y, metric: str = "mse") -> float:
        return Evaluator.evaluate(metric, y, self.predict(x))

    # -- persistence -------------------------------------------------------
    def state(self):
        est = self.model.estimator
        return {"params": est.params, "state": est.state or {}}

    def save(self, path: str) -> None:
        from analytics_zoo_tpu.train import checkpoint as ckpt

        ckpt.save_pytree(path, self.state())

    def restore(self, path: str, x_shape, out_dim, config) -> None:
        from analytics_zoo_tpu.train import checkpoint as ckpt

        self.config = dict(config)
        self.model = _build_lstm(x_shape, config, out_dim)
        from analytics_zoo_tpu.train.optimizers import Adam

        self.model.compile(
            optimizer=Adam(lr=float(config.get("lr", 1e-3))), loss="mse")
        tree = ckpt.load_pytree(path)
        self.model.estimator.set_initial_weights(tree["params"],
                                                 tree.get("state", {}))


class Seq2SeqForecaster(VanillaLSTM):
    """Multi-step forecaster (future_seq_len > 1).

    The reference uses an encoder-decoder (Seq2Seq.py); on TPU a direct
    multi-horizon head on the LSTM encoder trains in one fused program
    without a sequential decode loop — same capability (N-step forecast),
    better XLA shape.
    """

    def __init__(self, future_seq_len: int = 2, **kw):
        super().__init__(**kw)
        self.future_seq_len = future_seq_len
