"""Time-series models for AutoML trials
(reference automl/model/VanillaLSTM.py, Seq2Seq.py — keras and pytorch
variants collapse into one JAX-native implementation each).

``fit_eval(x, y, validation_data, **config) -> val_metric`` is the
trainable contract the search engine scores.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from analytics_zoo_tpu.automl.common.metrics import Evaluator


def _build_lstm(input_shape, config, out_dim):
    from analytics_zoo_tpu.nn import reset_name_scope
    from analytics_zoo_tpu.nn.layers.core import Dense, Dropout
    from analytics_zoo_tpu.nn.layers.recurrent import LSTM
    from analytics_zoo_tpu.nn.topology import Sequential

    reset_name_scope()
    m = Sequential()
    m.add(LSTM(int(config.get("lstm_1_units", 32)), return_sequences=True,
               input_shape=tuple(input_shape)))
    m.add(Dropout(float(config.get("dropout", 0.2))))
    m.add(LSTM(int(config.get("lstm_2_units", 32))))
    m.add(Dropout(float(config.get("dropout", 0.2))))
    m.add(Dense(out_dim))
    return m


class VanillaLSTM:
    """2-layer LSTM regressor (future_seq_len == 1)."""

    out_is_seq = False

    def __init__(self, check_optional_config: bool = False):
        self.model = None
        self.config: Dict = {}

    def _ensure(self, x, y, config):
        out_dim = y.shape[1] if y.ndim > 1 else 1
        self.config = dict(config)
        self.model = _build_lstm(x.shape[1:], config, out_dim)
        from analytics_zoo_tpu.train.optimizers import Adam

        self.model.compile(
            optimizer=Adam(lr=float(config.get("lr", 1e-3))), loss="mse")

    def fit_eval(self, x, y, validation_data=None, metric: str = "mse",
                 **config) -> float:
        if y.ndim == 1:
            y = y[:, None]
        self._ensure(x, y, config)
        vx, vy = validation_data if validation_data is not None else (x, y)
        if vy.ndim == 1:
            vy = vy[:, None]
        self.model.fit(x, y, batch_size=int(config.get("batch_size", 32)),
                       nb_epoch=int(config.get("epochs", 1)), verbose=False)
        pred = self.model.predict(vx, batch_size=1024)
        return Evaluator.evaluate(metric, vy, pred)

    def predict(self, x) -> np.ndarray:
        return self.model.predict(x, batch_size=1024)

    def evaluate(self, x, y, metric: str = "mse") -> float:
        return Evaluator.evaluate(metric, y, self.predict(x))

    # -- persistence -------------------------------------------------------
    def state(self):
        est = self.model.estimator
        return {"params": est.params, "state": est.state or {}}

    def save(self, path: str) -> None:
        from analytics_zoo_tpu.train import checkpoint as ckpt

        ckpt.save_pytree(path, self.state())

    def restore(self, path: str, x_shape, out_dim, config) -> None:
        from analytics_zoo_tpu.train import checkpoint as ckpt

        # rebuild through _ensure so subclasses (encoder-decoder)
        # reconstruct their own architecture
        x = np.zeros((2,) + tuple(x_shape), np.float32)
        y = np.zeros((2, out_dim), np.float32)
        self._ensure(x, y, config)
        tree = ckpt.load_pytree(path)
        self.model.estimator.set_initial_weights(tree["params"],
                                                 tree.get("state", {}))


def _build_encdec_block():
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.nn.module import StatelessLayer

    class Seq2SeqBlock(StatelessLayer):
        """Encoder-decoder forecaster (reference automl/model/Seq2Seq.py):
        an LSTM encodes the history window; a decoder LSTM unrolls
        ``future_seq_len`` steps autoregressively from the encoder state
        (its own previous prediction as input — inference-consistent, no
        teacher-forcing/inference mismatch), each step projected to the
        target dim.  Both scans are ``lax.scan`` — one jitted program.
        """

        def __init__(self, future_seq_len: int, latent_dim: int = 32,
                     out_dim: int = 1, **kw):
            super().__init__(**kw)
            self.future_seq_len = future_seq_len
            self.latent_dim = latent_dim
            self.out_dim = out_dim

        @staticmethod
        def _lstm_params(rng, d_in, d_h):
            k1, k2 = jax.random.split(rng)
            glorot = jax.nn.initializers.glorot_uniform()
            return {"wi": glorot(k1, (d_in, 4 * d_h), jnp.float32),
                    "wh": glorot(k2, (d_h, 4 * d_h), jnp.float32),
                    "b": jnp.zeros((4 * d_h,), jnp.float32)}

        @staticmethod
        def _lstm_step(p, carry, x):
            h_prev, c_prev = carry
            d_h = h_prev.shape[-1]
            g = x @ p["wi"] + h_prev @ p["wh"] + p["b"]
            i = jax.nn.sigmoid(g[..., :d_h])
            f = jax.nn.sigmoid(g[..., d_h:2 * d_h] + 1.0)  # forget bias 1
            o = jax.nn.sigmoid(g[..., 2 * d_h:3 * d_h])
            c = f * c_prev + i * jnp.tanh(g[..., 3 * d_h:])
            h = o * jnp.tanh(c)
            return h, c

        def build_params(self, rng, input_shape):
            d_in = input_shape[-1]
            k1, k2, k3 = jax.random.split(rng, 3)
            glorot = jax.nn.initializers.glorot_uniform()
            return {
                "enc": self._lstm_params(k1, d_in, self.latent_dim),
                "dec": self._lstm_params(k2, self.out_dim, self.latent_dim),
                "proj_w": glorot(k3, (self.latent_dim, self.out_dim),
                                 jnp.float32),
                "proj_b": jnp.zeros((self.out_dim,), jnp.float32),
            }

        def forward(self, params, x, training=False, rng=None):
            b = x.shape[0]
            h0 = (jnp.zeros((b, self.latent_dim), x.dtype),
                  jnp.zeros((b, self.latent_dim), x.dtype))

            def enc_step(carry, x_t):
                return self._lstm_step(params["enc"], carry, x_t), None

            carry, _ = jax.lax.scan(enc_step, h0, x.swapaxes(0, 1))

            y0 = jnp.zeros((b, self.out_dim), x.dtype)

            def dec_step(state, _):
                carry, y_prev = state
                carry = self._lstm_step(params["dec"], carry, y_prev)
                y_t = carry[0] @ params["proj_w"] + params["proj_b"]
                return (carry, y_t), y_t

            _, ys = jax.lax.scan(dec_step, (carry, y0), None,
                                 length=self.future_seq_len)
            return ys.swapaxes(0, 1).reshape(b, -1)   # (B, F*out_dim)

    return Seq2SeqBlock


class Seq2SeqForecaster(VanillaLSTM):
    """Multi-step forecaster (future_seq_len > 1) — a true LSTM
    encoder-decoder (reference automl/model/Seq2Seq.py), decoder unrolled
    as a ``lax.scan`` over the horizon.
    """

    def __init__(self, future_seq_len: int = 2, **kw):
        super().__init__(**kw)
        self.future_seq_len = future_seq_len

    def _ensure(self, x, y, config):
        from analytics_zoo_tpu.nn import reset_name_scope
        from analytics_zoo_tpu.nn.topology import Sequential
        from analytics_zoo_tpu.train.optimizers import Adam

        reset_name_scope()
        out_dim = y.shape[1] if y.ndim > 1 else 1
        self.config = dict(config)
        block_cls = _build_encdec_block()
        m = Sequential()
        m.add(block_cls(
            future_seq_len=max(self.future_seq_len, 1),
            latent_dim=int(config.get("latent_dim",
                                      config.get("lstm_1_units", 32))),
            out_dim=max(1, out_dim // max(self.future_seq_len, 1)),
            input_shape=tuple(x.shape[1:])))
        self.model = m
        self.model.compile(
            optimizer=Adam(lr=float(config.get("lr", 1e-3))), loss="mse")
