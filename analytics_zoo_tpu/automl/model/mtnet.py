"""MTNet — the reference's flagship time-series AutoML model
(reference pyzoo/zoo/automl/model/MTNet_keras.py: MTNetKeras).

Architecture (MTNet paper — "A Memory-Augmented Neural Network for
Multivariate Time Series Forecasting"):

- the history window splits into ``long_num`` memory chunks of
  ``time_step`` steps plus one short-term chunk of ``time_step`` steps;
- three CNN→attention-GRU encoders embed them: ``memory`` and
  ``context`` over the long chunks, ``query`` over the short chunk;
- attention of query over memory weights the context vectors; the
  concatenated [weighted context, query] feeds a dense head
  (nonlinear component);
- an autoregressive linear head on the last ``ar_window`` short-term
  steps is added (the Lintel-style AR shortcut).

TPU-native design notes (not a keras translation):
- one jitted program: the per-chunk encoder is ``vmap``-ed over the
  chunk dim instead of a Python loop of shared-weight submodels
  (reference MTNet_keras.py:421-428 loops ``num`` times);
- the conv (kernel spans the full feature width) lowers to one einsum
  (MXU matmul) over unfolded windows; the attention-GRU is a
  ``lax.scan`` whose attention term is precomputed (X·W1+b) once;
- two reference quirks are corrected rather than copied: its Permute
  runs the GRU over the channel dim (MTNet_keras.py:425 comment), and
  its Softmax(axis=-1) normalises a singleton axis (:335-337), which
  makes attention a no-op; here the GRU runs over time and the softmax
  normalises over the ``long_num`` memories (the paper's intent).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.automl.common.metrics import Evaluator
from analytics_zoo_tpu.nn.module import StatelessLayer


def _trunc_normal(rng, shape, stddev=0.1):
    return stddev * jax.random.truncated_normal(rng, -2.0, 2.0, shape,
                                                jnp.float32)


def _gru_params(rng, d_in: int, d_h: int):
    k1, k2 = jax.random.split(rng)
    return {"wi": _trunc_normal(k1, (d_in, 3 * d_h)),
            "wh": _trunc_normal(k2, (d_h, 3 * d_h)),
            "b": jnp.zeros((3 * d_h,), jnp.float32)}


def _gru_step(p, h, x, act):
    r_h = h.shape[-1]
    gi = x @ p["wi"] + p["b"]
    gh = h @ p["wh"]
    z = jax.nn.sigmoid(gi[..., :r_h] + gh[..., :r_h])
    r = jax.nn.sigmoid(gi[..., r_h:2 * r_h] + gh[..., r_h:2 * r_h])
    n = act(gi[..., 2 * r_h:] + (r * h) @ p["wh"][:, 2 * r_h:])
    return (1.0 - z) * n + z * h


class MTNetBlock(StatelessLayer):
    """The MTNet network as one layer: inputs (long, short) →
    prediction (B, output_dim).

    ``long``: (B, long_num, time_step, D); ``short``: (B, time_step, D).
    """

    def __init__(self, output_dim: int, time_step: int, long_num: int,
                 ar_window: int = 1, cnn_height: int = 1,
                 cnn_hid_size: int = 32,
                 rnn_hid_sizes: Sequence[int] = (16, 32), **kw):
        super().__init__(**kw)
        if ar_window > time_step:
            raise ValueError(f"ar_window {ar_window} must not exceed "
                             f"time_step {time_step}")
        if cnn_height > time_step:
            raise ValueError(f"cnn_height {cnn_height} must not exceed "
                             f"time_step {time_step}")
        self.output_dim = output_dim
        self.time_step = time_step
        self.long_num = long_num
        self.ar_window = ar_window
        self.cnn_height = cnn_height
        self.cnn_hid = cnn_hid_size
        self.rnn_hid_sizes = list(rnn_hid_sizes)

    # -- params -----------------------------------------------------------
    def _encoder_params(self, rng, d_feat: int):
        h, r_last = self.cnn_hid, self.rnn_hid_sizes[-1]
        ks = jax.random.split(rng, 8 + len(self.rnn_hid_sizes))
        p = {
            "conv_w": _trunc_normal(ks[0], (self.cnn_height, d_feat, h)),
            "conv_b": 0.1 * jnp.ones((h,), jnp.float32),
            "attn_w1": _trunc_normal(ks[1], (h, h)),
            "attn_b2": jnp.zeros((h,), jnp.float32),
            "attn_w2": _trunc_normal(ks[2], (r_last, h)),
            "attn_v": _trunc_normal(ks[3], (h, 1)),
            "attn_w3": _trunc_normal(ks[4], (2 * h, h)),
            "attn_b3": jnp.zeros((h,), jnp.float32),
        }
        d_in = h
        for i, r_h in enumerate(self.rnn_hid_sizes):
            p[f"gru{i}"] = _gru_params(ks[5 + i], d_in, r_h)
            d_in = r_h
        return p

    def build_params(self, rng, long_shape, short_shape=None):
        d_feat = long_shape[-1]
        ks = jax.random.split(rng, 5)
        nl_in = self.rnn_hid_sizes[-1] * (self.long_num + 1)
        params = {
            "mem": self._encoder_params(ks[0], d_feat),
            "ctx": self._encoder_params(ks[1], d_feat),
            "query": self._encoder_params(ks[2], d_feat),
            "head_w": _trunc_normal(ks[3], (nl_in, self.output_dim)),
            "head_b": 0.1 * jnp.ones((self.output_dim,), jnp.float32),
        }
        if self.ar_window > 0:
            params["ar_w"] = _trunc_normal(
                ks[4], (self.ar_window * d_feat, self.output_dim))
            params["ar_b"] = 0.1 * jnp.ones((self.output_dim,), jnp.float32)
        return params

    # -- encoder ----------------------------------------------------------
    def _encode(self, p, series):
        """series (B, T, D) → (B, R_last): conv over time + attention-GRU."""
        ch = self.cnn_height
        t_c = self.time_step - ch + 1
        # unfold T into t_c windows of height ch; full-width kernel → one
        # einsum onto the MXU: (B, t_c, ch, D) x (ch, D, H)
        idx = jnp.arange(t_c)[:, None] + jnp.arange(ch)[None, :]
        windows = series[:, idx]                       # (B, t_c, ch, D)
        conv = jnp.einsum("btcd,cdh->bth", windows, p["conv_w"])
        x_seq = jax.nn.relu(conv + p["conv_b"])        # (B, t_c, H)

        # attention term precomputed once for the whole scan
        xw1 = x_seq @ p["attn_w1"] + p["attn_b2"]      # (B, t_c, H)

        b = series.shape[0]
        h0 = tuple(jnp.zeros((b, r), jnp.float32)
                   for r in self.rnn_hid_sizes)

        def step(hs, x_t):
            top = hs[-1]
            e = jnp.tanh(xw1 + (top @ p["attn_w2"])[:, None, :])
            attn = jax.nn.softmax(e @ p["attn_v"], axis=1)   # (B, t_c, 1)
            x_weighted = jnp.sum(attn * x_seq, axis=1)       # (B, H)
            x_in = jnp.concatenate([x_t, x_weighted], -1) @ p["attn_w3"] \
                + p["attn_b3"]
            new = []
            inp = x_in
            for i, r in enumerate(self.rnn_hid_sizes):
                inp = _gru_step(p[f"gru{i}"], hs[i], inp, jax.nn.relu)
                new.append(inp)
            return tuple(new), None

        (hs, _) = jax.lax.scan(step, h0, x_seq.swapaxes(0, 1))
        return hs[-1]

    def forward(self, params, long, short, training=False, rng=None):
        b = long.shape[0]
        long = long.reshape(b, self.long_num, self.time_step, -1)
        # vmap the shared-weight encoder over the memory chunks
        enc_m = jax.vmap(lambda s: self._encode(params["mem"], s),
                         in_axes=1, out_axes=1)(long)     # (B, n, R)
        enc_c = jax.vmap(lambda s: self._encode(params["ctx"], s),
                         in_axes=1, out_axes=1)(long)     # (B, n, R)
        query = self._encode(params["query"], short)      # (B, R)

        # attention of query over memories, softmax over long_num
        logits = jnp.einsum("bnr,br->bn", enc_m, query)
        prob = jax.nn.softmax(logits, axis=-1)            # (B, n)
        weighted = enc_c * prob[:, :, None]               # (B, n, R)
        flat = jnp.concatenate([weighted, query[:, None, :]],
                               axis=1).reshape(b, -1)
        pred = flat @ params["head_w"] + params["head_b"]
        if self.ar_window > 0:
            ar = short[:, -self.ar_window:].reshape(b, -1)
            pred = pred + ar @ params["ar_w"] + params["ar_b"]
        return pred


class MTNet:
    """AutoML trainable wrapping MTNetBlock under the SPMD Estimator
    (fit_eval contract — automl/model/time_sequence.py).

    The feature transformer's rolling window of length
    ``(long_num + 1) * time_step`` splits into long/short inputs here
    (reference MTNetKeras._reshape_input_x).
    """

    out_is_seq = False

    def __init__(self, check_optional_config: bool = False,
                 future_seq_len: int = 1):
        self.model = None
        self.config: Dict = {}
        self.future_seq_len = future_seq_len

    # -- data layout ------------------------------------------------------
    @staticmethod
    def _cfg(config):
        """Resolve config with the reference's recipe aliases
        (filter_size→cnn_height, ar_size→ar_window —
        time_sequence_predictor.py:99-110)."""
        return {
            "time_step": int(config.get("time_step", 1)),
            "long_num": int(config.get("long_num", 7)),
            "cnn_height": int(config.get("cnn_height",
                                         config.get("filter_size", 1))),
            "ar_window": int(config.get("ar_window",
                                        config.get("ar_size", 1))),
            "cnn_hid_size": int(config.get("cnn_hid_size", 32)),
            "rnn_hid_sizes": list(config.get("rnn_hid_sizes", [16, 32])),
        }

    def _split(self, x, config):
        c = self._cfg(config)
        t, n = c["time_step"], c["long_num"]
        need = (n + 1) * t
        if x.shape[1] != need:
            raise ValueError(
                f"MTNet needs past_seq_len == (long_num+1)*time_step = "
                f"{need}, got {x.shape[1]}; set past_seq_len accordingly "
                "in the recipe")
        b, _, d = x.shape
        long = x[:, :n * t].reshape(b, n, t, d)
        short = x[:, n * t:]
        return long.astype(np.float32), short.astype(np.float32)

    def _ensure(self, x, y, config):
        from analytics_zoo_tpu.nn import Input, Model, reset_name_scope
        from analytics_zoo_tpu.train.optimizers import Adam

        reset_name_scope()
        c = self._cfg(config)
        t, n = c["time_step"], c["long_num"]
        d = x.shape[-1]
        out_dim = y.shape[1] if y.ndim > 1 else 1
        self.config = dict(config)
        block = MTNetBlock(
            output_dim=out_dim, time_step=t, long_num=n,
            ar_window=c["ar_window"], cnn_height=c["cnn_height"],
            cnn_hid_size=c["cnn_hid_size"],
            rnn_hid_sizes=c["rnn_hid_sizes"])
        li = Input(shape=(n, t, d))
        si = Input(shape=(t, d))
        out = block(li, si)
        self.model = Model([li, si], out)
        self.model.compile(optimizer=Adam(lr=float(config.get("lr", 1e-3))),
                           loss="mae")

    # -- trainable contract ----------------------------------------------
    def fit_eval(self, x, y, validation_data=None, metric: str = "mse",
                 **config) -> float:
        if y.ndim == 1:
            y = y[:, None]
        self._ensure(x, y, config)
        long, short = self._split(x, config)
        if validation_data is not None:
            vx, vy = validation_data
        else:
            vx, vy = x, y
        if vy.ndim == 1:
            vy = vy[:, None]
        self.model.fit([long, short], y,
                       batch_size=int(config.get("batch_size", 32)),
                       nb_epoch=int(config.get("epochs", 1)), verbose=False)
        vl, vs = self._split(vx, config)
        pred = self.model.predict([vl, vs], batch_size=1024)
        return Evaluator.evaluate(metric, vy, pred)

    def predict(self, x) -> np.ndarray:
        long, short = self._split(x, self.config)
        return self.model.predict([long, short], batch_size=1024)

    def evaluate(self, x, y, metric: str = "mse") -> float:
        if y.ndim == 1:
            y = y[:, None]
        return Evaluator.evaluate(metric, y, self.predict(x))

    # -- persistence ------------------------------------------------------
    def state(self):
        est = self.model.estimator
        return {"params": est.params, "state": est.state or {}}

    def save(self, path: str) -> None:
        from analytics_zoo_tpu.train import checkpoint as ckpt

        ckpt.save_pytree(path, self.state())

    def restore(self, path: str, x_shape, out_dim, config) -> None:
        from analytics_zoo_tpu.train import checkpoint as ckpt

        c = self._cfg(config)
        t, n = c["time_step"], c["long_num"]
        # x_shape = (past_seq_len, n_features), batch-less (pipeline
        # contract, automl/pipeline/time_sequence.py)
        x = np.zeros((2, (n + 1) * t, x_shape[-1]), np.float32)
        y = np.zeros((2, out_dim), np.float32)
        self._ensure(x, y, config)
        long, short = self._split(x, config)
        self.model.estimator._ensure_built([long, short])
        tree = ckpt.load_pytree(path)
        self.model.estimator.set_initial_weights(tree["params"],
                                                 tree.get("state", {}))
        self.config = dict(config)
