"""Rolling-window time-series feature engineering
(reference automl/feature/time_sequence.py:30-540: datetime feature
generation :526, rolling :415-470, scaling :503).

Input: a DataFrame with a datetime column + target column (+ extra
feature columns).  ``fit_transform`` generates calendar features, scales,
and rolls into (X, y) supervised windows; ``post_processing`` unscales
predictions back into a datetime-indexed frame.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

_DT_FEATURES = ("HOUR", "DAY", "MONTH", "DAYOFWEEK", "WEEKDAY", "WEEKEND",
                "IS_AWAKE", "IS_BUSY_HOURS")


class TimeSequenceFeatureTransformer:
    """Feature transformer for TimeSequencePredictor."""

    def __init__(self, future_seq_len: int = 1, dt_col: str = "datetime",
                 target_col: str = "value",
                 extra_features_col: Optional[Sequence[str]] = None,
                 drop_missing: bool = True):
        self.future_seq_len = future_seq_len
        self.dt_col = dt_col
        self.target_col = target_col
        self.extra_features_col = list(extra_features_col or [])
        self.drop_missing = drop_missing
        # fitted state
        self.scale_min: Optional[np.ndarray] = None
        self.scale_max: Optional[np.ndarray] = None
        self.config: Dict = {}

    # -- feature generation ------------------------------------------------
    def get_feature_list(self, input_df: pd.DataFrame) -> List[str]:
        """All candidate feature names the search can select from."""
        return [f"{f}({self.dt_col})" for f in _DT_FEATURES] + \
            list(self.extra_features_col)

    def _gen_calendar(self, dt: pd.Series) -> pd.DataFrame:
        dt = pd.to_datetime(dt)
        hour = dt.dt.hour
        out = {
            f"HOUR({self.dt_col})": hour,
            f"DAY({self.dt_col})": dt.dt.day,
            f"MONTH({self.dt_col})": dt.dt.month,
            f"DAYOFWEEK({self.dt_col})": dt.dt.dayofweek,
            f"WEEKDAY({self.dt_col})": (dt.dt.dayofweek < 5).astype(int),
            f"WEEKEND({self.dt_col})": (dt.dt.dayofweek >= 5).astype(int),
            f"IS_AWAKE({self.dt_col})": ((hour >= 6) & (hour <= 23))
            .astype(int),
            f"IS_BUSY_HOURS({self.dt_col})": hour.isin(
                [7, 8, 9, 17, 18, 19]).astype(int),
        }
        return pd.DataFrame(out)

    def _feature_frame(self, input_df: pd.DataFrame,
                       selected: Sequence[str]) -> np.ndarray:
        """(target, selected features...) matrix in time order."""
        df = input_df
        if self.drop_missing:
            df = df.dropna(subset=[self.dt_col, self.target_col])
        cal = self._gen_calendar(df[self.dt_col]).reset_index(drop=True)
        cols = [df[self.target_col].reset_index(drop=True).rename("__y")]
        for name in selected:
            if name in cal.columns:
                cols.append(cal[name])
            elif name in df.columns:
                cols.append(df[name].reset_index(drop=True))
            else:
                raise ValueError(f"unknown feature {name!r}")
        return pd.concat(cols, axis=1).to_numpy(np.float32)

    # -- scaling (fit on train, reuse at test) -----------------------------
    def _fit_scale(self, mat: np.ndarray) -> np.ndarray:
        self.scale_min = mat.min(axis=0)
        self.scale_max = mat.max(axis=0)
        return self._scale(mat)

    def _scale(self, mat: np.ndarray) -> np.ndarray:
        span = np.where(self.scale_max - self.scale_min == 0, 1.0,
                        self.scale_max - self.scale_min)
        return (mat - self.scale_min) / span

    def _unscale_y(self, y: np.ndarray) -> np.ndarray:
        span = (self.scale_max[0] - self.scale_min[0]) or 1.0
        return y * span + self.scale_min[0]

    # -- rolling -----------------------------------------------------------
    @staticmethod
    def _roll(mat: np.ndarray, past: int, future: int
              ) -> Tuple[np.ndarray, np.ndarray]:
        n = mat.shape[0] - past - future + 1
        if n <= 0:
            raise ValueError(
                f"series too short: {mat.shape[0]} rows for "
                f"past={past} + future={future}")
        idx = np.arange(past)[None, :] + np.arange(n)[:, None]
        x = mat[idx]                                    # (n, past, F)
        yi = past + np.arange(future)[None, :] + np.arange(n)[:, None]
        y = mat[yi, 0]                                  # (n, future)
        return x, y

    # -- public API --------------------------------------------------------
    def fit_transform(self, input_df: pd.DataFrame, **config
                      ) -> Tuple[np.ndarray, np.ndarray]:
        self.config = dict(config)
        selected = config.get("selected_features",
                              self.get_feature_list(input_df))
        # persist the RESOLVED selection: save/load must rebuild the
        # exact input width even when the recipe omitted the key
        self.config["selected_features"] = list(selected)
        past = int(config.get("past_seq_len", 2))
        mat = self._feature_frame(input_df, selected)
        mat = self._fit_scale(mat)
        return self._roll(mat, past, self.future_seq_len)

    def transform(self, input_df: pd.DataFrame, is_train: bool = False
                  ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        if self.scale_min is None:
            raise RuntimeError("fit_transform first")
        selected = self.config.get("selected_features",
                                   self.get_feature_list(input_df))
        past = int(self.config.get("past_seq_len", 2))
        mat = self._scale(self._feature_frame(input_df, selected))
        if is_train:
            return self._roll(mat, past, self.future_seq_len)
        # Test mode: EVERY window of length `past`, including the final
        # one whose forecast lies beyond the frame — predict() must be
        # able to forecast the actual future, not just in-frame steps.
        n = mat.shape[0] - past + 1
        if n <= 0:
            raise ValueError("series shorter than past_seq_len")
        idx = np.arange(past)[None, :] + np.arange(n)[:, None]
        return mat[idx], None

    def post_processing(self, input_df: pd.DataFrame, y_pred: np.ndarray,
                        is_train: bool = False):
        """Unscale predictions; for test mode attach the datetime of the
        FORECAST TARGET step — window i covers rows [i, i+past) and
        predicts row i+past, so its stamp is dt[i+past], extrapolated by
        the series period when the target lies beyond the frame
        (reference post_processing :230)."""
        y = self._unscale_y(np.asarray(y_pred))
        if is_train:
            return y
        past = int(self.config.get("past_seq_len", 2))
        dt = pd.to_datetime(input_df[self.dt_col]).reset_index(drop=True)
        dt_vals = dt.to_numpy()
        step = (dt_vals[-1] - dt_vals[-2]) if len(dt_vals) > 1 else \
            np.timedelta64(0, "s")
        idx = past + np.arange(len(y))
        stamps = np.asarray(
            [dt_vals[i] if i < len(dt_vals)
             else dt_vals[-1] + (i - len(dt_vals) + 1) * step for i in idx])
        out = {self.dt_col: stamps}
        for k in range(y.shape[1] if y.ndim > 1 else 1):
            col = y[:, k] if y.ndim > 1 else y
            out[f"{self.target_col}_{k}" if
                (y.ndim > 1 and y.shape[1] > 1) else self.target_col] = col
        return pd.DataFrame(out)

    # -- persistence -------------------------------------------------------
    def save(self, file_path: str) -> None:
        blob = {"future_seq_len": self.future_seq_len,
                "dt_col": self.dt_col, "target_col": self.target_col,
                "extra_features_col": self.extra_features_col,
                "drop_missing": self.drop_missing,
                "config": {k: (list(v) if isinstance(v, (list, tuple))
                               else v) for k, v in self.config.items()},
                "scale_min": (self.scale_min.tolist()
                              if self.scale_min is not None else None),
                "scale_max": (self.scale_max.tolist()
                              if self.scale_max is not None else None)}
        with open(file_path, "w") as f:
            json.dump(blob, f)

    @classmethod
    def load(cls, file_path: str) -> "TimeSequenceFeatureTransformer":
        with open(file_path) as f:
            blob = json.load(f)
        ft = cls(future_seq_len=blob["future_seq_len"],
                 dt_col=blob["dt_col"], target_col=blob["target_col"],
                 extra_features_col=blob["extra_features_col"],
                 drop_missing=blob["drop_missing"])
        ft.config = blob["config"]
        if blob["scale_min"] is not None:
            ft.scale_min = np.asarray(blob["scale_min"], np.float32)
            ft.scale_max = np.asarray(blob["scale_max"], np.float32)
        return ft

    restore = load
