"""Regression metrics for AutoML model selection
(reference automl/common/metrics.py, 245 LoC)."""

from __future__ import annotations

from typing import Callable, Dict

import numpy as np


def _flat(y_true, y_pred):
    y_true = np.asarray(y_true, np.float64).reshape(-1)
    y_pred = np.asarray(y_pred, np.float64).reshape(-1)
    if y_true.shape != y_pred.shape:
        raise ValueError(f"shape mismatch {y_true.shape} vs {y_pred.shape}")
    return y_true, y_pred


def mean_squared_error(y_true, y_pred) -> float:
    y_true, y_pred = _flat(y_true, y_pred)
    return float(np.mean((y_true - y_pred) ** 2))


def root_mean_squared_error(y_true, y_pred) -> float:
    return float(np.sqrt(mean_squared_error(y_true, y_pred)))


def mean_absolute_error(y_true, y_pred) -> float:
    y_true, y_pred = _flat(y_true, y_pred)
    return float(np.mean(np.abs(y_true - y_pred)))


def r_square(y_true, y_pred) -> float:
    y_true, y_pred = _flat(y_true, y_pred)
    ss_res = np.sum((y_true - y_pred) ** 2)
    ss_tot = np.sum((y_true - np.mean(y_true)) ** 2)
    return float(1.0 - ss_res / ss_tot) if ss_tot > 0 else 0.0


def symmetric_mean_absolute_percentage_error(y_true, y_pred) -> float:
    y_true, y_pred = _flat(y_true, y_pred)
    denom = (np.abs(y_true) + np.abs(y_pred)) / 2.0
    denom = np.where(denom == 0, 1.0, denom)
    return float(100.0 * np.mean(np.abs(y_true - y_pred) / denom))


def mean_absolute_percentage_error(y_true, y_pred) -> float:
    y_true, y_pred = _flat(y_true, y_pred)
    denom = np.where(np.abs(y_true) < 1e-8, 1e-8, np.abs(y_true))
    return float(100.0 * np.mean(np.abs((y_true - y_pred) / denom)))


_METRICS: Dict[str, Callable] = {
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "rmse": root_mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
    "r2": r_square,
    "r_square": r_square,
    "smape": symmetric_mean_absolute_percentage_error,
    "mape": mean_absolute_percentage_error,
}

#: metrics where larger is better (everything else minimises)
_MAXIMIZE = {"r2", "r_square"}


class Evaluator:
    """Static metric dispatch (reference Evaluator.evaluate)."""

    @staticmethod
    def evaluate(metric: str, y_true, y_pred) -> float:
        m = metric.lower()
        if m not in _METRICS:
            raise ValueError(f"unknown metric {metric!r}; "
                             f"known: {sorted(_METRICS)}")
        return _METRICS[m](y_true, y_pred)

    @staticmethod
    def get_metric_mode(metric: str) -> str:
        """'max' for reward-style metrics (r2), else 'min'."""
        return "max" if metric.lower() in _MAXIMIZE else "min"
