"""Vmapped trial populations: N hyper-parameter configs as ONE program.

The reference scaled tuning out with Ray actors over a cluster
(RayTuneSearchEngine.py:28).  The TPU-native equivalent for numeric
hyper-parameters is to make the POPULATION a batch dimension: stack the
configs, ``jax.vmap`` the whole training function over them, and let
XLA turn N tiny trainings into batched MXU work — one dispatch, no
per-trial dispatch latency, and the mesh's data axis can shard the
population (trials ride devices with zero orchestration).

Constraints are the honest vmap ones: every config must share shapes
(structural params — layer sizes, seq lens — are fixed per call;
numeric params — lr, dropout, init scale, regularization — vary).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence, Tuple

import numpy as np


def is_numeric_hparam(v: Any) -> bool:
    """The ONE numeric-vs-structural predicate (shared with the engine's
    vmap grouping so they cannot disagree).  bools are structural: a
    traced bool breaks Python truth tests inside the trainable."""
    return (isinstance(v, (int, float, np.floating, np.integer))
            and not isinstance(v, (bool, np.bool_)))


def split_config(configs: Sequence[Dict[str, Any]]):
    """Split configs into (stacked, const_numeric, structural).

    The calling convention is VALUE-INDEPENDENT: every numeric key
    always reaches the trainable inside its cfg dict (varying ones as
    stacked/vmapped leaves — ints keep integer dtype — constant ones as
    plain python constants), and ``**structural`` carries only
    non-numeric keys.  Raises if a non-numeric key differs (vmap cannot
    trace shape-changing params).
    """
    keys = set()
    for c in configs:
        keys.update(c)
    stacked: Dict[str, np.ndarray] = {}
    const_num: Dict[str, Any] = {}
    structural: Dict[str, Any] = {}
    for k in sorted(keys):
        vals = [c.get(k) for c in configs]
        same = all(v == vals[0] for v in vals[1:]) if len(vals) > 1 else True
        if all(is_numeric_hparam(v) for v in vals):
            if same:
                const_num[k] = vals[0]
            elif all(isinstance(v, (int, np.integer)) for v in vals):
                # keep integer semantics — but note a traced int cannot
                # size a shape; structural ints must be constant
                stacked[k] = np.asarray(vals, np.int32)
            else:
                stacked[k] = np.asarray(vals, np.float32)
        elif same:
            structural[k] = vals[0]
        else:
            raise ValueError(
                f"config key {k!r} varies across the population but is "
                f"not numeric ({vals[:3]}...); structural params must be "
                "constant within one vmapped batch — group configs by "
                "structure first (see SearchEngine backend='vmap')")
    return stacked, const_num, structural


# one compiled program per (train_fn, stacked keys, constants): the jit
# wrapper must be REUSED or every batch re-traces and recompiles.
# BOUNDED (LRU): each entry pins the trainable's closure + executable,
# so unbounded growth would leak in long-lived tuning services.
_JIT_CACHE: "OrderedDict[Tuple, Any]" = None  # type: ignore[assignment]
_JIT_CACHE_MAX = 32


def _compiled(train_fn, stacked_keys: Tuple[str, ...],
              const_num: Dict[str, Any], structural: Dict[str, Any]):
    import collections

    import jax

    global _JIT_CACHE
    if _JIT_CACHE is None:
        _JIT_CACHE = collections.OrderedDict()
    key = (id(train_fn), stacked_keys,
           tuple(sorted((k, repr(v)) for k, v in const_num.items())),
           tuple(sorted((k, repr(v)) for k, v in structural.items())))
    fn = _JIT_CACHE.get(key)
    if fn is None:
        def one(leaves):
            cfg = dict(const_num)
            cfg.update(leaves)
            return train_fn(cfg, **structural)

        fn = jax.jit(jax.vmap(one))
        _JIT_CACHE[key] = fn
        while len(_JIT_CACHE) > _JIT_CACHE_MAX:
            _JIT_CACHE.popitem(last=False)
    else:
        _JIT_CACHE.move_to_end(key)
    return fn


def vmapped_trials(train_fn: Callable[..., Any],
                   configs: Sequence[Dict[str, Any]],
                   ) -> List[float]:
    """Run ``train_fn(cfg_dict, **structural) -> scalar score`` for
    every config as one vmapped jitted call; returns per-trial scores.

    ``cfg_dict`` always carries EVERY numeric key (varying ones as
    traced scalars, batch-constant ones as python constants);
    ``**structural`` carries the non-numeric keys.  ``train_fn`` must
    be pure and jax-traceable in the varying leaves.
    """
    import jax
    import jax.numpy as jnp

    stacked, const_num, structural = split_config(list(configs))
    if not stacked:
        # degenerate population: one trace, N identical results
        score = jax.jit(lambda: jnp.asarray(
            train_fn(dict(const_num), **structural)))()
        return [float(score)] * len(configs)

    fn = _compiled(train_fn, tuple(sorted(stacked)), const_num,
                   structural)
    scores = fn(dict(stacked))
    return [float(s) for s in np.asarray(scores)]
