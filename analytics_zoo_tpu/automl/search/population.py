"""Vmapped trial populations: N hyper-parameter configs as ONE program.

The reference scaled tuning out with Ray actors over a cluster
(RayTuneSearchEngine.py:28).  The TPU-native equivalent for numeric
hyper-parameters is to make the POPULATION a batch dimension: stack the
configs, ``jax.vmap`` the whole training function over them, and let
XLA turn N tiny trainings into batched MXU work — one dispatch, no
per-trial dispatch latency, and the mesh's data axis can shard the
population (trials ride devices with zero orchestration).

Constraints are the honest vmap ones: every config must share shapes
(structural params — layer sizes, seq lens — are fixed per call;
numeric params — lr, dropout, init scale, regularization — vary).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence, Tuple

import numpy as np


def is_numeric_hparam(v: Any) -> bool:
    """The ONE numeric-vs-structural predicate (shared with the engine's
    vmap grouping so they cannot disagree).  bools are structural: a
    traced bool breaks Python truth tests inside the trainable."""
    return (isinstance(v, (int, float, np.floating, np.integer))
            and not isinstance(v, (bool, np.bool_)))


def split_config(configs: Sequence[Dict[str, Any]]):
    """Split configs into (stacked numeric leaves, shared structural).

    Numeric keys that vary across the population become stacked arrays
    (ints stay integer dtype); keys whose value is identical stay
    scalar/structural.  Raises if a non-numeric key differs (vmap cannot
    trace shape-changing params).
    """
    keys = set()
    for c in configs:
        keys.update(c)
    stacked: Dict[str, np.ndarray] = {}
    shared: Dict[str, Any] = {}
    for k in sorted(keys):
        vals = [c.get(k) for c in configs]
        same = all(v == vals[0] for v in vals[1:]) if len(vals) > 1 else True
        if same:
            shared[k] = vals[0]
        elif all(is_numeric_hparam(v) for v in vals):
            if all(isinstance(v, (int, np.integer)) for v in vals):
                # keep integer semantics — but note a traced int cannot
                # size a shape; structural ints must be constant
                stacked[k] = np.asarray(vals, np.int32)
            else:
                stacked[k] = np.asarray(vals, np.float32)
        else:
            raise ValueError(
                f"config key {k!r} varies across the population but is "
                f"not numeric ({vals[:3]}...); structural params must be "
                "constant within one vmapped batch — group configs by "
                "structure first (see SearchEngine backend='vmap')")
    return stacked, shared


# one compiled program per (train_fn, stacked keys, shared config): the
# jit wrapper must be REUSED or every batch re-traces and recompiles
_JIT_CACHE: Dict[Tuple, Any] = {}


def _compiled(train_fn, stacked_keys: Tuple[str, ...],
              shared: Dict[str, Any]):
    import jax

    key = (id(train_fn), stacked_keys,
           tuple(sorted((k, repr(v)) for k, v in shared.items())))
    fn = _JIT_CACHE.get(key)
    if fn is None:
        def one(leaves):
            return train_fn(leaves, **shared)

        fn = jax.jit(jax.vmap(one))
        _JIT_CACHE[key] = fn
    return fn


def vmapped_trials(train_fn: Callable[..., Any],
                   configs: Sequence[Dict[str, Any]],
                   ) -> List[float]:
    """Run ``train_fn(numeric_cfg_dict, **shared) -> scalar score`` for
    every config as one vmapped jitted call; returns per-trial scores.

    ``train_fn`` must be a pure jax-traceable function of the numeric
    config leaves (each a scalar inside the trace).
    """
    import jax
    import jax.numpy as jnp

    stacked, shared = split_config(list(configs))
    if not stacked:
        # degenerate population: one trace, N identical results
        score = jax.jit(lambda: jnp.asarray(train_fn({}, **shared)))()
        return [float(score)] * len(configs)

    fn = _compiled(train_fn, tuple(sorted(stacked)), shared)
    scores = fn(dict(stacked))
    return [float(s) for s in np.asarray(scores)]
