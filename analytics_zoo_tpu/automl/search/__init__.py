"""Hyper-parameter search: sampling primitives, recipes, and an
in-process engine with a ray.tune-shaped API.

Reference capability: ``RayTuneSearchEngine`` (automl/search/
RayTuneSearchEngine.py:28) running trials as Ray actors over RayOnSpark,
with Bayesian optimization via tune's BayesOptSearch (:25).  TPU-native
redesign: a trial is a jitted JAX program on the local mesh, so the
engine runs trials concurrently in-process (thread pool; process pool
for GIL-bound host-heavy trainables) — no second runtime to bootstrap
(RayOnSpark's barrier-stage dance, ray/util/raycontext.py:155-189, is
obsolete by construction).  ``search_alg="tpe"`` replaces BayesOptSearch
with a numpy-only TPE sampler (search/tpe.py) whose proposals are a
deterministic function of (seed, history) — reruns at the same
parallelism reproduce bit-for-bit regardless of thread scheduling.
"""

from __future__ import annotations

import concurrent.futures as cf
import logging
import math
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from analytics_zoo_tpu.automl.search.space import (  # noqa: F401
    Choice, FeatureSubset, GridSearch, LogUniform, RandInt, Sampler,
    Uniform, expand_grid, finalize_config, sample_config)
from analytics_zoo_tpu.automl.search.tpe import TPESampler

logger = logging.getLogger("analytics_zoo_tpu.automl")


# ---------------------------------------------------------------------------
# recipes (reference time_sequence_predictor.py:37-334)
# ---------------------------------------------------------------------------

class Recipe:
    """A search space + trial budget."""

    num_samples: int = 1
    training_iteration: int = 10

    def search_space(self, all_available_features: Sequence[str]
                     ) -> Dict[str, Any]:
        raise NotImplementedError


class SmokeRecipe(Recipe):
    """Tiny space to validate the plumbing (reference SmokeRecipe)."""

    num_samples = 1
    training_iteration = 1

    def search_space(self, all_available_features):
        return {
            "selected_features": list(all_available_features),
            "past_seq_len": 2,
            "lstm_1_units": 16,
            "lstm_2_units": 16,
            "dropout": 0.2,
            "lr": 1e-3,
            "batch_size": 32,
            "epochs": 1,
        }


class RandomRecipe(Recipe):
    """Random sampling over the LSTM space (reference RandomRecipe)."""

    def __init__(self, num_rand_samples: int = 1, look_back: int = 2):
        self.num_samples = num_rand_samples
        self.training_iteration = 10
        self.look_back = look_back

    def search_space(self, all_available_features):
        return {
            "selected_features": FeatureSubset(all_available_features),
            "past_seq_len": (RandInt(self.look_back[0], self.look_back[1])
                             if isinstance(self.look_back, (tuple, list))
                             else self.look_back),
            "lstm_1_units": Choice([16, 32, 64, 128]),
            "lstm_2_units": Choice([16, 32, 64]),
            "dropout": Uniform(0.2, 0.5),
            "lr": LogUniform(1e-4, 1e-2),
            "batch_size": Choice([32, 64, 128]),
            "epochs": 5,
        }


class GridRandomRecipe(Recipe):
    """Grid over structure x random over the rest (reference
    GridRandomRecipe)."""

    def __init__(self, num_rand_samples: int = 1, look_back: int = 2):
        self.num_samples = num_rand_samples
        self.training_iteration = 10
        self.look_back = look_back

    def search_space(self, all_available_features):
        return {
            "selected_features": FeatureSubset(all_available_features),
            "past_seq_len": (RandInt(self.look_back[0], self.look_back[1])
                             if isinstance(self.look_back, (tuple, list))
                             else self.look_back),
            "lstm_1_units": GridSearch([16, 64]),
            "lstm_2_units": GridSearch([16, 64]),
            "dropout": Uniform(0.2, 0.5),
            "lr": LogUniform(1e-4, 1e-2),
            "batch_size": Choice([32, 64]),
            "epochs": 5,
        }


class MTNetSmokeRecipe(Recipe):
    """One MTNet trial with fixed hyper-parameters (reference
    MTNetSmokeRecipe, time_sequence_predictor.py:88-117).  past_seq_len
    is pinned to (long_num + 1) * time_step as MTNet's window split
    requires."""

    num_samples = 1
    training_iteration = 1

    def search_space(self, all_available_features):
        return {
            "selected_features": list(all_available_features),
            "model": "MTNet",
            "lr": 1e-3,
            "batch_size": 16,
            "epochs": 1,
            "dropout": 0.2,
            "time_step": 3,
            "long_num": 3,
            "cnn_height": 2,
            "ar_window": 2,
            "cnn_hid_size": 16,
            "rnn_hid_sizes": [8, 16],
            "past_seq_len": (3 + 1) * 3,
        }


class MTNetGridRandomRecipe(Recipe):
    """Grid over MTNet structure × random over training params; the
    grid keeps (long_num, time_step) pairs with a consistent
    past_seq_len per combo (the reference samples past_seq_len as a
    dependent RandomSample — here each grid point carries its own)."""

    def __init__(self, num_rand_samples: int = 1,
                 time_steps: Sequence[int] = (3, 4),
                 long_nums: Sequence[int] = (3, 4)):
        self.num_samples = num_rand_samples
        self.training_iteration = 10
        combos = [{"time_step": t, "long_num": n,
                   "past_seq_len": (n + 1) * t}
                  for t in time_steps for n in long_nums]
        self._combos = combos

    def search_space(self, all_available_features):
        return {
            "selected_features": list(all_available_features),
            "model": "MTNet",
            "__mtnet_shape": GridSearch(self._combos),
            "cnn_height": Choice([1, 2]),
            "cnn_hid_size": Choice([16, 32]),
            "ar_window": Choice([1, 2]),
            "dropout": Uniform(0.2, 0.5),
            "lr": LogUniform(1e-4, 1e-2),
            "batch_size": Choice([32, 64]),
            "epochs": 5,
        }


class BayesRecipe(Recipe):
    """TPE (Bayesian-optimization-style) search over the LSTM space —
    the reference's BayesRecipe (time_sequence_predictor.py, driving
    tune BayesOptSearch).  Same space as RandomRecipe; the engine's TPE
    sampler concentrates later trials around observed good regions, so
    at equal trial budget it finds better configs than random sampling.
    """

    search_alg = "tpe"

    def __init__(self, num_samples: int = 16, look_back: int = 2,
                 n_startup: Optional[int] = None):
        self.num_samples = num_samples
        self.training_iteration = 10
        self.look_back = look_back
        self.n_startup = n_startup if n_startup is not None \
            else max(4, num_samples // 4)

    def search_space(self, all_available_features):
        return RandomRecipe(1, self.look_back).search_space(
            all_available_features)


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

@dataclass
class TrialResult:
    config: Dict[str, Any]
    metric: float
    extra: Dict[str, Any] = field(default_factory=dict)


def _run_one_trial(trainable, fail_score: float, cfg: Dict[str, Any]
                   ) -> TrialResult:
    """One trial, exception-contained (module-level so the process
    backend can pickle it).  A failing or non-numeric-scoring trial is
    recorded as worst-possible, not fatal — one bad sampled config must
    not lose the whole search (ray.tune's failed-trial tolerance)."""
    cfg = finalize_config(cfg)
    try:
        out = trainable(dict(cfg))
        if isinstance(out, tuple):
            score, extra = out
        else:
            score, extra = out, {}
        score = float(score)
    except Exception as e:
        logger.warning("trial failed for config %s: %s", cfg, e)
        return TrialResult(cfg, fail_score, {"error": str(e)})
    return TrialResult(cfg, score, extra)


class SearchEngine:
    """Run trials over a search space, keep the best by metric.

    ``trainable(config) -> float | (float, extra_dict)`` — like a
    ray.tune trainable's final reported metric.
    """

    def __init__(self, search_space: Dict[str, Any], metric_mode: str = "min",
                 num_samples: int = 1, max_parallel: int = 1, seed: int = 42,
                 search_alg: Any = "random", backend: str = "thread",
                 n_startup: Optional[int] = None):
        """``search_alg``: "random" (i.i.d. sampling, grid dims expanded
        exhaustively), "tpe" (sequential model-based, search/tpe.py), or
        ANY object with ``propose(history) -> config`` (the pluggable
        hook — history is a list of (raw_config, score) pairs).
        ``backend``:
          - "thread" (default): trials are jitted programs that release
            the GIL;
          - "process": host-heavy picklable trainables;
          - "device": thread pool with each trial PINNED to a mesh
            device round-robin (``jax.default_device``) — K trials run
            on K devices concurrently, the TPU-native replacement for
            the reference's Ray-actor scale-out
            (RayTuneSearchEngine.py:28);
          - "vmap": the whole population is ONE vmapped jitted program
            (search/population.py) — the trainable must be a pure
            jax-traceable ``fn(numeric_cfg, **shared) -> score``.
        ``n_startup``: random trials before TPE kicks in.
        """
        self.search_space = search_space
        self.metric_mode = metric_mode
        self.num_samples = num_samples
        self.max_parallel = max(1, max_parallel)
        self.seed = seed
        self.search_alg = search_alg
        self.backend = backend
        self.n_startup = n_startup
        self.results: List[TrialResult] = []

    def _configs(self) -> List[Dict[str, Any]]:
        rng = random.Random(self.seed)
        configs = []
        for grid_cfg in expand_grid(self.search_space):
            for _ in range(self.num_samples):
                configs.append(sample_config(grid_cfg, rng))
        return configs

    def _budget(self) -> int:
        return len(expand_grid(self.search_space)) * self.num_samples

    def _pool(self):
        if self.backend == "process":
            return cf.ProcessPoolExecutor(self.max_parallel)
        return cf.ThreadPoolExecutor(self.max_parallel)

    def _run_batch(self, trainable, configs) -> List[TrialResult]:
        import functools
        import pickle
        from concurrent.futures.process import BrokenProcessPool

        fail_score = float("-inf") if self.metric_mode == "max" \
            else float("inf")
        one = functools.partial(_run_one_trial, trainable, fail_score)

        if self.backend == "vmap":
            return self._run_vmap(trainable, configs, fail_score)
        if self.backend == "device":
            return self._run_device(one, configs)
        if self.max_parallel == 1 or len(configs) == 1:
            return [one(c) for c in configs]
        if self.backend == "process":
            try:
                with self._pool() as pool:
                    return list(pool.map(one, configs))
            except (AttributeError, TypeError, ImportError,
                    ModuleNotFoundError, pickle.PicklingError,
                    BrokenProcessPool, OSError) as e:
                # unpicklable trainable/results (closures, live models) or
                # a crashed worker — degrade to threads.  NOTE: trials
                # dispatched before the error may rerun; the process
                # backend is for module-level pure trainables.
                logger.warning("process pool unusable (%s); running "
                               "trials in threads", e)
        with cf.ThreadPoolExecutor(self.max_parallel) as pool:
            return list(pool.map(one, configs))

    def _run_device(self, one, configs) -> List[TrialResult]:
        """Round-robin trial→device placement over the mesh: K
        concurrent trials occupy K devices (each trial's jitted programs
        compile and run on its pinned device via jax.default_device)."""
        import jax

        from analytics_zoo_tpu.core.context import get_zoo_context

        devices = list(get_zoo_context().mesh.devices.flat)
        par = min(self.max_parallel, len(devices)) or 1

        def pinned(i_cfg):
            i, cfg = i_cfg
            dev = devices[i % len(devices)]
            with jax.default_device(dev):
                r = one(cfg)
            r.extra.setdefault("device", str(dev))
            return r

        if par == 1 or len(configs) == 1:
            return [pinned(ic) for ic in enumerate(configs)]
        with cf.ThreadPoolExecutor(par) as pool:
            return list(pool.map(pinned, enumerate(configs)))

    def _run_vmap(self, trainable, configs, fail_score) -> List[TrialResult]:
        """Population-as-a-batch: every config in ONE vmapped program
        (search/population.py).  Grid/structural keys must agree within
        a batch; configs are grouped by their structural signature and
        each group runs as one dispatch."""
        from analytics_zoo_tpu.automl.search.population import (
            is_numeric_hparam, vmapped_trials)

        configs = [finalize_config(c) for c in configs]
        # group by structural signature (same predicate split_config
        # uses, so numpy scalars batch together instead of fragmenting)
        groups: Dict[Any, List[int]] = {}
        for i, c in enumerate(configs):
            sig = tuple(sorted((k, str(v)) for k, v in c.items()
                               if not is_numeric_hparam(v)))
            groups.setdefault(sig, []).append(i)
        results: List[Optional[TrialResult]] = [None] * len(configs)
        for idxs in groups.values():
            batch = [configs[i] for i in idxs]
            try:
                scores = vmapped_trials(trainable, batch)
            except Exception as e:
                logger.warning("vmapped batch failed (%s); scoring as "
                               "failed", e)
                for i in idxs:
                    results[i] = TrialResult(configs[i], fail_score,
                                             {"error": str(e)})
                continue
            for i, s in zip(idxs, scores):
                results[i] = TrialResult(configs[i], float(s))
        return list(results)

    def run(self, trainable: Callable[[Dict[str, Any]], Any]
            ) -> List[TrialResult]:
        if hasattr(self.search_alg, "propose"):
            self.results = self._run_tpe(trainable,
                                         sampler=self.search_alg)
        elif self.search_alg in ("tpe", "bayes", "bayesopt"):
            self.results = self._run_tpe(trainable)
        else:
            self.results = self._run_batch(trainable, self._configs())
        for i, r in enumerate(self.results):
            logger.info("trial %d/%d metric=%.6g", i + 1,
                        len(self.results), r.metric)
        return self.results

    def _run_tpe(self, trainable, sampler=None) -> List[TrialResult]:
        """Sequential model-based search in rounds of ``max_parallel``:
        propose a batch from the TPE sampler, evaluate concurrently,
        feed the scores back.  Proposals are drawn sequentially from one
        seeded rng in the driver thread, so a rerun at the same
        parallelism reproduces the exact trial sequence regardless of
        worker scheduling (within a batch, later proposals don't see
        batch-mates' scores — the standard batching tradeoff)."""
        budget = self._budget()
        if sampler is None:
            sampler = TPESampler(
                self.search_space, mode=self.metric_mode,
                n_startup=self.n_startup if self.n_startup is not None
                else max(4, budget // 4),
                seed=self.seed)
        results: List[TrialResult] = []
        history: List = []
        while len(results) < budget:
            k = min(self.max_parallel, budget - len(results))
            batch = [sampler.propose(history) for _ in range(k)]
            out = self._run_batch(trainable, batch)
            results.extend(out)
            # feed the sampler the RAW proposals (pre-finalize_config),
            # so dependent-bundle keys keep being modeled
            history.extend((raw, r.metric) for raw, r in zip(batch, out))
        return results

    def best(self) -> TrialResult:
        if not self.results:
            raise RuntimeError("run() first")
        ok = [r for r in self.results if "error" not in r.extra]
        if not ok:
            raise RuntimeError(
                f"all {len(self.results)} trials failed; first error: "
                f"{self.results[0].extra.get('error')}")
        key = (max if self.metric_mode == "max" else min)
        return key(ok, key=lambda r: r.metric)


__all__ = ["SearchEngine", "TrialResult", "Recipe", "SmokeRecipe",
           "RandomRecipe", "GridRandomRecipe", "BayesRecipe",
           "MTNetSmokeRecipe", "MTNetGridRandomRecipe", "Choice",
           "RandInt", "Uniform", "LogUniform", "GridSearch",
           "FeatureSubset", "TPESampler", "sample_config", "expand_grid",
           "finalize_config"]
