"""Hyper-parameter search: sampling primitives, recipes, and an
in-process engine with a ray.tune-shaped API.

Reference capability: ``RayTuneSearchEngine`` (automl/search/
RayTuneSearchEngine.py:28) running trials as Ray actors over RayOnSpark.
TPU-native redesign: a trial is a jitted JAX program on the local mesh,
so the engine runs trials in a thread pool in-process — no second
runtime to bootstrap (RayOnSpark's barrier-stage dance,
ray/util/raycontext.py:155-189, is obsolete by construction).  If ray is
installed the same search space works with ray.tune unchanged.
"""

from __future__ import annotations

import concurrent.futures as cf
import itertools
import logging
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

logger = logging.getLogger("analytics_zoo_tpu.automl")


# ---------------------------------------------------------------------------
# sampling primitives (tune.choice / randint / uniform / grid_search)
# ---------------------------------------------------------------------------

class Sampler:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclass
class Choice(Sampler):
    values: Sequence[Any]

    def sample(self, rng):
        return rng.choice(list(self.values))


@dataclass
class RandInt(Sampler):
    low: int
    high: int    # inclusive

    def sample(self, rng):
        return rng.randint(self.low, self.high)


@dataclass
class Uniform(Sampler):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclass
class LogUniform(Sampler):
    low: float
    high: float

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclass
class GridSearch(Sampler):
    """Expanded exhaustively (cartesian with other GridSearch dims)."""

    values: Sequence[Any]


def sample_config(space: Dict[str, Any], rng: random.Random) -> Dict[str, Any]:
    out = {}
    for k, v in space.items():
        if isinstance(v, GridSearch):
            out[k] = rng.choice(list(v.values))
        elif isinstance(v, Sampler):
            out[k] = v.sample(rng)
        else:
            out[k] = v
    return out


def expand_grid(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cartesian product over GridSearch dims (non-grid dims untouched)."""
    grid_keys = [k for k, v in space.items() if isinstance(v, GridSearch)]
    if not grid_keys:
        return [dict(space)]
    combos = itertools.product(*[space[k].values for k in grid_keys])
    out = []
    for combo in combos:
        d = dict(space)
        d.update(dict(zip(grid_keys, combo)))
        out.append(d)
    return out


# ---------------------------------------------------------------------------
# recipes (reference time_sequence_predictor.py:37-334)
# ---------------------------------------------------------------------------

class Recipe:
    """A search space + trial budget."""

    num_samples: int = 1
    training_iteration: int = 10

    def search_space(self, all_available_features: Sequence[str]
                     ) -> Dict[str, Any]:
        raise NotImplementedError


class SmokeRecipe(Recipe):
    """Tiny space to validate the plumbing (reference SmokeRecipe)."""

    num_samples = 1
    training_iteration = 1

    def search_space(self, all_available_features):
        return {
            "selected_features": list(all_available_features),
            "past_seq_len": 2,
            "lstm_1_units": 16,
            "lstm_2_units": 16,
            "dropout": 0.2,
            "lr": 1e-3,
            "batch_size": 32,
            "epochs": 1,
        }


class RandomRecipe(Recipe):
    """Random sampling over the LSTM space (reference RandomRecipe)."""

    def __init__(self, num_rand_samples: int = 1, look_back: int = 2):
        self.num_samples = num_rand_samples
        self.training_iteration = 10
        self.look_back = look_back

    def search_space(self, all_available_features):
        return {
            "selected_features": FeatureSubset(all_available_features),
            "past_seq_len": (RandInt(self.look_back[0], self.look_back[1])
                             if isinstance(self.look_back, (tuple, list))
                             else self.look_back),
            "lstm_1_units": Choice([16, 32, 64, 128]),
            "lstm_2_units": Choice([16, 32, 64]),
            "dropout": Uniform(0.2, 0.5),
            "lr": LogUniform(1e-4, 1e-2),
            "batch_size": Choice([32, 64, 128]),
            "epochs": 5,
        }


class GridRandomRecipe(Recipe):
    """Grid over structure x random over the rest (reference
    GridRandomRecipe)."""

    def __init__(self, num_rand_samples: int = 1, look_back: int = 2):
        self.num_samples = num_rand_samples
        self.training_iteration = 10
        self.look_back = look_back

    def search_space(self, all_available_features):
        return {
            "selected_features": FeatureSubset(all_available_features),
            "past_seq_len": (RandInt(self.look_back[0], self.look_back[1])
                             if isinstance(self.look_back, (tuple, list))
                             else self.look_back),
            "lstm_1_units": GridSearch([16, 64]),
            "lstm_2_units": GridSearch([16, 64]),
            "dropout": Uniform(0.2, 0.5),
            "lr": LogUniform(1e-4, 1e-2),
            "batch_size": Choice([32, 64]),
            "epochs": 5,
        }


@dataclass
class FeatureSubset(Sampler):
    """Random non-empty subset of generated features (the reference's
    per-feature Choice([0,1]) encoding, RayTuneSearchEngine.py)."""

    values: Sequence[str]

    def sample(self, rng):
        vals = list(self.values)
        if not vals:
            return []
        picked = [v for v in vals if rng.random() < 0.5]
        return picked or [rng.choice(vals)]


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

@dataclass
class TrialResult:
    config: Dict[str, Any]
    metric: float
    extra: Dict[str, Any] = field(default_factory=dict)


class SearchEngine:
    """Run trials over a search space, keep the best by metric.

    ``trainable(config) -> float | (float, extra_dict)`` — like a
    ray.tune trainable's final reported metric.
    """

    def __init__(self, search_space: Dict[str, Any], metric_mode: str = "min",
                 num_samples: int = 1, max_parallel: int = 1, seed: int = 42):
        self.search_space = search_space
        self.metric_mode = metric_mode
        self.num_samples = num_samples
        self.max_parallel = max(1, max_parallel)
        self.seed = seed
        self.results: List[TrialResult] = []

    def _configs(self) -> List[Dict[str, Any]]:
        rng = random.Random(self.seed)
        configs = []
        for grid_cfg in expand_grid(self.search_space):
            for _ in range(self.num_samples):
                configs.append(sample_config(grid_cfg, rng))
        return configs

    def run(self, trainable: Callable[[Dict[str, Any]], Any]
            ) -> List[TrialResult]:
        configs = self._configs()
        fail_score = float("-inf") if self.metric_mode == "max" \
            else float("inf")

        def one(cfg):
            # a failing trial is recorded as worst-possible, not fatal —
            # one bad sampled config must not lose the whole search
            # (ray.tune's failed-trial tolerance)
            try:
                out = trainable(dict(cfg))
            except Exception as e:
                logger.warning("trial failed for config %s: %s", cfg, e)
                return TrialResult(cfg, fail_score, {"error": str(e)})
            if isinstance(out, tuple):
                score, extra = out
            else:
                score, extra = out, {}
            return TrialResult(cfg, float(score), extra)

        if self.max_parallel == 1:
            self.results = [one(c) for c in configs]
        else:
            with cf.ThreadPoolExecutor(self.max_parallel) as pool:
                self.results = list(pool.map(one, configs))
        for i, r in enumerate(self.results):
            logger.info("trial %d/%d metric=%.6g", i + 1,
                        len(self.results), r.metric)
        return self.results

    def best(self) -> TrialResult:
        if not self.results:
            raise RuntimeError("run() first")
        ok = [r for r in self.results if "error" not in r.extra]
        if not ok:
            raise RuntimeError(
                f"all {len(self.results)} trials failed; first error: "
                f"{self.results[0].extra.get('error')}")
        key = (max if self.metric_mode == "max" else min)
        return key(ok, key=lambda r: r.metric)


__all__ = ["SearchEngine", "TrialResult", "Recipe", "SmokeRecipe",
           "RandomRecipe", "GridRandomRecipe", "Choice", "RandInt",
           "Uniform", "LogUniform", "GridSearch", "FeatureSubset",
           "sample_config", "expand_grid"]
