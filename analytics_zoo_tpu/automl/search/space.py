"""Search-space sampling primitives (tune.choice / randint / uniform /
grid_search equivalents) shared by the random engine and the TPE sampler."""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Dict, List, Sequence

# ---------------------------------------------------------------------------
# sampling primitives (tune.choice / randint / uniform / grid_search)
# ---------------------------------------------------------------------------

class Sampler:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclass
class Choice(Sampler):
    values: Sequence[Any]

    def sample(self, rng):
        return rng.choice(list(self.values))


@dataclass
class RandInt(Sampler):
    low: int
    high: int    # inclusive

    def sample(self, rng):
        return rng.randint(self.low, self.high)


@dataclass
class Uniform(Sampler):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclass
class LogUniform(Sampler):
    low: float
    high: float

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclass
class GridSearch(Sampler):
    """Expanded exhaustively (cartesian with other GridSearch dims)."""

    values: Sequence[Any]


def sample_config(space: Dict[str, Any], rng: random.Random) -> Dict[str, Any]:
    out = {}
    for k, v in space.items():
        if isinstance(v, GridSearch):
            out[k] = rng.choice(list(v.values))
        elif isinstance(v, Sampler):
            out[k] = v.sample(rng)
        else:
            out[k] = v
    return out


def expand_grid(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cartesian product over GridSearch dims (non-grid dims untouched)."""
    grid_keys = [k for k, v in space.items() if isinstance(v, GridSearch)]
    if not grid_keys:
        return [dict(space)]
    combos = itertools.product(*[space[k].values for k in grid_keys])
    out = []
    for combo in combos:
        d = dict(space)
        d.update(dict(zip(grid_keys, combo)))
        out.append(d)
    return out



@dataclass
class FeatureSubset(Sampler):
    """Random non-empty subset of generated features (the reference's
    per-feature Choice([0,1]) encoding, RayTuneSearchEngine.py)."""

    values: Sequence[str]

    def sample(self, rng):
        vals = list(self.values)
        if not vals:
            return []
        picked = [v for v in vals if rng.random() < 0.5]
        return picked or [rng.choice(vals)]




def finalize_config(cfg: Dict[str, Any]) -> Dict[str, Any]:
    """Resolve a sampled config for the trainable: dict-valued ``__*``
    keys are dependent-parameter bundles (e.g. MTNet's (time_step,
    long_num, past_seq_len) triple, which must stay consistent) and are
    flattened into the config."""
    out = {}
    for k, v in cfg.items():
        if k.startswith("__") and isinstance(v, dict):
            out.update(v)
        else:
            out[k] = v
    return out
