"""TPE (tree-structured Parzen estimator) sampler — Bayesian-optimization
search parity with the reference's ray.tune + BayesOpt engine
(reference automl/search/RayTuneSearchEngine.py:25,126-199, which wires
``BayesOptSearch`` into tune).

Design (the hyperopt-style independent TPE, CPU-side, numpy-only):
after ``n_startup`` seeded random trials, observations split into good
(best ``gamma`` fraction) and bad; per dimension we model densities
l(x) over good and g(x) over bad — Gaussian kernels at observed points
for numeric dims, smoothed count ratios for categorical dims, per-item
Bernoulli rates for feature subsets — then draw candidates from l and
keep the one maximizing Σ log l(x)/g(x) (numeric dims; categorical dims
take ONE stochastic draw weighted by the smoothed l/g count ratio, which
discounts merely-often-sampled arms while preserving exploration).
Proposals are a deterministic function of (seed, history), so a search
reruns bit-for-bit at the same parallelism.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Sequence, Tuple

from analytics_zoo_tpu.automl.search.space import (Choice, FeatureSubset,
                                                   GridSearch, LogUniform,
                                                   RandInt, Sampler, Uniform,
                                                   sample_config)


def _gauss_logpdf(x: float, mu: float, sigma: float) -> float:
    z = (x - mu) / sigma
    return -0.5 * z * z - math.log(sigma * math.sqrt(2 * math.pi))


class _NumericDim:
    """TPE over a bounded numeric dim (optionally log-scaled / integer)."""

    def __init__(self, low: float, high: float, log: bool = False,
                 integer: bool = False):
        self.low, self.high = float(low), float(high)
        self.log = log
        self.integer = integer

    def _warp(self, v: float) -> float:
        return math.log(v) if self.log else float(v)

    def _unwarp(self, w: float) -> Any:
        v = math.exp(w) if self.log else w
        v = min(max(v, self.low), self.high)
        return int(round(v)) if self.integer else v

    def _bounds(self) -> Tuple[float, float]:
        return ((math.log(self.low), math.log(self.high)) if self.log
                else (self.low, self.high))

    def _kde_sample(self, pts: List[float], rng: random.Random) -> float:
        lo, hi = self._bounds()
        width = hi - lo or 1.0
        if not pts or rng.random() < 0.2:     # prior mass keeps exploring
            return rng.uniform(lo, hi)
        mu = pts[rng.randrange(len(pts))]
        sigma = max(width / max(len(pts), 2), 1e-6 * width)
        return min(max(rng.gauss(mu, sigma), lo), hi)

    def _kde_logpdf(self, w: float, pts: List[float]) -> float:
        lo, hi = self._bounds()
        width = hi - lo or 1.0
        base = -math.log(width)               # uniform prior component
        if not pts:
            return base
        sigma = max(width / max(len(pts), 2), 1e-6 * width)
        comps = [_gauss_logpdf(w, mu, sigma) for mu in pts]
        comps.append(base)                    # mixture with the prior
        m = max(comps)
        return m + math.log(sum(math.exp(c - m) for c in comps)
                            / len(comps))

    def propose(self, good: List[Any], bad: List[Any], rng: random.Random,
                n_candidates: int) -> Any:
        g_pts = [self._warp(v) for v in good]
        b_pts = [self._warp(v) for v in bad]
        best_w, best_score = None, -math.inf
        for _ in range(n_candidates):
            w = self._kde_sample(g_pts, rng)
            score = self._kde_logpdf(w, g_pts) - self._kde_logpdf(w, b_pts)
            if score > best_score:
                best_w, best_score = w, score
        return self._unwarp(best_w)


class _CategoricalDim:
    """TPE over a finite choice set: smoothed good/bad count ratio."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def propose(self, good: List[Any], bad: List[Any], rng: random.Random,
                n_candidates: int) -> Any:
        del n_candidates  # categorical: single stochastic draw (below)

        def key(v):
            return repr(v)

        n_vals = len(self.values)
        gcnt = {key(v): 0.0 for v in self.values}
        bcnt = dict(gcnt)
        for v in good:
            gcnt[key(v)] = gcnt.get(key(v), 0.0) + 1.0
        for v in bad:
            bcnt[key(v)] = bcnt.get(key(v), 0.0) + 1.0
        # ONE draw ∝ the smoothed l(v)/g(v) ratio — the ratio (not raw
        # good counts) discounts arms that are merely sampled often, and
        # a single stochastic draw (not argmax-of-many) preserves
        # exploration; the +1 priors keep every arm live and a 10%
        # uniform floor guarantees escape
        if rng.random() < 0.1:
            return self.values[rng.randrange(n_vals)]
        weights = [((gcnt[key(v)] + 1.0) / (len(good) + n_vals))
                   / ((bcnt[key(v)] + 1.0) / (len(bad) + n_vals))
                   for v in self.values]
        r = rng.random() * sum(weights)
        acc = 0.0
        for v, w in zip(self.values, weights):
            acc += w
            if r <= acc:
                return v
        return self.values[-1]


class _SubsetDim:
    """TPE over feature subsets: independent per-item Bernoulli rates."""

    def __init__(self, values: Sequence[str]):
        self.values = list(values)

    def propose(self, good: List[Any], bad: List[Any], rng: random.Random,
                n_candidates: int) -> List[str]:
        if not self.values:
            return []
        n_good = max(len(good), 1)
        n_bad = max(len(bad), 1)
        picked = []
        for item in self.values:
            g = sum(1 for s in good if item in s)
            b = sum(1 for s in bad if item in s)
            # smoothed inclusion odds: favor items over-represented in
            # good configs, keep a floor/ceiling for exploration
            p_good = (g + 1.0) / (n_good + 2.0)
            p_bad = (b + 1.0) / (n_bad + 2.0)
            p = min(max(p_good * 0.5 / max(p_bad, 1e-6), 0.1), 0.9)
            if rng.random() < p:
                picked.append(item)
        return picked or [self.values[rng.randrange(len(self.values))]]


def _dim_for(sampler: Sampler):
    if isinstance(sampler, FeatureSubset):
        return _SubsetDim(sampler.values)
    if isinstance(sampler, (Choice, GridSearch)):
        return _CategoricalDim(sampler.values)
    if isinstance(sampler, RandInt):
        return _NumericDim(sampler.low, sampler.high, integer=True)
    if isinstance(sampler, LogUniform):
        return _NumericDim(sampler.low, sampler.high, log=True)
    if isinstance(sampler, Uniform):
        return _NumericDim(sampler.low, sampler.high)
    return None


class TPESampler:
    """Propose configs for a search space given observed (config, metric)
    history.  ``mode``: "min" | "max"."""

    def __init__(self, space: Dict[str, Any], mode: str = "min",
                 n_startup: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: int = 42):
        self.space = space
        self.mode = mode
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self.dims = {k: _dim_for(v) for k, v in space.items()
                     if isinstance(v, Sampler)}

    def propose(self, history: List[Tuple[Dict[str, Any], float]]
                ) -> Dict[str, Any]:
        finite = [(c, m) for c, m in history if math.isfinite(m)]
        if len(finite) < self.n_startup:
            return sample_config(self.space, self.rng)
        ordered = sorted(finite, key=lambda cm: cm[1],
                         reverse=(self.mode == "max"))
        n_good = max(1, int(math.ceil(self.gamma * len(ordered))))
        good = [c for c, _ in ordered[:n_good]]
        bad = [c for c, _ in ordered[n_good:]] or good
        out = {}
        for k, v in self.space.items():
            dim = self.dims.get(k)
            if dim is None:
                out[k] = v if not isinstance(v, Sampler) \
                    else v.sample(self.rng)
                continue
            out[k] = dim.propose([c[k] for c in good if k in c],
                                 [c[k] for c in bad if k in c],
                                 self.rng, self.n_candidates)
        return out
