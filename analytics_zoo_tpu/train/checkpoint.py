"""Checkpoint / resume.

Reference capability: BigDL epoch snapshots via ``setCheckpoint``
(Topology.scala:246-256), timestamped checkpoint dirs + latest-by-mtime
recovery (Topology.scala:1293-1306,1519-1536), retry-from-checkpoint
(Topology.scala:1179-1261 — implemented in Estimator.fit).

Format: our own compact layout — one ``.npz`` holding every array leaf
keyed by its pytree path, plus a pickled treedef skeleton.  This avoids a
hard orbax dependency while staying host-portable.

``CheckpointManager.save_async`` implements the ``async_checkpoint``
config knob: the device→host copy happens synchronously (cheap — it only
waits for in-flight steps touching the buffers), then serialization + the
atomic rename run on a background thread so the training loop resumes
immediately.  ``wait()`` joins the in-flight write and re-raises its
error, and is called before any restore so readers never race a writer.
"""

from __future__ import annotations

import os
import pickle
import re
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

_LEAF = "__leaf__"


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def save_pytree(path: str, tree: Any) -> None:
    """Atomically save a pytree of arrays/scalars to ``path`` (.zoo dir)."""
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(tree)
    arrays: Dict[str, np.ndarray] = {}
    for i, (p, leaf) in enumerate(leaves_with_paths):
        arrays[f"{i:06d}|{_path_str(p)}"] = np.asarray(leaf)
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    # atomic write: tmp + rename
    dirname = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, __treedef__=np.frombuffer(
                pickle.dumps(treedef), dtype=np.uint8), **arrays)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_pytree(path: str) -> Any:
    with np.load(path, allow_pickle=False) as z:
        treedef = pickle.loads(z["__treedef__"].tobytes())
        keys = sorted((k for k in z.files if k != "__treedef__"),
                      key=lambda k: int(k.split("|", 1)[0]))
        leaves = [z[k] for k in keys]
    return jax.tree_util.tree_unflatten(treedef, leaves)


class CheckpointManager:
    """Numbered snapshots in a directory + latest-recovery.

    Mirrors the reference's timestamped dirs / ``getLatestFile`` recovery
    (Topology.scala:1519-1536) with explicit step numbering instead of
    mtimes (mtimes lie on object stores).
    """

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)
        self._writer: Optional[threading.Thread] = None
        self._writer_err: Optional[BaseException] = None

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt_{step:010d}.npz")

    def save(self, step: int, tree: Any) -> str:
        self.wait()
        path = self._path(step)
        save_pytree(path, tree)
        self._gc()
        return path

    def save_async(self, step: int, tree: Any) -> str:
        """Write the snapshot on a background thread (``async_checkpoint``).

        The pytree is materialised to host numpy up front, so the caller
        may keep mutating/donating its device buffers immediately.
        """
        self.wait()
        host_tree = jax.tree_util.tree_map(np.asarray, jax.device_get(tree))
        path = self._path(step)

        def write():
            try:
                save_pytree(path, host_tree)
                self._gc()
            except BaseException as e:
                self._writer_err = e

        self._writer = threading.Thread(target=write, daemon=True)
        self._writer.start()
        return path

    def wait(self, raise_errors: bool = True) -> None:
        """Join any in-flight async write; re-raise its failure (unless
        ``raise_errors=False`` — used by restore, where a stale write
        error must not mask recovery from an older good snapshot)."""
        if self._writer is not None:
            self._writer.join()
            self._writer = None
        if self._writer_err is not None:
            err, self._writer_err = self._writer_err, None
            if raise_errors:
                raise err
            import logging
            logging.getLogger("analytics_zoo_tpu.train").warning(
                "ignoring failed async checkpoint write during restore: %s",
                err)

    def all_steps(self) -> List[int]:
        steps = []
        for fn in os.listdir(self.directory):
            m = re.fullmatch(r"ckpt_(\d+)\.npz", fn)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def restore(self, step: Optional[int] = None) -> Tuple[int, Any]:
        self.wait(raise_errors=False)
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        return step, load_pytree(self._path(step))

    def _gc(self) -> None:
        steps = self.all_steps()
        for s in steps[: max(0, len(steps) - self.keep)]:
            try:
                os.unlink(self._path(s))
            except OSError:
                pass
