"""Checkpoint / resume.

Reference capability: BigDL epoch snapshots via ``setCheckpoint``
(Topology.scala:246-256), timestamped checkpoint dirs + latest-by-mtime
recovery (Topology.scala:1293-1306,1519-1536), retry-from-checkpoint
(Topology.scala:1179-1261 — implemented in Estimator.fit).

Format: our own compact layout — one ``.npz`` holding every array leaf
keyed by its pytree path, plus a pickled treedef skeleton.  This avoids a
hard orbax dependency while staying host-portable.

Durability (docs/ROBUSTNESS.md):

- **atomic + synced writes** — serialize into a tempfile in the target
  directory, ``fsync`` the file, ``os.replace`` onto the final path, then
  ``fsync`` the directory, so a preemption at ANY instant leaves either
  the old file set or the new one — never a torn archive at the final
  path.
- **per-leaf CRC32 manifest** — stored inside the archive
  (``__manifest__``); ``load_pytree(verify=True)`` recomputes every
  leaf's CRC and raises :class:`CheckpointCorruptError` on mismatch, so
  silent bit-rot (or a torn file written by a non-atomic writer) is
  detected, not trained on.
- **verified fallback restore** — ``CheckpointManager.restore()`` walks
  snapshots newest→oldest, quarantines torn/corrupt files (renamed to
  ``*.corrupt``, counted in ``robust/ckpt_quarantined``) and recovers
  from the newest *intact* one; corruption is only fatal when no intact
  snapshot remains.
- **retried writes** — transient I/O errors during a save go through a
  ``RetryPolicy`` before surfacing.

``CheckpointManager.save_async`` implements the ``async_checkpoint``
config knob: the device→host copy happens synchronously (cheap — it only
waits for in-flight steps touching the buffers), then serialization + the
atomic rename run on a background thread so the training loop resumes
immediately.  ``wait()`` joins the in-flight write and re-raises its
error, and is called before any restore so readers never race a writer.
GC runs under ``_fs_lock`` so a background writer's GC can never hand a
concurrent ``all_steps()``/``restore()`` a half-deleted directory.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import re
import shutil
import tempfile
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from analytics_zoo_tpu.observe import metrics as obs
from analytics_zoo_tpu.observe.trace import TRACER
from analytics_zoo_tpu.robust import HostLostError, RetryPolicy, faults

logger = logging.getLogger("analytics_zoo_tpu.train")

_LEAF = "__leaf__"
_MANIFEST = "__manifest__"
_TREEDEF = "__treedef__"
FORMAT_VERSION = 2


class CheckpointCorruptError(RuntimeError):
    """The archive is readable but fails integrity verification
    (missing manifest entries or a per-leaf CRC32 mismatch)."""


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _crc32(a: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(a).tobytes()) & 0xFFFFFFFF


def _fsync_dir(dirname: str) -> None:
    """Persist the rename itself (POSIX: a rename is durable only once
    the containing directory is synced)."""
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return  # e.g. object-store FUSE mounts without dir handles
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_npz(path: str, arrays: Dict[str, np.ndarray],
                fsync: bool = True,
                fault_site: str = "checkpoint.write") -> None:
    """Write an ``.npz`` archive atomically + durably: tmp file → fsync →
    ``os.replace`` → directory fsync.  ``fault_site`` is the chaos hook
    consulted between the flush and the rename — a planned exception
    simulates dying mid-write (final path untouched), ``action="torn"``
    simulates a NON-atomic writer dying (the final path receives a
    truncated archive)."""
    dirname = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(dirname, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        plan = faults.fire(fault_site)
        if plan is not None:
            if plan.exc is not None:
                raise plan.exc
            if plan.action == "torn":
                frac = plan.payload if plan.payload is not None else 0.5
                size = os.path.getsize(tmp)
                with open(tmp, "r+b") as f:
                    f.truncate(max(1, int(size * float(frac))))
        os.replace(tmp, path)
        if fsync:
            _fsync_dir(dirname)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _atomic_text(path: str, text: str, fsync: bool = True) -> None:
    """Small sidecar files (manifest / commit markers) written with the
    same tmp → fsync → rename discipline as the archives."""
    dirname = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(dirname, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if fsync:
            _fsync_dir(dirname)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def save_pytree(path: str, tree: Any, fsync: bool = True) -> None:
    """Atomically + durably save a pytree of arrays/scalars to ``path``.

    The archive embeds a JSON manifest with a CRC32 per leaf so readers
    can verify integrity end-to-end (``load_pytree(verify=True)``).
    """
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(tree)
    arrays: Dict[str, np.ndarray] = {}
    manifest_leaves: Dict[str, Dict[str, Any]] = {}
    for i, (p, leaf) in enumerate(leaves_with_paths):
        key = f"{i:06d}|{_path_str(p)}"
        a = np.asarray(leaf)
        arrays[key] = a
        manifest_leaves[key] = {"crc32": _crc32(a), "dtype": str(a.dtype),
                                "shape": list(a.shape)}
    treedef_bytes = np.frombuffer(pickle.dumps(treedef), dtype=np.uint8)
    manifest_leaves[_TREEDEF] = {"crc32": _crc32(treedef_bytes),
                                 "dtype": "uint8",
                                 "shape": [int(treedef_bytes.size)]}
    manifest = {"version": FORMAT_VERSION, "leaves": manifest_leaves}
    manifest_bytes = np.frombuffer(
        json.dumps(manifest, sort_keys=True).encode("utf-8"), dtype=np.uint8)
    _atomic_npz(path, {_TREEDEF: treedef_bytes, _MANIFEST: manifest_bytes,
                       **arrays}, fsync=fsync)


def load_pytree(path: str, verify: bool = True) -> Any:
    """Load a pytree archive; with ``verify`` (default) recompute every
    leaf's CRC32 against the embedded manifest.  Archives written before
    the manifest existed (format v1) load unverified with a debug log —
    old snapshots stay restorable."""
    with np.load(path, allow_pickle=False) as z:
        manifest = None
        if _MANIFEST in z.files:
            manifest = json.loads(z[_MANIFEST].tobytes().decode("utf-8"))
        elif verify:
            logger.debug("checkpoint %s has no integrity manifest "
                         "(pre-v%d format); loading unverified",
                         path, FORMAT_VERSION)
        treedef_bytes = z[_TREEDEF]
        keys = sorted((k for k in z.files
                       if k not in (_TREEDEF, _MANIFEST)),
                      key=lambda k: int(k.split("|", 1)[0]))
        if verify and manifest is not None:
            expected = manifest.get("leaves", {})
            want = set(expected) - {_TREEDEF}
            have = set(keys)
            if want != have:
                raise CheckpointCorruptError(
                    f"{path}: manifest/leaf mismatch "
                    f"(missing={sorted(want - have)[:3]} "
                    f"extra={sorted(have - want)[:3]})")
            if _TREEDEF in expected and \
                    _crc32(treedef_bytes) != expected[_TREEDEF]["crc32"]:
                raise CheckpointCorruptError(f"{path}: treedef CRC mismatch")
        leaves = []
        for k in keys:
            a = z[k]
            if verify and manifest is not None:
                if _crc32(a) != manifest["leaves"][k]["crc32"]:
                    raise CheckpointCorruptError(
                        f"{path}: CRC mismatch on leaf {k!r}")
            leaves.append(a)
        treedef = pickle.loads(treedef_bytes.tobytes())
    return jax.tree_util.tree_unflatten(treedef, leaves)


class CheckpointManager:
    """Numbered snapshots in a directory + verified latest-recovery.

    Mirrors the reference's timestamped dirs / ``getLatestFile`` recovery
    (Topology.scala:1519-1536) with explicit step numbering instead of
    mtimes (mtimes lie on object stores).
    """

    def __init__(self, directory: str, keep: int = 3, verify: bool = True,
                 retry: Optional[RetryPolicy] = None):
        self.directory = directory
        self.keep = keep
        self.verify = verify
        os.makedirs(directory, exist_ok=True)
        self._writer: Optional[threading.Thread] = None
        self._writer_err: Optional[BaseException] = None
        # serializes GC deletes against foreground listings/restores so
        # a background save_async's GC can never hand all_steps() or
        # restore() a half-deleted directory
        self._fs_lock = threading.Lock()
        self._retry = retry or RetryPolicy(
            max_attempts=3, base_delay_s=0.05, max_delay_s=1.0,
            retry_on=(OSError,), name="checkpoint_write")

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt_{step:010d}.npz")

    def save(self, step: int, tree: Any) -> str:
        self.wait()
        path = self._path(step)
        sp = TRACER.start("checkpoint/save", step=step, mode="sync")
        try:
            with obs.time_stage("checkpoint_seconds", op="save",
                                flat="checkpoint/write_sync"):
                self._retry.call(save_pytree, path, tree)
        except BaseException as e:
            obs.count("checkpoint_total", op="save", status="error")
            sp.end(status="error", error=str(e))
            raise
        obs.count("checkpoint_total", op="save", status="ok")
        sp.end()
        self._gc()
        return path

    def save_async(self, step: int, tree: Any) -> str:
        """Write the snapshot on a background thread (``async_checkpoint``).

        The pytree is materialised to host numpy up front, so the caller
        may keep mutating/donating its device buffers immediately.
        """
        self.wait()
        host_tree = jax.tree_util.tree_map(np.asarray, jax.device_get(tree))
        path = self._path(step)

        sp = TRACER.start("checkpoint/save", step=step, mode="async")

        def write():
            try:
                with obs.time_stage("checkpoint_seconds", op="save_async",
                                    flat="checkpoint/write_async"):
                    self._retry.call(save_pytree, path, host_tree)
                obs.count("checkpoint_total", op="save_async", status="ok")
                sp.end()
                self._gc()
            except BaseException as e:
                obs.count("checkpoint_total", op="save_async",
                          status="error")
                sp.end(status="error", error=str(e))
                self._writer_err = e  # zoolint: disable=THR-SHARED-MUT(wait() joins the writer thread before reading _writer_err; join() is the happens-before edge)

        self._writer = threading.Thread(target=write, daemon=True)
        self._writer.start()
        return path

    def wait(self, raise_errors: bool = True) -> None:
        """Join any in-flight async write; re-raise its failure (unless
        ``raise_errors=False`` — used by restore, where a stale write
        error must not mask recovery from an older good snapshot)."""
        if self._writer is not None:
            self._writer.join()
            self._writer = None
        if self._writer_err is not None:
            err, self._writer_err = self._writer_err, None
            if raise_errors:
                raise err
            logger.warning(
                "ignoring failed async checkpoint write during restore: %s",
                err)

    def all_steps(self) -> List[int]:
        steps = []
        with self._fs_lock:
            for fn in os.listdir(self.directory):
                m = re.fullmatch(r"ckpt_(\d+)\.npz", fn)
                if m:
                    steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _quarantine(self, step: int, err: BaseException) -> None:
        """Move a torn/corrupt snapshot out of the recovery set (kept on
        disk for post-mortem, renamed so it can never be restored)."""
        path = self._path(step)
        try:
            with self._fs_lock:
                os.replace(path, path + ".corrupt")
        except OSError:
            pass
        obs.count("checkpoint_total", op="restore", status="quarantined",
                  flat="robust/ckpt_quarantined")
        logger.warning("checkpoint step %d is corrupt (%s: %s); quarantined "
                       "as %s.corrupt — falling back to an older snapshot",
                       step, type(err).__name__, err, os.path.basename(path))

    def restore(self, step: Optional[int] = None) -> Tuple[int, Any]:
        """Load a snapshot, verifying integrity (``verify``).

        With ``step=None`` (latest), torn or corrupt snapshots are
        quarantined and the newest *intact* one wins; corruption is only
        fatal when nothing intact remains.  An explicitly requested step
        is loaded strictly — its corruption raises.
        """
        self.wait(raise_errors=False)
        sp = TRACER.start("checkpoint/restore", step=step)
        with obs.time_stage("checkpoint_seconds", op="restore"):
            try:
                if step is not None:
                    tree = load_pytree(self._path(step), verify=self.verify)
                    obs.count("checkpoint_total", op="restore", status="ok")
                    sp.end(restored_step=step)
                    return step, tree
                steps = self.all_steps()
                if not steps:
                    raise FileNotFoundError(
                        f"no checkpoints in {self.directory}")
                for s in reversed(steps):
                    try:
                        tree = load_pytree(self._path(s),
                                           verify=self.verify)
                        obs.count("checkpoint_total", op="restore",
                                  status="ok")
                        sp.end(restored_step=s)
                        return s, tree
                    except KeyboardInterrupt:
                        raise
                    except Exception as e:
                        # torn zip (BadZipFile/EOF), CRC mismatch,
                        # unpickle noise — every flavour of "this file
                        # is not a usable snapshot"
                        self._quarantine(s, e)
                raise FileNotFoundError(
                    f"no intact checkpoints in {self.directory} "
                    f"({len(steps)} candidate(s) quarantined)")
            except BaseException as e:
                obs.count("checkpoint_total", op="restore", status="error")
                sp.end(status="error", error=str(e))
                raise

    def _gc(self) -> None:
        with self._fs_lock:
            steps = []
            for fn in os.listdir(self.directory):
                m = re.fullmatch(r"ckpt_(\d+)\.npz", fn)
                if m:
                    steps.append(int(m.group(1)))
            steps.sort()
            for s in steps[: max(0, len(steps) - self.keep)]:
                try:
                    os.unlink(self._path(s))
                except OSError:
                    pass


# --------------------------------------------------------------------------
# Distributed (multi-controller) checkpoints
# --------------------------------------------------------------------------

_COMMITTED = "COMMITTED"
_MANIFEST_FILE = "MANIFEST.json"
_DSTEP_RE = re.compile(r"dstep_(\d+)")
_SHARD_RE = re.compile(r"shard_(\d+)of(\d+)\.npz")
DIST_FORMAT_VERSION = 1


def has_distributed_layout(directory: str) -> bool:
    """True if ``directory`` holds per-step shard directories written by
    :class:`DistributedCheckpointManager` — the sniff `set_checkpoint`
    uses so a single-process run can resume a multi-process run's
    checkpoints (elastic restore) without being told the format."""
    try:
        return any(_DSTEP_RE.fullmatch(fn)
                   for fn in os.listdir(directory))
    except OSError:
        return False


def _shard_name(pid: int, nproc: int) -> str:
    return f"shard_{pid:05d}of{nproc:05d}.npz"


def _norm_index(idx, shape) -> Tuple[Tuple[int, int], ...]:
    """A device's index tuple (slices) → hashable ((start, stop), ...)."""
    out = []
    for sl, dim in zip(idx, shape):
        start, stop, _ = sl.indices(dim)
        out.append((int(start), int(stop)))
    return tuple(out)


def _global_plan(leaves_with_paths, process_of_device):
    """The chunk layout of a checkpoint tree — who owns which slice.

    Every process computes this identically from the SPMD-identical tree
    (no coordination needed): a sharded ``jax.Array`` splits into one
    chunk per DISTINCT device index (replica copies collapse), owned by
    the process of the lowest-id device holding it; host leaves and
    fully-replicated arrays are one full chunk owned by process 0.

    Returns ``(leaf_specs, chunk_table)``: the JSON-ready manifest
    section keyed by leaf, and a flat ``[(chunk_key, owner, leaf_pos,
    norm_index)]`` list for writers.
    """
    leaf_specs: Dict[str, Dict[str, Any]] = {}
    chunk_table: List[Tuple[str, int, int, Tuple]] = []
    cid = 0
    for i, (p, leaf) in enumerate(leaves_with_paths):
        key = f"{i:06d}|{_path_str(p)}"
        shape = tuple(int(d) for d in getattr(leaf, "shape",
                                              np.shape(leaf)))
        dtype = str(getattr(leaf, "dtype", np.asarray(leaf).dtype))
        sharding = getattr(leaf, "sharding", None)
        chunks = []
        if sharding is not None and \
                not getattr(leaf, "is_fully_replicated", True):
            groups: Dict[Tuple, list] = {}
            for dev, idx in sharding.devices_indices_map(shape).items():
                groups.setdefault(_norm_index(idx, shape), []).append(dev)
            for norm in sorted(groups):
                owner = int(process_of_device(
                    min(groups[norm], key=lambda d: d.id)))
                ckey = f"c{cid:06d}"
                cid += 1
                chunks.append({"id": ckey, "shard": owner,
                               "index": [list(se) for se in norm]})
                chunk_table.append((ckey, owner, i, norm))
            from analytics_zoo_tpu.parallel.sharding import spec_str
            spec = spec_str(leaf)
        else:
            norm = tuple((0, d) for d in shape)
            ckey = f"c{cid:06d}"
            cid += 1
            chunks.append({"id": ckey, "shard": 0,
                           "index": [list(se) for se in norm]})
            chunk_table.append((ckey, 0, i, norm))
            spec = "replicated"
        leaf_specs[key] = {"dtype": dtype, "shape": list(shape),
                           "sharding": spec, "chunks": chunks}
    return leaf_specs, chunk_table


def _extract_chunk(leaf, norm_index) -> np.ndarray:
    """The host bytes of one owned chunk.  Only chunks this process owns
    are ever extracted, so the matching addressable shard must exist."""
    sharding = getattr(leaf, "sharding", None)
    if sharding is None or getattr(leaf, "is_fully_replicated", True):
        return np.asarray(leaf)
    for s in leaf.addressable_shards:
        if _norm_index(s.index, leaf.shape) == norm_index:
            return np.asarray(s.data)
    raise RuntimeError(
        f"owned chunk {norm_index} has no addressable shard on this "
        "process — sharding/ownership plan out of sync")


def _read_shard_header(path: str) -> Dict[str, Any]:
    """The embedded JSON manifest of one shard archive (lazy member read
    — does not load the chunk arrays)."""
    with np.load(path, allow_pickle=False) as z:
        if _MANIFEST not in z.files:
            raise CheckpointCorruptError(f"{path}: no embedded manifest")
        return json.loads(z[_MANIFEST].tobytes().decode("utf-8"))


def _fire_host_lost() -> None:
    plan = faults.fire("dist.host_lost")
    if plan is not None:
        if plan.exc is not None:
            raise plan.exc
        raise HostLostError(
            "planned host loss (chaos site dist.host_lost)")


class DistributedCheckpointManager(CheckpointManager):
    """Sharded multi-controller checkpoints with a two-phase commit.

    Layout — one directory per step::

        dstep_0000000042/
          shard_00000of00002.npz   # chunks owned by process 0 (+ treedef)
          shard_00001of00002.npz   # chunks owned by process 1
          MANIFEST.json            # process 0, after the write barrier
          COMMITTED                # process 0, last — the commit point

    Each process writes ONLY the chunks it owns (computed identically
    everywhere by :func:`_global_plan`, no coordination), embedding the
    full global layout plus per-chunk CRC32s in its shard.  Commit is
    two-phase: all processes write+fsync their shard, meet a deadline
    barrier, then process 0 merges the CRC tables into ``MANIFEST.json``
    and publishes ``COMMITTED``; a second barrier releases everyone.  A
    host dying at ANY instant leaves either a fully committed step or an
    uncommitted directory that restore quarantines — never a torn
    "latest".  A peer missing a barrier for ``dist_barrier_timeout_s``
    surfaces as :class:`~analytics_zoo_tpu.robust.HostLostError` instead
    of a hang.

    Restore is **elastic** (reshard-on-restore): the manifest records
    the *saved* topology, restore reassembles the full global tree on
    every host from whatever shards were recorded — so a checkpoint
    written by 2 processes resumes at 1 or 4 — and the Estimator re-lays
    it onto the live mesh via ``parallel.sharding.tree_put_global``.
    ``save_preempt`` (SIGTERM path) writes the local shard plus a
    ``PREEMPT_<pid>`` marker with NO barrier — restore accepts a step
    with preempt markers when every recorded chunk verifies.

    The constructor seams (``process_index`` / ``process_count`` /
    ``process_of_device`` / ``barrier``) exist so single-process tests
    can simulate several writers over one virtual device mesh.
    """

    def __init__(self, directory: str, keep: int = 3, verify: bool = True,
                 retry: Optional[RetryPolicy] = None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 process_of_device=None,
                 barrier=None,
                 barrier_timeout_s: Optional[float] = None):
        super().__init__(directory, keep=keep, verify=verify, retry=retry)
        self._pid = process_index
        self._nproc = process_count
        self._proc_of_dev = process_of_device or \
            (lambda d: d.process_index)
        self._barrier = barrier
        self._barrier_timeout_s = barrier_timeout_s

    # -- topology ----------------------------------------------------------

    @property
    def process_index(self) -> int:
        return jax.process_index() if self._pid is None else self._pid

    @property
    def process_count(self) -> int:
        return jax.process_count() if self._nproc is None else self._nproc

    def _barrier_wait(self, name: str, phase: str) -> float:
        fn = self._barrier
        if fn is None:
            from analytics_zoo_tpu.core.context import dist_barrier as fn
        waited = fn(name, timeout_s=self._barrier_timeout_s,
                    phase=phase) or 0.0
        obs.observe("checkpoint_barrier_wait_ms", waited * 1000.0,
                    flat=f"checkpoint/barrier_{phase}_ms", phase=phase)
        return waited

    # -- save --------------------------------------------------------------

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"dstep_{step:010d}")

    def _path(self, step: int) -> str:  # quarantine/rename target
        return self._step_dir(step)

    def _prepare(self, step: int, tree: Any):
        """Flatten + plan + pull owned chunks to host (synchronous part
        of every save — after it returns the caller may mutate/donate
        the device buffers)."""
        _fire_host_lost()
        leaves_with_paths, treedef = \
            jax.tree_util.tree_flatten_with_path(tree)
        pid, nproc = self.process_index, self.process_count
        leaf_specs, chunk_table = _global_plan(leaves_with_paths,
                                               self._proc_of_dev)
        arrays: Dict[str, np.ndarray] = {}
        crcs: Dict[str, int] = {}
        for ckey, owner, leaf_pos, norm in chunk_table:
            if owner != pid:
                continue
            a = _extract_chunk(leaves_with_paths[leaf_pos][1], norm)
            arrays[ckey] = a
            crcs[ckey] = _crc32(a)
        header: Dict[str, Any] = {
            "version": FORMAT_VERSION, "dist_version": DIST_FORMAT_VERSION,
            "step": int(step), "process_index": pid,
            "process_count": nproc, "treedef_shard": 0,
            "leaves": leaf_specs, "chunk_crcs": crcs,
        }
        if pid == 0:
            treedef_bytes = np.frombuffer(pickle.dumps(treedef),
                                          dtype=np.uint8)
            arrays[_TREEDEF] = treedef_bytes
            header["treedef_crc"] = _crc32(treedef_bytes)
        arrays[_MANIFEST] = np.frombuffer(
            json.dumps(header, sort_keys=True).encode("utf-8"),
            dtype=np.uint8)
        return header, arrays

    def _write_shard(self, step: int, header, arrays) -> str:
        d = self._step_dir(step)
        path = os.path.join(d, _shard_name(header["process_index"],
                                           header["process_count"]))
        self._retry.call(_atomic_npz, path, arrays,
                         fault_site="dist.shard_write")
        obs.observe("checkpoint_shard_bytes", os.path.getsize(path),
                    flat="checkpoint/shard_bytes")
        return path

    def _write_manifest_and_commit(self, step: int, header) -> None:
        """Process 0, after the write barrier: merge every shard's CRC
        table into the global manifest, then publish the commit point."""
        d = self._step_dir(step)
        nproc = header["process_count"]
        merged = dict(header)
        merged["chunk_crcs"] = {}
        merged["shards"] = []
        for p in range(nproc):
            sp = os.path.join(d, _shard_name(p, nproc))
            h = _read_shard_header(sp)
            if h["process_count"] != nproc or h["step"] != step:
                raise CheckpointCorruptError(
                    f"{sp}: shard header disagrees with the save "
                    f"(step {h['step']}/{step}, "
                    f"nproc {h['process_count']}/{nproc})")
            merged["chunk_crcs"].update(h["chunk_crcs"])
            merged["shards"].append(os.path.basename(sp))
        _atomic_text(os.path.join(d, _MANIFEST_FILE),
                     json.dumps(merged, sort_keys=True, indent=1))
        _atomic_text(os.path.join(d, _COMMITTED),
                     json.dumps({"step": int(step),
                                 "process_count": nproc}))

    def _write_and_commit(self, step: int, prepared,
                          preempt: bool = False) -> None:
        header, arrays = prepared
        self._write_shard(step, header, arrays)
        if preempt:
            # no barrier on the SIGTERM path — peers may already be gone
            _atomic_text(
                os.path.join(self._step_dir(step),
                             f"PREEMPT_{header['process_index']:05d}"),
                json.dumps({"step": int(step),
                            "process_index": header["process_index"],
                            "process_count": header["process_count"]}))
            return
        self._barrier_wait(f"zoo_ckpt_write_{step}", "write")
        if header["process_index"] == 0:
            self._write_manifest_and_commit(step, header)
        self._barrier_wait(f"zoo_ckpt_commit_{step}", "commit")

    def save(self, step: int, tree: Any) -> str:
        self.wait()
        sp = TRACER.start("checkpoint/save", step=step, mode="dist")
        try:
            with obs.time_stage("checkpoint_seconds", op="save_dist",
                                flat="checkpoint/write_dist"):
                prepared = self._prepare(step, tree)
                self._write_and_commit(step, prepared)
        except BaseException as e:
            obs.count("checkpoint_total", op="save_dist", status="error")
            sp.end(status="error", error=str(e))
            raise
        obs.count("checkpoint_total", op="save_dist", status="ok")
        sp.end()
        self._gc()
        return self._step_dir(step)

    def save_async(self, step: int, tree: Any) -> str:
        """Chunk extraction happens synchronously (cheap — host copies of
        owned slices only); the write + both barriers + commit run on a
        background thread on EVERY process symmetrically, so the barriers
        still meet.  The barrier deadline bounds how long a background
        writer can hang on a dead peer; the error lands in
        ``_writer_err`` and surfaces at the next ``wait()``."""
        self.wait()
        prepared = self._prepare(step, tree)
        sp = TRACER.start("checkpoint/save", step=step, mode="dist_async")

        def write():
            try:
                with obs.time_stage("checkpoint_seconds",
                                    op="save_dist_async",
                                    flat="checkpoint/write_dist_async"):
                    self._write_and_commit(step, prepared)
                obs.count("checkpoint_total", op="save_dist_async",
                          status="ok")
                sp.end()
                self._gc()
            except BaseException as e:
                obs.count("checkpoint_total", op="save_dist_async",
                          status="error")
                sp.end(status="error", error=str(e))
                self._writer_err = e  # zoolint: disable=THR-SHARED-MUT(wait() joins the writer thread before reading _writer_err; join() is the happens-before edge)

        self._writer = threading.Thread(target=write, daemon=True)
        self._writer.start()
        return self._step_dir(step)

    def save_preempt(self, step: int, tree: Any) -> str:
        """Final flush on SIGTERM: local shard + ``PREEMPT_<pid>`` marker,
        no barriers (peers are dying too, on their own schedule).  The
        step is restorable iff every recorded chunk landed — restore
        verifies and otherwise falls back to the newest committed step."""
        self.wait(raise_errors=False)
        sp = TRACER.start("checkpoint/save", step=step,
                          mode="dist_preempt")
        try:
            with obs.time_stage("checkpoint_seconds", op="save_preempt",
                                flat="checkpoint/write_preempt"):
                prepared = self._prepare(step, tree)
                self._write_and_commit(step, prepared, preempt=True)
        except BaseException as e:
            obs.count("checkpoint_total", op="save_preempt",
                      status="error")
            sp.end(status="error", error=str(e))
            raise
        obs.count("checkpoint_total", op="save_preempt", status="ok")
        sp.end()
        return self._step_dir(step)

    # -- listing / gc ------------------------------------------------------

    def all_steps(self) -> List[int]:
        steps = []
        with self._fs_lock:
            try:
                entries = os.listdir(self.directory)
            except OSError:
                return []
            for fn in entries:
                m = _DSTEP_RE.fullmatch(fn)
                if m:
                    steps.append(int(m.group(1)))
        return sorted(steps)

    def _gc(self) -> None:
        # one mutator: process 0 owns deletes (shared filesystem)
        if self.process_index != 0:
            return
        with self._fs_lock:
            steps = []
            for fn in os.listdir(self.directory):
                m = _DSTEP_RE.fullmatch(fn)
                if m:
                    steps.append(int(m.group(1)))
            steps.sort()
            for s in steps[: max(0, len(steps) - self.keep)]:
                shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def _quarantine(self, step: int, err: BaseException) -> None:
        d = self._step_dir(step)
        if self.process_index == 0:
            try:
                with self._fs_lock:
                    os.replace(d, d + ".corrupt")
            except OSError:
                pass
        obs.count("checkpoint_total", op="restore", status="quarantined",
                  flat="robust/ckpt_quarantined")
        logger.warning(
            "distributed checkpoint step %d is unusable (%s: %s); "
            "quarantined as %s.corrupt — falling back to an older step",
            step, type(err).__name__, err, os.path.basename(d))

    # -- restore -----------------------------------------------------------

    def _load_step(self, step: int) -> Any:
        d = self._step_dir(step)
        if not os.path.isdir(d):
            raise FileNotFoundError(d)
        entries = os.listdir(d)
        committed = _COMMITTED in entries
        preempt = any(fn.startswith("PREEMPT_") for fn in entries)
        if not committed and not preempt:
            raise CheckpointCorruptError(
                f"{d}: no COMMITTED marker and no preempt flush — a host "
                "died mid-save")
        manifest = None
        if _MANIFEST_FILE in entries:
            with open(os.path.join(d, _MANIFEST_FILE)) as f:
                manifest = json.load(f)
        if manifest is None:
            # preempt flush: no global manifest — every shard embeds the
            # identical global layout, so any present shard serves
            shard_files = sorted(fn for fn in entries
                                 if _SHARD_RE.fullmatch(fn))
            if not shard_files:
                raise CheckpointCorruptError(f"{d}: no shards")
            manifest = _read_shard_header(os.path.join(d, shard_files[0]))
        nproc_rec = int(manifest["process_count"])
        if int(manifest["step"]) != step:
            raise CheckpointCorruptError(
                f"{d}: manifest step {manifest['step']} != {step}")
        leaves_spec = manifest["leaves"]
        # merged CRC table when the global manifest has one (committed
        # saves); shard-embedded tables are checked either way
        global_crcs = manifest.get("chunk_crcs", {}) \
            if _MANIFEST_FILE in entries else {}

        # chunks grouped by owning shard so each archive opens once
        by_shard: Dict[int, List[Tuple[str, str]]] = {}
        for key, ent in leaves_spec.items():
            for ch in ent["chunks"]:
                by_shard.setdefault(int(ch["shard"]), []).append(
                    (ch["id"], key))
        treedef_shard = int(manifest.get("treedef_shard", 0))
        by_shard.setdefault(treedef_shard, [])

        chunk_data: Dict[str, np.ndarray] = {}
        treedef_bytes = None
        for p, wanted in sorted(by_shard.items()):
            path = os.path.join(d, _shard_name(p, nproc_rec))
            if not os.path.exists(path):
                raise CheckpointCorruptError(
                    f"{d}: missing shard {p}/{nproc_rec}")
            with np.load(path, allow_pickle=False) as z:
                if _MANIFEST not in z.files:
                    raise CheckpointCorruptError(
                        f"{path}: no embedded manifest")
                h = json.loads(z[_MANIFEST].tobytes().decode("utf-8"))
                if h["process_count"] != nproc_rec or h["step"] != step:
                    raise CheckpointCorruptError(
                        f"{path}: shard header disagrees with manifest "
                        f"(step {h['step']}/{step}, "
                        f"nproc {h['process_count']}/{nproc_rec})")
                for ckey, _leaf in wanted:
                    if ckey not in z.files:
                        raise CheckpointCorruptError(
                            f"{path}: chunk {ckey} missing")
                    a = z[ckey]
                    if self.verify:
                        crc = _crc32(a)
                        want = h.get("chunk_crcs", {}).get(ckey)
                        if want is not None and crc != want:
                            raise CheckpointCorruptError(
                                f"{path}: CRC mismatch on chunk {ckey}")
                        gwant = global_crcs.get(ckey)
                        if gwant is not None and crc != gwant:
                            raise CheckpointCorruptError(
                                f"{path}: chunk {ckey} disagrees with "
                                "the global manifest CRC")
                    chunk_data[ckey] = a
                if p == treedef_shard:
                    if _TREEDEF not in z.files:
                        raise CheckpointCorruptError(
                            f"{path}: treedef missing")
                    treedef_bytes = z[_TREEDEF]
                    want = manifest.get("treedef_crc")
                    if self.verify and want is not None and \
                            _crc32(treedef_bytes) != want:
                        raise CheckpointCorruptError(
                            f"{path}: treedef CRC mismatch")

        # reassemble the global tree (elastic: independent of the live
        # process count — the Estimator re-lays it onto the current mesh)
        leaves = []
        for key in sorted(leaves_spec,
                          key=lambda k: int(k.split("|", 1)[0])):
            ent = leaves_spec[key]
            shape = tuple(ent["shape"])
            chunks = ent["chunks"]
            first = chunk_data[chunks[0]["id"]]
            if len(chunks) == 1:
                out = first.reshape(shape)
            else:
                out = np.empty(shape, dtype=first.dtype)
                covered = 0
                for ch in chunks:
                    a = chunk_data[ch["id"]]
                    sl = tuple(slice(s, e) for s, e in ch["index"])
                    out[sl] = a
                    covered += int(a.size)
                if covered != out.size:
                    raise CheckpointCorruptError(
                        f"{d}: leaf {key!r} chunks cover {covered} of "
                        f"{out.size} elements")
            leaves.append(out)
        treedef = pickle.loads(treedef_bytes.tobytes())
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def restore(self, step: Optional[int] = None) -> Tuple[int, Any]:
        """Newest restorable step wins: a step is eligible iff it has a
        ``COMMITTED`` marker (normal save) or any ``PREEMPT_*`` marker
        (SIGTERM flush), and every recorded chunk is present and CRC-
        clean.  Anything else is quarantined (renamed ``*.corrupt`` by
        process 0) and the walk continues to the next-older step; an
        explicitly requested step is loaded strictly."""
        self.wait(raise_errors=False)
        _fire_host_lost()
        sp = TRACER.start("checkpoint/restore", step=step, mode="dist")
        with obs.time_stage("checkpoint_seconds", op="restore"):
            try:
                if step is not None:
                    tree = self._load_step(step)
                    obs.count("checkpoint_total", op="restore",
                              status="ok")
                    sp.end(restored_step=step)
                    return step, tree
                steps = self.all_steps()
                if not steps:
                    raise FileNotFoundError(
                        f"no checkpoints in {self.directory}")
                for s in reversed(steps):
                    try:
                        tree = self._load_step(s)
                        obs.count("checkpoint_total", op="restore",
                                  status="ok")
                        sp.end(restored_step=s)
                        return s, tree
                    except KeyboardInterrupt:
                        raise
                    except Exception as e:
                        self._quarantine(s, e)
                raise FileNotFoundError(
                    f"no intact checkpoints in {self.directory} "
                    f"({len(steps)} candidate(s) quarantined)")
            except BaseException as e:
                obs.count("checkpoint_total", op="restore",
                          status="error")
                sp.end(status="error", error=str(e))
                raise
