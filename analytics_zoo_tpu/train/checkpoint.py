"""Checkpoint / resume.

Reference capability: BigDL epoch snapshots via ``setCheckpoint``
(Topology.scala:246-256), timestamped checkpoint dirs + latest-by-mtime
recovery (Topology.scala:1293-1306,1519-1536), retry-from-checkpoint
(Topology.scala:1179-1261 — implemented in Estimator.fit).

Format: our own compact layout — one ``.npz`` holding every array leaf
keyed by its pytree path, plus a pickled treedef skeleton.  This avoids a
hard orbax dependency while staying host-portable.

Durability (docs/ROBUSTNESS.md):

- **atomic + synced writes** — serialize into a tempfile in the target
  directory, ``fsync`` the file, ``os.replace`` onto the final path, then
  ``fsync`` the directory, so a preemption at ANY instant leaves either
  the old file set or the new one — never a torn archive at the final
  path.
- **per-leaf CRC32 manifest** — stored inside the archive
  (``__manifest__``); ``load_pytree(verify=True)`` recomputes every
  leaf's CRC and raises :class:`CheckpointCorruptError` on mismatch, so
  silent bit-rot (or a torn file written by a non-atomic writer) is
  detected, not trained on.
- **verified fallback restore** — ``CheckpointManager.restore()`` walks
  snapshots newest→oldest, quarantines torn/corrupt files (renamed to
  ``*.corrupt``, counted in ``robust/ckpt_quarantined``) and recovers
  from the newest *intact* one; corruption is only fatal when no intact
  snapshot remains.
- **retried writes** — transient I/O errors during a save go through a
  ``RetryPolicy`` before surfacing.

``CheckpointManager.save_async`` implements the ``async_checkpoint``
config knob: the device→host copy happens synchronously (cheap — it only
waits for in-flight steps touching the buffers), then serialization + the
atomic rename run on a background thread so the training loop resumes
immediately.  ``wait()`` joins the in-flight write and re-raises its
error, and is called before any restore so readers never race a writer.
GC runs under ``_fs_lock`` so a background writer's GC can never hand a
concurrent ``all_steps()``/``restore()`` a half-deleted directory.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import re
import tempfile
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from analytics_zoo_tpu.observe import metrics as obs
from analytics_zoo_tpu.observe.trace import TRACER
from analytics_zoo_tpu.robust import RetryPolicy, faults

logger = logging.getLogger("analytics_zoo_tpu.train")

_LEAF = "__leaf__"
_MANIFEST = "__manifest__"
_TREEDEF = "__treedef__"
FORMAT_VERSION = 2


class CheckpointCorruptError(RuntimeError):
    """The archive is readable but fails integrity verification
    (missing manifest entries or a per-leaf CRC32 mismatch)."""


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _crc32(a: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(a).tobytes()) & 0xFFFFFFFF


def _fsync_dir(dirname: str) -> None:
    """Persist the rename itself (POSIX: a rename is durable only once
    the containing directory is synced)."""
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return  # e.g. object-store FUSE mounts without dir handles
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def save_pytree(path: str, tree: Any, fsync: bool = True) -> None:
    """Atomically + durably save a pytree of arrays/scalars to ``path``.

    The archive embeds a JSON manifest with a CRC32 per leaf so readers
    can verify integrity end-to-end (``load_pytree(verify=True)``).
    """
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(tree)
    arrays: Dict[str, np.ndarray] = {}
    manifest_leaves: Dict[str, Dict[str, Any]] = {}
    for i, (p, leaf) in enumerate(leaves_with_paths):
        key = f"{i:06d}|{_path_str(p)}"
        a = np.asarray(leaf)
        arrays[key] = a
        manifest_leaves[key] = {"crc32": _crc32(a), "dtype": str(a.dtype),
                                "shape": list(a.shape)}
    treedef_bytes = np.frombuffer(pickle.dumps(treedef), dtype=np.uint8)
    manifest_leaves[_TREEDEF] = {"crc32": _crc32(treedef_bytes),
                                 "dtype": "uint8",
                                 "shape": [int(treedef_bytes.size)]}
    manifest = {"version": FORMAT_VERSION, "leaves": manifest_leaves}
    manifest_bytes = np.frombuffer(
        json.dumps(manifest, sort_keys=True).encode("utf-8"), dtype=np.uint8)
    dirname = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(dirname, exist_ok=True)
    # atomic write: tmp + fsync + rename + dir fsync
    fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **{_TREEDEF: treedef_bytes,
                           _MANIFEST: manifest_bytes}, **arrays)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        plan = faults.fire("checkpoint.write")
        if plan is not None:
            if plan.exc is not None:
                raise plan.exc
            if plan.action == "torn":
                # simulate a non-atomic writer dying mid-write: the final
                # path receives a truncated archive
                frac = plan.payload if plan.payload is not None else 0.5
                size = os.path.getsize(tmp)
                with open(tmp, "r+b") as f:
                    f.truncate(max(1, int(size * float(frac))))
        os.replace(tmp, path)
        if fsync:
            _fsync_dir(dirname)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_pytree(path: str, verify: bool = True) -> Any:
    """Load a pytree archive; with ``verify`` (default) recompute every
    leaf's CRC32 against the embedded manifest.  Archives written before
    the manifest existed (format v1) load unverified with a debug log —
    old snapshots stay restorable."""
    with np.load(path, allow_pickle=False) as z:
        manifest = None
        if _MANIFEST in z.files:
            manifest = json.loads(z[_MANIFEST].tobytes().decode("utf-8"))
        elif verify:
            logger.debug("checkpoint %s has no integrity manifest "
                         "(pre-v%d format); loading unverified",
                         path, FORMAT_VERSION)
        treedef_bytes = z[_TREEDEF]
        keys = sorted((k for k in z.files
                       if k not in (_TREEDEF, _MANIFEST)),
                      key=lambda k: int(k.split("|", 1)[0]))
        if verify and manifest is not None:
            expected = manifest.get("leaves", {})
            want = set(expected) - {_TREEDEF}
            have = set(keys)
            if want != have:
                raise CheckpointCorruptError(
                    f"{path}: manifest/leaf mismatch "
                    f"(missing={sorted(want - have)[:3]} "
                    f"extra={sorted(have - want)[:3]})")
            if _TREEDEF in expected and \
                    _crc32(treedef_bytes) != expected[_TREEDEF]["crc32"]:
                raise CheckpointCorruptError(f"{path}: treedef CRC mismatch")
        leaves = []
        for k in keys:
            a = z[k]
            if verify and manifest is not None:
                if _crc32(a) != manifest["leaves"][k]["crc32"]:
                    raise CheckpointCorruptError(
                        f"{path}: CRC mismatch on leaf {k!r}")
            leaves.append(a)
        treedef = pickle.loads(treedef_bytes.tobytes())
    return jax.tree_util.tree_unflatten(treedef, leaves)


class CheckpointManager:
    """Numbered snapshots in a directory + verified latest-recovery.

    Mirrors the reference's timestamped dirs / ``getLatestFile`` recovery
    (Topology.scala:1519-1536) with explicit step numbering instead of
    mtimes (mtimes lie on object stores).
    """

    def __init__(self, directory: str, keep: int = 3, verify: bool = True,
                 retry: Optional[RetryPolicy] = None):
        self.directory = directory
        self.keep = keep
        self.verify = verify
        os.makedirs(directory, exist_ok=True)
        self._writer: Optional[threading.Thread] = None
        self._writer_err: Optional[BaseException] = None
        # serializes GC deletes against foreground listings/restores so
        # a background save_async's GC can never hand all_steps() or
        # restore() a half-deleted directory
        self._fs_lock = threading.Lock()
        self._retry = retry or RetryPolicy(
            max_attempts=3, base_delay_s=0.05, max_delay_s=1.0,
            retry_on=(OSError,), name="checkpoint_write")

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt_{step:010d}.npz")

    def save(self, step: int, tree: Any) -> str:
        self.wait()
        path = self._path(step)
        sp = TRACER.start("checkpoint/save", step=step, mode="sync")
        try:
            with obs.time_stage("checkpoint_seconds", op="save",
                                flat="checkpoint/write_sync"):
                self._retry.call(save_pytree, path, tree)
        except BaseException as e:
            obs.count("checkpoint_total", op="save", status="error")
            sp.end(status="error", error=str(e))
            raise
        obs.count("checkpoint_total", op="save", status="ok")
        sp.end()
        self._gc()
        return path

    def save_async(self, step: int, tree: Any) -> str:
        """Write the snapshot on a background thread (``async_checkpoint``).

        The pytree is materialised to host numpy up front, so the caller
        may keep mutating/donating its device buffers immediately.
        """
        self.wait()
        host_tree = jax.tree_util.tree_map(np.asarray, jax.device_get(tree))
        path = self._path(step)

        sp = TRACER.start("checkpoint/save", step=step, mode="async")

        def write():
            try:
                with obs.time_stage("checkpoint_seconds", op="save_async",
                                    flat="checkpoint/write_async"):
                    self._retry.call(save_pytree, path, host_tree)
                obs.count("checkpoint_total", op="save_async", status="ok")
                sp.end()
                self._gc()
            except BaseException as e:
                obs.count("checkpoint_total", op="save_async",
                          status="error")
                sp.end(status="error", error=str(e))
                self._writer_err = e  # zoolint: disable=THR-SHARED-MUT(wait() joins the writer thread before reading _writer_err; join() is the happens-before edge)

        self._writer = threading.Thread(target=write, daemon=True)
        self._writer.start()
        return path

    def wait(self, raise_errors: bool = True) -> None:
        """Join any in-flight async write; re-raise its failure (unless
        ``raise_errors=False`` — used by restore, where a stale write
        error must not mask recovery from an older good snapshot)."""
        if self._writer is not None:
            self._writer.join()
            self._writer = None
        if self._writer_err is not None:
            err, self._writer_err = self._writer_err, None
            if raise_errors:
                raise err
            logger.warning(
                "ignoring failed async checkpoint write during restore: %s",
                err)

    def all_steps(self) -> List[int]:
        steps = []
        with self._fs_lock:
            for fn in os.listdir(self.directory):
                m = re.fullmatch(r"ckpt_(\d+)\.npz", fn)
                if m:
                    steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _quarantine(self, step: int, err: BaseException) -> None:
        """Move a torn/corrupt snapshot out of the recovery set (kept on
        disk for post-mortem, renamed so it can never be restored)."""
        path = self._path(step)
        try:
            with self._fs_lock:
                os.replace(path, path + ".corrupt")
        except OSError:
            pass
        obs.count("checkpoint_total", op="restore", status="quarantined",
                  flat="robust/ckpt_quarantined")
        logger.warning("checkpoint step %d is corrupt (%s: %s); quarantined "
                       "as %s.corrupt — falling back to an older snapshot",
                       step, type(err).__name__, err, os.path.basename(path))

    def restore(self, step: Optional[int] = None) -> Tuple[int, Any]:
        """Load a snapshot, verifying integrity (``verify``).

        With ``step=None`` (latest), torn or corrupt snapshots are
        quarantined and the newest *intact* one wins; corruption is only
        fatal when nothing intact remains.  An explicitly requested step
        is loaded strictly — its corruption raises.
        """
        self.wait(raise_errors=False)
        sp = TRACER.start("checkpoint/restore", step=step)
        with obs.time_stage("checkpoint_seconds", op="restore"):
            try:
                if step is not None:
                    tree = load_pytree(self._path(step), verify=self.verify)
                    obs.count("checkpoint_total", op="restore", status="ok")
                    sp.end(restored_step=step)
                    return step, tree
                steps = self.all_steps()
                if not steps:
                    raise FileNotFoundError(
                        f"no checkpoints in {self.directory}")
                for s in reversed(steps):
                    try:
                        tree = load_pytree(self._path(s),
                                           verify=self.verify)
                        obs.count("checkpoint_total", op="restore",
                                  status="ok")
                        sp.end(restored_step=s)
                        return s, tree
                    except KeyboardInterrupt:
                        raise
                    except Exception as e:
                        # torn zip (BadZipFile/EOF), CRC mismatch,
                        # unpickle noise — every flavour of "this file
                        # is not a usable snapshot"
                        self._quarantine(s, e)
                raise FileNotFoundError(
                    f"no intact checkpoints in {self.directory} "
                    f"({len(steps)} candidate(s) quarantined)")
            except BaseException as e:
                obs.count("checkpoint_total", op="restore", status="error")
                sp.end(status="error", error=str(e))
                raise

    def _gc(self) -> None:
        with self._fs_lock:
            steps = []
            for fn in os.listdir(self.directory):
                m = re.fullmatch(r"ckpt_(\d+)\.npz", fn)
                if m:
                    steps.append(int(m.group(1)))
            steps.sort()
            for s in steps[: max(0, len(steps) - self.keep)]:
                try:
                    os.unlink(self._path(s))
                except OSError:
                    pass
