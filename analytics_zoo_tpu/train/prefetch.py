"""Input-pipeline overlap: background-thread batch prefetch.

Reference capability: the reference keeps workers fed via Spark partition
locality + PMEM-cached partitions (feature/FeatureSet.scala:690-722) and
multi-threaded minibatch assembly (feature/common/MTSampleToMiniBatch.scala).

TPU-native design: the host prepares the *next* sharded batch (fancy
indexing, per-batch transforms, ``device_put`` onto the mesh) on a
background thread while the device executes the current step.  JAX
dispatch is asynchronous, so one batch of lookahead is enough to hide
host work; the queue depth is the ``data_prefetch`` config knob.

The consumer's blocked-on-queue time aggregates under the
``prefetch/consumer_wait`` timer (core/profiling.TIMERS): a large total
relative to step time means the input pipeline — not the device — is the
bottleneck, which is exactly when the DEVICE cache level
(data/featureset.CacheLevel) pays off.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

from analytics_zoo_tpu.core.profiling import timeit
from analytics_zoo_tpu.observe import metrics as obs
from analytics_zoo_tpu.robust import faults

logger = logging.getLogger("analytics_zoo_tpu.train")

_SENTINEL = object()


class PrefetchIterator:
    """Wraps an iterator, running it (plus an optional per-item transform)
    on a daemon thread ``depth`` items ahead of the consumer.

    Exceptions raised by the producer are re-raised at the consumption
    point, so failure-retry semantics in the Estimator are preserved.
    """

    def __init__(self, it: Iterable, transform: Optional[Callable] = None,
                 depth: int = 2):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        # producer thread writes _err, the consumer polls it from
        # __next__/_get while the producer may still be running — a
        # plain unlocked field here is the THR-SHARED-MUT race zoolint
        # flags (the reader could act on a half-observed error state)
        self._err_lock = threading.Lock()
        self._err: Optional[BaseException] = None
        self._stop = threading.Event()
        self._closed = False
        self._close_lock = threading.Lock()

        def put_retry(obj) -> bool:
            """Deliver unless the consumer called close(); never drop."""
            stalled = False
            while not self._stop.is_set():
                try:
                    self._q.put(obj, timeout=0.1)
                    # qsize() is advisory under concurrency, which is
                    # fine for a gauge; the flat mirror keeps legacy
                    # health() readers working
                    obs.set_gauge("prefetch_queue_depth", self._q.qsize(),
                                  flat="prefetch/queue_depth")
                    return True
                except queue.Full:
                    if not stalled:
                        # count once per item: the producer outran the
                        # consumer by a full queue — the inverse signal
                        # of prefetch/consumer_wait
                        stalled = True
                        obs.count("prefetch_producer_stalls_total",
                                  flat="prefetch/producer_stalls")
                    continue
            return False

        def run():
            try:
                for item in it:
                    # chaos hook: a planned producer crash surfaces here
                    # exactly like a real data-pipeline failure would
                    faults.inject("prefetch.producer")
                    if transform is not None:
                        item = transform(item)
                    if not put_retry(item):
                        return
            except BaseException as e:  # propagate to consumer
                with self._err_lock:
                    self._err = e
            finally:
                # The sentinel must NEVER be dropped: with a short epoch
                # the whole dataset fits in the queue while the consumer
                # sits in its first XLA compile (minutes for big models),
                # and a dropped sentinel leaves the consumer blocked on
                # get() forever once it drains the queue.  Consumers must
                # close() on early exit (the Estimator does) so this
                # retry terminates on abandonment.
                put_retry(_SENTINEL)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        # poll rather than block indefinitely: if the producer thread is
        # gone without its sentinel having been consumed (belt to the
        # suspenders above), surface its error / end-of-iteration instead
        # of hanging the training loop
        with timeit("prefetch/consumer_wait"):
            item = self._get()
        obs.set_gauge("prefetch_queue_depth", self._q.qsize(),
                      flat="prefetch/queue_depth")
        if item is _SENTINEL:
            self._thread.join()
            err = self._error()
            if err is not None:
                raise err
            raise StopIteration
        return item

    def _error(self) -> Optional[BaseException]:
        with self._err_lock:
            return self._err

    def _get(self) -> Any:
        while True:
            try:
                return self._q.get(timeout=1.0)
            except queue.Empty:
                if not self._thread.is_alive():
                    try:
                        return self._q.get_nowait()
                    except queue.Empty:
                        err = self._error()
                        if err is not None:
                            raise err
                        raise StopIteration from None

    def close(self, timeout: float = 5.0) -> None:
        """Stop the producer (used on early exit / exception paths).

        Idempotent.  Drains the queue so a producer blocked in
        ``put_retry`` can observe the stop flag, then joins it with a
        bounded ``timeout``: a producer wedged inside the source
        iterator or transform (which Python threads cannot interrupt)
        is surfaced as a logged warning instead of silently leaking —
        the daemon flag still guarantees it cannot block interpreter
        exit."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        deadline = None
        while self._thread.is_alive():
            # keep draining: the producer may have re-filled the queue
            # between our drain and its next put_retry attempt
            try:
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.05)
            if not self._thread.is_alive():
                break
            import time as _time
            if deadline is None:
                deadline = _time.monotonic() + timeout
            elif _time.monotonic() > deadline:
                logger.warning(
                    "prefetch producer did not stop within %.1fs of "
                    "close(); it is wedged in the source iterator or "
                    "transform and will be abandoned (daemon thread)",
                    timeout)
                break


def prefetch(it: Iterable, transform: Optional[Callable] = None,
             depth: int = 2) -> Iterable:
    """``depth<=0`` disables prefetching (synchronous passthrough)."""
    if depth <= 0:
        if transform is None:
            return it
        return (transform(x) for x in it)
    return PrefetchIterator(it, transform=transform, depth=depth)
