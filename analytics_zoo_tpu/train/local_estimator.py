"""LocalEstimator — single-device training facade.

Reference capability: ``LocalEstimator`` (pipeline/estimator/
LocalEstimator.scala:39-250) clones the model per CPU thread and runs a
hand-rolled parallel fwd/bwd with gradient averaging.  On TPU that whole
mechanism is the degenerate case of the SPMD Estimator (XLA owns the
chip's parallelism), so this class IS the Estimator pinned to a
one-device mesh — same fit/evaluate/predict, zero second code path.
"""

from __future__ import annotations

from typing import Optional

from analytics_zoo_tpu.core.context import ZooContext
from analytics_zoo_tpu.train.estimator import Estimator

__all__ = ["LocalEstimator"]


class LocalEstimator(Estimator):
    """Estimator on a 1-device mesh (reference LocalEstimator.scala:39)."""

    def __init__(self, model, optimizer="adam", loss="mse", metrics=None,
                 ctx: Optional[ZooContext] = None, **kw):
        import jax
        import numpy as np
        from jax.sharding import Mesh

        from analytics_zoo_tpu.core.context import get_zoo_context

        base = ctx or get_zoo_context()
        # pin to the first device only — a true local run regardless of
        # how many devices the global context spans
        mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
        local_ctx = ZooContext(
            config=base.config.replace(mesh_shape=(1,),
                                       mesh_axis_names=("data",)),
            mesh=mesh)
        super().__init__(model, optimizer=optimizer, loss=loss,
                         metrics=metrics, ctx=local_ctx, **kw)
