"""Estimator — the training/eval/predict engine.

Reference capability: ``InternalDistriOptimizer`` + ``Estimator``
(api/keras/models/Topology.scala:962-1598, pipeline/estimator/Estimator.scala:65).
The reference runs 2 Spark jobs per iteration (forward/backward tasks, then
a block-manager gradient shuffle + weight re-broadcast, wp-bigdl.md:113-160).

TPU-native design: ONE jitted SPMD step.  Parameters/optimizer state are
replicated over the mesh; the batch is sharded along the ``data`` axis;
``jax.grad`` of a sharded-batch loss makes XLA insert a single fused
all-reduce (psum) over ICI for the gradients.  The whole iteration —
forward, backward, allreduce, optimizer update — is one XLA program with
donated buffers, so there is no parameter server, no task launch overhead,
and no host round-trip in the hot loop.

Also carried over, re-designed:
- trigger-driven validation/checkpointing (`ZooTrigger` → core.triggers)
- failure retry from latest checkpoint within a sliding time window
  (Topology.scala:1179-1261; ``bigdl.failure.retryTimes`` /
  ``retryTimeInterval`` sysprops → ``failure_retry_times`` /
  ``failure_retry_interval_s`` config knobs)
- LocalEstimator (LocalEstimator.scala:39) collapses into this same class
  on a 1-device mesh.

TPU perf levers wired through config:
- ``compute_dtype="bfloat16"`` — mixed precision: master params/opt-state
  stay float32, forward/backward run in bf16 (MXU-native), loss and
  gradients accumulate in float32.
- ``data_prefetch`` — background-thread batch prep + device_put overlap
  (train/prefetch.py) so the chip never waits on host indexing.
- ``async_checkpoint`` — snapshot writes happen off-thread
  (train/checkpoint.py::save_async).
"""

from __future__ import annotations

import logging
import math
import pickle
import signal
import threading
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analytics_zoo_tpu.core.context import (ZooContext, dist_barrier,
                                             explicit_prng_key,
                                             get_zoo_context)
from analytics_zoo_tpu.core.profiling import TIMERS, timeit
from analytics_zoo_tpu.core.triggers import (EveryEpoch, Trigger, TriggerState)
from analytics_zoo_tpu.observe import metrics as obs
from analytics_zoo_tpu.observe.export import publish_to_summary, to_prometheus
from analytics_zoo_tpu.observe.trace import TRACER
from analytics_zoo_tpu.nn import metrics as metrics_lib
from analytics_zoo_tpu.nn import objectives
from analytics_zoo_tpu.robust import (HostLostError, RetryPolicy,
                                      TrainingPreempted, faults)
from analytics_zoo_tpu.train import checkpoint as ckpt_lib
from analytics_zoo_tpu.train import optimizers as optim_lib
from analytics_zoo_tpu.train import prefetch as prefetch_lib

logger = logging.getLogger("analytics_zoo_tpu.train")


def _as_list(x) -> List[np.ndarray]:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _cast_floats(tree, dtype):
    """Cast floating leaves of a pytree to ``dtype`` (ints/bools pass)."""
    return jax.tree_util.tree_map(
        lambda a: a.astype(dtype)
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a, tree)


def _cast_like(tree, ref):
    """Cast every leaf of ``tree`` to the dtype of the matching ``ref``
    leaf (restores e.g. float32 BN statistics after a bf16 forward)."""
    return jax.tree_util.tree_map(
        lambda a, r: a.astype(jnp.asarray(r).dtype), tree, ref)


def resident_epoch_indices(rng, n: int, shuffle: bool = True,
                           pair_structured: bool = False):
    """Gather order for ONE device-resident epoch over ``n`` rows.

    Runs INSIDE the jitted epoch body (``jax.random.permutation`` on
    device): every row index in [0, n) appears exactly once — full
    epoch coverage, unlike a with-replacement sampler.  Pair-structured
    losses (rank_hinge) permute (pos, neg) couples so partners stay
    adjacent (mirrors the host path's pair shuffle).  The tail beyond
    ``steps * batch`` is dropped by the caller's fori bound, matching
    the host path's ``drop_remainder`` — reshuffling each epoch varies
    which rows fall there.
    """
    if not shuffle:
        return jnp.arange(n)
    if pair_structured:
        pairs = jax.random.permutation(rng, n // 2)
        idx = jnp.stack([pairs * 2, pairs * 2 + 1], axis=1).reshape(-1)
        if n % 2:
            idx = jnp.concatenate([idx, jnp.asarray([n - 1])])
        return idx
    return jax.random.permutation(rng, n)


class Estimator:
    """fit/evaluate/predict over a model following the Layer protocol."""

    def __init__(self, model, optimizer="adam", loss="mse",
                 metrics: Optional[Sequence] = None,
                 ctx: Optional[ZooContext] = None,
                 grad_clip_norm: Optional[float] = None,
                 grad_clip_value: Optional[float] = None,
                 sharding="dp", compute_dtype: Optional[str] = None,
                 aux_loss_weight: float = 0.01,
                 grad_accum_steps: int = 1):
        self.model = model
        self.aux_loss_weight = aux_loss_weight
        self.tx = optim_lib.get(optimizer)
        # clip wraps the base optimizer BEFORE MultiSteps so that with
        # grad accumulation the clip sees the accumulated/averaged
        # gradient (conventional clip-after-accumulate semantics), not
        # each micro-batch gradient
        if grad_clip_norm is not None:
            self.tx = optax.chain(optax.clip_by_global_norm(grad_clip_norm), self.tx)
        elif grad_clip_value is not None:
            self.tx = optax.chain(optax.clip(grad_clip_value), self.tx)
        if grad_accum_steps > 1:
            # one optimizer update per A micro-batches: grads average in
            # f32 inside opt-state, params stay fixed between updates —
            # the A-times-larger effective batch without A-times the
            # activation memory (complements steps_per_execution, which
            # fuses real updates per dispatch)
            self.tx = optax.MultiSteps(self.tx, grad_accum_steps)
        self.grad_accum_steps = grad_accum_steps
        self._sharding_strategy = sharding  # "dp" | "tp" | ShardingStrategy
        self.loss_fn = objectives.get(loss)
        self.metrics = [metrics_lib.get(m) for m in (metrics or [])]
        self.ctx = ctx or get_zoo_context()
        # mixed precision: config `compute_dtype` knob, overridable per-run
        cd = compute_dtype or self.ctx.config.compute_dtype
        self.compute_dtype = jnp.dtype(cd) if cd not in (None, "float32") \
            else None

        # mutable training state (host handles to device arrays)
        self.params = None
        self.state = None
        self.opt_state = None
        self.global_step = 0
        self.finished_epochs = 0
        self.history: List[Dict[str, float]] = []

        self._ckpt_mgr: Optional[ckpt_lib.CheckpointManager] = None
        self._ckpt_trigger: Trigger = EveryEpoch()
        self._val_trigger: Optional[Trigger] = None
        self._val_batch: Optional[int] = None
        self._last_val_iter = -1
        self._last_val_result: Optional[Dict[str, float]] = None
        self._tb_writer = None
        self._rng = explicit_prng_key(self.ctx.config.seed)
        # resilience state (docs/ROBUSTNESS.md): the host-side shuffle rng
        # is an attribute (not a fit() local) so checkpoints can capture it
        # and fit(resume=True) can continue the exact shuffle stream
        self._host_rng = np.random.RandomState(self.ctx.config.seed)
        self._lr_scale = 1.0            # NaN-rollback learning-rate backoff
        self._guard = None              # device-resident NaN-guard carry
        self._pending_resume: Optional[Tuple[int, int, Any]] = None
        self._preempt = threading.Event()

        self._train_step = None
        self._multi_step = None
        self._eval_step = None
        self._predict_step = None
        self._resident_epoch = None
        self._resident_epoch_key = None
        self._stream_shard = None
        self._stream_shard_key = None
        self._stream_plan = None        # set by _resolve_data_path
        # which input path the last fit() ran ("device_resident" /
        # "stream" / "host_prefetch") and why — bench and tests read these
        self.last_data_path: Optional[str] = None
        self.last_data_path_reason: Optional[str] = None
        # observability: the fit-level root span, the current epoch's
        # child span (train/step spans parent under it), and the metric
        # snapshot taken at fit() entry (training_report() deltas it)
        self._fit_span = None
        self._epoch_span = None
        self._fit_metrics_mark = None
        # training-side flight recorder (arm_flight_recorder): checked
        # at epoch boundaries, tripped manually on a HostLostError
        self._flight_recorder = None
        # monotone stream-rotation counter: makes the zoo_data_* barrier
        # names unique across NaN-rollback replays of the same epoch
        self._data_rotation = 0

    # ------------------------------------------------------------------
    # configuration
    # ------------------------------------------------------------------
    def set_checkpoint(self, path: str, over_write: bool = True,
                       trigger: Optional[Trigger] = None, keep: int = 3):
        cfg = self.ctx.config
        # Multi-controller runs get the sharded two-phase manager; so
        # does ANY run resuming a directory that already holds the
        # distributed layout — that's the elastic path (a 1-process run
        # restoring a 2-process run's shards).
        distributed = cfg.ckpt_distributed and (
            jax.process_count() > 1
            or ckpt_lib.has_distributed_layout(path))
        if distributed:
            self._ckpt_mgr = ckpt_lib.DistributedCheckpointManager(
                path, keep=keep, verify=cfg.ckpt_verify,
                barrier_timeout_s=cfg.dist_barrier_timeout_s)
        else:
            self._ckpt_mgr = ckpt_lib.CheckpointManager(
                path, keep=keep, verify=cfg.ckpt_verify)
        if trigger is not None:
            self._ckpt_trigger = trigger
        return self

    def set_tensorboard(self, log_dir: str):
        from analytics_zoo_tpu.core.summary import SummaryWriter
        self._tb_writer = SummaryWriter(log_dir)
        return self

    def arm_flight_recorder(self, *, window_s: float = 5.0,
                            out_dir: Optional[str] = None,
                            watch: Optional[Sequence] = None,
                            **kw):
        """Arm a training-side flight recorder (docs/OBSERVABILITY.md):
        windows are evaluated at epoch boundaries, watching the data
        tier's failure counters — a ``zoo_data_*`` barrier breach
        (``dist_barrier_timeouts_total``) or a stream-path downgrade
        (``data_stream_fallbacks_total``) trips a snapshot of the span
        ring + metric window.  A fatal ``HostLostError`` during fit()
        also trips it manually, so the mesh-death post-mortem keeps its
        evidence.  Extra ``watch`` pairs and FlightRecorder kwargs pass
        through.  Returns the recorder."""
        from analytics_zoo_tpu.observe.recorder import FlightRecorder

        counters = [("dist_barrier_timeouts_total", {}),
                    ("data_stream_fallbacks_total", {})]
        if watch:
            counters.extend(watch)
        self._flight_recorder = FlightRecorder(
            watch_counters=counters, window_s=window_s, out_dir=out_dir,
            **kw)
        self._flight_recorder.check()       # open the first window
        return self._flight_recorder

    # ------------------------------------------------------------------
    # initialization & compiled steps
    # ------------------------------------------------------------------
    def set_initial_weights(self, params, state=None):
        """Weights applied instead of random init at first build
        (used by ZooModel.load_model)."""
        self._initial_weights = (params, state or {})
        if self.params is not None:
            rep = self.ctx.replicated_sharding()
            self.params = jax.device_put(params, self._param_shardings(params))
            self.state = jax.device_put(state or {}, rep)
            self.opt_state = jax.jit(
                self.tx.init, out_shardings=self._opt_shardings())(self.params)
        return self

    def _strategy(self):
        """The resolved ShardingStrategy (strings lowered per-call against
        the current mesh, so one Estimator works across meshes)."""
        from analytics_zoo_tpu.parallel.sharding import (
            ShardingStrategy, make_strategy)

        strat = self._sharding_strategy
        if isinstance(strat, str):
            strat = make_strategy(strat, self.ctx.mesh)
        assert isinstance(strat, ShardingStrategy)
        # models that routed embedding tables to the sharded placement
        # carry a ``_sharded_tables`` manifest (models/recommendation.py);
        # wrap the user's strategy so those tables split row-wise over
        # the model axis and the trace sees the sharded lowering
        tables = getattr(self.model, "_sharded_tables", None)
        if tables:
            from analytics_zoo_tpu.parallel.table_sharding import \
                ensure_table_sharding
            strat = ensure_table_sharding(strat, tables)
        return strat

    def _param_shardings(self, params):
        """Per-parameter shardings from the strategy (replicated for DP;
        Megatron-style model-axis splits for TP; stacked block splits for
        PP — parallel/sharding.py)."""
        return self._strategy().param_shardings(self.ctx.mesh, params)

    def _opt_shardings(self):
        """Sharding tree for the optimizer state: subtrees shaped like the
        params pytree (adam mu/nu, momentum...) take the param shardings —
        so e.g. a row-sharded embedding table's Adam moments are sharded
        identically — everything else (step counts) is replicated
        (train/optimizers.py opt_state_shardings)."""
        from analytics_zoo_tpu.train.optimizers import opt_state_shardings
        return opt_state_shardings(
            self.tx, self.params, self._param_shardings(self.params),
            self.ctx.replicated_sharding())

    def _ensure_built(self, inputs: List[np.ndarray]):
        if self.params is not None:
            return
        self._rng, init_rng = jax.random.split(self._rng)
        shapes = [(2,) + tuple(x.shape[1:]) for x in inputs]
        # jit the one-time build: layer initializers create constants
        # (jnp.zeros biases, glorot scale factors) that are implicit
        # host->device transfers when run eagerly; inside jit they are
        # baked into the executable, so the build is silent under
        # jax.transfer_guard("disallow") and the params never bounce
        # through host numpy.  PRNG results are bit-identical either way.
        self.params, self.state = jax.jit(
            lambda r: self.model.init(r, *shapes))(init_rng)
        pending = getattr(self, "_initial_weights", None)
        if pending is not None:
            # merge by layer name so a superset (e.g. the full model a
            # sub-graph was cut from — nn/net.py new_graph) loads cleanly;
            # layers NOT covered keep random init, which is almost always
            # a bug on the user's side (renamed layer, wrong checkpoint) —
            # say so loudly
            pp, ps = pending
            if isinstance(pp, dict) and isinstance(self.params, dict):
                missing = sorted(set(self.params) - set(pp))
                if missing:
                    logger.warning(
                        "initial weights cover %d/%d layers; these keep "
                        "their RANDOM init: %s", len(pp), len(self.params),
                        missing)
                self.params = {k: pp.get(k, v)
                               for k, v in self.params.items()}
                self.state = {k: (ps or {}).get(k, v)
                              for k, v in self.state.items()}
            else:
                self.params, self.state = pending
        # place params per strategy; state replicated (small BN buffers);
        # optimizer state takes the matching param shardings explicitly
        # (tx.init's zeros_like would otherwise constant-fold onto one dev).
        rep = self.ctx.replicated_sharding()
        self.params = jax.device_put(self.params, self._param_shardings(self.params))
        self.state = jax.device_put(self.state, rep)
        # the step carry also includes the PRNG key: replicate it
        # EXPLICITLY here, or the first jitted step does an implicit
        # single-device -> mesh reshard (a hidden d2d transfer that
        # jax.transfer_guard("disallow") rejects)
        self._rng = jax.device_put(self._rng, rep)
        self.opt_state = jax.jit(
            self.tx.init, out_shardings=self._opt_shardings())(self.params)

    # ------------------------------------------------------------------
    # NaN/Inf guard (docs/ROBUSTNESS.md)
    # ------------------------------------------------------------------
    def _fresh_guard(self):
        """Device-resident guard carry: bad/consecutive-bad step counters
        plus the rollback learning-rate scale.  Rides the donated step
        carry so the happy path costs ZERO extra host syncs — the host
        reads it back once per epoch (``_check_nan_guard``)."""
        rep = self.ctx.replicated_sharding()
        # host numpy scalars + ONE explicit device_put: eager jnp.zeros
        # would be an implicit h2d transfer per leaf (trips
        # jax.transfer_guard("disallow") — the runtime twin of
        # zoolint JG-TRANSFER-HOT)
        return jax.device_put(
            {"bad": np.zeros((), np.int32),
             "consec": np.zeros((), np.int32),
             "max_consec": np.zeros((), np.int32),
             "lr_scale": np.float32(self._lr_scale)}, rep)

    @staticmethod
    def _guard_step(guard, finite):
        """One step's guard-carry update (traced inside the jitted step)."""
        bad_inc = jnp.where(finite, 0, 1).astype(jnp.int32)
        consec = jnp.where(finite, 0, guard["consec"] + 1).astype(jnp.int32)
        return {"bad": guard["bad"] + bad_inc,
                "consec": consec,
                "max_consec": jnp.maximum(guard["max_consec"], consec),
                "lr_scale": guard["lr_scale"]}

    def _check_nan_guard(self, steps_in_window: int) -> bool:
        """Epoch-boundary policy check: ONE host sync reads the guard
        carry back, applies ``nan_policy``, and re-arms a fresh guard.
        Returns True when the policy rolled training back to the last
        checkpoint (the caller must re-run from ``finished_epochs``)."""
        cfg = self.ctx.config
        g = jax.device_get(self._guard)
        TIMERS.incr("robust/guard_check")
        self._guard = self._fresh_guard()
        bad = int(g["bad"])
        max_consec = int(g["max_consec"])
        if bad == 0:
            return False
        TIMERS.incr("robust/nan_steps", bad)
        logger.warning("%d/%d steps had a non-finite loss (max %d "
                       "consecutive); nan_policy=%s", bad, steps_in_window,
                       max_consec, cfg.nan_policy)
        if cfg.nan_policy == "raise":
            TIMERS.incr("robust/nan_raised")
            raise FloatingPointError(
                f"{bad} non-finite training step(s) in the last "
                f"{steps_in_window} (nan_policy=raise); the bad updates "
                f"were skipped on device, params remain finite")
        TIMERS.incr("robust/nan_skipped", bad)
        if cfg.nan_policy == "rollback" and max_consec >= cfg.max_bad_steps:
            if self._ckpt_mgr is not None:
                self._ckpt_mgr.wait(raise_errors=False)
            if (self._ckpt_mgr is None
                    or self._ckpt_mgr.latest_step() is None):
                raise FloatingPointError(
                    f"{max_consec} consecutive non-finite steps >= "
                    f"max_bad_steps={cfg.max_bad_steps} but no checkpoint "
                    "to roll back to (set_checkpoint first)")
            # back off from the LIVE scale (restore would reset it to the
            # checkpoint's value, so repeated rollbacks must compound past
            # the restore)
            backed_off = self._lr_scale * cfg.nan_backoff_factor
            TIMERS.incr("robust/nan_rollbacks")
            logger.warning(
                "rolling back to last checkpoint after %d consecutive "
                "non-finite steps; learning-rate scale backed off to %.4g",
                max_consec, backed_off)
            self._restore_checkpoint()
            self._lr_scale = backed_off
            self._guard = self._fresh_guard()   # picks up the new lr_scale
            return True
        if cfg.nan_policy == "skip" and max_consec >= cfg.max_bad_steps:
            raise FloatingPointError(
                f"{max_consec} consecutive non-finite steps >= "
                f"max_bad_steps={cfg.max_bad_steps} under nan_policy=skip "
                "— training is making no progress")
        return False

    def _build_train_step(self):
        model, loss_fn, tx = self.model, self.loss_fn, self.tx
        data_shard = self.ctx.data_sharding()
        rep = self.ctx.replicated_sharding()
        cdtype = self.compute_dtype
        aux_w = self.aux_loss_weight
        # transfer-learning freeze (nn/net.py GraphNet.freeze): frozen
        # top-level param subtrees get zero updates inside the jitted step
        frozen = frozenset(getattr(model, "_frozen", ()))
        self._frozen_built = frozen

        strat = self._strategy()
        mesh = self.ctx.mesh

        guard_step = self._guard_step

        def step(params, state, opt_state, rng, guard, xs, y):
            # rng is carried ON DEVICE and split inside the step — passing
            # a host step counter per step would cost a blocking scalar
            # transfer (tens of ms over remote-tunnel links) per iteration
            rng, sub = jax.random.split(rng)

            def lossf(p, rng=sub):
                # Mixed precision: params + float inputs cast to the
                # compute dtype for forward/backward (bf16 on the MXU);
                # the cast's transpose re-accumulates grads in f32 against
                # the f32 master params, and the loss is taken in f32.
                if cdtype is not None:
                    p_c = _cast_floats(p, cdtype)
                    xs_c = _cast_floats(xs, cdtype)
                    st_c = _cast_floats(state, cdtype)
                else:
                    p_c, xs_c, st_c = p, xs, state
                # the strategy context is live while jit TRACES this body:
                # layers with a parallel lowering (ring attention for SP,
                # the GPipe block stack for PP) read it and bake the
                # regime into the compiled program (parallel/mode.py)
                with strat.activate(mesh):
                    preds, new_state = model.call(p_c, st_c, *xs_c,
                                                  training=True, rng=rng)
                if cdtype is not None:
                    preds = _cast_floats(preds, jnp.float32)
                    new_state = _cast_like(new_state, state)
                loss = loss_fn(y, preds)
                # weight-decay regularizers on the f32 master params (a
                # literal 0.0 when no layer has one) + layer auxiliary
                # losses (SparseMoE load balancing, surfaced via state)
                reg = getattr(model, "regularization_loss", None)
                if reg is not None:
                    loss = loss + reg(p)
                if aux_w:
                    from analytics_zoo_tpu.nn.layers.moe import moe_aux_loss
                    loss = loss + aux_w * moe_aux_loss(new_state)
                return loss, new_state

            (loss, new_state), grads = jax.value_and_grad(
                lossf, has_aux=True)(params)
            updates, new_opt = tx.update(grads, opt_state, params)
            # NaN-rollback LR backoff: a replicated scalar in the guard
            # carry scales the update — changing it costs no recompile
            updates = jax.tree_util.tree_map(
                lambda u: u * guard["lr_scale"].astype(u.dtype)
                if jnp.issubdtype(jnp.asarray(u).dtype, jnp.floating) else u,
                updates)
            if frozen:
                updates = {
                    k: (jax.tree_util.tree_map(jnp.zeros_like, u)
                        if k in frozen else u)
                    for k, u in updates.items()}
            new_params = optax.apply_updates(params, updates)
            # NaN/Inf guard: a non-finite loss means this update is junk —
            # discard it ON DEVICE (params/state/opt keep their pre-step
            # values) and count it in the carried guard; the host applies
            # the nan_policy at epoch granularity (zero per-step syncs)
            finite = jnp.isfinite(loss)

            def keep_if_finite(new, old):
                return jax.tree_util.tree_map(
                    lambda a, b: jnp.where(finite, a, b), new, old)

            new_params = keep_if_finite(new_params, params)
            new_state = keep_if_finite(new_state, state)
            new_opt = keep_if_finite(new_opt, opt_state)
            return (new_params, new_state, new_opt, rng,
                    guard_step(guard, finite), loss)

        # params/state/opt shardings are inherited from their device_put
        # placement (replicated for DP, model-axis split for TP) — pinning
        # only the batch keeps one step implementation for every strategy.
        self._train_step = jax.jit(
            step,
            in_shardings=(None, None, None, rep, rep, data_shard,
                          data_shard),
            donate_argnums=(0, 1, 2, 3, 4),
        )
        self._single_step_fn = step

    def _build_multi_step(self):
        """K steps per dispatch: lax.scan over a (K, B, ...) superbatch
        uploaded in ONE transfer (``steps_per_execution`` config knob).

        Amortizes per-step host->device latency — the TPU-native answer to
        the reference's per-iteration Spark job launches (wp-bigdl.md:171
        measured >10%% overhead at 500 tasks/iter; here the dispatch cost
        goes to ~zero for K >> 1).
        """
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self._train_step is None:
            self._build_train_step()
        single = self._single_step_fn
        rep = self.ctx.replicated_sharding()
        # batch axis is axis 1 of the (K, B, ...) superbatch
        chunk_shard = NamedSharding(self.ctx.mesh, P(None, self.ctx.data_axis))

        def multi(params, state, opt_state, rng, guard, xs_stack, y_stack):
            def body(carry, batch):
                p, s, o, r, g = carry
                bxs, by = batch
                p, s, o, r, g, loss = single(p, s, o, r, g, bxs, by)
                return (p, s, o, r, g), loss

            (params, state, opt_state, rng, guard), losses = jax.lax.scan(
                body, (params, state, opt_state, rng, guard),
                (xs_stack, y_stack))
            return params, state, opt_state, rng, guard, losses

        self._multi_step = jax.jit(
            multi,
            in_shardings=(None, None, None, rep, rep, chunk_shard,
                          chunk_shard),
            donate_argnums=(0, 1, 2, 3, 4),
        )

    def _build_resident_epoch(self, n: int, eff_batch: int, steps: int,
                              shuffle: bool):
        """ONE jitted program per epoch over HBM-resident arrays: an
        on-device ``jax.random.permutation`` picks the epoch's gather
        order, and a ``fori_loop`` of ``steps`` train steps slices the
        permutation and gathers each minibatch from the resident arrays
        in-step.  The carry (params/state/opt/rng) is donated, the data
        arrays are NOT (they feed every epoch) — so an epoch moves zero
        bytes host→device and costs one dispatch (the TPU answer to the
        reference's per-iteration Spark jobs AND to per-batch
        ``device_put``, which the r05 bench measured as a ~9.4× gap
        between step compute and end-to-end throughput)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (n, eff_batch, steps, bool(shuffle))
        if self._resident_epoch is not None \
                and self._resident_epoch_key == key:
            return self._resident_epoch
        if self._train_step is None:
            self._build_train_step()
        single = self._single_step_fn
        mesh = self.ctx.mesh
        data_axis = self.ctx.data_axis
        pair_structured = getattr(self.loss_fn, "batch_structured", False)

        def constrain(v):
            # gathered minibatches shard over the data axis like any
            # host-fed batch, whatever the resident arrays' placement
            return jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, P(data_axis,
                                         *([None] * (v.ndim - 1)))))

        def epoch(params, state, opt_state, rng, guard, xs, y):
            rng, prm = jax.random.split(rng)
            perm = resident_epoch_indices(
                prm, n, shuffle=shuffle, pair_structured=pair_structured)

            def body(i, carry):
                p, s, o, r, g, loss_sum, good = carry
                idx = jax.lax.dynamic_slice_in_dim(perm, i * eff_batch,
                                                   eff_batch)
                bxs = [constrain(jnp.take(a, idx, axis=0)) for a in xs]
                by = constrain(jnp.take(y, idx, axis=0))
                p, s, o, r, g, loss = single(p, s, o, r, g, bxs, by)
                # NaN guard: bad-step counts accumulate in the carried
                # guard; the epoch-mean loss aggregates finite steps only
                # so one bad step cannot poison the reported loss
                finite = jnp.isfinite(loss)
                loss_sum = loss_sum + jnp.where(finite, loss, 0.0)
                good = good + finite.astype(jnp.int32)
                return (p, s, o, r, g, loss_sum, good)

            carry = (params, state, opt_state, rng, guard,
                     jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32))
            (params, state, opt_state, rng, guard, loss_sum,
             good) = jax.lax.fori_loop(0, steps, body, carry)
            mean = loss_sum / jnp.maximum(good, 1).astype(jnp.float32)
            return params, state, opt_state, rng, guard, mean

        self._resident_epoch = jax.jit(epoch, donate_argnums=(0, 1, 2, 3, 4))
        self._resident_epoch_key = key
        return self._resident_epoch

    def _put_sharded(self, arrs: List[np.ndarray], shard):
        """Host batch → device arrays under ``shard``.  Multi-controller
        processes hold only their LOCAL rows of the global batch; the
        runtime assembles the global array without cross-host copies
        (every process must supply the same row count per step)."""
        TIMERS.incr("estimator/host_device_put", len(arrs))
        if self.ctx.process_count > 1:
            return [jax.make_array_from_process_local_data(
                shard, np.asarray(a)) for a in arrs]
        return [jax.device_put(jnp.asarray(a), shard) for a in arrs]

    @property
    def _data_div(self) -> int:
        """Row-count divisor for batches: local devices under
        multi-controller (batches count process-local rows), the full
        mesh otherwise."""
        return (self.ctx.local_device_count if self.ctx.process_count > 1
                else self.ctx.num_devices)

    def _global_eff_batch(self, batch_size: int) -> int:
        """The GLOBAL effective batch the resident/stream programs
        dispatch: ``batch_size`` rounded up to the per-process divisor,
        times the process count — ``batch_size`` follows the host
        path's convention of counting PROCESS-LOCAL rows under
        multi-controller, so a worker passing
        ``global_batch // process_count`` yields the same global
        geometry (and therefore the same stream plan / shard cursor) at
        every topology.  That invariance is what makes preempt-resume
        elastic across process counts."""
        d = self._data_div
        eff = int(math.ceil(max(batch_size, d) / d)) * d
        if self.ctx.process_count > 1:
            eff *= self.ctx.process_count
        return eff

    def _commit_carry(self, tree):
        """Commit the training carry (params/state/opt/rng)
        mesh-replicated before the first resident/stream dispatch —
        compile stability (see the call sites) AND, under
        multi-controller, the host-local leaves must become
        process-spanning global arrays or the jitted shard program
        would see mixed layouts.  Leaves already laid out on the
        global mesh (a reshard-on-restore) pass through untouched."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(self.ctx.mesh, P())
        if self.ctx.process_count == 1:
            return jax.device_put(tree, rep)
        from analytics_zoo_tpu.parallel.sharding import device_put_global

        def put(x):
            if isinstance(x, jax.Array) and not x.is_fully_addressable:
                return x
            return device_put_global(x, rep)

        return jax.tree_util.tree_map(put, tree)

    def _shard_chunk(self, arrs: List[np.ndarray]):
        from jax.sharding import NamedSharding, PartitionSpec as P

        # batch axis is axis 1 of the (K, B, ...) superbatch
        shard = NamedSharding(self.ctx.mesh, P(None, self.ctx.data_axis))
        with timeit("estimator/shard_chunk"):
            return self._put_sharded(arrs, shard)

    def _build_eval_step(self):
        model, loss_fn, mets = self.model, self.loss_fn, self.metrics
        data_shard = self.ctx.data_sharding()
        rep = self.ctx.replicated_sharding()

        batch_structured = getattr(loss_fn, "batch_structured", False)
        supports_mask = getattr(loss_fn, "supports_mask", False)
        mask_count = getattr(loss_fn, "mask_count", None)
        cdtype = self.compute_dtype
        strat = self._strategy()
        mesh = self.ctx.mesh

        def step(params, state, xs, y, mask):
            if cdtype is not None:
                params = _cast_floats(params, cdtype)
                state = _cast_floats(state, cdtype)
                xs = _cast_floats(xs, cdtype)
            with strat.activate(mesh):
                preds, _ = model.call(params, state, *xs, training=False,
                                      rng=None)
            if cdtype is not None:
                preds = _cast_floats(preds, jnp.float32)
            if batch_structured and supports_mask:
                # Loss couples rows across the batch (e.g. rank_hinge) but
                # can exclude padded rows exactly via its mask support;
                # aggregation weight = the loss's own unit count (pairs).
                cnt = mask_count(mask) if mask_count else jnp.sum(mask)
                stats = {"loss_sum": loss_fn(y, preds, mask=mask) * cnt,
                         "count": cnt}
            elif batch_structured:
                # Couples rows and has no mask support: compute over the
                # whole batch; padded rows are a small approximation on
                # the final partial batch only.
                stats = {"loss_sum": loss_fn(y, preds) * jnp.sum(mask),
                         "count": jnp.sum(mask)}
            else:
                # Per-sample losses (vmap over the mean-reduced loss, B=1)
                # so padded rows are excluded exactly via the mask.
                per = jax.vmap(
                    lambda yt, yp: loss_fn(yt[None], yp[None]))(y, preds)
                stats = {"loss_sum": jnp.sum(per * mask),
                         "count": jnp.sum(mask)}
            out = {"__loss": stats}
            for m in mets:
                out[m.name] = m.update(y, preds, mask)
            return out

        self._eval_step = jax.jit(
            step, in_shardings=(None, None, data_shard, data_shard, data_shard),
            out_shardings=rep)

    def _build_predict_step(self):
        model = self.model
        data_shard = self.ctx.data_sharding()
        rep = self.ctx.replicated_sharding()
        cdtype = self.compute_dtype

        strat = self._strategy()
        mesh = self.ctx.mesh

        def step(params, state, xs):
            if cdtype is not None:
                params = _cast_floats(params, cdtype)
                state = _cast_floats(state, cdtype)
                xs = _cast_floats(xs, cdtype)
            with strat.activate(mesh):
                preds, _ = model.call(params, state, *xs, training=False,
                                      rng=None)
            if cdtype is not None:
                preds = _cast_floats(preds, jnp.float32)
            return preds

        # Multi-controller: a data-sharded output spans non-addressable
        # devices, so each process could not read its rows back —
        # replicate the (small, batch-sized) predictions instead and let
        # predict_raw slice out the local rows.
        out_shard = (rep if self.ctx.process_count > 1 else data_shard)
        self._predict_step = jax.jit(
            step, in_shardings=(None, None, data_shard),
            out_shardings=out_shard)

    # ------------------------------------------------------------------
    # data plumbing
    # ------------------------------------------------------------------
    def _pad_to_devices(self, arrs: List[np.ndarray], batch: int
                        ) -> Tuple[List[np.ndarray], int]:
        """Pad batch dim up to ``batch`` (already a mesh-size multiple) so
        every step sees ONE static shape (no per-remainder recompiles);
        returns the real row count."""
        n = arrs[0].shape[0]
        d = self._data_div
        target = max(batch, d, int(math.ceil(n / d)) * d)
        if target == n:
            return arrs, n
        padded = []
        for a in arrs:
            pad = np.zeros((target - n,) + a.shape[1:], a.dtype)
            padded.append(np.concatenate([a, pad], axis=0))
        return padded, n

    def _shard_batch(self, arrs: List[np.ndarray]):
        with timeit("estimator/shard_batch"):
            return self._put_sharded(arrs, self.ctx.data_sharding())

    def _maybe_midepoch_validation(self, validation_data, epoch: int,
                                   train_batch: int):
        """Iteration-granular validation: when a ``validation_trigger``
        (e.g. SeveralIteration) fires between epoch boundaries, evaluate
        now and record a history row (reference validates at arbitrary
        trigger points inside the optimizer loop, Topology.scala:223-244).
        Loss is not materialised here to avoid a per-step device sync."""
        if validation_data is None or self._val_trigger is None:
            return
        tstate = TriggerState(epoch=epoch, iteration=self.global_step,
                              epoch_finished=False)
        if not self._val_trigger(tstate):
            return
        self._last_val_iter = self.global_step
        val = self.evaluate(validation_data[0], validation_data[1],
                            batch_size=self._val_batch or train_batch)
        self._last_val_result = val
        rec = {"iteration": self.global_step}
        rec.update({f"val_{k}": v for k, v in val.items()})
        self.history.append(rec)
        if self._tb_writer is not None:
            for k, v in rec.items():
                if k != "iteration":
                    self._tb_writer.add_scalar(k, v, self.global_step)

    # ------------------------------------------------------------------
    # fit
    # ------------------------------------------------------------------
    def fit(self, x, y=None, batch_size: int = 32, epochs: int = 1,
            validation_data=None, end_trigger: Optional[Trigger] = None,
            shuffle: bool = True, verbose: bool = True,
            validation_trigger: Optional[Trigger] = None,
            validation_batch_size: Optional[int] = None,
            resume: bool = False):
        """Synchronous SPMD training with retry-from-checkpoint.

        ``x`` — array or list of arrays (multi-input models); or a
        FeatureSet/dataset yielding ``(inputs..., y)`` batches.
        ``validation_trigger`` — evaluate only when it fires (default:
        every epoch); ``validation_batch_size`` defaults to the training
        batch (reference setValidation trigger/batch semantics,
        Topology.scala:223-244).
        ``resume`` — continue from the newest intact checkpoint (set via
        ``set_checkpoint`` or the ``checkpoint_dir`` config knob): full
        training state — params, optimizer, device AND host rng streams,
        epoch/step position — is restored, so an interrupted run re-run
        with ``resume=True`` reproduces the uninterrupted run exactly
        (docs/ROBUSTNESS.md).  A SIGTERM during fit flushes one final
        synchronous checkpoint and raises
        :class:`~analytics_zoo_tpu.robust.TrainingPreempted`.
        """
        from analytics_zoo_tpu.data.featureset import FeatureSet

        self._val_trigger = validation_trigger
        self._val_batch = validation_batch_size
        if resume:
            self._try_resume()
        else:
            # a non-resuming fit() replays the configured shuffle stream
            # from its seed (deterministic runs); resume instead restores
            # the stream position from the checkpoint manifest
            self._host_rng = np.random.RandomState(self.ctx.config.seed)
            self._pending_resume = None
        self._preempt.clear()
        # freeze()/unfreeze() after a previous fit must take effect: the
        # compiled step captured the old frozen set, so rebuild it
        cur_frozen = frozenset(getattr(self.model, "_frozen", ()))
        if (self._train_step is not None
                and cur_frozen != getattr(self, "_frozen_built", cur_frozen)):
            self._train_step = None
            self._multi_step = None
            self._resident_epoch = None
            self._stream_shard = None
        restore_sig = self._install_preempt_handler()
        # fit-level root span + metric mark: every epoch/step span chains
        # under this trace, and training_report() deltas the registry
        # against the mark so it covers exactly this run
        self._fit_metrics_mark = obs.METRICS.snapshot()
        self._fit_span = TRACER.start("train/fit", epochs=epochs,
                                      batch_size=batch_size)
        try:
            if isinstance(x, FeatureSet):
                path, reason = self._resolve_data_path(x, batch_size)
                self.last_data_path, self.last_data_path_reason = \
                    path, reason
                if path == "device_resident":
                    out = self._fit_device_resident(
                        x, batch_size, epochs, validation_data,
                        end_trigger, verbose, shuffle)
                elif path == "stream":
                    out = self._fit_stream(
                        x, batch_size, epochs, validation_data,
                        end_trigger, verbose, shuffle)
                else:
                    out = self._fit_featureset(x, batch_size, epochs,
                                               validation_data, end_trigger,
                                               verbose, shuffle)
            else:
                out = self._fit_arrays(x, y, batch_size, epochs,
                                       validation_data, end_trigger, shuffle,
                                       verbose)
            self._fit_span.end(epochs_done=self.finished_epochs)
            return out
        except BaseException as e:
            if self._epoch_span is not None:
                self._epoch_span.end(status="error", error=str(e))
                self._epoch_span = None
            if (self._flight_recorder is not None
                    and isinstance(e, HostLostError)):
                # a mesh-death is exactly the moment operators need the
                # span ring + metric window preserved — trip manually,
                # the periodic check never runs again in this process
                self._flight_recorder.trigger(
                    "host_lost", {"barrier": e.barrier,
                                  "timeout_s": e.timeout_s})
            self._fit_span.end(status=type(e).__name__, error=str(e))
            raise
        finally:
            restore_sig()

    # ------------------------------------------------------------------
    # observability (docs/OBSERVABILITY.md)
    # ------------------------------------------------------------------
    def training_report(self) -> Dict[str, Any]:
        """Training-side observability rollup — the fit() analog of
        serving ``health()``: progress counters, the labeled-metric
        delta since the last ``fit()`` entered (step/epoch timings,
        checkpoint ops, loss/throughput gauges), and span-ring stats so
        a run's timeline is known to be reconstructable."""
        report: Dict[str, Any] = {
            "global_step": self.global_step,
            "finished_epochs": self.finished_epochs,
            "last_data_path": self.last_data_path,
            "history": list(self.history),
            "spans": {
                "completed": TRACER.completed_count(),
                "active": TRACER.active_count(),
                "ring": TRACER.ring_size(),
            },
        }
        if self._fit_span is not None:
            report["fit_trace"] = self._fit_span.trace
        if self._fit_metrics_mark is not None:
            report["metrics_delta"] = obs.METRICS.delta(
                self._fit_metrics_mark)
        return report

    def metrics_text(self) -> str:
        """The labeled metric registry in Prometheus text format."""
        return to_prometheus(obs.METRICS)

    def publish_metrics(self, step: Optional[int] = None) -> int:
        """Bridge the labeled registry into the TensorBoard writer set
        via ``set_tensorboard`` (no-op 0 without one); returns the
        number of scalars written."""
        if self._tb_writer is None:
            return 0
        return publish_to_summary(self._tb_writer,
                                  step if step is not None
                                  else self.global_step)

    # ------------------------------------------------------------------
    # resilience plumbing (docs/ROBUSTNESS.md)
    # ------------------------------------------------------------------
    def _try_resume(self) -> bool:
        """``fit(resume=True)``: restore full training state from the
        newest intact checkpoint; a missing checkpoint is a fresh start,
        never an error (so the same command line works for attempt #1
        and every restart after a preemption)."""
        cfg = self.ctx.config
        if self._ckpt_mgr is None and cfg.checkpoint_dir:
            self.set_checkpoint(cfg.checkpoint_dir)
        if self._ckpt_mgr is None or self._ckpt_mgr.latest_step() is None:
            logger.info("fit(resume=True): no checkpoint found; "
                        "starting fresh")
            self._host_rng = np.random.RandomState(cfg.seed)
            self._pending_resume = None
            return False
        self._restore_checkpoint()
        TIMERS.incr("robust/auto_resume")
        return True

    def _install_preempt_handler(self) -> Callable[[], None]:
        """SIGTERM → request a final synchronous checkpoint at the next
        step boundary (the preemption story: lose at most one step, not
        the run).  Returns a callable restoring the previous handler.
        No-op off the main thread (signal.signal would raise)."""
        if threading.current_thread() is not threading.main_thread():
            return lambda: None

        def _on_sigterm(signum, frame):
            logger.warning("SIGTERM received: flushing a final checkpoint "
                           "at the next step boundary")
            self._preempt.set()

        try:
            prev = signal.signal(signal.SIGTERM, _on_sigterm)
        except (ValueError, OSError):
            return lambda: None

        def restore():
            try:
                signal.signal(signal.SIGTERM, prev)
            except (ValueError, OSError, TypeError):
                pass

        return restore

    def _flush_preempt(self, epoch: int, in_epoch_step: int,
                       epoch_rng_state) -> None:
        """Preemption (SIGTERM or injected): flush ONE synchronous
        checkpoint carrying the mid-epoch resume manifest, then abort
        fit with :class:`TrainingPreempted`."""
        step = self.global_step
        if self._ckpt_mgr is not None:
            # DistributedCheckpointManager flushes barrier-free (peers
            # are dying on their own schedule); the single-process
            # manager's plain save is already barrier-free
            saver = getattr(self._ckpt_mgr, "save_preempt",
                            self._ckpt_mgr.save)
            saver(step, self._snapshot(
                resume_epoch=epoch, in_epoch_step=in_epoch_step,
                epoch_rng_state=epoch_rng_state))
            TIMERS.incr("robust/preempt_flush")
            logger.warning(
                "preempted at global step %d (epoch %d, in-epoch step %d): "
                "final synchronous checkpoint flushed; fit(resume=True) "
                "continues exactly here", step, epoch + 1, in_epoch_step)
        else:
            logger.warning("preempted at global step %d with NO checkpoint "
                           "manager set; training state is lost", step)
        raise TrainingPreempted(
            f"training preempted at global step {step}", step=step)

    def _maybe_preempt(self, epoch: int, in_epoch_step: int,
                       epoch_rng_state=None) -> None:
        """Per-step preemption check (host paths; the device-resident
        path checks between its one-dispatch epochs)."""
        if faults.fire("estimator.preempt") is not None:
            self._preempt.set()
        if self._preempt.is_set():
            self._flush_preempt(epoch, in_epoch_step, epoch_rng_state)

    @staticmethod
    def _inject_step_faults(bx, by):
        """Chaos hook consulted once per prepared dispatch: a planned
        ``estimator.step`` fault either raises (pipeline failure) or
        NaN-poisons the batch (numerical blow-up) — both exactly at the
        planned dispatch index."""
        plan = faults.fire("estimator.step")
        if plan is not None:
            if plan.exc is not None:
                raise plan.exc
            if plan.action == "nan":
                poisoned = faults.poison_nan(list(bx) + [by])
                bx, by = poisoned[:-1], poisoned[-1]
        return bx, by

    def _dispatch_step(self, kind, batch_x, batch_y, *, epoch_fn=None,
                       epoch_steps=None):
        """THE training dispatch point — every fit path funnels here.

        All three compiled step shapes share one calling convention (a
        6-tuple donated carry in, the advanced carry + loss out), so
        folding them lets both humans and static analysis reason about
        one step-fn dispatch instead of three:

        - ``"1"``     — one jitted train step on a (B, ...) batch
        - ``"K"``     — the lax.scan multi-step on a (K, B, ...)
                        superbatch (``steps_per_execution``)
        - ``"epoch"`` — the device-resident whole-epoch program
                        (caller supplies ``epoch_fn`` + ``epoch_steps``)
        - ``"shard"`` — the STREAM tier's whole-shard program (same
                        calling convention as "epoch"; ``batch_x``
                        carries the epoch loss accumulator as its first
                        leaf and the loss out is the advanced
                        accumulator)

        Returns ``(advanced_steps, loss)`` with ``loss`` still on
        device: per-step losses for "1"/"K", the epoch mean for
        "epoch", the accumulator for "shard".  ``global_step`` advances
        here and nowhere else during fit.
        """
        if kind in ("epoch", "shard"):
            fn, k = epoch_fn, int(epoch_steps)
        elif kind == "K":
            # the superbatch leading axis IS the step count (tail
            # chunks shorter than steps_per_execution included)
            fn, k = self._multi_step, int(batch_y.shape[0])
        else:
            fn, k = self._train_step, 1
        parent = self._epoch_span or self._fit_span
        sp = (TRACER.start("train/step", trace=parent.trace,
                           parent=parent.sid, kind=kind)
              if parent is not None else None)
        t0 = time.perf_counter()
        (self.params, self.state, self.opt_state, self._rng,
         self._guard, loss) = fn(self.params, self.state, self.opt_state,
                                 self._rng, self._guard, batch_x, batch_y)
        # dispatch-side wall time: the carry returns while the device
        # still computes, so this is host dispatch latency, not step math
        obs.observe("train_step_seconds", time.perf_counter() - t0,
                    kind=kind)
        obs.count("train_steps_total", k, kind=kind)
        if sp is not None:
            sp.end(steps=k)
        self.global_step += k
        return k, loss

    def _fit_arrays(self, x, y, batch_size, epochs, validation_data,
                    end_trigger, shuffle, verbose):
        xs = _as_list(x)
        assert y is not None, "y required for array training"
        n = xs[0].shape[0]
        # multi-controller: x/y are the process-LOCAL shard of the dataset
        # and batch_size counts local rows, so divisibility is against the
        # local device count (the global batch is local x process_count).
        d = self._data_div
        eff_batch = max(batch_size, d)
        if batch_size % d != 0:
            eff_batch = int(math.ceil(batch_size / d)) * d
            logger.warning("batch_size %d not divisible by %d devices; "
                           "using %d", batch_size, d, eff_batch)
        steps_per_epoch = n // eff_batch
        if steps_per_epoch == 0:
            raise ValueError(f"dataset ({n}) smaller than batch ({eff_batch})")
        dropped = n - steps_per_epoch * eff_batch
        if dropped:
            logger.warning(
                "dropping %d/%d samples per epoch (dataset not a multiple of "
                "batch %d); reshuffling each epoch varies which are dropped",
                dropped, n, eff_batch)

        self._ensure_built(xs)
        if self._train_step is None:
            self._build_train_step()

        cfg = self.ctx.config
        # Failure-retry semantics of the reference's retryTimes /
        # retryTimeInterval pair (Topology.scala:1179-1261), now expressed
        # through the reusable RetryPolicy: failures age out of a sliding
        # window, and each retry backs off exponentially before restoring
        # the last checkpoint.
        retry = RetryPolicy.from_config(
            cfg, max_attempts=cfg.failure_retry_times,
            window_s=cfg.failure_retry_interval_s,
            name="estimator_fit").state()
        K = max(1, int(cfg.steps_per_execution))
        if K > 1 and self._val_trigger is not None:
            logger.warning(
                "steps_per_execution=%d: validation/trigger checks happen "
                "every K-th iteration (K-step chunks are one dispatch)", K)
        if K > 1 and self._multi_step is None:
            self._build_multi_step()
        n_chunks = steps_per_epoch // K if K > 1 else 0
        rem = steps_per_epoch - n_chunks * K
        epoch = self.finished_epochs
        self._guard = self._fresh_guard()
        # Device-resident mode: when the caller hands in jax.Arrays, every
        # epoch's shuffle permutation, gather, and (K, B) reshape happen ON
        # DEVICE — an epoch moves zero bytes host→device.  This is the hot
        # path for data that fits HBM (e.g. the NCF north-star convergence
        # run pre-samples all epochs on device and trains from the
        # resident arrays).
        # (multi-controller is excluded: _put_sharded must pull chunks to
        # host for make_array_from_process_local_data there, which would
        # make device inputs a device→host→device round trip per batch)
        device_resident = (all(isinstance(a, jax.Array) for a in xs)
                           and isinstance(y, jax.Array)
                           and self.ctx.process_count == 1)
        self.last_data_path = ("device_resident" if device_resident
                               else "host_prefetch")
        self.last_data_path_reason = ("jax.Array inputs" if device_resident
                                      else "host array inputs")
        y_arr = y if device_resident else np.asarray(y)

        # Pair-structured losses (rank_hinge: (pos, neg) rows interleaved)
        # must shuffle PAIRS, not rows — a row-level permutation would
        # scramble which positive faces which negative every epoch and
        # silently train on random pairings.
        pair_structured = getattr(self.loss_fn, "batch_structured", False)

        def _pair_perm_np(rng):
            pairs = rng.permutation(n // 2)
            idx = np.empty((n // 2) * 2, np.int64)
            idx[0::2] = pairs * 2
            idx[1::2] = pairs * 2 + 1
            if n % 2:
                idx = np.concatenate([idx, [n - 1]])
            return idx

        while epoch < epochs:
            batches = None
            try:
                t0 = time.time()
                if self._fit_span is not None:
                    self._epoch_span = TRACER.start(
                        "train/epoch", trace=self._fit_span.trace,
                        parent=self._fit_span.sid, epoch=epoch + 1)
                # Mid-epoch resume (preemption manifest): rewind the host
                # shuffle rng to the interrupted epoch's start state so the
                # SAME permutation is redrawn, then skip the steps the
                # interrupted run already trained — the step sequence seen
                # by the optimizer is bit-identical to an uninterrupted run.
                start_step = 0
                if (self._pending_resume is not None
                        and self._pending_resume[0] == epoch):
                    _, start_step, rng_state = self._pending_resume
                    self._pending_resume = None
                    if rng_state is not None:
                        self._host_rng.set_state(rng_state)
                    # steps advance K at a time inside chunks; align down so
                    # resume never starts mid-chunk (the flush only happens
                    # at dispatch boundaries, so this is exact in practice)
                    if K > 1 and start_step < n_chunks * K:
                        start_step = (start_step // K) * K
                    logger.info("resuming epoch %d at in-epoch step %d",
                                epoch + 1, start_step)
                elif self._pending_resume is not None:
                    self._pending_resume = None
                epoch_rng_state = self._host_rng.get_state()
                if not shuffle:
                    perm = None         # contiguous slices in both modes
                elif device_resident and pair_structured:
                    pairs = jax.random.permutation(
                        explicit_prng_key(cfg.seed + 7919 * epoch), n // 2)
                    perm = jnp.stack([pairs * 2, pairs * 2 + 1],
                                     axis=1).reshape(-1)
                    if n % 2:
                        perm = jnp.concatenate(
                            [perm, jnp.asarray([n - 1])])
                elif device_resident:
                    perm = jax.random.permutation(
                        explicit_prng_key(cfg.seed + 7919 * epoch), n)
                elif pair_structured:
                    perm = _pair_perm_np(self._host_rng)
                else:
                    perm = self._host_rng.permutation(n)
                losses = []

                def gen(perm=perm, start=start_step):
                    for ci in range(n_chunks):
                        s0 = ci * K
                        if s0 < start:      # resume: already trained
                            continue
                        ofs = s0 * eff_batch
                        sl = (slice(ofs, ofs + K * eff_batch)
                              if perm is None
                              else perm[ofs:ofs + K * eff_batch])
                        yield ("K",
                               [a[sl].reshape((K, eff_batch) + a.shape[1:])
                                for a in xs],
                               y_arr[sl].reshape(
                                   (K, eff_batch) + y_arr.shape[1:]))
                    for ri in range(rem):
                        s0 = n_chunks * K + ri
                        if s0 < start:
                            continue
                        ofs = s0 * eff_batch
                        sl = (slice(ofs, ofs + eff_batch) if perm is None
                              else perm[ofs:ofs + eff_batch])
                        yield ("1", [a[sl] for a in xs], y_arr[sl])

                def prep(item):
                    kind, bx, by = item
                    bx, by = self._inject_step_faults(bx, by)
                    put = self._shard_chunk if kind == "K" else \
                        self._shard_batch
                    return kind, put(list(bx)), put([by])[0]

                # overlap host batch prep + device_put with device compute
                batches = prefetch_lib.prefetch(gen(), prep,
                                                depth=cfg.data_prefetch)
                in_epoch = start_step
                for kind, batch_x, batch_y in batches:
                    # pre-dispatch check: a flush here can never mark a
                    # fully-trained epoch as mid-epoch (in_epoch stays
                    # strictly below steps_per_epoch)
                    self._maybe_preempt(epoch, in_epoch, epoch_rng_state)
                    k, loss = self._dispatch_step(kind, batch_x, batch_y)
                    in_epoch += k
                    losses.append(loss)
                    self._maybe_midepoch_validation(validation_data,
                                                    epoch + 1, eff_batch)
                # ONE host sync per epoch reads the NaN-guard counters that
                # rode the device carry (policy: skip / rollback / raise)
                if self._check_nan_guard(in_epoch - start_step):
                    if self._epoch_span is not None:
                        self._epoch_span.end(status="rollback")
                        self._epoch_span = None
                    epoch = self.finished_epochs   # rolled back
                    continue
                epoch += 1
                self.finished_epochs = epoch
                # nanmean: skipped (non-finite) steps must not poison the
                # epoch metric — their updates were discarded on device
                mean_loss = (float(jnp.nanmean(jnp.concatenate(
                    [jnp.atleast_1d(l) for l in losses])))
                    if losses else float("nan"))
                dt = time.time() - t0
                rec = {"epoch": epoch, "loss": mean_loss,
                       "throughput": steps_per_epoch * eff_batch / dt}
                obs.observe("train_epoch_seconds", dt)
                obs.set_gauge("train_loss", mean_loss)
                obs.set_gauge("train_throughput_rows_per_s",
                              rec["throughput"])
                if self._epoch_span is not None:
                    self._epoch_span.end(loss=mean_loss)
                    self._epoch_span = None
                tstate = TriggerState(epoch=epoch, iteration=self.global_step,
                                      epoch_finished=True, loss=mean_loss)
                if validation_data is not None and (
                        self._val_trigger is None
                        or self._val_trigger(tstate)):
                    # reuse a mid-epoch eval that just ran on this exact
                    # step instead of evaluating twice
                    if self._last_val_iter == self.global_step:
                        val = self._last_val_result
                    else:
                        val = self.evaluate(validation_data[0],
                                            validation_data[1],
                                            batch_size=self._val_batch
                                            or eff_batch)
                    rec.update({f"val_{k}": v for k, v in val.items()})
                    tstate.score = val.get(
                        self.metrics[0].name if self.metrics else "loss")
                self.history.append(rec)
                if self._tb_writer is not None:
                    for k, v in rec.items():
                        if k != "epoch":
                            self._tb_writer.add_scalar(k, v, self.global_step)
                    self._tb_writer.flush()
                if verbose:
                    logger.info("epoch %d: %s", epoch,
                                {k: round(v, 5) for k, v in rec.items()
                                 if k != "epoch"})
                if self._ckpt_mgr is not None and self._ckpt_trigger(tstate):
                    self._save_checkpoint()
                if end_trigger is not None and end_trigger(tstate):
                    break
            except (KeyboardInterrupt, TrainingPreempted,
                    FloatingPointError, HostLostError):
                # release the prefetch producer (its sentinel delivery
                # waits for close() on abandonment); preemption, the
                # "raise" NaN policy, and a dead peer must surface, never
                # be retried (retrying solo past a lost host would fork
                # the SPMD program)
                if batches is not None and hasattr(batches, "close"):
                    batches.close()
                raise
            except Exception as e:  # failure-retry (Topology.scala:1179-1261)
                if batches is not None and hasattr(batches, "close"):
                    batches.close()
                if self._epoch_span is not None:
                    self._epoch_span.end(status="retry", error=str(e))
                    self._epoch_span = None
                if self._ckpt_mgr is not None:
                    # an async write may still be in flight — land it so
                    # the retry decision sees the newest snapshot
                    self._ckpt_mgr.wait(raise_errors=False)
                if (self._ckpt_mgr is None
                        or self._ckpt_mgr.latest_step() is None
                        or not retry.record_failure()):
                    raise
                logger.warning("step failed (%s); retry %s from checkpoint",
                               e, retry.describe())
                retry.backoff()
                self._restore_checkpoint()
                self._guard = self._fresh_guard()
                # re-sync the loop counter so rolled-back epochs re-train
                epoch = self.finished_epochs
        if self._ckpt_mgr is not None:
            self._ckpt_mgr.wait()   # join any in-flight async write
        return self.history

    def _resolve_data_path(self, fs, batch_size: int = 32
                           ) -> Tuple[str, str]:
        """Which input path a FeatureSet trains through:
        ``("device_resident" | "stream" | "host_prefetch", reason)``.

        Tier router (reference tier-selection semantics,
        feature/FeatureSet.scala:690-722), keyed on the FeatureSet's
        pinned cache level (else the ``data_cache_level`` config
        default) and ``data_device_budget_bytes``:

        - fits the budget           → device_resident (per-host HBM
                                      residency of the rows each
                                      process's devices own)
        - over budget / sliced      → stream (double-buffered shard
                                      rotation), when a feasible
                                      :func:`~analytics_zoo_tpu.data.streaming.plan_stream`
                                      geometry exists
        - stream infeasible / HOST  → host prefetch

        Multi-controller runs route through the SAME matrix — each
        process materializes or streams only its own rows
        (docs/DATA.md "Multi-controller") — except that the quantized
        stream cache is disabled (per-host scale/zero scalars would
        disagree).

        Every downgrade is automatic and logged, never an error; every
        decision is counted in
        ``data_path_selected_total{path,reason}`` with a bounded
        reason-code vocabulary so production downgrades alert instead
        of hiding in logs."""
        from analytics_zoo_tpu.data import streaming as stream_lib
        from analytics_zoo_tpu.data.featureset import (CacheLevel,
                                                       SlicedFeatureSet)

        def pick(path: str, code: str, reason: str) -> Tuple[str, str]:
            obs.count("data_path_selected_total", path=path, reason=code,
                      flat=f"estimator/data_path_{path}")
            return path, reason

        cfg = self.ctx.config
        self._stream_plan = None
        level = fs.cache_level or CacheLevel.normalize(cfg.data_cache_level)
        if level == CacheLevel.HOST:
            return pick("host_prefetch", "cache_level_host",
                        "cache level HOST")
        budget = int(cfg.data_device_budget_bytes)
        sliced = isinstance(fs, SlicedFeatureSet)
        if not sliced and fs.nbytes <= budget:
            # whole-dataset residency beats any rotation whenever it
            # fits — a STREAM request downgrades to plain DEVICE
            return pick("device_resident", "fits_budget",
                        "fits device budget")
        eff_batch = self._global_eff_batch(batch_size)
        cache_dtype = cfg.data_cache_dtype
        if cache_dtype is not None and self.ctx.process_count > 1:
            logger.warning(
                "quantized stream cache (%s) is single-controller only "
                "— per-host quantization would derive disagreeing "
                "replicated scale/zero scalars; streaming uncompressed",
                cache_dtype)
            cache_dtype = None
        plan, why = stream_lib.plan_stream(
            fs, budget, eff_batch, slots=cfg.data_stream_slots,
            cache_dtype=cache_dtype)
        over = ("sliced (beyond-memory) featureset" if sliced else
                f"dataset {fs.nbytes}B over device budget {budget}B")
        if plan is None:
            logger.warning(
                "%s and streaming is infeasible (%s); falling back to "
                "the host prefetch path", over, why)
            return pick("host_prefetch", "stream_infeasible",
                        f"{over}; stream infeasible: {why}")
        logger.info(
            "STREAM tier engaged: %s; rotating %d shards of %d rows "
            "(%.1f MiB/shard in HBM, %d slots%s)", over, plan.n_shards,
            plan.shard_rows, plan.device_shard_bytes / 2 ** 20, plan.slots,
            f", {plan.cache_dtype} device cache" if plan.cache_dtype
            else "")
        self._stream_plan = plan
        return pick("stream", "sliced" if sliced else "over_budget",
                    f"{over}; streaming {plan.n_shards} shards of "
                    f"{plan.shard_rows} rows")

    def _epoch_bookkeeping(self, epoch1, mean_loss, dt, count,
                           validation_data, val_batch_default, verbose,
                           end_trigger) -> bool:
        """Shared end-of-epoch tail (history row, validation trigger,
        tensorboard, checkpoint trigger); True = end_trigger fired."""
        self.finished_epochs = epoch1
        rec = {"epoch": epoch1, "loss": mean_loss,
               "throughput": count / dt}
        tstate = TriggerState(epoch=epoch1, iteration=self.global_step,
                              epoch_finished=True, loss=mean_loss)
        if validation_data is not None and (
                self._val_trigger is None
                or self._val_trigger(tstate)):
            if self._last_val_iter == self.global_step:
                val = self._last_val_result
            else:
                val = self.evaluate(validation_data[0],
                                    validation_data[1],
                                    batch_size=self._val_batch
                                    or val_batch_default)
            rec.update({f"val_{k}": v for k, v in val.items()})
            tstate.score = val.get(
                self.metrics[0].name if self.metrics else "loss")
        self.history.append(rec)
        if self._tb_writer is not None:
            for k, v in rec.items():
                if k != "epoch":
                    self._tb_writer.add_scalar(k, v, self.global_step)
            self._tb_writer.flush()
        if verbose:
            logger.info("epoch %d: %s", epoch1, rec)
        if self._ckpt_mgr is not None and self._ckpt_trigger(tstate):
            self._save_checkpoint()
        if self._flight_recorder is not None:
            self._flight_recorder.check()
        return end_trigger is not None and end_trigger(tstate)

    def _fit_device_resident(self, fs, batch_size, epochs, validation_data,
                             end_trigger, verbose, shuffle):
        """The HBM-resident fast path: materialize the FeatureSet into
        device memory once (``FeatureSet.device_arrays``), then train
        each epoch as ONE jitted dispatch (``_build_resident_epoch``) —
        no per-batch host indexing, no per-batch ``device_put``, no
        per-step dispatch."""
        arrays = fs.device_arrays(self.ctx)
        xs, y = list(arrays[:-1]), arrays[-1]
        if not xs:          # single-array FeatureSet has no label split
            raise ValueError(
                "device-resident training needs (inputs..., label) arrays")
        self._ensure_built(xs)
        n = int(arrays[0].shape[0])
        eff_batch = self._global_eff_batch(batch_size)
        steps = n // eff_batch
        if steps == 0:
            raise ValueError(
                f"FeatureSet ({n} rows) yields no full batch of "
                f"{eff_batch} (drop_remainder)")
        if self._val_trigger is not None:
            logger.warning(
                "device-resident path runs each epoch as one dispatch; "
                "validation_trigger is evaluated at epoch boundaries only")
        epoch_fn = self._build_resident_epoch(n, eff_batch, steps, shuffle)
        if self._pending_resume is not None:
            # resident epochs are one dispatch, so resume granularity is
            # the epoch boundary: a mid-epoch manifest (written by a host
            # input path) restarts its epoch from the restored weights
            if self._pending_resume[1] > 0:
                logger.warning("device-resident path resumes at epoch "
                               "boundaries; dropping mid-epoch resume marker")
            self._pending_resume = None
        # commit the carry under the mesh BEFORE the first dispatch: the
        # epoch outputs come back mesh-replicated, and a first call with
        # uncommitted host-placed params would compile a second, separate
        # executable for epoch 2+ (measured: epochs 1-2 each ~40x slower
        # than steady state on the CPU mesh)
        (self.params, self.state, self.opt_state, self._rng) = \
            self._commit_carry(
                (self.params, self.state, self.opt_state, self._rng))
        self._guard = self._fresh_guard()
        epoch = self.finished_epochs
        while epoch < epochs:
            self._maybe_preempt(epoch, 0)
            # chaos hook: poison planned rows of this epoch's (copy-on-
            # write) inputs so the in-dispatch NaN guard has real work
            xs_e, y_e = xs, y
            plan = faults.fire("estimator.resident_nan_rows")
            if plan is not None and plan.action == "nan":
                rows = jnp.asarray(plan.payload)

                def _poison(a):
                    if jnp.issubdtype(a.dtype, jnp.floating):
                        return a.at[rows].set(jnp.nan)
                    return a

                xs_e = [_poison(a) for a in xs]
                y_e = _poison(y)
            t0 = time.time()
            with timeit("estimator/resident_epoch"):
                _, mean_loss = self._dispatch_step(
                    "epoch", xs_e, y_e, epoch_fn=epoch_fn,
                    epoch_steps=steps)
                # epoch-granular sync: the entire epoch is ONE jitted
                # dispatch, so this float() blocks once per epoch, not
                # per batch — exactly the granularity we want
                mean_loss = float(mean_loss)  # zoolint: disable=JG-TRANSFER-HOT(one sync per epoch by design; the loop variable here is epochs, not batches)
            if self._check_nan_guard(steps):
                epoch = self.finished_epochs    # rolled back
                continue
            dt = time.time() - t0
            epoch += 1
            if self._epoch_bookkeeping(epoch, mean_loss, dt,
                                       steps * eff_batch, validation_data,
                                       batch_size, verbose, end_trigger):
                break
        if self._ckpt_mgr is not None:
            self._ckpt_mgr.wait()   # join any in-flight async write
        return self.history

    def _build_stream_shard(self, plan, shuffle: bool):
        """ONE jitted program per STREAM shard: permute the shard's rows
        on device (level 2 of the two-level shuffle), then a
        ``fori_loop`` of ``steps_per_shard`` train steps gathers each
        minibatch from the resident shard in-step — the shard analog of
        ``_build_resident_epoch``, compiled once and reused for every
        shard of every epoch (all shards share one static shape).

        Differences from the resident epoch program:

        - the epoch loss accumulator ``{"sum", "good"}`` rides through
          ``xs[0]`` instead of starting at zero, so per-step losses
          accumulate across shards in the SAME device-side add order as
          the resident single-dispatch epoch (bit-exact parity);
        - the in-shard permutation arrives as ``xs[1]`` — a replicated
          int32 vector the uploader derives host-side from
          ``(seed, epoch, shard_id)`` alone
          (data/streaming.shard_permutation), NOT from the carried
          device rng: every host of a multi-controller mesh gathers by
          the identical permutation with zero coordination, and a
          resumed shard cursor replays it exactly at any topology;
        - quantized feature leaves arrive as ``{"q", "scale", "zero"}``
          pytrees and are decoded in-kernel AFTER the minibatch gather
          (ops/quantization.dequantize_features) — only the gathered
          rows pay the decode, and HBM holds 1-byte rows."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from analytics_zoo_tpu.ops.quantization import dequantize_features

        key = (plan.shard_rows, plan.eff_batch, plan.steps_per_shard,
               bool(shuffle), plan.cache_dtype, plan.quantized)
        if self._stream_shard is not None and self._stream_shard_key == key:
            return self._stream_shard
        if self._train_step is None:
            self._build_train_step()
        single = self._single_step_fn
        mesh = self.ctx.mesh
        data_axis = self.ctx.data_axis
        eff_batch = plan.eff_batch
        steps = plan.steps_per_shard

        def constrain(v):
            return jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, P(data_axis,
                                         *([None] * (v.ndim - 1)))))

        def gather(leaf, idx):
            if isinstance(leaf, dict):
                q = jnp.take(leaf["q"], idx, axis=0)
                return constrain(
                    dequantize_features(q, leaf["scale"], leaf["zero"]))
            return constrain(jnp.take(leaf, idx, axis=0))

        def shard(params, state, opt_state, rng, guard, xs, y):
            acc, perm, arrays = xs[0], xs[1], xs[2:]

            def body(i, carry):
                p, s, o, r, g, loss_sum, good = carry
                idx = jax.lax.dynamic_slice_in_dim(perm, i * eff_batch,
                                                   eff_batch)
                bxs = [gather(a, idx) for a in arrays]
                by = gather(y, idx)
                p, s, o, r, g, loss = single(p, s, o, r, g, bxs, by)
                finite = jnp.isfinite(loss)
                loss_sum = loss_sum + jnp.where(finite, loss, 0.0)
                good = good + finite.astype(jnp.int32)
                return (p, s, o, r, g, loss_sum, good)

            carry = (params, state, opt_state, rng, guard,
                     acc["sum"], acc["good"])
            (params, state, opt_state, rng, guard, loss_sum,
             good) = jax.lax.fori_loop(0, steps, body, carry)
            return (params, state, opt_state, rng, guard,
                    {"sum": loss_sum, "good": good})

        # carry donated; the shard arrays are NOT (their HBM slots are
        # recycled by the uploader via the lease protocol), and neither
        # is the accumulator (its leaf doubles as the release sync
        # handle, so the buffer must survive the dispatch)
        self._stream_shard = jax.jit(shard, donate_argnums=(0, 1, 2, 3, 4))
        self._stream_shard_key = key
        return self._stream_shard

    def _stream_host_tail(self, fs, plan, order, from_shard, acc,
                          perm_fn=None):
        """Finish a STREAM epoch on the host path after an uploader
        failure: the remaining shards of the epoch's order train through
        per-batch ``device_put`` dispatches (each shard's rows in the
        same ``perm_fn`` order the stream program would have gathered) —
        degraded throughput, but the epoch completes with full row
        coverage and the losses fold into the same device accumulator.
        Returns ``(acc, steps_trained)``."""
        steps = 0
        losses = []
        for pos in range(from_shard, plan.n_shards):
            shard_id = int(order[pos])
            arrays = plan.load_shard(fs, shard_id)
            if perm_fn is not None:
                perm = np.asarray(perm_fn(shard_id))
                arrays = [np.asarray(a)[perm] for a in arrays]
            for s in range(plan.steps_per_shard):
                sl = slice(s * plan.eff_batch, (s + 1) * plan.eff_batch)
                bx = [np.asarray(a[sl]) for a in arrays[:-1]]
                by = np.asarray(arrays[-1][sl])
                bx, by = self._inject_step_faults(bx, by)
                batch = self._shard_batch(bx + [by])
                _, loss = self._dispatch_step("1", batch[:-1], batch[-1])
                losses.append(loss)
                steps += 1
        if losses:
            # fold the host-path step losses into the device accumulator
            # (device->device, eager) so the epoch mean covers every
            # trained step with the resident finite-only semantics
            stack = jnp.stack([jnp.asarray(l) for l in losses])
            finite = jnp.isfinite(stack)
            acc = {"sum": acc["sum"]
                   + jnp.sum(jnp.where(finite, stack, 0.0)),
                   "good": acc["good"]
                   + jnp.sum(finite.astype(jnp.int32))}
        return acc, steps

    def _fit_stream(self, fs, batch_size, epochs, validation_data,
                    end_trigger, verbose, shuffle):
        """The STREAM tier: rotate budget-sized shards through HBM with
        a double-buffered background uploader
        (data/streaming.ShardUploader) while each resident shard trains
        as ONE jitted dispatch (``_build_stream_shard``) — datasets
        bigger than the device budget keep the resident path's
        zero-per-batch-transfer property, paying ``n_shards`` uploads
        per epoch that overlap compute.

        Failure story: a mid-rotation uploader crash
        (:class:`~analytics_zoo_tpu.data.streaming.StreamUploadError`)
        finishes the epoch's remaining shards through the host path —
        the epoch is never lost — and the next epoch retries a fresh
        uploader.  Preemption flushes a manifest whose
        ``in_epoch_step`` encodes the shard cursor
        (``shards_done * steps_per_shard``); resume re-derives the
        epoch's shard order from (seed, epoch) and restarts at that
        exact shard.

        Multi-controller: each process streams only the shard rows its
        devices own (``plan.process_view``), the rotation rendezvouses
        at ``zoo_data_*`` deadline barriers (epoch start on this
        thread, per staged shard on the uploader thread) so a dead or
        straggling peer surfaces as a typed ``HostLostError`` on every
        survivor instead of a hang, and the host-tail fallback is
        DISABLED — one host degrading to per-batch dispatches while its
        peers run the shard program would deadlock the mesh's
        collectives, so an upload failure is fatal here.  The plan's
        geometry is a pure function of (budget, global batch), so a
        preempted shard cursor resumes at any process count."""
        from analytics_zoo_tpu.data import streaming as stream_lib

        cfg = self.ctx.config
        plan = self._stream_plan
        if plan is None:    # direct call without the router: re-derive
            plan, why = stream_lib.plan_stream(
                fs, int(cfg.data_device_budget_bytes),
                self._global_eff_batch(batch_size),
                slots=cfg.data_stream_slots,
                cache_dtype=(None if self.ctx.process_count > 1
                             else cfg.data_cache_dtype))
            if plan is None:
                raise ValueError(f"stream fit infeasible: {why}")
        self._ensure_built(plan.probe_inputs(fs))
        shard_fn = self._build_stream_shard(plan, shuffle)
        steps = plan.steps_per_shard
        mc = self.ctx.process_count > 1
        view = plan.process_view(self.ctx) if mc else None
        pair_structured = getattr(self.loss_fn, "batch_structured", False)
        if self._val_trigger is not None:
            logger.warning(
                "stream path dispatches whole shards; validation_trigger "
                "is evaluated at epoch boundaries only")
        # shard-granular resume: the manifest's in_epoch_step was written
        # as shards_done * steps_per_shard, and the shard order re-derives
        # from (seed, epoch) — no carried rng state to restore
        start_shard = 0
        if self._pending_resume is not None:
            r_epoch, r_step, _ = self._pending_resume
            self._pending_resume = None
            if r_epoch == self.finished_epochs and r_step > 0:
                start_shard = min(r_step // steps, plan.n_shards)
                logger.info("stream resume: epoch %d restarts at shard "
                            "%d/%d", r_epoch + 1, start_shard,
                            plan.n_shards)
        # commit the carry under the mesh BEFORE the first dispatch
        # (same compile-stability reasoning as _fit_device_resident)
        (self.params, self.state, self.opt_state, self._rng) = \
            self._commit_carry(
                (self.params, self.state, self.opt_state, self._rng))
        self._guard = self._fresh_guard()
        epoch = self.finished_epochs
        while epoch < epochs:
            t0 = time.time()
            order = plan.epoch_order(cfg.seed, epoch, shuffle)
            acc = self._commit_carry({"sum": np.zeros((), np.float32),
                                      "good": np.zeros((), np.int32)})

            def perm_fn(shard_id, _epoch=epoch):
                return plan.shard_perm(cfg.seed, _epoch, shard_id,
                                       shuffle=shuffle,
                                       pair_structured=pair_structured)

            barrier_fn = None
            if mc:
                # a fresh monotone rotation id per uploader keeps the
                # zoo_data_* barrier names unique for the life of the
                # coordination service (a NaN rollback replays an epoch,
                # and wait_at_barrier rejects name reuse)
                self._data_rotation += 1
                rot = self._data_rotation
                w = dist_barrier(f"zoo_data_epoch_r{rot}",
                                 phase="zoo_data_epoch")
                obs.observe("checkpoint_barrier_wait_ms", w * 1e3,
                            phase="zoo_data_epoch",
                            flat="checkpoint/barrier_zoo_data_epoch_ms")

                def barrier_fn(pos, _rot=rot):
                    bw = dist_barrier(f"zoo_data_shard_r{_rot}_p{pos}",
                                      phase="zoo_data_shard")
                    obs.observe("checkpoint_barrier_wait_ms", bw * 1e3,
                                phase="zoo_data_shard",
                                flat="checkpoint/barrier_zoo_data_shard_ms")

            uploader = stream_lib.ShardUploader(
                fs, plan, order, self.ctx, start=start_shard, view=view,
                perm_fn=perm_fn, barrier_fn=barrier_fn)
            wait_ms = 0.0
            trained = 0
            try:
                shards_done = start_shard
                while shards_done < plan.n_shards:
                    self._maybe_preempt(epoch, shards_done * steps)
                    try:
                        tw = time.perf_counter()
                        lease = uploader.get()
                        wait_ms += (time.perf_counter() - tw) * 1e3
                    except stream_lib.StreamUploadError as e:
                        if mc:
                            # one host finishing on per-batch dispatches
                            # while its peers run the shard program
                            # would deadlock the mesh's collectives —
                            # surface the failure instead of degrading
                            raise
                        obs.count("data_stream_fallbacks_total",
                                  reason="upload_error",
                                  flat="estimator/stream_fallbacks")
                        logger.warning(
                            "shard uploader failed mid-rotation (%s); "
                            "finishing epoch %d on the host path (%d/%d "
                            "shards remain)", e, epoch + 1,
                            plan.n_shards - shards_done, plan.n_shards)
                        acc, tail = self._stream_host_tail(
                            fs, plan, order, shards_done, acc,
                            perm_fn=perm_fn)
                        trained += tail
                        break
                    with timeit("estimator/stream_shard"):
                        _, acc = self._dispatch_step(
                            "shard", [acc, lease.perm] + list(lease.xs),
                            lease.y, epoch_fn=shard_fn, epoch_steps=steps)
                    # the accumulator leaf is this shard's sync handle:
                    # its HBM slot may be overwritten only after this
                    # shard's compute has finished
                    lease.release(after=acc["sum"])
                    trained += steps
                    if plan.decode_bytes_per_shard:
                        obs.count("data_decode_bytes_total",
                                  plan.decode_bytes_per_shard,
                                  dtype=plan.cache_dtype,
                                  flat="stream/decode_bytes")
                    shards_done += 1
            finally:
                up_stats = uploader.stats()
                uploader.close()
            start_shard = 0
            if self._check_nan_guard(max(trained, 1)):
                epoch = self.finished_epochs    # rolled back
                continue
            # epoch-granular sync: the mean divides in f32 host-side so
            # it matches the resident program's on-device division bit
            # for bit
            g = jax.device_get(acc)  # zoolint: disable=JG-TRANSFER-HOT(one sync per epoch by design; the loop variable here is epochs, not batches)
            mean_loss = float(np.float32(g["sum"])
                              / np.maximum(g["good"], 1).astype(np.float32))
            # overlap counter-proof: 1 - (consumer blocked on uploads /
            # total upload wall time).  ~1.0 = uploads fully hidden
            # behind compute; ~0.0 = the rotation is upload-bound
            up = up_stats["upload_ms_total"]
            overlap = 1.0 if up <= 0 else min(
                1.0, max(0.0, 1.0 - wait_ms / up))
            obs.set_gauge("data_stream_overlap_frac", overlap,
                          flat="stream/overlap_frac")
            dt = time.time() - t0
            epoch += 1
            if self._epoch_bookkeeping(epoch, mean_loss, dt,
                                       trained * plan.eff_batch,
                                       validation_data, batch_size,
                                       verbose, end_trigger):
                break
        if self._ckpt_mgr is not None:
            self._ckpt_mgr.wait()   # join any in-flight async write
        return self.history

    def _fit_featureset(self, fs, batch_size, epochs, validation_data,
                        end_trigger, verbose, shuffle=True):
        """Train from a FeatureSet (iterator-based, supports DISK_AND_DRAM)."""
        first = True
        cfg = self.ctx.config
        K = max(1, int(cfg.steps_per_execution))
        # bounded shuffle window keeps disk-backed tiers near-sequential
        shuffle_buffer = (cfg.shuffle_buffer
                          if fs.memory_type != "DRAM" else None)
        if self._pending_resume is not None:
            # FeatureSet iterators own their shuffle stream, so resume
            # granularity is the epoch boundary: restart the interrupted
            # epoch from the restored (mid-epoch) weights
            if self._pending_resume[1] > 0:
                logger.warning("FeatureSet path resumes at epoch "
                               "boundaries; restarting the interrupted epoch")
            self._pending_resume = None
        self._guard = self._fresh_guard()
        epoch = self.finished_epochs
        while epoch < epochs:
            t0 = time.time()
            losses = []
            count = 0
            in_epoch = 0
            raw = fs.batches(batch_size, shuffle=shuffle,
                             drop_remainder=True,
                             pad_to=self.ctx.num_devices,
                             shuffle_buffer=shuffle_buffer)
            if first:
                # peek one batch to build params/steps, then chain it back
                import itertools
                raw = iter(raw)
                try:
                    peek = next(raw)
                except StopIteration:
                    raise ValueError(
                        f"FeatureSet ({len(fs)} rows) yields no full batch "
                        f"of {batch_size} (drop_remainder)") from None
                self._ensure_built(list(peek[:-1]))
                if self._train_step is None:
                    self._build_train_step()
                if K > 1 and self._multi_step is None:
                    self._build_multi_step()
                first = False
                raw = itertools.chain([peek], raw)

            def chunked(it):
                """Group K same-shape batches into (K, B, ...) stacks
                (drop_remainder=True guarantees uniform shapes)."""
                buf = []
                for b in it:
                    buf.append(b)
                    if len(buf) == K:
                        yield ("K", [np.stack([bb[j] for bb in buf])
                                     for j in range(len(buf[0]))])
                        buf = []
                for b in buf:
                    yield ("1", list(b))

            def prep(item):
                kind, arrs = item
                *bx, by = arrs
                bx, by = self._inject_step_faults(bx, by)
                put = self._shard_chunk if kind == "K" else self._shard_batch
                rows = (by.shape[0] * by.shape[1] if kind == "K"
                        else by.shape[0])
                return kind, put(list(bx)), put([by])[0], rows

            src = chunked(raw) if K > 1 else (("1", list(b)) for b in raw)
            batches = prefetch_lib.prefetch(src, prep,
                                            depth=cfg.data_prefetch)
            try:
                for kind, batch_x, batch_y, bn in batches:
                    self._maybe_preempt(epoch, in_epoch)
                    k, loss = self._dispatch_step(kind, batch_x, batch_y)
                    in_epoch += k
                    count += bn
                    losses.append(loss)
                    self._maybe_midepoch_validation(validation_data,
                                                    epoch + 1, batch_size)
            except BaseException:
                if hasattr(batches, "close"):
                    batches.close()
                raise
            if self._check_nan_guard(in_epoch):
                epoch = self.finished_epochs    # rolled back
                continue
            mean_loss = float(jnp.nanmean(jnp.concatenate(
                    [jnp.atleast_1d(l) for l in losses])))
            dt = time.time() - t0
            epoch += 1
            if self._epoch_bookkeeping(epoch, mean_loss, dt, count,
                                       validation_data, batch_size,
                                       verbose, end_trigger):
                break
        if self._ckpt_mgr is not None:
            self._ckpt_mgr.wait()   # join any in-flight async write
        return self.history

    # ------------------------------------------------------------------
    # evaluate / predict
    # ------------------------------------------------------------------
    def evaluate(self, x, y=None, batch_size: int = 32) -> Dict[str, float]:
        xs = _as_list(x)
        self._ensure_built(xs)
        if self._eval_step is None:
            self._build_eval_step()
        n = xs[0].shape[0]
        d = self.ctx.num_devices
        eff_batch = int(math.ceil(max(batch_size, d) / d)) * d
        y = np.asarray(y)
        agg = None
        for s in range(int(math.ceil(n / eff_batch))):
            sl = slice(s * eff_batch, min((s + 1) * eff_batch, n))
            bx = [a[sl] for a in xs]
            by = y[sl]
            mask = np.ones((by.shape[0],), np.float32)
            (bx_p, real) = self._pad_to_devices(bx, eff_batch)
            (by_p, _) = self._pad_to_devices([by], eff_batch)
            (mask_p, _) = self._pad_to_devices([mask], eff_batch)
            stats = self._eval_step(self.params, self.state,
                                    self._shard_batch(bx_p),
                                    self._shard_batch(by_p)[0],
                                    self._shard_batch(mask_p)[0])
            # accumulate ON DEVICE (async dispatch) — device_get here
            # would force a host sync every batch (JG-TRANSFER-HOT)
            agg = stats if agg is None else jax.tree_util.tree_map(
                jnp.add, agg, stats)
        # finalize ON DEVICE in one jitted call (metrics are
        # jit-friendly by design; eager finalize would re-upload its
        # scalar constants), then ONE device->host transfer for the
        # whole evaluation pass
        def _finalize(a):
            out = {"loss": a["__loss"]["loss_sum"] / a["__loss"]["count"]}
            for m in self.metrics:
                out[m.name] = m.finalize(a[m.name])
            return out

        finals = jax.device_get(jax.jit(_finalize)(agg))
        return {k: float(v) for k, v in finals.items()}

    def predict(self, x, batch_size: int = 32) -> np.ndarray:
        out = self.predict_raw(x, batch_size=batch_size)
        return out[0]

    def predict_classes(self, x, batch_size: int = 32,
                        zero_based_label: bool = True) -> np.ndarray:
        """Class indices from the model's scores (reference
        Predictable.predictClasses, Predictor.scala:226-416); 1-based
        when ``zero_based_label=False`` (BigDL convention)."""
        scores = self.predict(x, batch_size=batch_size)
        scores = np.asarray(scores)
        if scores.ndim == 1 or scores.shape[-1] == 1:
            cls = (scores.reshape(len(scores)) > 0.5).astype(np.int64)
        else:
            cls = np.argmax(scores, axis=-1).astype(np.int64)
        return cls if zero_based_label else cls + 1

    def predict_raw(self, x, batch_size: int = 32) -> List[np.ndarray]:
        """Like predict but preserves multi-output models: returns one
        array per model output (single-output models → a 1-list)."""
        xs = _as_list(x)
        self._ensure_built(xs)
        if self._predict_step is None:
            self._build_predict_step()
        n = xs[0].shape[0]
        d = self._data_div
        eff_batch = int(math.ceil(max(batch_size, d) / d)) * d
        # Multi-controller: the replicated global output interleaves every
        # process's rows at the global indices its addressable devices own
        # under the data sharding.  create_device_mesh permutes devices for
        # ICI topology, so those rows are NOT necessarily a contiguous
        # process-major slice — derive the index set from the sharding.
        multiproc = self.ctx.process_count > 1
        # every batch is padded to eff_batch rows, so the index map is the
        # same for all of them — compute it once
        row_idx = (self._local_row_indices(
            eff_batch * self.ctx.process_count) if multiproc else None)
        outs: Optional[List[List[np.ndarray]]] = None
        for s in range(int(math.ceil(n / eff_batch))):
            sl = slice(s * eff_batch, min((s + 1) * eff_batch, n))
            bx = [a[sl] for a in xs]
            bx_p, real = self._pad_to_devices(bx, eff_batch)
            preds = self._predict_step(self.params, self.state,
                                       self._shard_batch(bx_p))
            # predictions ARE the output: they must land on host, and
            # fetching per batch bounds peak HBM for arbitrarily large n
            preds = jax.device_get(preds)  # zoolint: disable=JG-TRANSFER-HOT(outputs must reach the host; per-batch readback bounds device memory for large inputs)
            if not isinstance(preds, (list, tuple)):
                preds = [preds]
            if outs is None:
                outs = [[] for _ in preds]
            for o, p in zip(outs, preds):
                p = np.asarray(p)
                if row_idx is not None:
                    p = p[row_idx]
                o.append(p[:real])
        return [np.concatenate(o, axis=0) for o in outs]

    def _local_row_indices(self, global_rows: int) -> np.ndarray:
        """Ascending global row indices owned by THIS process's devices
        under the data sharding.  ``make_array_from_process_local_data``
        lays a process's local rows into exactly these positions (local
        order ↔ ascending global shard index), so gathering them back
        recovers the local batch — including padding at the tail —
        regardless of how ``create_device_mesh`` permuted the devices."""
        shard = self.ctx.data_sharding()
        idx_map = shard.addressable_devices_indices_map((global_rows,))
        spans = {(s[0].start or 0,
                  global_rows if s[0].stop is None else s[0].stop)
                 for s in idx_map.values()}   # dedup: tp/pp replicas share rows
        return np.concatenate(
            [np.arange(a, b) for a, b in sorted(spans)])

    # ------------------------------------------------------------------
    # checkpoint plumbing
    # ------------------------------------------------------------------
    def _snapshot(self, resume_epoch: Optional[int] = None,
                  in_epoch_step: int = 0, epoch_rng_state=None):
        """Full training state: model/opt/rng plus the resume manifest
        (docs/ROBUSTNESS.md).  Host rng states are pickled numpy
        ``RandomState`` tuples stored as uint8 arrays — ``epoch_rng`` is
        the stream position at the START of the (possibly interrupted)
        epoch so a mid-epoch resume can redraw the identical shuffle."""
        if epoch_rng_state is None:
            epoch_rng_state = self._host_rng.get_state()
        meta = {"global_step": np.asarray(self.global_step),
                "finished_epochs": np.asarray(self.finished_epochs),
                "rng": np.asarray(self._rng),
                "lr_scale": np.asarray(self._lr_scale, np.float32),
                "resume_epoch": np.asarray(
                    self.finished_epochs if resume_epoch is None
                    else resume_epoch),
                "in_epoch_step": np.asarray(in_epoch_step),
                "data_path": np.asarray(self.last_data_path or "unset"),
                "host_rng": np.frombuffer(
                    pickle.dumps(self._host_rng.get_state()), np.uint8),
                "epoch_rng": np.frombuffer(
                    pickle.dumps(epoch_rng_state), np.uint8)}
        return {"params": self.params, "state": self.state,
                "opt_state": self.opt_state, "meta": meta}

    def _save_checkpoint(self):
        with timeit("estimator/checkpoint_save"):
            if self.ctx.config.async_checkpoint:
                path = self._ckpt_mgr.save_async(self.global_step,
                                                 self._snapshot())
            else:
                path = self._ckpt_mgr.save(self.global_step, self._snapshot())
        logger.info("checkpoint saved: %s", path)

    def _restore_checkpoint(self):
        from analytics_zoo_tpu.parallel.sharding import tree_put_global
        step, tree = self._ckpt_mgr.restore()
        rep = self.ctx.replicated_sharding()
        # Elastic table growth: if the live model was built with MORE
        # embedding rows than the snapshot (vocabulary grew between
        # runs), merge the restored rows into the freshly built tables —
        # snapshot rows bit-exact, new rows keep fresh init, new rows'
        # optimizer moments zero (== fresh tx.init).
        tables = getattr(self.model, "_sharded_tables", None) or \
            getattr(self.model, "_elastic_tables", None)
        if tables and self.params is not None:
            from analytics_zoo_tpu.parallel.table_sharding import (
                grow_restored_opt_state, grow_restored_tree)
            tree["params"] = grow_restored_tree(
                tree["params"], self.params, tables)
            tree["opt_state"] = grow_restored_opt_state(
                tree["opt_state"], jax.eval_shape(self.tx.init, self.params))
        # tree_put_global is the reshard-on-restore seam: restore hands
        # back the FULL global host tree on every process, and placement
        # re-lays it onto whatever mesh is live now — so a checkpoint
        # written at one process count resumes at another
        self.params = tree_put_global(tree["params"],
                                      self._param_shardings(tree["params"]))
        self.state = tree_put_global(tree["state"], rep)
        try:
            # mirror a fresh init's shardings (matches TP param splits)
            self.opt_state = tree_put_global(tree["opt_state"],
                                             self._opt_shardings())
        except (ValueError, TypeError) as e:
            logger.warning(
                "optimizer-state shardings could not be mirrored (%s); "
                "restoring replicated — TP runs lose opt-state sharding", e)
            self.opt_state = tree_put_global(tree["opt_state"], rep)
        self.global_step = int(tree["meta"]["global_step"])
        self.finished_epochs = int(tree["meta"]["finished_epochs"])
        meta = tree["meta"]
        if "rng" in meta:   # resume the dropout/shuffle rng stream
            self._rng = jnp.asarray(meta["rng"])
        else:
            # pre-rng-meta checkpoint: the live key may be a donated
            # (deleted) buffer after a failed step — re-seed so retry works
            self._rng = jax.random.fold_in(
                explicit_prng_key(self.ctx.config.seed), step)
        if "lr_scale" in meta:
            self._lr_scale = float(meta["lr_scale"])
        if "host_rng" in meta and np.asarray(meta["host_rng"]).size:
            st = pickle.loads(np.asarray(meta["host_rng"]).tobytes())
            self._host_rng = np.random.RandomState()
            self._host_rng.set_state(st)
        # Resume manifest.  Armed whenever an epoch-start rng state was
        # recorded, even at in_epoch_step == 0: a preemption flush on the
        # FIRST iteration of an epoch happens after that epoch's shuffle
        # permutation was already drawn, so the restart must rewind the
        # host rng to the epoch start or it redraws a different perm.
        # (For ordinary boundary snapshots epoch_rng equals host_rng and
        # the rewind is a no-op.)
        self._pending_resume = None
        r_step = int(meta["in_epoch_step"]) if "in_epoch_step" in meta else 0
        rng_state = None
        if "epoch_rng" in meta and np.asarray(meta["epoch_rng"]).size:
            rng_state = pickle.loads(
                np.asarray(meta["epoch_rng"]).tobytes())
        if r_step > 0 or rng_state is not None:
            r_epoch = int(meta.get("resume_epoch", self.finished_epochs))
            self._pending_resume = (r_epoch, r_step, rng_state)
        logger.info("restored checkpoint step %d", step)

    def load_checkpoint(self, directory: str):
        self.set_checkpoint(directory)
        self._restore_checkpoint()
        return self
