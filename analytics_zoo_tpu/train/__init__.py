from analytics_zoo_tpu.train import checkpoint, optimizers  # noqa: F401
from analytics_zoo_tpu.train.estimator import Estimator  # noqa: F401
from analytics_zoo_tpu.train.local_estimator import LocalEstimator  # noqa: F401
