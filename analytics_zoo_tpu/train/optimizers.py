"""Optimizers — optax-based, with the reference's Zoo-specific extras.

Reference capability: api/keras/optimizers/Adam.scala (147 LoC, Adam with
pluggable LR schedules) and AdamWeightDecay.scala (155 LoC, BERT-style
decoupled weight decay + linear warmup/decay), plus the BigDL optimizers
reachable through string lowering (sgd/rmsprop/adagrad/adadelta/adamax).

Everything returns an ``optax.GradientTransformation`` so the train step is
one fused XLA program (no per-parameter Python loops).

``opt_state_shardings`` is the partition rule that keeps optimizer
state co-located with the params it updates: any opt-state subtree
shaped like the params pytree (Adam mu/nu, SGD momentum, Adagrad
accumulators...) inherits the params' shardings leaf-for-leaf — so a
row-sharded embedding table's moments are row-sharded over the same
mesh axis, and the update never allgathers them — while scalar
bookkeeping (step counts) replicates.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import optax

ScheduleOrFloat = Union[float, Callable[[int], float]]


def opt_state_shardings(tx: optax.GradientTransformation, params,
                        param_shardings, replicated):
    """Sharding pytree for ``tx.init(params)``: params-shaped subtrees
    take ``param_shardings`` (optimizer moments follow their params'
    placement — the rule that keeps a sharded table's Adam state
    sharded); every other leaf takes ``replicated``.

    Matching is structural (``tree_structure`` equality against the
    params pytree), so the rule covers any optax chain without
    per-optimizer special cases."""
    ptree = jax.tree_util.tree_structure(params)
    opt_shapes = jax.eval_shape(tx.init, params)

    def is_params_like(sub):
        try:
            return jax.tree_util.tree_structure(sub) == ptree
        except Exception:
            return False

    def map_sub(sub):
        if is_params_like(sub):
            return param_shardings
        return jax.tree_util.tree_map(lambda _: replicated, sub)

    return jax.tree_util.tree_map(map_sub, opt_shapes,
                                  is_leaf=is_params_like)


def make_schedule(lr: ScheduleOrFloat, schedule: Optional[str] = None,
                  decay: float = 0.0, warmup_steps: int = 0,
                  total_steps: Optional[int] = None):
    """Build an optax schedule from Keras/Zoo-style knobs.

    ``decay`` replicates Keras' ``lr / (1 + decay * iterations)``;
    ``schedule`` in {poly, cosine, exponential} covers the Zoo SGD
    schedules; warmup covers AdamWeightDecay's warmup portion.
    """
    if callable(lr):
        return lr
    base = float(lr)

    if schedule is None:
        if decay:
            sched = lambda step: base / (1.0 + decay * step)  # noqa: E731
        else:
            sched = optax.constant_schedule(base)
    elif schedule == "poly":
        assert total_steps, "poly schedule needs total_steps"
        sched = optax.polynomial_schedule(base, 0.0, power=1.0,
                                          transition_steps=total_steps)
    elif schedule == "cosine":
        assert total_steps, "cosine schedule needs total_steps"
        sched = optax.cosine_decay_schedule(base, decay_steps=total_steps)
    elif schedule == "exponential":
        sched = optax.exponential_decay(base, transition_steps=1000,
                                        decay_rate=0.96)
    else:
        raise ValueError(f"unknown schedule {schedule!r}")

    if warmup_steps > 0:
        warm = optax.linear_schedule(0.0, base, warmup_steps)
        sched = optax.join_schedules([warm, sched], [warmup_steps])
    return sched


def Adam(lr: ScheduleOrFloat = 1e-3, beta_1: float = 0.9,
         beta_2: float = 0.999, epsilon: float = 1e-8, decay: float = 0.0,
         schedule: Optional[str] = None, warmup_steps: int = 0,
         total_steps: Optional[int] = None) -> optax.GradientTransformation:
    """Reference api/keras/optimizers/Adam.scala (schedule-aware Adam)."""
    sched = make_schedule(lr, schedule, decay, warmup_steps, total_steps)
    return optax.adam(sched, b1=beta_1, b2=beta_2, eps=epsilon)


def AdamWeightDecay(lr: ScheduleOrFloat = 1e-3, warmup_portion: float = -1.0,
                    total: int = -1, schedule: str = "linear",
                    beta_1: float = 0.9, beta_2: float = 0.999,
                    epsilon: float = 1e-6, weight_decay: float = 0.01
                    ) -> optax.GradientTransformation:
    """BERT-style AdamW (reference AdamWeightDecay.scala:
    linear warmup over ``warmup_portion * total`` steps, then linear decay
    to zero over ``total`` steps, decoupled weight decay)."""
    if total > 0:
        warmup = int(max(warmup_portion, 0.0) * total)
        sched = optax.join_schedules(
            [optax.linear_schedule(0.0, float(lr), max(warmup, 1)),
             optax.linear_schedule(float(lr), 0.0, max(total - warmup, 1))],
            [max(warmup, 1)])
    else:
        sched = make_schedule(lr)
    return optax.adamw(sched, b1=beta_1, b2=beta_2, eps=epsilon,
                       weight_decay=weight_decay)


def SGD(lr: ScheduleOrFloat = 0.01, momentum: float = 0.0,
        decay: float = 0.0, nesterov: bool = False,
        schedule: Optional[str] = None, warmup_steps: int = 0,
        total_steps: Optional[int] = None) -> optax.GradientTransformation:
    sched = make_schedule(lr, schedule, decay, warmup_steps, total_steps)
    return optax.sgd(sched, momentum=momentum or None, nesterov=nesterov)


def RMSprop(lr: ScheduleOrFloat = 1e-3, rho: float = 0.9,
            epsilon: float = 1e-8, decay: float = 0.0):
    return optax.rmsprop(make_schedule(lr, decay=decay), decay=rho, eps=epsilon)


def Adagrad(lr: ScheduleOrFloat = 0.01):
    return optax.adagrad(make_schedule(lr))


def Adadelta(lr: ScheduleOrFloat = 1.0, rho: float = 0.95,
             epsilon: float = 1e-8):
    return optax.adadelta(make_schedule(lr), rho=rho, eps=epsilon)


def Adamax(lr: ScheduleOrFloat = 2e-3, beta_1: float = 0.9,
           beta_2: float = 0.999, epsilon: float = 1e-8):
    return optax.adamax(make_schedule(lr), b1=beta_1, b2=beta_2, eps=epsilon)


_REGISTRY = {
    "adam": Adam,
    "adamweightdecay": AdamWeightDecay,
    "adamw": AdamWeightDecay,
    "sgd": SGD,
    "rmsprop": RMSprop,
    "adagrad": Adagrad,
    "adadelta": Adadelta,
    "adamax": Adamax,
}


def get(optimizer) -> optax.GradientTransformation:
    """String → optimizer lowering (reference KerasUtils.scala:165-167)."""
    if isinstance(optimizer, optax.GradientTransformation):
        return optimizer
    if callable(optimizer) and not isinstance(optimizer, str):
        return optimizer()
    key = str(optimizer).lower()
    if key not in _REGISTRY:
        raise ValueError(f"unknown optimizer {optimizer!r}; "
                         f"known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]()
