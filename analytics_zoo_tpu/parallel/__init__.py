from analytics_zoo_tpu.parallel.sharding import (  # noqa: F401
    AutoSharding,
    DataParallel,
    ExpertParallel,
    PipelineStrategy,
    SequenceParallel,
    ShardingStrategy,
    TensorParallel,
    make_strategy,
    replica_devices,
)
from analytics_zoo_tpu.parallel.mode import (  # noqa: F401
    PipelineMode,
    SeqParallelMode,
    current_pipeline,
    current_seq_parallel,
    parallel_mode,
)
from analytics_zoo_tpu.parallel.sequence import (  # noqa: F401
    ring_attention,
    ring_self_attention,
)
from analytics_zoo_tpu.parallel.pipeline import (  # noqa: F401
    PipelineParallel,
    pipeline_apply,
    pipeline_spmd,
    stack_stage_params,
    stage_shardings,
)
