from analytics_zoo_tpu.parallel.sharding import (  # noqa: F401
    AutoSharding,
    DataParallel,
    ExpertParallel,
    PipelineStrategy,
    SequenceParallel,
    ShardingStrategy,
    TensorParallel,
    make_strategy,
    replica_devices,
)
from analytics_zoo_tpu.parallel.mode import (  # noqa: F401
    PipelineMode,
    SeqParallelMode,
    TableShardMode,
    current_pipeline,
    current_seq_parallel,
    current_table_sharding,
    parallel_mode,
    table_mode,
)
from analytics_zoo_tpu.parallel.table_sharding import (  # noqa: F401
    ROW_ALIGN,
    TablePlacement,
    TableShardedStrategy,
    choose_table_placement,
    ensure_table_sharding,
    grow_restored_opt_state,
    grow_restored_tree,
    init_table_sharded,
    padded_rows,
    resolve_table_ways,
    sharded_bag,
    sharded_gather,
)
from analytics_zoo_tpu.parallel.hot_cache import (  # noqa: F401
    CacheSnapshot,
    HotRowCache,
    cached_sharded_bag,
    cached_sharded_gather,
    cold_bucket,
    table_row_reader,
)
from analytics_zoo_tpu.parallel.sequence import (  # noqa: F401
    ring_attention,
    ring_self_attention,
)
from analytics_zoo_tpu.parallel.pipeline import (  # noqa: F401
    PipelineParallel,
    pipeline_apply,
    pipeline_spmd,
    stack_stage_params,
    stage_shardings,
)
