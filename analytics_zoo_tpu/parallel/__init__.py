from analytics_zoo_tpu.parallel.sharding import (  # noqa: F401
    AutoSharding,
    DataParallel,
    ShardingStrategy,
    TensorParallel,
    make_strategy,
)
from analytics_zoo_tpu.parallel.sequence import (  # noqa: F401
    ring_attention,
    ring_self_attention,
)
