"""Pipeline parallelism: GPipe-style microbatched SPMD pipeline.

Reference capability: **absent** (SURVEY.md §2.4 — the reference's only
distributed-training strategy is synchronous data parallelism; PP is an
explicit gap).  This module is the TPU-native upgrade: layer *stages* are
sharded over a ``pipe`` mesh axis (each device holds one stage's weights),
microbatches flow through the ring via ``lax.ppermute`` neighbour
exchanges over ICI, and the whole schedule — fill, steady state, drain —
is one ``lax.scan`` inside one jitted SPMD program.  No send/recv runtime,
no scheduler thread: the schedule is data.

Design notes (the scaling-book recipe, not a torch-pipe translation):
- All devices run the SAME program (SPMD).  Stage identity comes from
  ``lax.axis_index``; a device computes its stage function on whatever
  activation it currently holds.
- Stage weights live stacked along a leading ``n_stages`` dim which is
  sharded over the pipe axis, so each device materialises only its own
  stage (1/S of the pipeline's parameters) — the PP memory win.
- The loop runs ``n_micro + n_stages - 1`` ticks.  At tick ``t`` stage
  ``s`` computes microbatch ``t - s``; bubbles at fill/drain are the
  standard GPipe cost (fraction ``(S-1)/(M+S-1)``).
- Everything (ppermute, where, dynamic slicing) is differentiable, so
  ``jax.grad`` of a pipelined forward IS pipelined backward — the reverse
  schedule falls out of autodiff, with activations rematerialised per
  ``jax.checkpoint`` policy if requested.

Constraint: ``stage_fn`` must be shape-preserving (activation in == out),
the canonical homogeneous-stack regime (transformer blocks, MLP blocks).
Embedding/head layers run outside the pipeline — apply them before/after.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.parallel.sequence import mark_varying as _pvary

try:  # jax >= 0.8
    from jax import shard_map  # type: ignore
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map  # type: ignore

StageFn = Callable[[Any, jax.Array], jax.Array]


def pipeline_spmd(stage_fn: StageFn, stage_params, x, axis_name: str,
                  n_microbatches: int, remat: bool = False,
                  vary_axes=None, aux=None):
    """Per-device body — call inside shard_map/pjit with ``axis_name``.

    ``stage_params``: this device's stage slice, leading dim 1 (the shard
    of the stacked (S, ...) pytree).  ``x``: the (B, ...) batch local to
    this device's data group (replicated over the pipe axis — every
    stage sees it; only stage 0 reads it).
    Returns the (B, ...) output, replicated over the pipe axis via a
    final psum.  ``vary_axes``: all shard_map axes the scan carries are
    device-varying over — pass ``(pipe, data)`` when composing with a
    data axis (defaults to ``(axis_name,)``).
    ``aux``: optional pytree of per-row side inputs (leading dim B —
    attention masks, segment ids) consumed by EVERY stage alongside its
    activation.  Aux never rides the ppermute ring: it is replicated
    over the pipe axis, and stage ``s`` at tick ``t`` indexes microbatch
    ``t - s`` directly (the one whose activation it holds), so
    ``stage_fn(params, x, aux)`` sees matched pairs.
    """
    S = lax.psum(1, axis_name)
    s = lax.axis_index(axis_name)
    local = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    M = n_microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by n_microbatches {M}")
    mb = x.reshape((M, B // M) + x.shape[1:])
    aux_mb = jax.tree_util.tree_map(
        lambda a: a.reshape((M, B // M) + a.shape[1:]), aux)

    perm = [(i, (i + 1) % S) for i in range(S)]
    vary = vary_axes or (axis_name,)
    state0 = _pvary(jnp.zeros_like(mb[0]), vary)
    out0 = _pvary(jnp.zeros_like(mb), vary)

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (clip: drained ticks recompute the
        # last microbatch; their results are never collected)
        inj = lax.dynamic_index_in_dim(mb, jnp.clip(t, 0, M - 1), 0,
                                       keepdims=False)
        state = jnp.where(s == 0, inj, state)
        if aux is None:
            out = fn(local, state)
        else:
            # the microbatch whose activation this stage holds at tick t
            ai = jnp.clip(t - s, 0, M - 1)
            aux_t = jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, ai, 0,
                                                   keepdims=False), aux_mb)
            out = fn(local, state, aux_t)
        # last stage emits microbatch t-(S-1) once the pipeline is full
        oi = t - (S - 1)
        upd = lax.dynamic_update_index_in_dim(
            outputs, out, jnp.clip(oi, 0, M - 1), 0)
        outputs = jnp.where((s == S - 1) & (oi >= 0), upd, outputs)
        # rotate activations one stage forward around the ring (ICI
        # neighbour exchange; the wraparound into stage 0 is overwritten
        # by the next injection)
        state = lax.ppermute(out, axis_name, perm)
        return (state, outputs), None

    (_, outputs), _ = lax.scan(tick, (state0, out0),
                               jnp.arange(M + S - 1))
    # outputs are zero except on the last stage; psum replicates them
    outputs = lax.psum(outputs, axis_name)
    return outputs.reshape((B,) + x.shape[1:])


def pipeline_apply(stage_fn: StageFn, stacked_params, x, mesh: Mesh,
                   axis_name: str = "pipe", n_microbatches: int = 4,
                   remat: bool = False, batch_axis: str = None, aux=None):
    """Run a homogeneous stage stack as a pipeline over ``mesh[axis_name]``.

    ``stacked_params``: pytree whose leaves have leading dim
    ``n_stages == mesh axis size`` (stage i's weights at index i).
    ``x``: (B, ...) batch.  Shape-preserving ``stage_fn(params, x) -> x``
    — or ``stage_fn(params, x, aux_microbatch)`` when ``aux`` is given.

    ``batch_axis``: compose pp×dp — shard the batch dim over this mesh
    axis; each data group runs its own pipeline over its pipe ring (the
    per-group microbatch count is still ``n_microbatches``, so the local
    B/dp must divide by it).
    ``aux``: pytree of (B, ...) side inputs (attention masks etc.) every
    stage reads alongside its activation — see ``pipeline_spmd``.
    """
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if axis_name not in sizes:
        raise ValueError(f"pipeline axis {axis_name!r} not in mesh axes "
                         f"{tuple(mesh.axis_names)}")
    S = sizes[axis_name]
    for path, leaf in jax.tree_util.tree_leaves_with_path(stacked_params):
        if leaf.shape[:1] != (S,):
            raise ValueError(
                f"stacked param {jax.tree_util.keystr(path)} has leading "
                f"dim {leaf.shape[:1]}, expected ({S},) to shard over "
                f"{axis_name!r}")

    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis_name, *([None] * (p.ndim - 1))), stacked_params)
    x_spec = P(batch_axis) if batch_axis else P()
    vary = (axis_name, batch_axis) if batch_axis else (axis_name,)
    body = functools.partial(pipeline_spmd, stage_fn,
                             axis_name=axis_name,
                             n_microbatches=n_microbatches, remat=remat,
                             vary_axes=vary)
    if aux is None:
        fn = shard_map(lambda ps, xs: body(ps, xs), mesh=mesh,
                       in_specs=(param_specs, x_spec), out_specs=x_spec)
        return fn(stacked_params, x)
    aux_specs = jax.tree_util.tree_map(lambda a: x_spec, aux)
    fn = shard_map(lambda ps, xs, au: body(ps, xs, aux=au), mesh=mesh,
                   in_specs=(param_specs, x_spec, aux_specs),
                   out_specs=x_spec)
    return fn(stacked_params, x, aux)


def stack_stage_params(params_list):
    """Stack S per-stage pytrees (identical structure) into one pytree
    with leading dim S — the layout ``pipeline_apply`` shards."""
    return jax.tree_util.tree_map(
        lambda *ps: jnp.stack(ps, axis=0), *params_list)


def stage_shardings(mesh: Mesh, stacked_params, axis_name: str = "pipe"):
    """NamedShardings placing each stage's slice on its pipe device —
    feed to device_put so stage weights never materialise replicated."""
    return jax.tree_util.tree_map(
        lambda p: NamedSharding(mesh,
                                P(axis_name, *([None] * (p.ndim - 1)))),
        stacked_params)


class PipelineParallel:
    """Convenience harness: pipeline a stack of homogeneous blocks with a
    (non-pipelined) head and tail, and train it with any optax-style
    optimizer — the PP counterpart of the TensorParallel strategy.

    The reference has no pipeline engine to mirror (SURVEY §2.4 lists PP
    as an explicit gap); the API here follows this framework's layer
    protocol instead: ``stage_fn(params, x)`` pure functions.
    """

    def __init__(self, mesh: Mesh, axis_name: str = "pipe",
                 n_microbatches: int = 4, remat: bool = False):
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        if axis_name not in sizes:
            raise ValueError(f"axis {axis_name!r} not in mesh "
                             f"{tuple(mesh.axis_names)}")
        self.mesh = mesh
        self.axis_name = axis_name
        self.n_stages = sizes[axis_name]
        self.n_microbatches = n_microbatches
        self.remat = remat

    def apply(self, stage_fn: StageFn, stacked_params, x):
        return pipeline_apply(stage_fn, stacked_params, x, self.mesh,
                              self.axis_name, self.n_microbatches,
                              self.remat)

    def shard_params(self, stacked_params):
        return jax.device_put(
            stacked_params,
            stage_shardings(self.mesh, stacked_params, self.axis_name))
