"""Hot-row replication cache for sharded embedding lookups (ISSUE 19).

Recommender traffic is zipfian: a tiny head of the 10⁸-row sharded
tables (PR 14) absorbs most lookups, yet ``sharded_bag`` pays the full
(B, D) psum exchange for every slot of every batch.  This module adds
the serving-side second tier:

- :class:`HotRowCache` tracks per-id lookup frequency from the batcher's
  id streams (count-based, lock-guarded, bounded by a lossy-counting
  decay, injectable clock), keeps a small host-side replica of the
  top-K most-frequent rows — consulted *before* dispatch, so a hit
  never enters a device program, touches HBM, or crosses a link — and
  refreshes the replica values from the authoritative shards on a
  period (staleness is bounded by ``refresh_period_s``).
- :func:`cached_sharded_gather` / :func:`cached_sharded_bag` route each
  id **before dispatch**: hot ids resolve from the local replica with
  no collective at all; cold ids dedup host-side and batch through ONE
  bounded-size ``sharded_gather`` program (bucket sizes are powers of
  two, so the compile count stays bounded).  A fully-hot batch skips
  the exchange program entirely.

The cache is strictly read-only over the table: training never consults
it (optimizer writes stay authoritative — the training win is the
within-batch dedup in ``ops.embedding_bag``), and serving invalidates
it on ``swap_replicas`` / hot reload so a weight swap can never serve
rows older than the next refresh.

Every *valid* lookup is counted (pad slots are excluded from routing
and metrics alike): ``table_hot_cache_lookups_total{outcome, table}``,
``table_hot_cache_bytes_saved_total{table}`` (exchange bytes the hot
ids did NOT ride the psum), ``table_hot_cache_refresh_total{event,
table}``, and the ``table_hot_cache_hit_rate{table}`` gauge.
"""

from __future__ import annotations

import threading
import time
from collections import Counter
from typing import Callable, NamedTuple, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.observe import metrics as obs

__all__ = ["HotRowCache", "CacheSnapshot", "cached_sharded_bag",
           "cached_sharded_gather", "cold_bucket", "table_row_reader"]

# the smallest cold-id program; buckets grow by powers of two above it,
# so a vocab-V table compiles at most log2(V) cold programs
MIN_COLD_BUCKET = 8

# default frequency-tracker bound: this many tracked ids per replica
# slot (and never fewer than TRACKED_FLOOR) before the lossy-counting
# decay kicks in — a 1024-row cache tracks at most 32Ki ids, not the
# whole 10^8-row vocab
TRACKED_PER_SLOT = 32
TRACKED_FLOOR = 1024


def cold_bucket(n: int) -> int:
    """Bounded cold-id batch size: the next power of two >= ``n`` (and
    >= ``MIN_COLD_BUCKET``) — the static shapes the cold ``sharded_
    gather`` programs compile at."""
    b = MIN_COLD_BUCKET
    while b < int(n):
        b <<= 1
    return b


class CacheSnapshot(NamedTuple):
    """One immutable view of the replica: ``sorted_ids``/``rows`` are
    the arrays a refresh installed together (never edited in place),
    ``version`` the install counter.  ``route``/``take`` against the
    SAME snapshot are consistent no matter how many refreshes or
    invalidations land in between."""
    sorted_ids: np.ndarray
    rows: np.ndarray
    version: int


class HotRowCache:
    """Top-K hot-row replica of one sharded table, frequency-ranked.

    Thread-safe: ``record`` runs on batcher/decode threads while
    ``route``/``refresh`` run on dispatch threads, so every shared
    mutation is taken under one lock.  The replica arrays themselves
    are replaced wholesale on refresh (never mutated in place); a
    multi-step reader MUST pin one :meth:`snapshot` and pass it to both
    ``route`` and ``take`` — that pair then sees a consistent, merely
    stale, view even when a refresh or invalidate lands between the
    calls.  ``clock`` is injectable for the staleness tests.

    ``mesh`` is carried only as the default mesh for the cold-path
    ``sharded_gather`` in the ``cached_*`` helpers; the replica itself
    is host memory (a hit costs zero HBM and zero ICI bytes).

    ``max_tracked_ids`` bounds the frequency tracker: past the bound
    every count is halved and zeros pruned (lossy counting — heavy
    hitters keep their relative order), then the smallest survivors
    dropped, so host memory stays O(bound) over any vocab.
    """

    def __init__(self, table: str, capacity: int, dim: int, *,
                 refresh_period_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 mesh=None, dtype=np.float32,
                 max_tracked_ids: Optional[int] = None):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.table = str(table)
        self.capacity = int(capacity)
        self.dim = int(dim)
        self.refresh_period_s = float(refresh_period_s)
        self.mesh = mesh
        self.dtype = np.dtype(dtype)
        self.max_tracked_ids = int(
            max(TRACKED_FLOOR, TRACKED_PER_SLOT * self.capacity)
            if max_tracked_ids is None else max_tracked_ids)
        if self.max_tracked_ids < self.capacity:
            raise ValueError(
                f"max_tracked_ids ({self.max_tracked_ids}) must be >= "
                f"capacity ({self.capacity})")
        self._clock = clock
        self._lock = threading.Lock()
        self._counts: Counter = Counter()
        # replica state; replaced together under the lock, published to
        # readers only as a CacheSnapshot
        self._sorted_ids = np.empty((0,), np.int64)
        self._rows = np.zeros((0, self.dim), self.dtype)
        self._version = 0
        self._last_refresh: Optional[float] = None
        self._hits = 0
        self._lookups = 0

    # -- frequency tracking (batcher id streams) ---------------------------
    def record(self, ids) -> None:
        """Fold one id stream into the frequency counts (any shape)."""
        flat = np.asarray(ids).reshape(-1)
        if flat.size == 0:
            return
        vals, cnts = np.unique(flat.astype(np.int64), return_counts=True)
        with self._lock:
            for v, c in zip(vals.tolist(), cnts.tolist()):
                self._counts[v] += c
            if len(self._counts) > self.max_tracked_ids:
                self._shrink_counts_locked()

    def _shrink_counts_locked(self) -> None:
        """Lossy-counting decay, called under ``self._lock``: halve
        every count and prune zeros; if the survivors still exceed the
        bound, keep only the heaviest ``max_tracked_ids`` (count desc,
        id asc — the same deterministic order ``top_ids`` ranks by)."""
        self._counts = Counter(
            {k: v >> 1 for k, v in self._counts.items() if v >> 1 > 0})
        if len(self._counts) > self.max_tracked_ids:
            items = sorted(self._counts.items(),
                           key=lambda kv: (-kv[1], kv[0]))
            self._counts = Counter(
                dict(items[:self.max_tracked_ids]))

    def top_ids(self) -> np.ndarray:
        """The current top-``capacity`` ids by observed frequency
        (count desc, id asc — deterministic under ties)."""
        with self._lock:
            items = list(self._counts.items())
        items.sort(key=lambda kv: (-kv[1], kv[0]))
        return np.asarray([k for k, _ in items[:self.capacity]],
                          np.int64)

    # -- replica lifecycle -------------------------------------------------
    def refresh(self, row_reader: Callable[[np.ndarray], np.ndarray]
                ) -> int:
        """Re-rank the top-K and re-read their rows from the
        authoritative shards via ``row_reader(ids) -> (len(ids), D)``.
        Returns the number of rows now cached."""
        ids = self.top_ids()
        rows = (np.asarray(row_reader(ids), self.dtype)
                if ids.size else np.zeros((0, self.dim), self.dtype))
        if rows.shape != (ids.size, self.dim):
            raise ValueError(
                f"row_reader returned {rows.shape} for {ids.size} ids "
                f"of dim {self.dim}")
        order = np.argsort(ids, kind="stable")
        with self._lock:
            self._sorted_ids = ids[order]
            self._rows = rows[order]
            self._version += 1
            self._last_refresh = self._clock()
        obs.count("table_hot_cache_refresh_total", 1,
                  flat="parallel/hot_cache_refresh",
                  event="refresh", table=self.table)
        return int(ids.size)

    def maybe_refresh(self, row_reader) -> bool:
        """Refresh iff never refreshed, invalidated, or the period has
        elapsed on the injected clock."""
        with self._lock:
            last = self._last_refresh
        if last is not None and \
                self._clock() - last < self.refresh_period_s:
            return False
        self.refresh(row_reader)
        return True

    def invalidate(self, reason: str = "swap") -> None:
        """Drop the replica (every id misses until the next refresh).
        Frequency counts survive — traffic knowledge is still valid
        when the weights change under a swap/hot-reload."""
        with self._lock:
            self._sorted_ids = np.empty((0,), np.int64)
            self._rows = np.zeros((0, self.dim), self.dtype)
            self._version += 1
            self._last_refresh = None
        obs.count("table_hot_cache_refresh_total", 1,
                  flat="parallel/hot_cache_invalidate",
                  event=f"invalidate_{reason}", table=self.table)

    # -- lookup routing ----------------------------------------------------
    def snapshot(self) -> CacheSnapshot:
        """The current replica view under ONE lock acquisition — the
        unit of consistency for a ``route``/``take`` pair."""
        with self._lock:
            return CacheSnapshot(self._sorted_ids, self._rows,
                                 self._version)

    def route(self, ids, snapshot: Optional[CacheSnapshot] = None
              ) -> Tuple[np.ndarray, np.ndarray]:
        """Split one flat id block into (slots, hot): ``hot[i]`` true
        where ``ids[i]`` is cached, ``slots[i]`` its replica row index
        *within ``snapshot``* (pass the same snapshot to ``take`` — a
        refresh between the calls re-ranks the replica, so indices are
        only meaningful against the snapshot they were computed from).
        Counts hits/misses/bytes-saved and updates the hit-rate gauge."""
        snap = snapshot if snapshot is not None else self.snapshot()
        flat = np.asarray(ids).reshape(-1).astype(np.int64)
        sids = snap.sorted_ids
        if sids.size == 0:
            slots = np.full(flat.shape, -1, np.int64)
            hot = np.zeros(flat.shape, bool)
        else:
            pos = np.searchsorted(sids, flat)
            pos_c = np.minimum(pos, sids.size - 1)
            hot = sids[pos_c] == flat
            slots = np.where(hot, pos_c, -1)
        hits = int(hot.sum())
        misses = int(flat.size - hits)
        with self._lock:
            self._hits += hits
            self._lookups += flat.size
            rate = self._hits / max(1, self._lookups)
        if hits:
            obs.count("table_hot_cache_lookups_total", hits,
                      flat="parallel/hot_cache_hit",
                      outcome="hit", table=self.table)
            obs.count("table_hot_cache_bytes_saved_total",
                      hits * self.dim * self.dtype.itemsize,
                      flat="parallel/hot_cache_bytes_saved",
                      table=self.table)
        if misses:
            obs.count("table_hot_cache_lookups_total", misses,
                      flat="parallel/hot_cache_miss",
                      outcome="miss", table=self.table)
        obs.set_gauge("table_hot_cache_hit_rate", rate,
                      table=self.table)
        return slots, hot

    def take(self, slots, snapshot: Optional[CacheSnapshot] = None
             ) -> np.ndarray:
        """Replica rows for ``slots`` — which MUST come from a ``route``
        against the SAME ``snapshot`` (without one, both calls race any
        concurrent refresh/invalidate and may index a re-ranked or
        emptied replica)."""
        rows = snapshot.rows if snapshot is not None \
            else self.snapshot().rows
        return rows[np.asarray(slots, np.int64)]

    # -- introspection -----------------------------------------------------
    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def stats(self) -> dict:
        with self._lock:
            return {"table": self.table, "capacity": self.capacity,
                    "cached_rows": int(self._sorted_ids.size),
                    "tracked_ids": len(self._counts),
                    "max_tracked_ids": self.max_tracked_ids,
                    "hits": self._hits, "lookups": self._lookups,
                    "hit_rate": self._hits / max(1, self._lookups),
                    "version": self._version,
                    "last_refresh": self._last_refresh}


def table_row_reader(table, *, mesh=None, axis: str = "model"):
    """A ``row_reader`` over the authoritative (possibly row-sharded)
    device table: reads exact current row values, so a refresh right
    after an optimizer step or weight swap serves the new weights."""
    import jax
    import jax.numpy as jnp

    def read(ids: np.ndarray) -> np.ndarray:
        if len(ids) == 0:
            return np.zeros((0, int(table.shape[1])))
        # the refresh IS the explicit staging chokepoint (like
        # init_table_sharded's upload): guarded serving paths stay
        # runnable because transfers only happen here, on a period
        with jax.transfer_guard("allow"):
            rows = jnp.take(table, jnp.asarray(np.asarray(ids),
                                               jnp.int32), axis=0)
            return np.asarray(jax.device_get(rows))

    return read


def _two_tier_rows(cache: HotRowCache, table, flat: np.ndarray, *,
                   mesh, axis: str) -> np.ndarray:
    """(n, D) rows for a flat clipped id block: hot from the replica,
    cold deduped host-side and fetched through one bounded
    ``sharded_gather`` program (none at all when fully hot).  One
    snapshot covers both the routing and the row reads, so a refresh
    or invalidate landing mid-lookup can never mix two replica
    generations (or index an emptied one)."""
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.parallel.table_sharding import sharded_gather

    dim = int(table.shape[1])
    snap = cache.snapshot()
    slots, hot = cache.route(flat, snapshot=snap)
    out = np.zeros((flat.size, dim), cache.dtype)
    if hot.any():
        out[hot] = cache.take(slots[hot], snapshot=snap)
    cold = flat[~hot]
    if cold.size:
        uniq = np.unique(cold)
        bucket = cold_bucket(uniq.size)
        padded = np.full((bucket,), int(uniq[0]), np.int32)
        padded[:uniq.size] = uniq.astype(np.int32)
        with jax.transfer_guard("allow"):
            ids_d = jax.device_put(jnp.asarray(padded))
            rows = np.asarray(jax.device_get(
                sharded_gather(table, ids_d, mesh=mesh, axis=axis)))
        out[~hot] = rows[np.searchsorted(uniq, cold)]
    return out


def cached_sharded_gather(cache: HotRowCache, table, ids, *, mesh,
                          axis: str = "model",
                          record: bool = True) -> np.ndarray:
    """Serving-side two-tier ``table[ids]``: numpy ids in (pre-dispatch,
    where the serving path holds host arrays), numpy rows out — exact
    same values as :func:`~analytics_zoo_tpu.parallel.table_sharding.
    sharded_gather` after a refresh, but hot ids never enter the psum
    exchange and the cold remainder rides a deduped bounded bucket."""
    ids_np = np.asarray(ids)
    vocab = int(table.shape[0])
    flat = np.clip(ids_np.reshape(-1).astype(np.int64), 0, vocab - 1)
    if record:
        cache.record(flat)
    out = _two_tier_rows(cache, table, flat, mesh=mesh, axis=axis)
    return out.reshape(tuple(ids_np.shape) + (cache.dim,))


def cached_sharded_bag(cache: HotRowCache, table, ids,
                       combiner: str = "sum", pad_id=None, *, mesh,
                       axis: str = "model",
                       record: bool = True) -> np.ndarray:
    """Two-tier ``embedding_bag`` over a sharded table: (B, N) ids ->
    (B, D), same mask/clip/combiner semantics as ``sharded_bag`` (pad
    slots contribute exact zeros and touch NOTHING — not the frequency
    counts, not the hit/miss metrics, not the cold exchange; an all-pad
    batch runs no lookup at all), parity at rtol 1e-6 against the
    uncached path."""
    if combiner not in ("sum", "mean", "sqrtn"):
        raise ValueError(f"combiner must be sum|mean|sqrtn, "
                         f"got {combiner!r}")
    ids_np = np.asarray(ids)
    if ids_np.ndim != 2:
        raise ValueError(f"ids must be (bags, max_nnz), got "
                         f"{ids_np.shape}")
    vocab = int(table.shape[0])
    mask = (np.ones(ids_np.shape, np.float32) if pad_id is None
            else (ids_np != pad_id).astype(np.float32))
    clipped = np.clip(ids_np.astype(np.int64), 0, vocab - 1)
    valid = mask.reshape(-1) > 0
    flat = clipped.reshape(-1)[valid]
    if record:
        cache.record(flat)
    rows = np.zeros((ids_np.size, cache.dim), np.float32)
    if flat.size:
        rows[valid] = _two_tier_rows(cache, table, flat, mesh=mesh,
                                     axis=axis).astype(np.float32)
    rows = rows.reshape(ids_np.shape + (cache.dim,))
    out = np.sum(rows * mask[..., None], axis=1)
    if combiner != "sum":
        n = np.maximum(mask.sum(axis=1, keepdims=True), 1.0)
        out = out / (n if combiner == "mean" else np.sqrt(n))
    return out.astype(cache.dtype)
