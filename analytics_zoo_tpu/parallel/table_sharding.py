"""Row-sharded giant embedding tables over the mesh's ``model`` axis.

The recommenders' north-star claim ("serve millions of users",
ROADMAP item 1) is capped by one chip's HBM as long as every device
replicates the full embedding table — a 10⁸-row production table at
D=64 is ~25 GiB of f32, several chips' worth on its own.  This module
partitions a table **row-wise** over the model axis and keeps the
minibatch lookup fully on-device:

- each model shard holds ``rows/ways`` contiguous table rows (plus the
  matching slice of the Adam moments — train/optimizers.py
  ``opt_state_shardings`` makes optimizer state follow the params);
- the lookup runs inside ``shard_map``: every shard masks the batch's
  ids down to the rows it owns (unowned slots become a ``-1`` pad the
  fused ``ops.embedding_bag`` kernel already ignores), gathers/combines
  **locally**, and a single ``psum`` over the model axis exchanges only
  the combined ``(B, D)`` partials — the gathered ``(B, N, D)`` rows
  never leave their owning shard, so the per-step exchange is
  ``B·D·4`` bytes per table instead of the allgathered table itself.

Placement is decided per table by :func:`choose_table_placement` — the
same bounded-reason-code router style as the Estimator's data-path
router (``data_path_selected_total``), counted in
``table_placement_selected_total{placement,reason}``:

========== =============================================================
replicated table fits ``data_device_budget_bytes`` (or no model axis)
sharded    over budget but ``nbytes/ways`` fits — row-shard it
stream     over budget even sharded: row-shard AND stream-initialize
           each shard straight onto its devices from a lazy row source
           (:func:`init_table_sharded`), never materializing a host
           mirror — the cold-row tier for tables bigger than the mesh
========== =============================================================

Tables pad their row count to ``ROW_ALIGN`` (a topology-independent
multiple that covers 1/2/4/8-way meshes), so a checkpoint written at
one sharding width restores at any other through the existing
``tree_put_global`` reshard seam; :func:`grow_restored_tree` handles
the elastic case where the restored table has FEWER rows than the
freshly built one (new rows keep their fresh initialization).
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.observe import metrics as obs
from analytics_zoo_tpu.parallel.sharding import (DataParallel,
                                                 ShardingStrategy,
                                                 path_str)

try:  # jax >= 0.4.35 re-export
    from jax import shard_map  # type: ignore
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

logger = logging.getLogger("analytics_zoo_tpu.parallel")

# Topology-independent row padding: a table padded to a multiple of 8
# row-shards evenly at every mesh width in {1, 2, 4, 8}, so the param
# SHAPE (and therefore the checkpoint layout) never depends on the mesh
# the model happened to be built on — that invariance is what lets a
# 2-way snapshot restore 1-way or 4-way via plain tree_put_global.
ROW_ALIGN = 8

TABLE_PLACEMENTS = ("replicated", "sharded", "stream")


def padded_rows(rows: int) -> int:
    """``rows`` rounded up to the topology-independent ``ROW_ALIGN``."""
    return int(-(-int(rows) // ROW_ALIGN) * ROW_ALIGN)


def resolve_table_ways(mesh, axis: str, rows: int) -> int:
    """How many ways a ``rows``-row table shards on ``mesh`` — 1 means
    "don't": the axis is missing, trivial, or does not divide the
    (already ROW_ALIGN-padded) row count.  The strategy's param specs
    and the layer's trace-time lowering both call this, so placement
    and compute can never disagree."""
    if mesh is None or axis not in mesh.axis_names:
        return 1
    ways = int(dict(zip(mesh.axis_names, mesh.devices.shape))[axis])
    if ways <= 1 or rows % ways:
        return 1
    return ways


def _data_axis(mesh, own_axis: str) -> Optional[str]:
    names = [a for a in mesh.axis_names if a != own_axis]
    if not names:
        return None
    return "data" if "data" in names else names[0]


# ---------------------------------------------------------------------------
# the sharded lookup: local gather + one (B, D) psum exchange
# ---------------------------------------------------------------------------


def sharded_bag(table, ids, combiner: str = "sum", pad_id=None, *,
                mesh, axis: str = "model", dedup: Optional[bool] = None):
    """``embedding_bag`` over a row-sharded table: ``(B, N)`` ids against
    a ``(rows, D)`` table laid out ``P(axis, None)`` -> ``(B, D)``.

    Inside ``shard_map`` each model shard rewrites the bag ids it does
    NOT own to ``-1`` — the fused kernel's mask is computed from the raw
    ids before clipping, so those slots contribute exact zeros — runs
    the PR 12 fused ``embedding_bag`` on its local rows, and one
    ``psum`` over ``axis`` assembles the global combine.  mean/sqrtn
    scaling applies AFTER the exchange from the global validity count
    (ids are replicated over the model axis, so every shard derives the
    same count).  Exchange bytes per step: ``B * D * 4`` per table.

    ``dedup`` routes the local gather through the within-batch unique-id
    path (``ops.embedding_bag.embedding_bag_dedup``: duplicate ids cost
    one row read, grads still accumulate per occurrence); ``None``
    resolves the ``dedup_ids`` knob, whose ``auto`` default turns dedup
    ON here — this is exactly the lookup where duplicate rows pay full
    HBM price on every shard.
    """
    from analytics_zoo_tpu.ops.embedding_bag import (dedup_wanted,
                                                     embedding_bag,
                                                     embedding_bag_dedup)

    rows = int(table.shape[0])
    ways = resolve_table_ways(mesh, axis, rows)
    if ways <= 1:
        return embedding_bag(table, ids, combiner, pad_id)
    if dedup is None:
        dedup = dedup_wanted(sharded=True)
    local_bag = embedding_bag_dedup if dedup else (
        lambda tab, i, c, pad_id: embedding_bag(tab, i, c, pad_id=pad_id))
    rows_local = rows // ways
    batch_ax = _data_axis(mesh, axis)

    def local(tab, ids_l):
        ids_l = ids_l.astype(jnp.int32)
        shard = jax.lax.axis_index(axis)
        lo = shard * rows_local
        valid = (jnp.ones(ids_l.shape, jnp.bool_) if pad_id is None
                 else ids_l != pad_id)
        owned = valid & (ids_l >= lo) & (ids_l < lo + rows_local)
        local_ids = jnp.where(owned, ids_l - lo, -1)
        part = local_bag(tab, local_ids, "sum", -1)
        total = jax.lax.psum(part.astype(jnp.float32), axis)
        if combiner != "sum":
            n = jnp.maximum(
                jnp.sum(valid.astype(jnp.float32), axis=1, keepdims=True),
                1.0)
            total = total / (n if combiner == "mean" else jnp.sqrt(n))
        return total.astype(tab.dtype)

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None), P(batch_ax, None)),
        out_specs=P(batch_ax, None),
        check_rep=False,
    )(table, ids)


def sharded_gather(table, ids, *, mesh, axis: str = "model",
                   dedup: Optional[bool] = None):
    """``table[ids]`` over a row-sharded table: ids of any shape ->
    ``ids.shape + (D,)`` — the degenerate single-slot bag, same local
    gather + psum exchange (and the same ``dedup_ids``-resolved
    unique-id routing) as :func:`sharded_bag`."""
    flat = ids.astype(jnp.int32).reshape((-1, 1))
    out = sharded_bag(table, flat, "sum", pad_id=None, mesh=mesh,
                      axis=axis, dedup=dedup)
    return out.reshape(tuple(ids.shape) + (int(table.shape[1]),))


# ---------------------------------------------------------------------------
# placement router (the data-path router's sibling)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TablePlacement:
    """One router decision: where a table's rows live, and why."""
    placement: str          # replicated | sharded | stream
    ways: int               # model-axis split the decision assumed
    reason_code: str        # bounded vocabulary (docs/OBSERVABILITY.md)
    reason: str             # human-readable


def choose_table_placement(*, nbytes: int, rows: int,
                           requested: str = "auto",
                           mesh=None, axis: str = "model",
                           budget_bytes: Optional[int] = None
                           ) -> TablePlacement:
    """Per-table placement: replicated < sharded < stream, decided from
    the table's bytes against ``data_device_budget_bytes`` and the mesh
    shape — the same decision style (and counter discipline) as the
    Estimator's FeatureSet path router.  Every decision is counted in
    ``table_placement_selected_total{placement,reason}`` with a bounded
    reason vocabulary; downgrades are automatic and logged, never an
    error."""
    if requested not in ("auto",) + TABLE_PLACEMENTS:
        raise ValueError(
            f"table_placement must be auto|replicated|sharded|stream, "
            f"got {requested!r}")
    if mesh is None or budget_bytes is None:
        from analytics_zoo_tpu.core.context import get_zoo_context
        ctx = get_zoo_context()
        if mesh is None:
            mesh = ctx.mesh
        if budget_bytes is None:
            budget_bytes = int(ctx.config.data_device_budget_bytes)
    rows_p = padded_rows(rows)
    ways = resolve_table_ways(mesh, axis, rows_p)
    axis_size = 0
    if mesh is not None and axis in mesh.axis_names:
        axis_size = int(dict(zip(mesh.axis_names,
                                 mesh.devices.shape))[axis])
    # no_model_axis: the mesh can't shard anything; axis_indivisible:
    # the axis exists but this table's (padded) rows don't split on it
    no_ways_code = ("axis_indivisible" if axis_size > 1 and ways <= 1
                    else "no_model_axis")

    def pick(placement: str, code: str, reason: str) -> TablePlacement:
        obs.count("table_placement_selected_total", placement=placement,
                  reason=code, flat=f"parallel/table_placement_{placement}")
        return TablePlacement(placement, ways if placement != "replicated"
                              else 1, code, reason)

    if requested == "replicated":
        return pick("replicated", "requested", "placement requested")
    if ways <= 1:
        if requested in ("sharded", "stream"):
            logger.warning(
                "table_placement=%r requested but the mesh %s has no "
                "usable %r axis for a %d-row table; placing replicated",
                requested, tuple(mesh.axis_names), axis, rows)
            return pick("replicated", no_ways_code,
                        f"no usable {axis!r} axis on this mesh for "
                        f"{rows_p} rows")
        if int(nbytes) <= int(budget_bytes):
            return pick("replicated", "fits_budget", "fits device budget")
        return pick("replicated", no_ways_code,
                    f"table {int(nbytes)}B over budget "
                    f"{int(budget_bytes)}B but no usable {axis!r} axis")
    if requested in ("sharded", "stream"):
        return pick(requested, "requested", "placement requested")
    if int(nbytes) <= int(budget_bytes):
        return pick("replicated", "fits_budget", "fits device budget")
    if int(nbytes) // ways <= int(budget_bytes):
        return pick("sharded", "over_budget",
                    f"table {int(nbytes)}B over device budget "
                    f"{int(budget_bytes)}B; {ways}-way rows fit")
    return pick("stream", "sharded_over_budget",
                f"table {int(nbytes)}B exceeds budget even {ways}-way "
                f"sharded; shard + stream-initialize cold rows")


# ---------------------------------------------------------------------------
# sharding strategy wrapper: listed tables ride P(axis, None)
# ---------------------------------------------------------------------------


class TableShardedStrategy(ShardingStrategy):
    """Wrap any base strategy so the listed layers' ``<name>/table``
    params split row-wise over the model axis; everything else (and any
    table the live mesh cannot shard) falls through to the base.

    ``activate`` publishes a :class:`~analytics_zoo_tpu.parallel.mode.
    TableShardMode` for the trace, which is how
    ``ShardedEmbeddingTable.forward`` knows to lower to the
    local-gather + psum exchange — placement and compute agree by
    construction because both sides call :func:`resolve_table_ways`.
    """

    def __init__(self, base: Optional[ShardingStrategy] = None,
                 tables: Sequence[str] = (), axis: str = "model"):
        self.base = base if base is not None else DataParallel()
        self.tables = tuple(tables)
        self.axis = axis
        self._pats = [re.compile(rf"(^|/){re.escape(t)}/table$")
                      for t in self.tables]

    def _is_table(self, path: str) -> bool:
        return any(p.search(path) for p in self._pats)

    def param_shardings(self, mesh, params):
        base_sh = self.base.param_shardings(mesh, params)

        def one(path, leaf, base_leaf):
            p = path_str(path)
            shape = getattr(leaf, "shape", ())
            if (self._is_table(p) and len(shape) == 2
                    and resolve_table_ways(mesh, self.axis, shape[0]) > 1):
                return NamedSharding(mesh, P(self.axis, None))
            return base_leaf

        return jax.tree_util.tree_map_with_path(one, params, base_sh)

    def activate(self, mesh):
        import contextlib

        from analytics_zoo_tpu.parallel.mode import (TableShardMode,
                                                     table_mode)

        stack = contextlib.ExitStack()
        stack.enter_context(self.base.activate(mesh))
        if self.axis in mesh.axis_names:
            stack.enter_context(table_mode(TableShardMode(
                mesh, self.axis, self.tables)))
        return stack


def ensure_table_sharding(strategy: ShardingStrategy,
                          tables: Sequence[str],
                          axis: str = "model") -> ShardingStrategy:
    """Idempotently wrap ``strategy`` so ``tables`` shard over ``axis``
    (the Estimator calls this when the compiled model carries a
    ``_sharded_tables`` manifest)."""
    if not tables:
        return strategy
    if isinstance(strategy, TableShardedStrategy) \
            and strategy.tables == tuple(tables):
        return strategy
    return TableShardedStrategy(base=strategy, tables=tables, axis=axis)


def per_chip_weight_nbytes(params, tables: Sequence[str], mesh,
                           axis: str = "model") -> int:
    """The PER-CHIP byte footprint of ``params`` when the listed
    tables row-shard over ``mesh``'s ``axis``: sharded 2-D table leaves
    charge ``nbytes / ways``, everything else (replicated) charges its
    full bytes.  This is the number the serving executor's HBM-budget
    planner must use for a mesh-replica slot — charging a sharded
    table's FULL bytes per chip is exactly the over-estimate that makes
    the over-budget giant-table model look unservable."""
    pats = table_leaf_patterns(tables)
    total = 0

    def one(path, leaf):
        nonlocal total
        shape = getattr(leaf, "shape", ())
        nbytes = int(getattr(leaf, "nbytes", 0) or 0)
        ways = 1
        if (any(p.search(path_str(path)) for p in pats)
                and len(shape) == 2):
            ways = resolve_table_ways(mesh, axis, int(shape[0]))
        total += nbytes // max(1, ways)
        return leaf

    jax.tree_util.tree_map_with_path(one, params)
    return int(total)


# ---------------------------------------------------------------------------
# STREAM-cold-rows initialization: shards land on-device, no host mirror
# ---------------------------------------------------------------------------


def init_table_sharded(mesh, rows: int, dim: int, row_source, *,
                       axis: str = "model", dtype=np.float32):
    """Materialize a row-sharded ``(padded_rows(rows), dim)`` table
    straight onto the mesh from a lazy ``row_source.rows(lo, hi)``
    generator (e.g. ``data.giant_table.SyntheticGiantTable``) — each
    device's row range is generated on demand and uploaded, so the full
    table NEVER exists on the host (the stream-cold-rows tier for
    tables bigger than host RAM).  Rows past ``rows`` (the ROW_ALIGN
    padding tail) are zero."""
    rows_p = padded_rows(rows)
    ways = resolve_table_ways(mesh, axis, rows_p)
    spec = P(axis, None) if ways > 1 else P()
    sharding = NamedSharding(mesh, spec)

    def shard_for(index) -> np.ndarray:
        lo, hi, _ = index[0].indices(rows_p)
        block = np.zeros((hi - lo, dim), dtype)
        live = min(hi, rows) - lo
        if live > 0:
            block[:live] = row_source.rows(lo, lo + live)
        return block

    # the explicit staging chokepoint, like device_put_global — guarded
    # training paths stay runnable (transfers here are the one upload)
    with jax.transfer_guard("allow"):
        return jax.make_array_from_callback(
            (rows_p, dim), sharding, shard_for)


# ---------------------------------------------------------------------------
# elastic growth on restore: more rows than the snapshot
# ---------------------------------------------------------------------------


def table_leaf_patterns(tables: Sequence[str]):
    return [re.compile(rf"(^|/){re.escape(t)}/table$") for t in tables]


def grow_restored_tree(restored, built, tables: Sequence[str]):
    """Merge a restored params tree into a freshly built one whose
    elastic tables have MORE rows: snapshot rows are kept bit-exact,
    rows beyond the snapshot keep the fresh build's initialization.
    Non-table leaves (and tables whose shapes already match) pass
    through untouched; a restored table LARGER than the built one is an
    error (shrinking a vocabulary would silently drop live rows)."""
    pats = table_leaf_patterns(tables)

    def one(path, new_leaf, old_leaf):
        p = path_str(path)
        old = np.asarray(old_leaf)
        if not any(pat.search(p) for pat in pats):
            return old
        new_shape = tuple(np.shape(new_leaf))
        if tuple(old.shape) == new_shape:
            return old
        if (len(old.shape) != 2 or len(new_shape) != 2
                or old.shape[1] != new_shape[1]):
            raise ValueError(
                f"restored table {p!r} has shape {tuple(old.shape)}, "
                f"incompatible with the built {new_shape}")
        if old.shape[0] > new_shape[0]:
            raise ValueError(
                f"restored table {p!r} has {old.shape[0]} rows but the "
                f"model was built with {new_shape[0]} — shrinking an "
                "embedding table on restore would drop live rows")
        tail = np.asarray(jax.device_get(new_leaf))[old.shape[0]:]
        logger.info("elastic table growth: %s %d -> %d rows (%d new rows "
                    "keep fresh init)", p, old.shape[0], new_shape[0],
                    new_shape[0] - old.shape[0])
        return np.concatenate([old.astype(tail.dtype), tail], axis=0)

    return jax.tree_util.tree_map_with_path(one, built, restored)


def grow_restored_opt_state(restored_opt, target_shapes):
    """The optimizer-state side of elastic growth: any restored leaf
    whose leading dim is SHORTER than the fresh ``tx.init`` shape (same
    trailing dims) zero-pads up to it — zeros ARE the fresh Adam/momentum
    state for the new rows, so grown rows optimize exactly like a cold
    start while snapshot rows keep their moments."""

    def one(old_leaf, tgt):
        old = np.asarray(old_leaf)
        tgt_shape = tuple(tgt.shape)
        if tuple(old.shape) == tgt_shape or old.ndim == 0:
            return old
        if (old.ndim == len(tgt_shape)
                and old.shape[1:] == tgt_shape[1:]
                and old.shape[0] < tgt_shape[0]):
            pad = np.zeros((tgt_shape[0] - old.shape[0],) + old.shape[1:],
                           old.dtype)
            return np.concatenate([old, pad], axis=0)
        raise ValueError(
            f"restored optimizer leaf shape {tuple(old.shape)} cannot "
            f"grow to {tgt_shape}")

    return jax.tree_util.tree_map(one, restored_opt, target_shapes)
