"""Sequence/context parallelism: ring attention over the mesh.

Reference capability: **absent** (SURVEY.md §5.7 — the reference's
TransformerLayer/BERT materialize full O(L²) attention on one host, and
sequence length is bounded by single-node memory).  This module is the
TPU-native upgrade that makes long context first-class: the sequence axis
is sharded over devices, K/V shards rotate around the ring via
``lax.ppermute`` (ICI neighbour exchanges), and each device folds incoming
blocks into the same online-softmax accumulator used by blockwise
attention (ops/attention.py) — i.e. ring attention (Liu et al.) is
literally blockwise attention whose KV loop runs over devices.

Use ``ring_attention`` inside ``shard_map`` with q/k/v sharded on the
sequence axis; ``ring_self_attention`` wraps the shard_map for you.
Differentiable end-to-end (ppermute has a transpose rule).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from analytics_zoo_tpu.ops.attention import online_softmax_fold

NEG_INF = -1e30


def mark_varying(x, axis_name):
    """Mark a freshly-created (replicated) array as device-varying along
    ``axis_name`` (a name or tuple of names) so shard_map scan carry
    types match axis-dependent loop outputs.  Shared by ring attention
    and the pipeline schedule."""
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    try:
        # only mark axes the value is not already varying over (pcast
        # rejects mixed varying/invarying inputs)
        cur = jax.typeof(x).vma
        axes = tuple(a for a in axes if a not in cur)
    except (AttributeError, TypeError):
        pass
    if not axes:
        return x
    try:
        return lax.pcast(x, axes, to="varying")
    except (AttributeError, TypeError):  # pragma: no cover — older jax
        try:
            return lax.pvary(x, axes)
        except AttributeError:
            return x


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   sm_scale: Optional[float] = None):
    """Attention where K/V are sharded over ``axis_name`` (per-device
    shapes: q (B, H, Lq_local, D), k/v (B, H, Lk_local, D)).

    Must run inside shard_map/pjit with ``axis_name`` bound.  Each of the
    ``n`` ring steps computes local blockwise attention against the
    currently-held KV shard, then rotates KV to the next device.
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, h, lq, d = q.shape
    lk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / jnp.sqrt(d)
    q_scaled = q * scale
    # global positions of my queries (sequence sharded evenly)
    q_pos = my * lq + jnp.arange(lq)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, i):
        m_prev, l_prev, acc, kc, vc = carry
        # device holding shard s sends to s+1, so after i rotations we hold
        # the shard originally on device (my - i) mod n
        src = (my - i) % n
        logits = jnp.einsum("bhqd,bhkd->bhqk", q_scaled, kc)
        if causal:
            k_pos = src * lk + jnp.arange(lk)
            cm = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(cm[None, None], logits, NEG_INF)
        m_out, l_new, acc = online_softmax_fold(m_prev, l_prev, acc, logits,
                                                vc)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (m_out, l_new, acc, kc, vc), None

    def _vary(x):
        # fresh accumulators must carry the same varying-axes type as the
        # q-derived scan outputs — including a batch axis when the caller
        # composes sp with dp (q is then varying over ('data', seq))
        try:
            axes = tuple(jax.typeof(q).vma | {axis_name})
        except (AttributeError, TypeError):
            axes = axis_name
        return mark_varying(x, axes)

    # f32 carry across ring steps, matching blockwise_attention/the Pallas
    # kernel's f32 scratch, so bf16 inputs don't round the accumulator
    init = (_vary(jnp.full((b, h, lq), NEG_INF, jnp.float32)),
            _vary(jnp.zeros((b, h, lq), jnp.float32)),
            _vary(jnp.zeros((b, h, lq, d), jnp.float32)), k, v)
    (m, l, acc, _, _), _ = lax.scan(step, init, jnp.arange(n))
    l = jnp.maximum(l, 1e-20)
    return (acc / l[..., None]).astype(q.dtype)


def ring_self_attention(q, k, v, mesh: Mesh, seq_axis: str,
                        causal: bool = False,
                        sm_scale: Optional[float] = None,
                        batch_axis: Optional[str] = None):
    """Convenience wrapper: shard q/k/v (B, H, L, D) on dim 2 over
    ``seq_axis`` of ``mesh`` and run ring attention.

    ``batch_axis``: additionally shard dim 0 over this mesh axis — the
    sp×dp composition (each data group runs its own ring; leaving it
    unset on a multi-axis mesh makes GSPMD allgather the batch).

    Since the ops/ring_attention.py tentpole this is a thin delegator
    into the counted dispatch contract: the sp regime asked for the ring
    explicitly, so the knob pins "on" (no min-length bail-out) and the
    per-hop compute routes pallas/interpret/pure-JAX via
    ``ops.dispatch.select_path`` — with a double-buffered ppermute
    schedule, causal hop skipping, and a custom_vjp backward that
    re-streams K/V instead of checkpointing every hop."""
    from analytics_zoo_tpu.ops.ring_attention import (
        ring_attention as _ring_op)

    return _ring_op(q, k, v, mesh=mesh, axis=seq_axis,
                    batch_axis=batch_axis, causal=causal,
                    sm_scale=sm_scale, knob="on")
