"""Parallelism strategies: parameter-sharding rules over the device mesh.

Reference capability (SURVEY.md §2.4): the reference has ONE strategy —
synchronous data parallelism via Spark-block-manager allreduce
(InternalDistriOptimizer, Topology.scala:1069-1267; wp-bigdl.md:113-160) —
and explicitly lacks TP/PP/SP.  The TPU build gets data parallelism as the
degenerate case of GSPMD, and tensor parallelism "for free" by annotating
parameter shardings: XLA inserts the all-gathers/reduce-scatters over ICI.

Design: a strategy is a function ``spec(path, leaf) -> PartitionSpec``
applied over the params pytree.  The Estimator puts params on the mesh with
those specs; batch inputs shard over the data axis; jit does the rest.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

SpecFn = Callable[[str, Any], P]


def _infer_batch_axis(mesh, own_axis: str) -> Optional[str]:
    """The mesh axis the batch shards over when composing with dp:
    prefer an axis literally named 'data', else the first axis that is
    not the strategy's own — None on a single-axis mesh."""
    names = [a for a in mesh.axis_names if a != own_axis]
    if not names:
        return None
    return "data" if "data" in names else names[0]


def path_str(path) -> str:
    """jax tree path -> 'a/b/c' string for regex matching."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


class ShardingStrategy:
    """Base: fully replicated parameters (pure data parallelism)."""

    def spec(self, path: str, leaf) -> P:
        return P()

    def param_shardings(self, mesh, params):
        """Pytree of NamedShardings matching ``params``."""
        def one(path, leaf):
            return NamedSharding(mesh, self.spec(path_str(path), leaf))

        return jax.tree_util.tree_map_with_path(one, params)

    def activate(self, mesh):
        """Context manager active while the Estimator traces its steps.

        Strategies that change the model's *forward lowering* (ring
        attention for SP, the GPipe schedule for PP) publish themselves
        through parallel.mode here; pure param-placement strategies
        (DP/TP/EP) need no hook.
        """
        import contextlib
        return contextlib.nullcontext()


class DataParallel(ShardingStrategy):
    """Replicate params, shard the batch (the reference's only mode)."""


class TensorParallel(ShardingStrategy):
    """Shard large parameters along ``axis`` (the mesh's model axis).

    Rules (applied in order):
    - explicit ``rules``: list of (regex on param path, PartitionSpec);
    - otherwise any leaf with ≥ ``min_size`` elements is sharded along its
      largest dimension divisible by the axis size (embedding tables split
      over vocab, Dense kernels over the wider of in/out) — the standard
      Megatron-style layout expressed as GSPMD annotations.

    ``mesh_axis_size`` may be omitted — ``param_shardings`` derives it from
    the mesh (and validates that ``axis`` exists there).
    """

    def __init__(self, axis: str = "model", mesh_axis_size: Optional[int] = None,
                 rules: Optional[Sequence] = None, min_size: int = 2 ** 16):
        self.axis = axis
        self.axis_size = mesh_axis_size
        self.rules = [(re.compile(pat), spec) for pat, spec in (rules or [])]
        self.min_size = min_size

    def _resolve(self, mesh):
        """Per-call (axis, axis_size) for ``mesh`` — never cached on self,
        so one strategy object works across different meshes."""
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        if self.axis not in sizes:
            raise ValueError(
                f"TensorParallel axis {self.axis!r} not in mesh axes "
                f"{tuple(mesh.axis_names)}; build the context with a model "
                "axis, e.g. init_zoo_context(mesh_shape=(d, t), "
                "axis_names=('data', 'model'))")
        if self.axis_size is not None and self.axis_size != sizes[self.axis]:
            raise ValueError(
                f"mesh_axis_size {self.axis_size} != mesh's "
                f"{self.axis!r} size {sizes[self.axis]}")
        return self.axis, sizes[self.axis]

    def param_shardings(self, mesh, params):
        axis, axis_size = self._resolve(mesh)

        def one(path, leaf):
            return NamedSharding(
                mesh, self._spec(path_str(path), leaf, axis, axis_size))

        return jax.tree_util.tree_map_with_path(one, params)

    def spec(self, path: str, leaf) -> P:
        if self.axis_size is None:
            raise ValueError(
                "TensorParallel.spec() without mesh_axis_size — use "
                "param_shardings(mesh, params), which resolves the axis "
                "size from the mesh")
        return self._spec(path, leaf, self.axis, self.axis_size)

    def _spec(self, path: str, leaf, axis: str, axis_size: int) -> P:
        for pat, spec in self.rules:
            if pat.search(path):
                return spec
        shape = getattr(leaf, "shape", ())
        if not shape or int(np.prod(shape)) < self.min_size:
            return P()
        if not axis_size or axis_size <= 1:
            return P()
        # largest dim divisible by the axis size
        cands = [(d, i) for i, d in enumerate(shape)
                 if d % axis_size == 0]
        if not cands:
            return P()
        _, dim = max(cands)
        spec = [None] * len(shape)
        spec[dim] = axis
        return P(*spec)


class ExpertParallel(ShardingStrategy):
    """Shard MoE expert weights (leading ``n_experts`` dim) over the
    mesh's ``expert`` axis — pairs with ``nn.layers.moe.SparseMoE``,
    whose per-expert weights are stacked on dim 0.  Non-expert params
    stay replicated (combine with TensorParallel via explicit rules if
    both regimes are wanted).
    """

    def __init__(self, axis: str = "expert",
                 pattern: str = r"(^|/)(w1|b1|w2|b2)$"):
        # matches SparseMoE's expert-stacked leaves both as a bare param
        # tree ("w1") and nested under a layer name ("sparsemoe_1/w1");
        # the gate kernel never matches and stays replicated
        self.axis = axis
        self.pattern = re.compile(pattern)

    def param_shardings(self, mesh, params):
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        if self.axis not in sizes:
            raise ValueError(
                f"ExpertParallel axis {self.axis!r} not in mesh axes "
                f"{tuple(mesh.axis_names)}; build the context with an "
                "expert axis, e.g. init_zoo_context(mesh_shape=(d, e), "
                "axis_names=('data', 'expert'))")
        n = sizes[self.axis]

        def one(path, leaf):
            p = path_str(path)
            shape = getattr(leaf, "shape", ())
            if self.pattern.search(p) and shape:
                if shape[0] % n:
                    raise ValueError(
                        f"expert param {p!r} has {shape[0]} experts, not "
                        f"divisible by the {self.axis!r} axis size {n} — "
                        "silently replicating would discard the requested "
                        "expert partitioning; adjust n_experts or the mesh")
                return NamedSharding(
                    mesh, P(self.axis, *([None] * (len(shape) - 1))))
            return NamedSharding(mesh, P())

        return jax.tree_util.tree_map_with_path(one, params)


class SequenceParallel(ShardingStrategy):
    """Sequence/context parallelism: parameters replicated, attention
    computed as ring attention with K/V rotating over ``mesh[axis]``
    (parallel/sequence.py).

    The regime the reference cannot reach (SURVEY §5.7: sequence length
    bounded by single-node memory): per-device attention memory is
    O(L·L/n) and the KV exchange rides ICI neighbour hops.  Activated
    through ``Estimator`` — ``compile(sharding="sp")`` on a mesh with a
    sequence axis makes every ``MultiHeadAttention`` in the model lower
    to the ring. Constraints: self-attention only, no padding masks
    (causal is fine), attention-prob dropout is skipped on the ring.
    """

    def __init__(self, axis: str = "seq"):
        self.axis = axis

    def activate(self, mesh):
        from analytics_zoo_tpu.parallel.mode import (SeqParallelMode,
                                                     parallel_mode)
        if self.axis not in mesh.axis_names:
            raise ValueError(
                f"SequenceParallel axis {self.axis!r} not in mesh axes "
                f"{tuple(mesh.axis_names)}; use init_zoo_context("
                "mesh_shape=(d, s), axis_names=('data', 'seq'))")
        return parallel_mode(seq=SeqParallelMode(
            mesh, self.axis,
            batch_axis=_infer_batch_axis(mesh, self.axis)))


_SEQ_MESH_CACHE: dict = {}


def seq_mesh(ways: int, axis: str = "seq"):
    """A 1-D ``(ways,)`` mesh over the first ``ways`` devices with a
    sequence axis — what the ``seq_shards`` config knob hands to
    ``ops.ring_attention`` when no explicit sequence-parallel regime is
    active (nn/layers/attention.py).  Cached per (ways, axis): layer
    forwards run at trace time and must not rebuild meshes per call.
    Returns None when fewer than ``ways`` devices exist (the caller
    falls back to single-device attention).
    """
    import jax
    from jax.sharding import Mesh

    key = (int(ways), axis, jax.default_backend())
    got = _SEQ_MESH_CACHE.get(key)
    if got is not None:
        return got
    devs = jax.devices()
    if ways < 2 or len(devs) < ways:
        return None
    mesh = Mesh(np.asarray(devs[:ways]), (axis,))
    _SEQ_MESH_CACHE[key] = mesh
    return mesh


class PipelineStrategy(ShardingStrategy):
    """GPipe pipeline parallelism as an Estimator regime.

    Stage weights are the model's stacked homogeneous block subtree
    (``TransformerLayer(stacked=True)`` stores its blocks as one pytree
    with leading dim ``n_block``); leaves under a ``blocks`` path shard
    over ``mesh[axis]`` (each device holds 1/S of the stack) and the
    forward routes through the microbatched ppermute ring
    (parallel/pipeline.py).  Everything outside the block stack
    (embeddings, heads) stays replicated and runs outside the pipeline.

    Composes with data parallelism: build the mesh as
    ``axis_names=('data', 'pipe')`` — the batch shards over ``data``,
    each data group runs its own pipeline over its ``pipe`` ring.
    """

    def __init__(self, axis: str = "pipe", n_microbatches: int = 4,
                 remat: bool = False,
                 pattern: str = r"(^|/)blocks(/|$)"):
        self.axis = axis
        self.n_microbatches = n_microbatches
        self.remat = remat
        self.pattern = re.compile(pattern)

    def _axis_size(self, mesh) -> int:
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        if self.axis not in sizes:
            raise ValueError(
                f"PipelineStrategy axis {self.axis!r} not in mesh axes "
                f"{tuple(mesh.axis_names)}; use init_zoo_context("
                "mesh_shape=(d, p), axis_names=('data', 'pipe'))")
        return sizes[self.axis]

    def param_shardings(self, mesh, params):
        n = self._axis_size(mesh)
        matched = []

        def one(path, leaf):
            p = path_str(path)
            shape = getattr(leaf, "shape", ())
            if self.pattern.search(p) and shape:
                if shape[0] != n:
                    # the GPipe body takes exactly one stage per device
                    # (pipeline_spmd reads p[0]); multiples cannot work
                    raise ValueError(
                        f"stacked block param {p!r} has {shape[0]} stages "
                        f"but the {self.axis!r} axis has {n} devices — "
                        "n_block must equal the pipe axis size")
                matched.append(p)
                return NamedSharding(
                    mesh, P(self.axis, *([None] * (len(shape) - 1))))
            return NamedSharding(mesh, P())

        out = jax.tree_util.tree_map_with_path(one, params)
        if not matched:
            raise ValueError(
                "sharding='pp' found no stacked block subtree (no param "
                "path matches 'blocks') — pipeline the model by stacking "
                "its homogeneous blocks, e.g. TransformerLayer("
                "stacked=True)")
        return out

    def activate(self, mesh):
        from analytics_zoo_tpu.parallel.mode import (PipelineMode,
                                                     parallel_mode)
        self._axis_size(mesh)
        return parallel_mode(pipe=PipelineMode(
            mesh, self.axis, n_microbatches=self.n_microbatches,
            remat=self.remat,
            batch_axis=_infer_batch_axis(mesh, self.axis)))


class AutoSharding(TensorParallel):
    """Mesh-adaptive: tensor-parallel over the mesh's last axis when it has
    a dedicated (non-data) axis, plain data parallelism otherwise."""

    def __init__(self, rules: Optional[Sequence] = None,
                 min_size: int = 2 ** 16):
        super().__init__(axis="", mesh_axis_size=None, rules=rules,
                         min_size=min_size)

    def _resolve(self, mesh):
        axis = mesh.axis_names[-1]
        return axis, dict(zip(mesh.axis_names,
                              mesh.devices.shape))[axis]

    def param_shardings(self, mesh, params):
        if len(mesh.axis_names) < 2:
            return DataParallel().param_shardings(mesh, params)
        return super().param_shardings(mesh, params)


def dataset_sharding(mesh, n_rows: int, ndim: int,
                     axis: str = "data") -> NamedSharding:
    """Placement for a DEVICE-cached (HBM-resident) dataset array.

    Rows split over the mesh's data axis so an N-device mesh holds 1/N of
    the dataset per chip (the capacity analog of the reference's
    partition-per-executor caching); every other dim is replicated.  When
    the row count doesn't divide the axis — or the axis is missing, e.g.
    a pure model-parallel mesh — the array is replicated instead: the
    resident epoch body gathers by *global* permutation indices, so a
    replicated copy is always correct, just not capacity-optimal.
    """
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if axis in sizes and sizes[axis] > 1 and n_rows % sizes[axis] == 0:
        return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))
    return NamedSharding(mesh, P())


def replica_devices(mesh, axis: str = "data") -> list:
    """Devices hosting one independent serving replica each.

    Serving wants full-model replicas round-robined by the device
    executor — the inference analog of data parallelism — so the natural
    replica set is the mesh's data axis: one device per data-axis index,
    fixed at index 0 along every model axis (those devices hold complete
    weight copies under DataParallel; a model-parallel serving path would
    need a sharded forward, which is the training stack's job).  Falls
    back to the mesh's flat device list when the axis is missing.
    """
    devs = np.asarray(mesh.devices)
    if axis in mesh.axis_names and devs.ndim == len(mesh.axis_names):
        idx = tuple(slice(None) if a == axis else 0
                    for a in mesh.axis_names)
        return list(np.atleast_1d(devs[idx]).ravel())
    return list(devs.ravel())


def spec_str(arr) -> str:
    """Compact description of a jax.Array's sharding for checkpoint
    manifests: ``"replicated"``, a PartitionSpec repr for NamedShardings,
    or the sharding class name otherwise.  Informational only — restore
    re-lays arrays out onto the *current* mesh (reshard-on-restore), so
    the recorded spec never constrains the topology a run resumes at."""
    sharding = getattr(arr, "sharding", None)
    if sharding is None or getattr(arr, "is_fully_replicated", True):
        return "replicated"
    spec = getattr(sharding, "spec", None)
    if spec is not None:
        return str(spec)
    return type(sharding).__name__


def device_put_global(x, sharding):
    """Place one host array onto a (possibly process-spanning) sharding.

    Single-controller: plain ``device_put``.  Multi-controller: every
    process holds the full host value (the distributed checkpoint
    restore reassembles the global tree on every host), so
    ``make_array_from_callback`` carves out each process's addressable
    chunks locally — no cross-host traffic, and it works for ANY target
    sharding, which is what makes restore elastic: a tree saved at one
    process count lays out onto whatever mesh is live now.

    This IS the explicit staging chokepoint (the multi-controller
    analog of a bare ``device_put``, which ``jax.transfer_guard``
    exempts), so the callback's internal puts are locally exempted too
    — transfer-guarded training paths stay runnable multi-controller.
    """
    if jax.process_count() > 1:
        a = np.asarray(x)
        with jax.transfer_guard("allow"):
            return jax.make_array_from_callback(
                a.shape, sharding, lambda idx: a[idx])
    import jax.numpy as jnp

    return jax.device_put(jnp.asarray(x), sharding)


def tree_put_global(tree, shardings):
    """``device_put_global`` over a pytree of host arrays against a
    matching pytree of shardings (or one sharding for the whole tree)."""
    import jax.tree_util as jtu

    is_sharding = lambda s: hasattr(s, "device_set")  # noqa: E731
    if is_sharding(shardings):
        return jtu.tree_map(
            lambda x: device_put_global(x, shardings), tree)
    return jtu.tree_map(device_put_global, tree, shardings)


def make_strategy(name: str, mesh, **kw) -> ShardingStrategy:
    """String lowering (config-system entry point)."""
    name = name.lower()
    if name in ("dp", "data", "data_parallel", "replicated"):
        return DataParallel()
    if name in ("auto",):
        return AutoSharding(**kw)
    if name in ("ep", "expert", "expert_parallel"):
        axis = kw.pop("axis", "expert")
        if axis not in mesh.axis_names:
            raise ValueError(
                f"sharding='ep' needs a mesh with an {axis!r} axis (got "
                f"axes {tuple(mesh.axis_names)}); use "
                "init_zoo_context(mesh_shape=(d, e), "
                "axis_names=('data', 'expert'))")
        return ExpertParallel(axis=axis, **kw)
    if name in ("sp", "seq", "sequence", "sequence_parallel", "ring"):
        axis = kw.pop("axis", "seq")
        if axis not in mesh.axis_names:
            raise ValueError(
                f"sharding='sp' needs a mesh with a {axis!r} axis (got "
                f"axes {tuple(mesh.axis_names)}); use "
                "init_zoo_context(mesh_shape=(d, s), "
                "axis_names=('data', 'seq'))")
        return SequenceParallel(axis=axis, **kw)
    if name in ("pp", "pipe", "pipeline", "pipeline_parallel", "gpipe"):
        axis = kw.pop("axis", "pipe")
        if axis not in mesh.axis_names:
            raise ValueError(
                f"sharding='pp' needs a mesh with a {axis!r} axis (got "
                f"axes {tuple(mesh.axis_names)}); use "
                "init_zoo_context(mesh_shape=(d, p), "
                "axis_names=('data', 'pipe'))")
        return PipelineStrategy(axis=axis, **kw)
    if name in ("tp", "tensor", "tensor_parallel"):
        axis = kw.pop("axis", None)
        if axis is None:
            if len(mesh.axis_names) < 2:
                raise ValueError(
                    "sharding='tp' needs a mesh with a model axis (got "
                    f"axes {tuple(mesh.axis_names)}); use "
                    "init_zoo_context(mesh_shape=(d, t), "
                    "axis_names=('data', 'model')) or sharding='auto'")
            axis = mesh.axis_names[-1]
        return TensorParallel(axis=axis, **kw)
    raise ValueError(f"unknown sharding strategy {name!r}; "
                     "known: dp, tp, ep, sp, pp, auto")
