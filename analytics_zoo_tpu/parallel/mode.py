"""Active-parallelism context: how strategies reach inside model forwards.

The Estimator's jitted steps wrap ``model.call`` in the strategy's
``activate()`` context (train/estimator.py).  During *tracing*, layers
that have a parallel lowering consult this module:

- ``MultiHeadAttention`` switches to ring attention over the sequence
  axis when ``current_seq_parallel()`` is set (parallel/sequence.py);
- ``TransformerLayer(stacked=True)`` routes its block stack through the
  GPipe schedule when ``current_pipeline()`` is set (parallel/pipeline.py).

This is trace-time-only state (a thread-local read while jit traces the
step); the compiled program embeds the parallel lowering, so nothing here
runs in the hot loop.  Thread-local so concurrent builds (AutoML trials)
can trace different regimes simultaneously.
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass
from typing import Optional

from jax.sharding import Mesh

_ACTIVE = threading.local()


@dataclass(frozen=True)
class SeqParallelMode:
    """Ring attention over ``mesh[axis]`` (sequence/context parallelism).
    ``batch_axis`` keeps the batch dim sharded (sp×dp composition) —
    without it GSPMD would allgather the batch into every data group."""
    mesh: Mesh
    axis: str
    batch_axis: Optional[str] = None


@dataclass(frozen=True)
class PipelineMode:
    """GPipe microbatched schedule over ``mesh[axis]``."""
    mesh: Mesh
    axis: str
    n_microbatches: int = 4
    remat: bool = False
    batch_axis: Optional[str] = None   # compose pp with dp


def current_seq_parallel() -> Optional[SeqParallelMode]:
    return getattr(_ACTIVE, "seq", None)


def current_pipeline() -> Optional[PipelineMode]:
    return getattr(_ACTIVE, "pipe", None)


@contextlib.contextmanager
def parallel_mode(seq: Optional[SeqParallelMode] = None,
                  pipe: Optional[PipelineMode] = None):
    prev = (getattr(_ACTIVE, "seq", None), getattr(_ACTIVE, "pipe", None))
    _ACTIVE.seq, _ACTIVE.pipe = seq, pipe
    try:
        yield
    finally:
        _ACTIVE.seq, _ACTIVE.pipe = prev
