"""Active-parallelism context: how strategies reach inside model forwards.

The Estimator's jitted steps wrap ``model.call`` in the strategy's
``activate()`` context (train/estimator.py).  During *tracing*, layers
that have a parallel lowering consult this module:

- ``MultiHeadAttention`` switches to ring attention over the sequence
  axis when ``current_seq_parallel()`` is set (parallel/sequence.py);
- ``TransformerLayer(stacked=True)`` routes its block stack through the
  GPipe schedule when ``current_pipeline()`` is set (parallel/pipeline.py);
- ``ShardedEmbeddingTable`` lowers its lookup to the local-gather + psum
  exchange when ``current_table_sharding()`` lists it
  (parallel/table_sharding.py).

This is trace-time-only state (a thread-local read while jit traces the
step); the compiled program embeds the parallel lowering, so nothing here
runs in the hot loop.  Thread-local so concurrent builds (AutoML trials)
can trace different regimes simultaneously.
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass
from typing import Optional, Tuple

from jax.sharding import Mesh

_ACTIVE = threading.local()


@dataclass(frozen=True)
class SeqParallelMode:
    """Ring attention over ``mesh[axis]`` (sequence/context parallelism).
    ``batch_axis`` keeps the batch dim sharded (sp×dp composition) —
    without it GSPMD would allgather the batch into every data group."""
    mesh: Mesh
    axis: str
    batch_axis: Optional[str] = None


@dataclass(frozen=True)
class PipelineMode:
    """GPipe microbatched schedule over ``mesh[axis]``."""
    mesh: Mesh
    axis: str
    n_microbatches: int = 4
    remat: bool = False
    batch_axis: Optional[str] = None   # compose pp with dp


@dataclass(frozen=True)
class TableShardMode:
    """Row-sharded embedding lookup over ``mesh[axis]`` for the named
    tables (parallel/table_sharding.py).  ``tables`` holds layer NAMES
    — a ``ShardedEmbeddingTable`` only lowers to the sharded exchange
    when its own name is listed, so strategies shard exactly the
    tables the placement router picked."""
    mesh: Mesh
    axis: str
    tables: Tuple[str, ...] = ()


def current_table_sharding() -> Optional[TableShardMode]:
    return getattr(_ACTIVE, "table", None)


def current_seq_parallel() -> Optional[SeqParallelMode]:
    return getattr(_ACTIVE, "seq", None)


def current_pipeline() -> Optional[PipelineMode]:
    return getattr(_ACTIVE, "pipe", None)


@contextlib.contextmanager
def parallel_mode(seq: Optional[SeqParallelMode] = None,
                  pipe: Optional[PipelineMode] = None):
    prev = (getattr(_ACTIVE, "seq", None), getattr(_ACTIVE, "pipe", None))
    _ACTIVE.seq, _ACTIVE.pipe = seq, pipe
    try:
        yield
    finally:
        _ACTIVE.seq, _ACTIVE.pipe = prev


@contextlib.contextmanager
def table_mode(mode: Optional[TableShardMode]):
    """Publish table sharding for the trace.  Deliberately separate
    from ``parallel_mode`` (touches ONLY ``_ACTIVE.table``) so a
    table-sharded strategy can wrap a seq/pipe base strategy without
    clobbering the base's trace-time state."""
    prev = getattr(_ACTIVE, "table", None)
    _ACTIVE.table = mode
    try:
        yield
    finally:
        _ACTIVE.table = prev
