from analytics_zoo_tpu.caffe.loader import (  # noqa: F401
    UnsupportedCaffeLayer, decode_caffemodel, load_caffe, load_caffe_parts,
    parse_prototxt)
