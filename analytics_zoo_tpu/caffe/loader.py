"""Minimal Caffe importer: prototxt + caffemodel → a trainable program.

Reference capability: the Scala Caffe importer
(zoo/src/main/scala/com/intel/analytics/zoo/models/caffe/CaffeLoader.scala:718
plus Converter.scala/LayerConverter.scala/V1LayerConverter.scala, ~2.9k LoC)
loading prototxt+caffemodel into BigDL graphs via protobuf.

TPU-native design: no ``caffe`` / protobuf dependency — the caffemodel's
NetParameter wire format is decoded with the same hand-rolled protobuf
reader the ONNX importer uses (onnx/proto.py), the prototxt with a ~60
line text-format parser, and the network is *translated into the ONNX
node vocabulary* and executed by the existing ``OnnxProgram`` runtime
(one op-list program under jit; trains under the Estimator via
``to_model``).  Layout stays NCHW like the ONNX path (onnx/loader.py:10).

Scope (the reference's core conv-net vocabulary): Input, Convolution,
Pooling (MAX/AVE/global, with Caffe's ceil-mode output sizes restored
via computed extra padding), InnerProduct, ReLU, Sigmoid, TanH, Softmax
(+SoftmaxWithLoss as inference softmax), Dropout, LRN, BatchNorm, Scale,
Concat, Eltwise (SUM/PROD/MAX), Flatten, Split; train-only layers
(Data/Accuracy/losses) are skipped.  Anything else raises
``UnsupportedCaffeLayer`` loudly with caffe2onnx guidance (the
reference's exotic-layer surface is legacy).

Known approximation: Caffe AVE pooling over a ceil-mode tail divides by
the in-bounds+pad window it actually covered; the translation divides by
the full kernel area (count_include_pad).  Nets whose spatial dims tile
evenly (the common case) are exact.
"""

from __future__ import annotations

import re
import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.onnx import proto
from analytics_zoo_tpu.onnx.loader import OnnxProgram
from analytics_zoo_tpu.onnx.proto import _fields, _read_varint


class UnsupportedCaffeLayer(ValueError):
    def __init__(self, layer_type: str, name: str = ""):
        super().__init__(
            f"Caffe layer type {layer_type!r}" +
            (f" (layer {name!r})" if name else "") +
            " is outside the minimal importer's conv-net vocabulary "
            "(Convolution/Pooling/InnerProduct/BatchNorm/Scale/ReLU/"
            "Sigmoid/TanH/Softmax/Dropout/LRN/Concat/Eltwise/Flatten); "
            "convert the model with caffe2onnx and use "
            "analytics_zoo_tpu.onnx.load_onnx instead")


# ---------------------------------------------------------------------------
# prototxt (protobuf text format) parser
# ---------------------------------------------------------------------------

_TOKEN = re.compile(r"""
    \s*(?:\#[^\n]*\s*)*            # whitespace / comments
    (?P<tok>
        [A-Za-z_][A-Za-z0-9_]* |   # identifier
        "(?:[^"\\]|\\.)*"      |   # quoted string
        '(?:[^'\\]|\\.)*'      |
        [-+]?[0-9.eE+-]+       |   # number
        [{}:]                      # punctuation
    )""", re.VERBOSE)


def _tokenize(text: str) -> List[str]:
    toks, pos = [], 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            rest = text[pos:].strip()
            if not rest:
                break
            raise ValueError(f"prototxt parse error near: {rest[:40]!r}")
        toks.append(m.group("tok"))
        pos = m.end()
    return toks


def _coerce(tok: str) -> Any:
    if tok[0] in "\"'":
        return tok[1:-1]
    if tok in ("true", "True"):
        return True
    if tok in ("false", "False"):
        return False
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        return tok        # bare enum (MAX, AVE, SUM ...)


def parse_prototxt(text: str) -> Dict[str, List[Any]]:
    """Protobuf text format → nested dict; every key maps to a LIST
    (repeated fields are first-class in caffe prototxts)."""
    toks = _tokenize(text)
    pos = 0

    def message() -> Dict[str, List[Any]]:
        nonlocal pos
        out: Dict[str, List[Any]] = {}
        while pos < len(toks) and toks[pos] != "}":
            key = toks[pos]
            pos += 1
            if pos < len(toks) and toks[pos] == ":":
                pos += 1
                val = _coerce(toks[pos])
                pos += 1
            elif pos < len(toks) and toks[pos] == "{":
                pos += 1
                val = message()
                if toks[pos] != "}":
                    raise ValueError("prototxt: unbalanced braces")
                pos += 1
            else:
                raise ValueError(f"prototxt: expected ':' or '{{' after "
                                 f"{key!r}")
            out.setdefault(key, []).append(val)
        return out

    msg = message()
    if pos != len(toks):
        raise ValueError("prototxt: trailing tokens")
    return msg


def _one(d: Dict[str, List[Any]], key: str, default=None):
    v = d.get(key)
    return v[0] if v else default


def _many(d: Dict[str, List[Any]], key: str) -> List[Any]:
    return list(d.get(key, []))


# ---------------------------------------------------------------------------
# caffemodel (NetParameter wire format) → {layer_name: [blob arrays]}
# ---------------------------------------------------------------------------

def _decode_blob(buf: bytes) -> np.ndarray:
    dims: List[int] = []
    legacy = [0, 0, 0, 0]          # num, channels, height, width
    floats: List[float] = []
    raw: List[bytes] = []
    for fnum, wtype, val in _fields(buf):
        if fnum == 7 and wtype == 2:          # shape: BlobShape{dim=1}
            for f2, w2, v2 in _fields(val):
                if f2 == 1:
                    if w2 == 2:               # packed varints
                        p = 0
                        while p < len(val if False else v2):
                            d, p = _read_varint(v2, p)
                            dims.append(d)
                    else:
                        dims.append(v2)
        elif fnum == 5:                        # data: repeated float
            if wtype == 2:                     # packed
                raw.append(val)
            else:                              # unpacked single
                floats.append(struct.unpack("<f", val)[0])
        elif fnum in (1, 2, 3, 4) and wtype == 0:
            legacy[fnum - 1] = val
    if raw:
        buf_all = b"".join(raw)
        arr = np.frombuffer(buf_all, dtype="<f4").astype(np.float32)
    else:
        arr = np.asarray(floats, np.float32)
    if not dims and any(legacy):
        dims = [d for d in legacy]
        # legacy blobs are always logically 4D; squeeze leading ones later
    if dims and int(np.prod(dims)) == arr.size:
        arr = arr.reshape(dims)
    return arr


def decode_caffemodel(buf: bytes) -> Dict[str, List[np.ndarray]]:
    """NetParameter → layer name → blobs.  Handles both the V2 ``layer``
    (field 100) and V1 ``layers`` (field 2) encodings (the reference
    ships both converters — LayerConverter/V1LayerConverter.scala)."""
    out: Dict[str, List[np.ndarray]] = {}
    for fnum, wtype, val in _fields(buf):
        if fnum == 100 and wtype == 2:        # V2 LayerParameter
            name, blobs = "", []
            for f2, w2, v2 in _fields(val):
                if f2 == 1:
                    name = v2.decode()
                elif f2 == 7:
                    blobs.append(_decode_blob(v2))
            if name and blobs:
                out[name] = blobs
        elif fnum == 2 and wtype == 2:        # V1LayerParameter
            name, blobs = "", []
            for f2, w2, v2 in _fields(val):
                if f2 == 4 and w2 == 2:
                    name = v2.decode()
                elif f2 == 6 and w2 == 2:
                    blobs.append(_decode_blob(v2))
            if name and blobs:
                out[name] = blobs
    return out


# ---------------------------------------------------------------------------
# translation to the ONNX vocabulary
# ---------------------------------------------------------------------------

# V1 enum type name → V2 string type
_V1_TYPES = {
    "CONVOLUTION": "Convolution", "POOLING": "Pooling",
    "INNER_PRODUCT": "InnerProduct", "RELU": "ReLU", "SIGMOID": "Sigmoid",
    "TANH": "TanH", "SOFTMAX": "Softmax", "SOFTMAX_LOSS": "SoftmaxWithLoss",
    "LRN": "LRN", "DROPOUT": "Dropout", "CONCAT": "Concat",
    "ELTWISE": "Eltwise", "FLATTEN": "Flatten", "SPLIT": "Split",
    "DATA": "Data", "ACCURACY": "Accuracy",
    "POWER": "Power", "ABSVAL": "AbsVal", "BNLL": "BNLL", "EXP": "Exp",
    "DECONVOLUTION": "Deconvolution", "SLICE": "Slice",
    "INNERPRODUCT": "InnerProduct",
}

_SKIP_TYPES = {"Data", "ImageData", "HDF5Data", "DummyData", "MemoryData",
               "Accuracy", "Silence", "EuclideanLoss", "HingeLoss",
               "SigmoidCrossEntropyLoss", "ContrastiveLoss",
               "InfogainLoss", "MultinomialLogisticLoss"}


def _pair(param, base: str, default: int) -> Tuple[int, int]:
    """Caffe's spatial params: repeated ``base`` or ``base_h``/``base_w``."""
    h = _one(param, f"{base}_h")
    w = _one(param, f"{base}_w")
    if h is not None or w is not None:
        return int(h or default), int(w or default)
    vals = _many(param, base)
    if not vals:
        return default, default
    if len(vals) == 1:
        return int(vals[0]), int(vals[0])
    return int(vals[0]), int(vals[1])


def _conv_out(h: int, k: int, p: int, s: int, d: int = 1) -> int:
    return (h + 2 * p - d * (k - 1) - 1) // s + 1


def _pool_out_caffe(h: int, k: int, p: int, s: int) -> int:
    out = -(-(h + 2 * p - k) // s) + 1       # ceil
    if p > 0 and (out - 1) * s >= h + p:     # caffe's clip rule
        out -= 1
    return out


class _Translator:
    """Builds the ONNX graph while tracking NCHW shapes (needed to
    restore Caffe's ceil-mode pooling sizes and to place Flatten before
    InnerProduct)."""

    def __init__(self, weights: Dict[str, List[np.ndarray]]):
        self.weights = weights
        self.nodes: List[proto.Node] = []
        self.inits: List[proto.Tensor] = []
        self.shapes: Dict[str, Tuple[int, ...]] = {}
        self._uid = 0

    def uid(self, base: str) -> str:
        self._uid += 1
        return f"{base}__{self._uid}"

    def add_init(self, name: str, arr: np.ndarray) -> str:
        self.inits.append(proto.Tensor(
            name=name, dims=tuple(arr.shape),
            data_type=proto._DTYPE_IDS[np.dtype(arr.dtype)], array=arr))
        return name

    def node(self, op: str, name: str, inputs: Sequence[str],
             outputs: Sequence[str], **attrs):
        self.nodes.append(proto.Node(op_type=op, name=name,
                                     inputs=list(inputs),
                                     outputs=list(outputs),
                                     attrs=dict(attrs)))

    # -- per-layer handlers ------------------------------------------------
    def convolution(self, name, param, bottom, top):
        blobs = self.weights.get(name)
        if not blobs:
            raise ValueError(f"conv layer {name!r} has no weights in the "
                             "caffemodel")
        w = blobs[0]
        if w.ndim != 4:
            w = w.reshape(w.shape[-4:]) if w.size else w
        kh, kw = _pair(param, "kernel_size", 0)
        if kh == 0:
            kh, kw = w.shape[2], w.shape[3]
        ph, pw = _pair(param, "pad", 0)
        sh, sw = _pair(param, "stride", 1)
        dil = int(_one(param, "dilation", 1))
        group = int(_one(param, "group", 1))
        ins = [bottom, self.add_init(f"{name}_W", w.astype(np.float32))]
        bias_term = _one(param, "bias_term", True)
        if bias_term and len(blobs) > 1:
            ins.append(self.add_init(f"{name}_b",
                                     blobs[1].reshape(-1).astype(np.float32)))
        self.node("Conv", name, ins, [top],
                  kernel_shape=[kh, kw], strides=[sh, sw],
                  pads=[ph, pw, ph, pw], dilations=[dil, dil], group=group)
        b, c, h, wd = self.shapes[bottom]
        self.shapes[top] = (b, w.shape[0],
                            _conv_out(h, kh, ph, sh, dil),
                            _conv_out(wd, kw, pw, sw, dil))

    def pooling(self, name, param, bottom, top):
        mode = str(_one(param, "pool", "MAX")).upper()
        if mode not in ("MAX", "AVE", "0", "1"):
            raise UnsupportedCaffeLayer(f"Pooling pool={mode}", name)
        is_max = mode in ("MAX", "0")
        if _one(param, "global_pooling", False):
            self.node("GlobalMaxPool" if is_max else "GlobalAveragePool",
                      name, [bottom], [top])
            b, c = self.shapes[bottom][:2]
            self.shapes[top] = (b, c, 1, 1)
            return
        kh, kw = _pair(param, "kernel_size", 0)
        ph, pw = _pair(param, "pad", 0)
        sh, sw = _pair(param, "stride", 1)
        b, c, h, w = self.shapes[bottom]
        oh = _pool_out_caffe(h, kh, ph, sh)
        ow = _pool_out_caffe(w, kw, pw, sw)
        # restore Caffe's ceil-mode output under floor-mode windows by
        # extending the END padding to exactly cover the tail windows
        eh = max(0, (oh - 1) * sh + kh - h - 2 * ph)
        ew = max(0, (ow - 1) * sw + kw - w - 2 * pw)
        self.node("MaxPool" if is_max else "AveragePool", name,
                  [bottom], [top], kernel_shape=[kh, kw],
                  strides=[sh, sw], pads=[ph, pw, ph + eh, pw + ew],
                  count_include_pad=1)
        self.shapes[top] = (b, c, oh, ow)

    def inner_product(self, name, param, bottom, top):
        blobs = self.weights.get(name)
        if not blobs:
            raise ValueError(f"ip layer {name!r} has no weights in the "
                             "caffemodel")
        w = blobs[0]
        w = w.reshape(w.shape[-2:]) if w.ndim > 2 else w     # (out, in)
        src = bottom
        shape = self.shapes[bottom]
        if len(shape) > 2:
            flat = self.uid(f"{name}_flat")
            self.node("Flatten", f"{name}_flatten", [bottom], [flat],
                      axis=1)
            src = flat
            shape = (shape[0], int(np.prod(shape[1:])))
        ins = [src, self.add_init(f"{name}_W", w.astype(np.float32))]
        if len(blobs) > 1 and _one(param, "bias_term", True):
            ins.append(self.add_init(f"{name}_b",
                                     blobs[1].reshape(-1).astype(np.float32)))
        self.node("Gemm", name, ins, [top], transB=1)
        self.shapes[top] = (shape[0], w.shape[0])

    def batch_norm(self, name, param, bottom, top):
        blobs = self.weights.get(name, [])
        if len(blobs) < 2:
            raise ValueError(f"BatchNorm layer {name!r} needs mean/var "
                             "blobs in the caffemodel")
        mean, var = blobs[0].reshape(-1), blobs[1].reshape(-1)
        if len(blobs) > 2 and blobs[2].size:
            sf = float(blobs[2].reshape(-1)[0])
            if sf != 0:
                mean = mean / sf
                var = var / sf
        c = mean.shape[0]
        eps = float(_one(param, "eps", 1e-5))
        ins = [bottom,
               self.add_init(f"{name}_scale", np.ones(c, np.float32)),
               self.add_init(f"{name}_bias", np.zeros(c, np.float32)),
               self.add_init(f"{name}_mean", mean.astype(np.float32)),
               self.add_init(f"{name}_var", var.astype(np.float32))]
        self.node("BatchNormalization", name, ins, [top], epsilon=eps)
        self.shapes[top] = self.shapes[bottom]

    def scale(self, name, param, bottom, top):
        blobs = self.weights.get(name, [])
        if not blobs:
            raise ValueError(f"Scale layer {name!r} has no blobs")
        shape = self.shapes[bottom]
        c = blobs[0].size
        bshape = (1, c) + (1,) * (len(shape) - 2)
        gamma = self.add_init(f"{name}_gamma",
                              blobs[0].reshape(bshape).astype(np.float32))
        mul_out = top if not (_one(param, "bias_term", False)
                              or len(blobs) > 1) else self.uid(name)
        self.node("Mul", name, [bottom, gamma], [mul_out])
        if mul_out != top:
            beta = self.add_init(f"{name}_beta",
                                 blobs[1].reshape(bshape).astype(np.float32))
            self.node("Add", f"{name}_bias", [mul_out, beta], [top])
        self.shapes[top] = shape

    def eltwise(self, name, param, bottoms, top):
        op = str(_one(param, "operation", "SUM")).upper()
        coeffs = [float(c) for c in _many(param, "coeff")]
        onnx_op = {"SUM": "Sum", "1": "Sum", "PROD": "Mul", "0": "Mul",
                   "MAX": "Max", "2": "Max"}.get(op)
        if onnx_op is None:
            raise UnsupportedCaffeLayer(f"Eltwise operation={op}", name)
        ins = list(bottoms)
        if coeffs and any(c != 1.0 for c in coeffs):
            if onnx_op != "Sum":
                raise UnsupportedCaffeLayer(
                    f"Eltwise coeff with operation={op}", name)
            if len(coeffs) != len(ins):     # caffe rejects this too
                raise UnsupportedCaffeLayer(
                    f"Eltwise: {len(coeffs)} coeffs for {len(ins)} "
                    "bottoms", name)
            scaled = []
            for k, (b, c) in enumerate(zip(ins, coeffs)):
                cn = self.add_init(f"{name}_coeff{k}",
                                   np.asarray(c, np.float32))
                out = self.uid(name)
                self.node("Mul", f"{name}_scale{k}", [b, cn], [out])
                scaled.append(out)
            ins = scaled
        self.node(onnx_op, name, ins, [top])
        self.shapes[top] = self.shapes[bottoms[0]]

    def _affine(self, name, bottom, scale, shift):
        """Emit y = scale*x + shift (skipping identity factors); returns
        the tensor name holding the result."""
        cur = bottom
        if scale != 1.0:
            c = self.add_init(f"{name}_scale", np.asarray(scale, np.float32))
            out = self.uid(name)
            self.node("Mul", f"{name}_mul", [cur, c], [out])
            cur = out
        if shift != 0.0:
            c = self.add_init(f"{name}_shift", np.asarray(shift, np.float32))
            out = self.uid(name)
            self.node("Add", f"{name}_add", [cur, c], [out])
            cur = out
        return cur

    def power(self, name, param, bottom, top):
        """y = (shift + scale * x) ** power (caffe PowerLayer)."""
        power = float(_one(param, "power", 1.0))
        cur = self._affine(name, bottom, float(_one(param, "scale", 1.0)),
                           float(_one(param, "shift", 0.0)))
        if power != 1.0:
            c = self.add_init(f"{name}_pow", np.asarray(power, np.float32))
            self.node("Pow", name, [cur, c], [top])
        else:
            self.node("Identity", name, [cur], [top])
        self.shapes[top] = self.shapes[bottom]

    def exp_log(self, name, param, bottom, top, kind):
        """Exp: y = base^(scale*x+shift); Log: y = log_base(scale*x+shift)
        (base=-1 means e)."""
        base = float(_one(param, "base", -1.0))
        cur = self._affine(name, bottom, float(_one(param, "scale", 1.0)),
                           float(_one(param, "shift", 0.0)))
        ln_b = 1.0 if base <= 0 else float(np.log(base))
        if kind == "Exp":
            if ln_b != 1.0:
                c = self.add_init(f"{name}_lnb", np.asarray(ln_b, np.float32))
                out = self.uid(name)
                self.node("Mul", f"{name}_lnb_mul", [cur, c], [out])
                cur = out
            self.node("Exp", name, [cur], [top])
        else:
            if ln_b != 1.0:
                out = self.uid(name)
                self.node("Log", f"{name}_ln", [cur], [out])
                c = self.add_init(f"{name}_invlnb",
                                  np.asarray(1.0 / ln_b, np.float32))
                self.node("Mul", name, [out, c], [top])
            else:
                self.node("Log", name, [cur], [top])
        self.shapes[top] = self.shapes[bottom]

    def prelu(self, name, param, bottom, top):
        blobs = self.weights.get(name, [])
        if not blobs:
            raise ValueError(f"PReLU layer {name!r} has no slope blob")
        shape = self.shapes[bottom]
        slope = blobs[0].reshape(-1).astype(np.float32)
        if _one(param, "channel_shared", False) or slope.size == 1:
            sl = slope.reshape(())
        else:
            sl = slope.reshape((1, slope.size) + (1,) * (len(shape) - 2))
        s = self.add_init(f"{name}_slope", sl)
        self.node("PRelu", name, [bottom, s], [top])
        self.shapes[top] = shape

    def bias(self, name, param, bottom, top):
        """Bias layer: add a learned per-channel blob (ScaleLayer minus
        the multiply).  Only the caffe defaults (axis=1, num_axes=1 — a
        per-channel broadcast) are supported; anything else must fail
        loud rather than import a silently-wrong broadcast."""
        axis = int(_one(param, "axis", 1))
        num_axes = int(_one(param, "num_axes", 1))
        if axis != 1 or num_axes != 1:
            raise UnsupportedCaffeLayer(
                f"Bias with axis={axis} num_axes={num_axes} (only the "
                "per-channel default axis=1/num_axes=1 is supported)", name)
        blobs = self.weights.get(name, [])
        if not blobs:
            raise ValueError(f"Bias layer {name!r} has no blob")
        shape = self.shapes[bottom]
        c = blobs[0].size
        b = self.add_init(f"{name}_b", blobs[0].reshape(
            (1, c) + (1,) * (len(shape) - 2)).astype(np.float32))
        self.node("Add", name, [bottom, b], [top])
        self.shapes[top] = shape

    def reshape(self, name, param, bottom, top):
        # only the full-shape default (axis=0, num_axes=-1) is supported;
        # partial-range reshapes would import silently wrong otherwise
        axis = int(_one(param, "axis", 0))
        num_axes = int(_one(param, "num_axes", -1))
        if axis != 0 or num_axes != -1:
            raise UnsupportedCaffeLayer(
                f"Reshape with axis={axis} num_axes={num_axes} (only the "
                "whole-shape default axis=0/num_axes=-1 is supported)", name)
        dims = [int(d) for d in _many(_one(param, "shape", {}), "dim")]
        if not dims:
            raise UnsupportedCaffeLayer("Reshape without shape.dim", name)
        shp = self.add_init(f"{name}_shape", np.asarray(dims, np.int64))
        self.node("Reshape", name, [bottom, shp], [top])
        src = self.shapes[bottom]
        out = [src[i] if d == 0 else d for i, d in enumerate(dims)]
        if -1 in out:
            known = int(np.prod([d for d in out if d != -1]))
            out[out.index(-1)] = int(np.prod(src)) // max(1, known)
        self.shapes[top] = tuple(out)

    def slice(self, name, param, bottom, tops):
        axis = int(_one(param, "axis", _one(param, "slice_dim", 1)))
        points = [int(p) for p in _many(param, "slice_point")]
        src = self.shapes[bottom]
        if points:
            bounds = [0] + points + [src[axis]]
            sizes = [bounds[i + 1] - bounds[i]
                     for i in range(len(bounds) - 1)]
        else:
            n = len(tops)
            if src[axis] % n:
                raise UnsupportedCaffeLayer(
                    f"Slice: dim {src[axis]} not divisible by {n}", name)
            sizes = [src[axis] // n] * n
        if len(sizes) != len(tops):
            raise UnsupportedCaffeLayer(
                f"Slice: {len(sizes)} pieces for {len(tops)} tops", name)
        self.node("Split", name, [bottom], list(tops),
                  axis=axis, split=sizes)
        for t, s in zip(tops, sizes):
            shp = list(src)
            shp[axis] = s
            self.shapes[t] = tuple(shp)

    def deconvolution(self, name, param, bottom, top):
        blobs = self.weights.get(name)
        if not blobs:
            raise ValueError(f"deconv layer {name!r} has no weights")
        w = blobs[0]                    # caffe: (Cin, Cout, kH, kW)
        kh, kw = _pair(param, "kernel_size", 0)
        if kh == 0:
            kh, kw = w.shape[2], w.shape[3]
        ph, pw = _pair(param, "pad", 0)
        sh, sw = _pair(param, "stride", 1)
        if int(_one(param, "group", 1)) != 1:
            raise UnsupportedCaffeLayer("Deconvolution group != 1", name)
        if int(_one(param, "dilation", 1)) != 1:
            raise UnsupportedCaffeLayer("Deconvolution dilation != 1", name)
        ins = [bottom, self.add_init(f"{name}_W", w.astype(np.float32))]
        if _one(param, "bias_term", True) and len(blobs) > 1:
            ins.append(self.add_init(
                f"{name}_b", blobs[1].reshape(-1).astype(np.float32)))
        self.node("ConvTranspose", name, ins, [top],
                  kernel_shape=[kh, kw], strides=[sh, sw],
                  pads=[ph, pw, ph, pw])
        b, c, h, wd = self.shapes[bottom]
        self.shapes[top] = (b, w.shape[1],
                            (h - 1) * sh + kh - 2 * ph,
                            (wd - 1) * sw + kw - 2 * pw)

    def lrn(self, name, param, bottom, top):
        region = str(_one(param, "norm_region", "ACROSS_CHANNELS")).upper()
        if region not in ("ACROSS_CHANNELS", "0"):
            raise UnsupportedCaffeLayer("LRN WITHIN_CHANNEL", name)
        self.node("LRN", name, [bottom], [top],
                  size=int(_one(param, "local_size", 5)),
                  alpha=float(_one(param, "alpha", 1.0)),
                  beta=float(_one(param, "beta", 0.75)),
                  bias=float(_one(param, "k", 1.0)))
        self.shapes[top] = self.shapes[bottom]


def _layer_entries(net: Dict[str, List[Any]]):
    """Normalize V2 ``layer`` / V1 ``layers`` prototxt entries to
    (name, type, bottoms, tops, layer_dict)."""
    raw = _many(net, "layer") or _many(net, "layers")
    for ld in raw:
        ltype = str(_one(ld, "type", ""))
        ltype = _V1_TYPES.get(ltype, ltype)
        yield (str(_one(ld, "name", "")), ltype,
               [str(b) for b in _many(ld, "bottom")],
               [str(t) for t in _many(ld, "top")], ld)


def _graph_inputs(net) -> List[Tuple[str, Tuple[int, ...]]]:
    out = []
    names = [str(n) for n in _many(net, "input")]
    if names:
        shapes = _many(net, "input_shape")
        if shapes:
            dims = [tuple(int(d) for d in _many(s, "dim")) for s in shapes]
        else:
            flat = [int(d) for d in _many(net, "input_dim")]
            per = len(flat) // max(len(names), 1)
            dims = [tuple(flat[i * per:(i + 1) * per])
                    for i in range(len(names))]
        out.extend(zip(names, dims))
    for name, ltype, _, tops, ld in _layer_entries(net):
        if ltype == "Input":
            ip = _one(ld, "input_param", {})
            shapes = _many(ip, "shape")
            dims = (tuple(int(d) for d in _many(shapes[0], "dim"))
                    if shapes else ())
            out.append((tops[0], dims))
    return out


def load_caffe_parts(prototxt_text: str, caffemodel: bytes) -> OnnxProgram:
    net = parse_prototxt(prototxt_text)
    weights = decode_caffemodel(caffemodel)
    tr = _Translator(weights)

    inputs = _graph_inputs(net)
    if not inputs:
        raise ValueError("prototxt declares no inputs (need input:/"
                         "input_dim: or an Input layer)")
    for name, dims in inputs:
        tr.shapes[name] = dims

    for name, ltype, bottoms, tops, ld in _layer_entries(net):
        # skip train-phase-only layers (include { phase: TRAIN })
        phases = [str(_one(inc, "phase", "")) for inc in _many(ld, "include")]
        if any(p.upper() == "TRAIN" for p in phases):
            continue
        if ltype in _SKIP_TYPES or ltype == "Input":
            continue
        bottom = bottoms[0] if bottoms else ""
        top = tops[0] if tops else bottom
        if ltype == "Convolution":
            tr.convolution(name, _one(ld, "convolution_param", {}),
                           bottom, top)
        elif ltype == "Pooling":
            tr.pooling(name, _one(ld, "pooling_param", {}), bottom, top)
        elif ltype == "InnerProduct":
            tr.inner_product(name, _one(ld, "inner_product_param", {}),
                             bottom, top)
        elif ltype == "BatchNorm":
            tr.batch_norm(name, _one(ld, "batch_norm_param", {}),
                          bottom, top)
        elif ltype == "Scale":
            tr.scale(name, _one(ld, "scale_param", {}), bottom, top)
        elif ltype == "ReLU":
            slope = float(_one(_one(ld, "relu_param", {}),
                               "negative_slope", 0.0))
            if slope:
                tr.node("LeakyRelu", name, [bottom], [top], alpha=slope)
            else:
                tr.node("Relu", name, [bottom], [top])
            tr.shapes[top] = tr.shapes[bottom]
        elif ltype == "Sigmoid":
            tr.node("Sigmoid", name, [bottom], [top])
            tr.shapes[top] = tr.shapes[bottom]
        elif ltype == "TanH":
            tr.node("Tanh", name, [bottom], [top])
            tr.shapes[top] = tr.shapes[bottom]
        elif ltype in ("Softmax", "SoftmaxWithLoss"):
            # loss head imports as its inference softmax (the label
            # bottom, if present, is dropped)
            tr.node("Softmax", name, [bottom], [top], axis=1)
            tr.shapes[top] = tr.shapes[bottom]
        elif ltype == "Dropout":
            ratio = float(_one(_one(ld, "dropout_param", {}),
                               "dropout_ratio", 0.5))
            tr.node("Dropout", name, [bottom], [top], ratio=ratio)
            tr.shapes[top] = tr.shapes[bottom]
        elif ltype == "LRN":
            tr.lrn(name, _one(ld, "lrn_param", {}), bottom, top)
        elif ltype == "Concat":
            cp = _one(ld, "concat_param", {})
            axis = int(_one(cp, "axis", _one(cp, "concat_dim", 1)))
            tr.node("Concat", name, bottoms, [top], axis=axis)
            ref = list(tr.shapes[bottoms[0]])
            ref[axis] = sum(tr.shapes[b][axis] for b in bottoms)
            tr.shapes[top] = tuple(ref)
        elif ltype == "Flatten":
            tr.node("Flatten", name, [bottom], [top], axis=1)
            s = tr.shapes[bottom]
            tr.shapes[top] = (s[0], int(np.prod(s[1:])))
        elif ltype == "Split":
            for t in tops:
                tr.node("Identity", f"{name}_{t}", [bottom], [t])
                tr.shapes[t] = tr.shapes[bottom]
        elif ltype == "Eltwise":
            tr.eltwise(name, _one(ld, "eltwise_param", {}), bottoms, top)
        elif ltype == "Power":
            tr.power(name, _one(ld, "power_param", {}), bottom, top)
        elif ltype == "Exp":
            tr.exp_log(name, _one(ld, "exp_param", {}), bottom, top,
                       kind="Exp")
        elif ltype == "Log":
            tr.exp_log(name, _one(ld, "log_param", {}), bottom, top,
                       kind="Log")
        elif ltype == "AbsVal":
            tr.node("Abs", name, [bottom], [top])
            tr.shapes[top] = tr.shapes[bottom]
        elif ltype == "BNLL":
            tr.node("Softplus", name, [bottom], [top])
            tr.shapes[top] = tr.shapes[bottom]
        elif ltype == "ELU":
            alpha = float(_one(_one(ld, "elu_param", {}), "alpha", 1.0))
            tr.node("Elu", name, [bottom], [top], alpha=alpha)
            tr.shapes[top] = tr.shapes[bottom]
        elif ltype == "PReLU":
            tr.prelu(name, _one(ld, "prelu_param", {}), bottom, top)
        elif ltype == "Bias":
            tr.bias(name, _one(ld, "bias_param", {}), bottom, top)
        elif ltype == "Reshape":
            tr.reshape(name, _one(ld, "reshape_param", {}), bottom, top)
        elif ltype == "Slice":
            tr.slice(name, _one(ld, "slice_param", {}), bottom, tops)
        elif ltype == "Deconvolution":
            tr.deconvolution(name, _one(ld, "convolution_param", {}),
                             bottom, top)
        else:
            raise UnsupportedCaffeLayer(ltype, name)

    produced = {o for n in tr.nodes for o in n.outputs}
    consumed = {i for n in tr.nodes for i in n.inputs}
    outs = [o for o in produced if o not in consumed] or \
        [tr.nodes[-1].outputs[0]]
    g = proto.Graph(
        name=str(_one(net, "name", "caffe_net")),
        nodes=tr.nodes, initializers=tr.inits,
        inputs=[proto.ValueInfo(name=n, shape=d) for n, d in inputs],
        outputs=[proto.ValueInfo(name=o) for o in sorted(outs)])
    return OnnxProgram(proto.Model(graph=g, producer="caffe-import"))


def load_caffe(def_path: str, model_path: str) -> OnnxProgram:
    """Load prototxt (``def_path``) + caffemodel (``model_path``) —
    the reference ``Net.loadCaffe(defPath, modelPath)``
    (api/Net.scala:169-189) signature."""
    with open(def_path) as f:
        text = f.read()
    with open(model_path, "rb") as f:
        buf = f.read()
    return load_caffe_parts(text, buf)
