"""NNImageReader — images as a DataFrame (reference NNImageReader.scala).

The reference reads images into a Spark DataFrame with the standard image
schema struct (origin, height, width, nChannels, mode, data).  Here the
same schema lands in a pandas DataFrame; ``data`` holds the raw
ndarray (H, W, C uint8, BGR — matching the OpenCV convention the
reference inherits from BigDL's OpenCVMat).
"""

from __future__ import annotations

import os
from typing import Optional

#: column order of the image schema (Spark's ImageSchema parity)
NNImageSchema = ("origin", "height", "width", "nChannels", "mode", "data")


class NNImageReader:
    """Read image files into an image-schema DataFrame
    (reference NNImageReader.readImages).  Listing + decoding is
    ``data.image.ImageSet.read`` — one implementation for both the
    ImageSet and DataFrame front doors."""

    @staticmethod
    def read_images(path: str, resize_h: Optional[int] = None,
                    resize_w: Optional[int] = None):
        import cv2
        import pandas as pd

        from analytics_zoo_tpu.data.image import ImageSet

        rows = []
        for feat in ImageSet.read(path).features:
            img = feat["image"]
            if resize_h and resize_w:
                img = cv2.resize(img, (resize_w, resize_h))
            h, w = img.shape[:2]
            c = img.shape[2] if img.ndim == 3 else 1
            rows.append({"origin": os.path.abspath(feat["path"]),
                         "height": h, "width": w, "nChannels": c,
                         "mode": 16 if c == 3 else 0,   # CV_8UC3 / CV_8UC1
                         "data": img})
        return pd.DataFrame(rows, columns=list(NNImageSchema))

    readImages = read_images
