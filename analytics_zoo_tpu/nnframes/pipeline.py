"""Spark-ML-shaped Pipeline over DataFrames (pandas in, pandas out).

Reference capability: NNEstimator/NNClassifier participating in
``pyspark.ml.Pipeline`` stages (apps/dogs-vs-cats, image-similarity —
``Pipeline(stages=[...]).fit(df)``).  The shim keeps the Spark ML
contract — estimator stages are ``fit`` into transformer models in
order, each transformer feeding the next stage's input — so reference
pipeline code ports by changing only the import.

A stage is anything with either ``fit(df) -> transformer`` (estimator)
or ``transform(df) -> df`` (transformer).  Plain-callable stages
(``df -> df``) are wrapped as transformers for feature-prep lambdas.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence


class _FnTransformer:
    """A bare ``df -> df`` callable as a pipeline transformer."""

    def __init__(self, fn: Callable):
        self.fn = fn

    def transform(self, df):
        return self.fn(df)


class PipelineModel:
    """Fitted pipeline: transformers applied in order
    (pyspark.ml.PipelineModel contract)."""

    def __init__(self, stages: Sequence[Any]):
        self.stages = list(stages)

    def transform(self, df):
        for s in self.stages:
            df = s.transform(df)
        return df


class Pipeline:
    """Ordered stages; ``fit`` trains estimator stages in sequence on
    the progressively-transformed DataFrame (pyspark.ml.Pipeline
    contract)."""

    def __init__(self, stages: Sequence[Any]):
        self.stages = list(stages)

    def fit(self, df) -> PipelineModel:
        fitted: List[Any] = []
        cur = df
        # pyspark.ml contract: during fit, transforms run only up to the
        # LAST ESTIMATOR (later stages never feed another fit, so their
        # transforms — including full NN inference over the training
        # set — are skipped)
        last_est = max((i for i, s in enumerate(self.stages)
                        if hasattr(s, "fit")), default=-1)
        for i, s in enumerate(self.stages):
            if callable(s) and not hasattr(s, "fit") \
                    and not hasattr(s, "transform"):
                s = _FnTransformer(s)
            if hasattr(s, "fit"):
                model = s.fit(cur)
                fitted.append(model)
                if i < last_est:
                    cur = model.transform(cur)
            elif hasattr(s, "transform"):
                fitted.append(s)
                if i < last_est:
                    cur = s.transform(cur)
            else:
                raise TypeError(
                    f"pipeline stage {s!r} has neither fit nor transform")
        return PipelineModel(fitted)
