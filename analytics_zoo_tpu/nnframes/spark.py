"""Real pyspark interop for NNFrames (optional import).

Reference behavior being matched: ``NNEstimator.fit`` accepts a
``pyspark.sql.DataFrame`` (NNEstimator.scala:198,414) and the fitted
``NNModel`` works as a stage inside a real ``pyspark.ml.Pipeline``
(nnframes guide "Use NNEstimator in a Spark ML Pipeline").

Environment note: this container has NO pyspark wheel and zero network
egress, so these paths cannot execute in CI here — they are exercised by
``tests/test_nnframes_pyspark.py`` which ``importorskip``s pyspark and
runs a reference-shaped ``Pipeline(stages=[...]).fit(df)`` under
``local[2]`` wherever pyspark exists.  Everything that does not need a
live SparkSession (column lowering of pyspark.ml Vector rows, the
pandas round-trip helpers) is tested unconditionally.

Design: collection, not re-implementation — the Spark driver collects
the DataFrame through Arrow (``toPandas``), the TPU mesh trains, and
``transform`` hands a DataFrame back to the session it came from.  The
reference moved data the same direction (executors feed the BigDL
optimizer's parameter-synchronised task set); here the heavy lifting is
SPMD on the device mesh, so Spark's role is ingest/egress, which a
collect covers up to driver memory.  For beyond-driver-memory sets,
``FeatureSet.from_npy_files`` (DISK_AND_DRAM) is the supported path.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np


def is_spark_df(df) -> bool:
    """True for a live pyspark.sql.DataFrame (duck-typed so the module
    imports without pyspark installed)."""
    return (hasattr(df, "toPandas") and hasattr(df, "sparkSession")
            and hasattr(df, "schema"))


def spark_df_to_pandas(df):
    """Collect a pyspark DataFrame to pandas on the driver, lowering
    pyspark.ml.linalg Vector cells to plain ndarrays so the NNFrames
    column extraction (nn_estimator._col_to_array) sees dense data."""
    pdf = df.toPandas()
    for c in pdf.columns:
        if len(pdf) and hasattr(pdf[c].iloc[0], "toArray"):
            pdf[c] = [np.asarray(v.toArray(), np.float32) for v in pdf[c]]
    return pdf


def pandas_to_spark_df(pdf, session, template_df=None):
    """Ship a pandas result back into the caller's SparkSession.
    ndarray cells become plain python lists (Spark has no ndarray
    encoder); scalars pass through.  When ``template_df`` carries the
    columns being returned, its schema is reused so Spark keeps the
    caller's column types instead of re-inferring them."""
    out = pdf.copy()
    for c in out.columns:
        if len(out) and isinstance(out[c].iloc[0], np.ndarray):
            out[c] = [v.tolist() for v in out[c]]
        elif out[c].dtype == np.float32:
            out[c] = out[c].astype(np.float64)
    if template_df is not None:
        try:
            from pyspark.sql.types import (ArrayType, DoubleType, LongType,
                                           StructField, StructType)

            fields = {f.name: f for f in template_df.schema.fields}

            def infer(c):
                if c in fields:
                    return fields[c]
                kind = out[c].dtype.kind        # new (e.g. prediction) col
                if kind == "f":
                    return StructField(c, DoubleType())
                if kind in ("i", "u"):
                    return StructField(c, LongType())
                if len(out) and isinstance(out[c].iloc[0], list):
                    return StructField(c, ArrayType(DoubleType()))
                raise TypeError(f"cannot infer spark type for {c!r}")

            schema = StructType([infer(c) for c in out.columns])
            return session.createDataFrame(out, schema=schema)
        except Exception:
            pass        # unmappable column: plain re-inference below
    return session.createDataFrame(out)


def as_spark_ml_stage(stage):
    """Wrap an NNFrames stage as a REAL pyspark.ml stage.

    ``pyspark.ml.Pipeline.fit`` type-checks every stage against
    ``pyspark.ml.base.Estimator``/``Transformer``, so the shim subclasses
    them for real (requires pyspark importable).  The wrapped estimator's
    ``_fit`` trains on the TPU mesh and returns a wrapped transformer,
    which Spark then calls ``_transform`` on — both directions collect /
    re-create DataFrames at the driver boundary.
    """
    from pyspark.ml.base import Estimator as SparkEstimator
    from pyspark.ml.base import Transformer as SparkTransformer

    if hasattr(stage, "fit"):               # NNEstimator / NNClassifier

        class _ZooSparkEstimator(SparkEstimator):
            def __init__(self, inner):
                super().__init__()
                self._inner = inner

            def _fit(self, dataset):
                model = self._inner.fit(dataset)
                return as_spark_ml_stage(model)

            def copy(self, extra=None):
                return _ZooSparkEstimator(self._inner.copy())

        return _ZooSparkEstimator(stage)

    class _ZooSparkModel(SparkTransformer):
        def __init__(self, inner):
            super().__init__()
            self._inner = inner

        def _transform(self, dataset):
            return self._inner.transform(dataset)

        def copy(self, extra=None):
            return _ZooSparkModel(self._inner.copy())

    return _ZooSparkModel(stage)


def init_spark_on_local(cores: int = 2, conf: Optional[dict] = None,
                        app_name: str = "analytics-zoo-tpu") -> Any:
    """Parity for the reference ``init_spark_on_local``
    (pyzoo/zoo/common/nncontext.py:23-44): builds a local[cores]
    SparkSession with Arrow enabled, AND initialises the zoo context so
    the same script drives Spark ingest + TPU training."""
    from pyspark.sql import SparkSession

    from analytics_zoo_tpu import init_zoo_context

    builder = (SparkSession.builder.master(f"local[{int(cores)}]")
               .appName(app_name)
               .config("spark.sql.execution.arrow.pyspark.enabled", "true"))
    for k, v in (conf or {}).items():
        builder = builder.config(k, v)
    session = builder.getOrCreate()
    init_zoo_context()
    return session
