"""NNFrames — DataFrame-native ML pipeline (Spark-ML-style Estimators).

Reference capability: ``pipeline/nnframes/`` — ``NNEstimator[T]``
(NNEstimator.scala:198, internalFit:414-491), ``NNModel`` Transformer,
``NNClassifier``/``NNClassifierModel`` (NNClassifier 306 LoC),
``NNImageReader`` (182 LoC), with preprocessing composed through
``FeatureLabelPreprocessing`` params.

TPU-native design: the DataFrame is a *host-side* pandas/pyarrow object —
there is no Spark on the data plane (SURVEY §7: the driver role collapses
into the single-controller JAX program).  ``fit`` lowers the frame's
columns to numpy, routes them through the FeatureSet tier, and trains with
the SPMD Estimator; ``transform`` appends a prediction column.  The
Spark-ML param surface (setBatchSize/setMaxEpoch/...) is kept so reference
pipelines port 1:1.
"""

from analytics_zoo_tpu.nnframes.nn_estimator import (NNClassifier,
                                                     NNClassifierModel,
                                                     NNEstimator, NNModel)
from analytics_zoo_tpu.nnframes.pipeline import (  # noqa: F401
    Pipeline, PipelineModel)
from analytics_zoo_tpu.nnframes.nn_image_reader import (NNImageReader,
                                                        NNImageSchema)

__all__ = ["NNEstimator", "NNModel", "NNClassifier", "NNClassifierModel",
           "NNImageReader", "NNImageSchema"]
