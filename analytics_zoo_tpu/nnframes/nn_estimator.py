"""NNEstimator / NNModel / NNClassifier — DataFrame in, model out.

Reference behavior being matched (not ported):
- ``NNEstimator.fit(df)`` extracts (featuresCol, labelCol), applies the
  sample preprocessing, builds a FeatureSet at the configured caching
  level and trains under the distributed optimizer
  (NNEstimator.scala:381-412 getDataSet, :414-479 internalFit).
- ``NNModel.transform(df)`` broadcasts the trained model and appends a
  prediction column per row (NNEstimator.scala:484-491 wrapBigDLModel).
- ``NNClassifier`` fixes the criterion to classification and its model
  argmaxes into a ``Double`` label column (NNClassifier.scala).

Here a "DataFrame" is pandas (or anything with ``to_pandas()``, e.g. a
pyarrow Table); columns hold scalars, lists, or ndarrays.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, List, Optional, Sequence, Union

import numpy as np


def _to_pandas(df):
    from analytics_zoo_tpu.nnframes.spark import (is_spark_df,
                                                  spark_df_to_pandas)

    if is_spark_df(df):                 # real pyspark.sql.DataFrame
        return spark_df_to_pandas(df)
    if hasattr(df, "to_pandas"):        # pyarrow.Table, polars, ...
        return df.to_pandas()
    return df


def _col_to_array(col, dtype=None) -> np.ndarray:
    """Lower a DataFrame column of scalars/lists/arrays to a dense
    ndarray (the SeqToTensor/MLlibVectorToTensor role,
    feature/common/Preprocessing.scala)."""
    vals = col.to_numpy() if hasattr(col, "to_numpy") else np.asarray(col)
    if vals.dtype == object:
        # pyspark.ml.linalg vectors expose toArray (MLlibVectorToTensor)
        vals = np.stack([np.asarray(v.toArray(), np.float32)
                         if hasattr(v, "toArray") else np.asarray(v)
                         for v in vals])
    if dtype is not None:
        vals = vals.astype(dtype)
    return vals


def _extract_features(df, features_col, preprocessing) -> List[np.ndarray]:
    """THE feature-column lowering (shared by NNEstimator and NNModel so
    dtype/preprocessing behavior cannot drift between fit and transform)."""
    cols = [features_col] if isinstance(features_col, str) \
        else list(features_col)
    xs = [_col_to_array(df[c]) for c in cols]
    if preprocessing is not None:
        xs = [preprocessing(x) for x in xs]
    return [x.astype(np.float32) if x.dtype == np.float64 else x
            for x in xs]


class _Params:
    """Spark-ML-style param plumbing: every setX returns self;
    ``copy()`` clones the stage (Estimator/Model share this base)."""

    def copy(self):
        return copy.copy(self)

    def __init__(self):
        self.batch_size = 32
        self.max_epoch = 1
        self.features_col = "features"
        self.label_col = "label"
        self.prediction_col = "prediction"
        self.caching_sample = "DRAM"     # memory tier for the FeatureSet
        self.learning_rate = 1e-3
        self.end_trigger = None
        self.validation = None           # (trigger, df, batch_size) parity
        self.checkpoint_path = None
        self.tensorboard_dir = None

    # -- setters (reference NNEstimator.scala param surface) --------------
    def set_batch_size(self, v: int):
        self.batch_size = int(v)
        return self

    def set_max_epoch(self, v: int):
        self.max_epoch = int(v)
        return self

    def set_learning_rate(self, v: float):
        self.learning_rate = float(v)
        return self

    def set_features_col(self, name: str):
        self.features_col = name
        return self

    def set_label_col(self, name: str):
        self.label_col = name
        return self

    def set_prediction_col(self, name: str):
        self.prediction_col = name
        return self

    def set_caching_sample(self, tier: str):
        self.caching_sample = tier
        return self

    def set_end_when(self, trigger):
        self.end_trigger = trigger
        return self

    def set_validation(self, trigger, df, batch_size: int = 32):
        self.validation = (trigger, df, batch_size)
        return self

    def set_checkpoint(self, path: str):
        self.checkpoint_path = path
        return self

    def set_tensorboard(self, log_dir: str):
        self.tensorboard_dir = log_dir
        return self

    # camelCase aliases so reference pipelines paste over
    setBatchSize = set_batch_size
    setMaxEpoch = set_max_epoch
    setLearningRate = set_learning_rate
    setFeaturesCol = set_features_col
    setLabelCol = set_label_col
    setPredictionCol = set_prediction_col
    setCachingSample = set_caching_sample
    setEndWhen = set_end_when
    setValidation = set_validation
    setCheckpoint = set_checkpoint
    setTensorboard = set_tensorboard


class NNEstimator(_Params):
    """Fit a Layer-protocol model from a DataFrame
    (reference NNEstimator.scala:198).

    ``feature_preprocessing`` / ``label_preprocessing``: callables
    ``ndarray -> ndarray`` applied to the whole extracted column (the
    FeatureLabelPreprocessing composition, NNEstimator.scala:92-130);
    image preprocessors from ``data.image`` compose here too.
    """

    def __init__(self, model, criterion: Union[str, Callable] = "mse",
                 feature_preprocessing: Optional[Callable] = None,
                 label_preprocessing: Optional[Callable] = None,
                 optimizer: Union[str, Any] = None):
        super().__init__()
        self.model = model
        self.criterion = criterion
        self.feature_preprocessing = feature_preprocessing
        self.label_preprocessing = label_preprocessing
        self.optimizer = optimizer

    def _extract(self, df, with_label: bool = True):
        df = _to_pandas(df)
        xs = _extract_features(df, self.features_col,
                               self.feature_preprocessing)
        y = None
        if with_label and self.label_col in getattr(df, "columns", []):
            y = _col_to_array(df[self.label_col])
            if self.label_preprocessing is not None:
                y = self.label_preprocessing(y)
            if y.dtype == np.float64:
                y = y.astype(np.float32)
        return xs, y

    def _build_estimator(self):
        from analytics_zoo_tpu.train.estimator import Estimator
        from analytics_zoo_tpu.train.optimizers import Adam

        opt = self.optimizer or Adam(lr=self.learning_rate)
        est = Estimator(self.model, optimizer=opt, loss=self.criterion)
        # transfer-learning flows hand NNEstimator a model whose layers
        # already carry weights (trained/loaded/staged) — seed them
        # instead of random-initialising silently
        from analytics_zoo_tpu.nn.topology import _carry_weights

        carried = _carry_weights(getattr(self.model, "_estimator", None)) \
            or getattr(self.model, "_pending_init", None)
        if carried is not None:
            est.set_initial_weights(*carried)
        if self.checkpoint_path:
            est.set_checkpoint(self.checkpoint_path)
        if self.tensorboard_dir:
            est.set_tensorboard(self.tensorboard_dir)
        return est

    def fit(self, df) -> "NNModel":
        """DataFrame -> FeatureSet(tier) -> SPMD training -> NNModel
        (reference internalFit, NNEstimator.scala:414-479)."""
        from analytics_zoo_tpu.data.featureset import FeatureSet

        xs, y = self._extract(df)
        if y is None:
            raise ValueError(f"label column {self.label_col!r} not in frame")
        est = self._build_estimator()
        fs = FeatureSet(xs + [y], memory_type=self.caching_sample)
        validation_data, val_trigger, val_batch = None, None, None
        if self.validation is not None:
            val_trigger, vdf, val_batch = self.validation
            vx, vy = self._extract(vdf)
            if vy is None:
                raise ValueError(
                    f"validation frame lacks label column {self.label_col!r}")
            validation_data = (vx, vy)
        est.fit(fs, batch_size=self.batch_size, epochs=self.max_epoch,
                validation_data=validation_data,
                validation_trigger=val_trigger,
                validation_batch_size=val_batch,
                end_trigger=self.end_trigger, verbose=False)
        return self._wrap_model(est)

    def _wrap_model(self, est) -> "NNModel":
        m = NNModel(self.model, estimator=est,
                    feature_preprocessing=self.feature_preprocessing)
        m.features_col = self.features_col
        m.prediction_col = self.prediction_col
        m.batch_size = self.batch_size
        return m


class NNModel(_Params):
    """Transformer: appends model predictions to a DataFrame
    (reference NNModel, NNEstimator.scala:484-491)."""

    def __init__(self, model, estimator=None,
                 feature_preprocessing: Optional[Callable] = None):
        super().__init__()
        self.model = model
        self.feature_preprocessing = feature_preprocessing
        if estimator is None:
            from analytics_zoo_tpu.train.estimator import Estimator

            estimator = Estimator(model, loss="mse")
        self.estimator = estimator

    def _extract_features(self, df):
        df = _to_pandas(df)
        return df, _extract_features(df, self.features_col,
                                     self.feature_preprocessing)

    def _postprocess_scores(self, scores: np.ndarray):
        """Raw model outputs -> prediction-column values (overridden by
        NNClassifierModel to argmax into class labels)."""
        if scores.ndim > 1 and scores.shape[-1] == 1:
            scores = scores[..., 0]
        return list(scores) if scores.ndim > 1 else scores

    def transform(self, df):
        from analytics_zoo_tpu.nnframes.spark import (is_spark_df,
                                                      pandas_to_spark_df)

        spark_session = df.sparkSession if is_spark_df(df) else None
        template = df if spark_session is not None else None
        df, xs = self._extract_features(df)
        scores = np.asarray(self.estimator.predict(
            xs, batch_size=self.batch_size))
        out = df.copy()
        out[self.prediction_col] = self._postprocess_scores(scores)
        for col, vals in self._extra_columns(scores).items():
            out[col] = vals
        if spark_session is not None:   # a Spark stage must return Spark
            return pandas_to_spark_df(out, spark_session,
                                      template_df=template)
        return out

    def _extra_columns(self, scores: np.ndarray) -> dict:
        """Additional output columns derived from the raw scores
        (NNClassifierModel adds rawPrediction here)."""
        return {}

    # -- persistence (reference NNModel.write/read) ------------------------
    def save(self, path: str) -> None:
        from analytics_zoo_tpu.train import checkpoint as ckpt

        ckpt.save_pytree(path, {"params": self.estimator.params,
                                "state": self.estimator.state or {}})

    def load_weights(self, path: str) -> "NNModel":
        from analytics_zoo_tpu.train import checkpoint as ckpt

        tree = ckpt.load_pytree(path)
        self.estimator.set_initial_weights(tree["params"],
                                           tree.get("state", {}))
        return self


class NNClassifier(NNEstimator):
    """NNEstimator specialised for classification
    (reference NNClassifier.scala): integer/float labels, and the fitted
    model predicts a class index column."""

    def __init__(self, model, criterion: Union[str, Callable] =
                 "sparse_categorical_crossentropy",
                 feature_preprocessing: Optional[Callable] = None,
                 zero_based_label: bool = True, **kw):
        super().__init__(model, criterion=criterion,
                         feature_preprocessing=feature_preprocessing, **kw)
        self.zero_based_label = zero_based_label

    def _extract(self, df, with_label: bool = True):
        xs, y = super()._extract(df, with_label)
        if y is not None:
            y = y.astype(np.int32)
            if not self.zero_based_label:   # reference 1-based labels
                y = y - 1
        return xs, y

    def _wrap_model(self, est) -> "NNClassifierModel":
        m = NNClassifierModel(
            self.model, estimator=est,
            feature_preprocessing=self.feature_preprocessing,
            zero_based_label=self.zero_based_label)
        m.features_col = self.features_col
        m.prediction_col = self.prediction_col
        m.batch_size = self.batch_size
        return m


class NNClassifierModel(NNModel):
    """Argmaxes class scores into the prediction column
    (reference NNClassifierModel)."""

    def __init__(self, model, estimator=None,
                 feature_preprocessing: Optional[Callable] = None,
                 zero_based_label: bool = True):
        super().__init__(model, estimator=estimator,
                         feature_preprocessing=feature_preprocessing)
        self.zero_based_label = zero_based_label

    def _postprocess_scores(self, scores: np.ndarray):
        if scores.ndim == 1 or scores.shape[-1] == 1:
            cls = (scores.reshape(len(scores)) > 0.5).astype(np.int64)
        else:
            cls = np.argmax(scores, axis=-1).astype(np.int64)
        if not self.zero_based_label:
            cls = cls + 1
        return cls.astype(np.float64)                      # Spark-ML Double

    def set_raw_prediction_col(self, v: str):
        self.raw_prediction_col = v
        return self

    setRawPredictionCol = set_raw_prediction_col

    def _extra_columns(self, scores: np.ndarray) -> dict:
        """Spark ML classifier column parity: ``rawPrediction`` carries
        the per-class score vector next to the argmaxed ``prediction``."""
        col = getattr(self, "raw_prediction_col", "rawPrediction")
        return {col: list(scores) if scores.ndim > 1 else scores}
